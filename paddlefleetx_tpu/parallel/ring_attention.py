"""Ring attention: context parallelism for long sequences.

The reference has NO long-context context-parallel path (SURVEY §5.7: max
trained context 1024; closest features are Megatron SP + the DAP axial
alltoall).  This is the idiomatic TPU answer: the sequence stays sharded
over the ``sep`` axis end-to-end; each device keeps its Q shard and the K/V
shards rotate around the ring (``ppermute`` hops over ICI), with
online-softmax accumulation so no device ever materialises the full
sequence — memory O(s/P), compute O(s²/P) per device.

Implemented as a ``sep``-manual ``shard_map`` through the version-split
adapter (``parallel/shard_map_compat.py``), with ``lax.scan`` over ring
steps so reverse-mode autodiff produces the reverse-ring backward
automatically.  On jax >= 0.9 the map is partially manual
(batch/heads/model axes stay GSPMD-auto inside); on jax 0.4.x it runs
full-manual with batch/heads sharded *at the map boundary* where the
shapes divide (the per-(batch, head) math needs no in-body communication,
so richer boundary specs keep DP/TP live without partial-auto), and when
nested inside another manual region (the 1F1B pipeline on 0.4.x, where a
second shard_map cannot open) the ring runs on the *ambient* manual
``sep`` axis: slice the locally-replicated sequence by ``axis_index``,
rotate K/V with ``ppermute``, ``all_gather`` the outputs back.
Complements Ulysses (sharding.py heads/(model,sep) rule): Ulysses reshards
seq<->heads with all-to-alls and needs heads >= sep degree; ring has no
head-count constraint and overlaps compute with neighbour exchange.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddlefleetx_tpu.parallel import shard_map_compat
from paddlefleetx_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_SEP,
)

NEG_INF = -1e30


def zigzag_permutation(seq_len: int, ring: int):
    """Balanced causal context-parallel layout (the zigzag/striped CP used
    by Megatron/llama3-scale training): split the sequence into 2*ring
    blocks and give device i blocks (i, 2*ring-1-i), so every device owns
    an early AND a late block and causal masking wastes the same ~half of
    the score blocks everywhere — with contiguous sharding device 0 is
    almost fully masked (idle) while device ring-1 does full work.

    Returns ``perm`` (int32 [seq_len]): feed ``tokens[:, perm]`` and pass
    ``positions=perm`` to :func:`ring_attention`; per-token outputs/losses
    are order-invariant, or invert with ``jnp.argsort(perm)``."""
    import numpy as np

    if seq_len % (2 * ring):
        raise ValueError(
            f"seq_len {seq_len} must be divisible by 2*ring = {2 * ring}"
        )
    block = seq_len // (2 * ring)
    idx = np.arange(seq_len).reshape(2 * ring, block)
    order = []
    for i in range(ring):
        order.append(idx[i])
        order.append(idx[2 * ring - 1 - i])
    return jnp.asarray(np.concatenate(order), jnp.int32)


def _softmax_update(q, k_c, v_c, m, l, acc, q_pos, k_pos, causal, scale):
    """Online-softmax update of (m, l, acc) with one K/V block.
    q: [b, sq, n, d]; k_c/v_c: [b, sk, n, d]; positions are GLOBAL token
    indices ([sq,1] / [1,sk]) for the causal mask."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_c, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        s = jnp.where((k_pos <= q_pos)[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1)
    # p in the V dtype: a bf16 p x bf16 v einsum runs the MXU at full
    # rate (fp32 operands quarter it — same finding as the flash kernels,
    # docs/performance_tuning.md op table); accumulation stays fp32 via
    # preferred_element_type.  No-op for fp32 inputs.
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_c.dtype), v_c,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _ring_body(q, q_pos, kv, step, *, ring_size, seq_local, causal, scale, chunk_k):
    """One ring step: partial attention of local q vs the currently-held
    K/V chunk.  q: [b, sl, n, d]; returns running (m, l, acc) update.

    Positions are explicit arrays (global token indices) carried alongside
    K/V around the ring — the causal mask never assumes the shard holds a
    contiguous block, which is what lets zigzag layouts balance causal
    work across the ring.

    ``chunk_k`` bounds the score buffer: the held K/V shard is processed in
    [sl, chunk_k] blocks under an inner ``lax.scan`` with rematerialised
    bodies, so peak memory is O(sl * chunk_k) instead of O(sl**2) — the
    flash-attention trade (recompute probabilities in the backward) in
    plain XLA einsums, which is what keeps very long local shards
    trainable."""
    k_c, v_c, k_pos_c, m, l, acc = kv
    q_pos2 = q_pos[:, None]

    if chunk_k is None or chunk_k >= seq_local:
        m, l, acc = _softmax_update(
            q, k_c, v_c, m, l, acc, q_pos2, k_pos_c[None, :], causal, scale
        )
    else:
        assert seq_local % chunk_k == 0, (seq_local, chunk_k)
        n_chunks = seq_local // chunk_k
        b, _, n, d = k_c.shape
        k_r = k_c.reshape(b, n_chunks, chunk_k, n, d).transpose(1, 0, 2, 3, 4)
        v_r = v_c.reshape(b, n_chunks, chunk_k, n, d).transpose(1, 0, 2, 3, 4)
        kp_r = k_pos_c.reshape(n_chunks, chunk_k)

        @jax.checkpoint
        def chunk_step(carry, args):
            m, l, acc = carry
            k_ch, v_ch, kp_ch = args
            m, l, acc = _softmax_update(
                q, k_ch, v_ch, m, l, acc, q_pos2, kp_ch[None, :], causal, scale
            )
            return (m, l, acc), None

        (m, l, acc), _ = jax.lax.scan(
            chunk_step, (m, l, acc), (k_r, v_r, kp_r)
        )

    # rotate K/V (and their positions) to the next rank
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]
    k_c = jax.lax.ppermute(k_c, AXIS_SEP, perm)
    v_c = jax.lax.ppermute(v_c, AXIS_SEP, perm)
    k_pos_c = jax.lax.ppermute(k_pos_c, AXIS_SEP, perm)
    return (k_c, v_c, k_pos_c, m, l, acc)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    causal: bool = True,
    chunk_k: Optional[int] = 1024,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """q,k,v: [b, s, n, d] with s sharded over ``sep``.  Output same spec.

    ``chunk_k``: inner K-block size bounding the per-ring-step score
    buffer to [s_local, chunk_k] (None = unchunked).  Shards shorter than
    the chunk (or not dividing it) run unchunked.

    ``positions``: [s] global token index of each row (sep-sharded with
    the sequence); defaults to arange — pass the permuted positions when
    the sequence is fed in a balanced layout (``zigzag_permutation``) so
    the causal mask follows the true token order."""
    ring = mesh.shape[AXIS_SEP]
    if ring == 1:
        from paddlefleetx_tpu.ops.attention import xla_attention

        if positions is None or not causal:
            return xla_attention(q, k, v, causal=causal)
        # permuted feed on a 1-device ring: honor the positions via an
        # explicit bias mask (silently masking by storage order would
        # return wrong values for zigzag-ordered inputs)
        allowed = positions[None, :] <= positions[:, None]  # [s, s]
        bias = jnp.where(allowed, 0.0, NEG_INF)[None, None].astype(jnp.float32)
        return xla_attention(q, k, v, causal=False, bias=bias)
    d = q.shape[-1]
    scale = 1.0 / (d**0.5)
    seq_local = q.shape[1] // ring
    # falsy = unchunked (the config layer documents 0 that way); shards
    # shorter than / not dividing the chunk also run unchunked
    if not chunk_k or seq_local <= chunk_k or seq_local % chunk_k:
        chunk_k = None
    if positions is None:
        positions = jnp.arange(q.shape[1], dtype=jnp.int32)

    def local_fn(q, k, v, pos):
        b, sl, n, _ = q.shape
        m0 = jnp.full((b, n, sl), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, n, sl), jnp.float32)
        acc0 = jnp.zeros((b, sl, n, d), jnp.float32)

        body = functools.partial(
            _ring_body, q, pos, ring_size=ring, seq_local=sl, causal=causal,
            scale=scale, chunk_k=chunk_k,
        )

        def scan_step(carry, _):
            return body(carry, None), None

        (k_f, v_f, _, m, l, acc), _ = jax.lax.scan(
            scan_step, (k, v, pos, m0, l0, acc0), None, length=ring
        )
        l_safe = jnp.maximum(l, 1e-30)
        out = acc / l_safe.transpose(0, 2, 1)[..., None]
        return out.astype(q.dtype)

    if AXIS_SEP in shard_map_compat.current_manual_axes():
        # 0.4.x nesting: the enclosing full-manual map (1F1B pipeline)
        # already made ``sep`` manual and a second shard_map cannot open
        # (its axes are already manual) — run the ring on the ambient axis.
        return _ring_nested_manual(q, k, v, positions, local_fn, ring, causal, scale)

    if shard_map_compat.HAS_JAX09_SHARD_MAP:
        # nested-map support (ring inside the 1F1B pipeline's stages-manual
        # shard_map): the inner map must be built against the AMBIENT
        # abstract mesh — passing the concrete Mesh from inside a manual
        # context trips a context-mesh mismatch in jax 0.9
        from jax.sharding import get_abstract_mesh

        amesh = get_abstract_mesh()
        inner_mesh = amesh if AXIS_SEP in amesh.axis_names else mesh
        full_specs = None
    else:
        inner_mesh = mesh
        # 0.4.x full-manual: the body is elementwise-independent over batch
        # and heads, so those dims can stay sharded at the map boundary
        # (no in-body communication needed) instead of being gathered —
        # keeps DP/TP live under full-manual.  Only axes whose sizes
        # divide the dims are taken (shard_map requires exact splits).
        b_axes = _divisible_axes(q.shape[0], (AXIS_DATA, AXIS_FSDP), mesh)
        h_axes = _divisible_axes(q.shape[2], (AXIS_MODEL,), mesh)
        qkv_spec = P(b_axes, AXIS_SEP, h_axes, None)
        full_specs = (
            (qkv_spec, qkv_spec, qkv_spec, P(AXIS_SEP)),
            qkv_spec,
        )
    return shard_map_compat.shard_map(
        local_fn,
        inner_mesh,
        in_specs=(P(None, AXIS_SEP), P(None, AXIS_SEP), P(None, AXIS_SEP), P(AXIS_SEP)),
        out_specs=P(None, AXIS_SEP),
        manual_axes={AXIS_SEP},
        full_specs=full_specs,
    )(q, k, v, positions)


def _divisible_axes(dim: int, axes, mesh):
    """Greedy prefix of ``axes`` whose combined size divides ``dim`` (and
    is > 1) — the shardable portion of a dim under full-manual specs."""
    chosen = []
    prod = 1
    for ax in axes:
        size = mesh.shape.get(ax, 1)
        if size > 1 and dim % (prod * size) == 0:
            chosen.append(ax)
            prod *= size
    if not chosen:
        return None
    return tuple(chosen) if len(chosen) > 1 else chosen[0]


@jax.custom_vjp
def _enter_replicated(x):
    """Replicated -> rank-local frame seam (identity forward).

    Inside the enclosing full-manual map the inputs are replicated over
    ``sep`` and each rank computes only its sequence block's
    contribution, so the raw cotangent arriving here is the rank's
    zero-padded partial (rank-varying).  The replicated input's true
    cotangent is the SUM of those disjoint partials, identical on every
    rank — a ``psum`` over the ring.  Without this seam the enclosing
    schedule's parameter grads inherit one arbitrary rank's partial
    (verified wrong by ~1e3 rel on pp2 x sep2 before the fix)."""
    return x


def _enter_replicated_fwd(x):
    return x, None


def _enter_replicated_bwd(_, ct):
    return (jax.lax.psum(ct, AXIS_SEP),)


_enter_replicated.defvjp(_enter_replicated_fwd, _enter_replicated_bwd)


@jax.custom_vjp
def _gather_replicated(out_l):
    """Rank-local -> replicated frame seam: all_gather forward, OWN-SLICE
    backward.

    Every sep rank redundantly consumes the gathered (replicated) output
    downstream, but those copies are ONE logical consumer — the enclosing
    map's out_specs claim sep-replication.  jax's default all_gather
    transpose (psum_scatter) would sum the identical per-rank cotangents
    and over-count each block by the ring size; the true cotangent of
    rank i's local block is simply its own slice of the (replicated)
    downstream cotangent, counted once."""
    return jax.lax.all_gather(out_l, AXIS_SEP, axis=1, tiled=True)


def _gather_replicated_fwd(out_l):
    return _gather_replicated(out_l), out_l.shape[1]  # static local length


def _gather_replicated_bwd(sl, ct):
    start = jax.lax.axis_index(AXIS_SEP) * sl
    return (jax.lax.dynamic_slice_in_dim(ct, start, sl, axis=1),)


_gather_replicated.defvjp(_gather_replicated_fwd, _gather_replicated_bwd)


def _ring_nested_manual(q, k, v, positions, local_fn, ring, causal, scale):
    """Ring attention on the *ambient* manual ``sep`` axis (jax 0.4.x,
    inside the pipeline's full-manual map).

    The enclosing map replicates non-``stages`` axes at its boundary, so
    every sep coordinate holds the full sequence.  Context parallelism is
    re-introduced explicitly: each sep rank slices out its sequence block,
    runs the ring schedule (``ppermute`` hops on the already-manual axis),
    and an ``all_gather`` rebuilds the full — genuinely replicated —
    output the rest of the (replicated) layer consumes.  The two frame
    seams carry custom VJPs (``_enter_replicated`` /
    ``_gather_replicated``) so the backward counts each block's cotangent
    exactly once and psums the disjoint per-rank input grads back to the
    replicated frame — the manual reverse ring, with sep-INVARIANT
    results (the enclosing map's out_specs assert sep-replication, so a
    rank-varying grad would silently emit one rank's partial)."""
    s = q.shape[1]
    if s % ring:
        # indivisible sequence: no balanced ring exists — run the dense
        # online-softmax locally (every rank replicated, mask by positions)
        b, _, n, d = q.shape
        m0 = jnp.full((b, n, s), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, n, s), jnp.float32)
        acc0 = jnp.zeros((b, s, n, d), jnp.float32)
        m, l, acc = _softmax_update(
            q, k, v, m0, l0, acc0, positions[:, None], positions[None, :],
            causal, scale,
        )
        l_safe = jnp.maximum(l, 1e-30)
        return (acc / l_safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    sl = s // ring
    start = jax.lax.axis_index(AXIS_SEP) * sl
    q, k, v = _enter_replicated(q), _enter_replicated(k), _enter_replicated(v)
    q_l = jax.lax.dynamic_slice_in_dim(q, start, sl, axis=1)
    k_l = jax.lax.dynamic_slice_in_dim(k, start, sl, axis=1)
    v_l = jax.lax.dynamic_slice_in_dim(v, start, sl, axis=1)
    pos_l = jax.lax.dynamic_slice_in_dim(positions, start, sl, axis=0)
    out_l = local_fn(q_l, k_l, v_l, pos_l)
    return _gather_replicated(out_l)
