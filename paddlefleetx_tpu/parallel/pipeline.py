"""Pipeline parallelism: microbatch schedules over the ``stages`` mesh axis.

TPU-native replacement for the reference's ``PipelineLayer`` runtime
(``GPTForPretrainingPipe`` hybrid_model.py:1055-1206: LayerDesc flattening,
1F1B schedule, ``num_virtual_pipeline_stages`` interleaving, p2p send/recv
between pp ranks, tied embeddings via SharedLayerDesc): layers are stacked
on a leading axis and sharded over ``stages``; schedules run inside a
``stages``-manual ``shard_map`` (explicit ``ppermute`` hops between
neighbour stages, riding ICI) through the version-split adapter
``parallel/shard_map_compat.py``: on jax >= 0.9 the map is *partially
manual* (TP/FSDP/DP keep flowing through GSPMD inside each stage); on jax
0.4.x — where partial-auto lowering is broken (PartitionId / SPMD CHECK,
see shard_map_compat docstring) — the same body runs *full-manual*, with
non-stage axes replicated at the map boundary (in-body activation
constraints naming them are dropped by ``sharding.with_logical_constraint``)
and ring attention nesting via ambient manual collectives instead of an
inner map.

Two schedules:

* :func:`pipelined_stack` — GPipe fill-drain, forward only.  Used for
  eval/inference where no backward wave exists and all-microbatch
  residency is the algorithmic minimum anyway.

* :func:`pipeline_loss_1f1b` — the training schedule.  True 1F1B memory
  behavior (reference hybrid_model.py:1206 / Megatron fig. 4): the
  backward of microbatch ``m`` starts as soon as its forward drains from
  the last stage, so each stage holds at most ``min(2*C-1, M)`` stashed
  stage inputs (C = total chunks) instead of GPipe's ``M``.  Because JAX
  autodiff would otherwise delay every backward until all forwards finish,
  the schedule computes gradients *inside* the forward pass (per-microbatch
  VJPs against stashed stage inputs) and exposes them through
  ``jax.custom_vjp`` — the outer ``jax.grad`` just scales them.  The
  per-microbatch loss (head + CE) runs on the last chunk inside the
  schedule, so the only cross-stage outputs are the scalar loss numerator
  and parameter gradients: the fp32 activation-psum output seam of the
  fill-drain path does not exist here.

Virtual stages (reference ``num_virtual_pipeline_stages``,
hybrid_model.py:1190-1206): with V > 1 each device holds V layer *chunks*
assigned round-robin (chunk c lives on device ``c % S``), shrinking the
bubble from (S-1)/T to ~(S-1)/(V*T').  The caller passes the stacked
layer params pre-permuted so each device's contiguous ``stages`` shard
contains its V chunks in slot order (see ``interleave_permutation``).

Tied embeddings need no SharedLayerDesc machinery: embedding and head
params enter the schedule as separate arguments; passing the same array
for both makes outer autodiff sum the two returned cotangents — exactly
the first/last-rank embedding-grad allreduce the reference does manually.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from paddlefleetx_tpu.parallel import shard_map_compat
from paddlefleetx_tpu.parallel.mesh import AXIS_STAGES


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    num_stages: int
    num_microbatches: int
    num_virtual_stages: int = 1


def interleave_permutation(num_layers: int, S: int, V: int) -> np.ndarray:
    """Index array mapping storage order -> schedule order for V>1.

    Execution chunk ``c`` (semantic layers [c*pc, (c+1)*pc)) runs on device
    ``c % S`` in local slot ``c // S``; device s's contiguous stage shard
    must therefore hold chunks ``[s, S+s, 2S+s, ...]`` back to back."""
    C = S * V
    pc = num_layers // C
    idx = []
    for s in range(S):
        for v in range(V):
            c = v * S + s
            idx.extend(range(c * pc, (c + 1) * pc))
    return np.asarray(idx, dtype=np.int32)


def _is_cpu(mesh) -> bool:
    return next(iter(mesh.devices.flat)).platform == "cpu"


def pipelined_stack(
    layer_fn: Callable[[Any, jax.Array, jax.Array, jax.Array], jax.Array],
    layers_params: Any,
    x: jax.Array,
    pcfg: PipelineConfig,
    mesh,
) -> jax.Array:
    """Run a stacked-layer transformer body as a forward-only stage pipeline.

    layer_fn(local_params, x_mb, stage_index, mb_index) -> y_mb runs this
    stage's layer block (a lax.scan over the local layers); ``mb_index`` is
    the microbatch the stage is processing this tick (for per-microbatch
    dropout keys).  ``layers_params`` leaves have leading dim num_layers,
    sharded over ``stages``; x: [b, s, h].
    """
    S, M = pcfg.num_stages, pcfg.num_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible by pipeline microbatches {M}")

    in_dtype = x.dtype
    # XLA CPU's AllReducePromotion pass crashes on bf16 all-reduces, so the
    # seam runs fp32 there; on TPU the boundary stays in the compute dtype
    # (VERDICT r1: don't pay S-wide fp32 broadcasts on real hardware).
    seam_dtype = jnp.float32 if _is_cpu(mesh) else in_dtype

    def pipe(local_layers, x):
        x = x.astype(in_dtype)
        stage = jax.lax.axis_index(AXIS_STAGES)
        mbs = x.reshape((M, b // M) + x.shape[1:])
        T = M + S - 1

        def tick(carry, t):
            buf, out = carry
            mb_idx = jnp.minimum(t, M - 1)
            x0 = jax.lax.dynamic_index_in_dim(mbs, mb_idx, axis=0, keepdims=False)
            x_in = jnp.where(stage == 0, jnp.where(t < M, 1.0, 0.0) * x0, buf)
            # stage s processes microbatch t-s at tick t (clamped: out-of-
            # range ticks compute on garbage that is never emitted)
            mb_live = jnp.clip(t - stage, 0, M - 1)
            y = layer_fn(local_layers, x_in, stage, mb_live)
            # last stage emits microbatch t-(S-1) at tick t
            emit_idx = jnp.maximum(t - (S - 1), 0)
            emit = jnp.where((stage == S - 1) & (t >= S - 1), y, 0.0)
            prev = jax.lax.dynamic_index_in_dim(out, emit_idx, axis=0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(out, prev + emit, emit_idx, axis=0)
            buf = jax.lax.ppermute(
                y, AXIS_STAGES, [(i, (i + 1) % S) for i in range(S)]
            )
            return (buf, out), None

        buf0 = jnp.zeros_like(mbs[0])
        out0 = jnp.zeros_like(mbs)
        (_, out), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(T))
        # outputs live on the last stage only; replicate across stages so the
        # (stage-replicated) head can consume them everywhere
        out = jax.lax.psum(out.astype(seam_dtype), AXIS_STAGES)
        return out.reshape(x.shape)

    out = shard_map_compat.shard_map(
        pipe,
        mesh,
        in_specs=(P(AXIS_STAGES), P()),
        out_specs=P(),
        manual_axes={AXIS_STAGES},
    )(layers_params, x.astype(seam_dtype))
    return out.astype(in_dtype)


# ---------------------------------------------------------------------------
# 1F1B training schedule
# ---------------------------------------------------------------------------
#
# Event timetable (C = S*V chunks, M microbatches; lockstep SPMD ticks):
#   fwd(m, c)  at tick  m + c
#   bwd(m, c)  at tick  m + 2C - 1 - c
# so microbatch m's backward enters the last chunk one tick after its
# forward leaves it, and drains toward stage 0 while later microbatches are
# still filling — the 1F1B interleave.  A stage input stashed at fwd(m, c)
# is consumed at bwd(m, c): lifetime 2(C-c)-1 <= 2C-1 ticks, so a circular
# stash of K = min(2C-1, M) slots suffices (the memory claim).
#
# Each tick every device runs, per local chunk slot: one forward
# (embed|recv -> chunk) and one VJP (recompute embed+chunk+head from the
# stashed input, pull back the cotangent arriving from the next chunk).
# Out-of-range events compute on zeros and are masked out of every
# accumulator.  Activations and cotangents ride neighbour-to-neighbour
# ppermutes in the compute dtype; the only stage-psums are parameter
# gradients and the scalar loss numerator.


def _tree_axpy(acc, new, w):
    # cast back to the accumulator dtype: w is fp32 (a liveness mask), so
    # the product would silently promote a bf16 grad accumulator to fp32
    # and break the scan carry's dtype invariant under multi_precision=
    # False / main_grad=False (bf16 params or grads)
    return jax.tree.map(lambda a, g: a + (w * g).astype(a.dtype), acc, new)


def _run_1f1b(fns, pcfg: PipelineConfig, mesh, params, batch):
    embed_fn, chunk_fn, head_fn = fns
    S, M, V = pcfg.num_stages, pcfg.num_microbatches, pcfg.num_virtual_stages
    C = S * V
    eparams, layers, hparams = params
    bsz = next(iter(batch.values())).shape[0]
    if bsz % M:
        raise ValueError(f"batch {bsz} not divisible by pipeline microbatches {M}")

    def pipe(eparams, layers, hparams, batch):
        stage = jax.lax.axis_index(AXIS_STAGES)
        # local stage shard of the stacked layers, split into V chunk slots
        local = jax.tree.map(
            lambda a: a.reshape((V, a.shape[0] // V) + a.shape[1:]), layers
        )
        mbs = jax.tree.map(
            lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), batch
        )
        mb0 = jax.tree.map(lambda a: a[0], mbs)
        x_aval = jax.eval_shape(embed_fn, eparams, mb0, jnp.int32(0))
        K = min(2 * C - 1, M)
        zbuf = jnp.zeros((V,) + x_aval.shape, x_aval.dtype)
        stash0 = jnp.zeros((V, K) + x_aval.shape, x_aval.dtype)
        g0 = (
            jax.tree.map(jnp.zeros_like, eparams),
            jax.tree.map(jnp.zeros_like, local),
            jax.tree.map(jnp.zeros_like, hparams),
        )
        T = M + 2 * C - 1

        def tick(carry, t):
            fwd_buf, bwd_buf, stash, (ge, gl, gh), numer = carry
            ys, gxs = [], []
            new_stash = stash
            for v in range(V):
                c = v * S + stage
                # chunk 0 (embedding input) can only live in slot 0, and the
                # last chunk C-1 (head+loss) only in slot V-1: skip the
                # statically-dead embed/head work in the other slots
                can_be_first = v == 0
                can_be_last = v == V - 1
                local_v = jax.tree.map(lambda a: a[v], local)
                # ---- forward event: chunk c runs microbatch t - c --------
                m_f = t - c
                f_live = (m_f >= 0) & (m_f < M)
                mfi = jnp.clip(m_f, 0, M - 1)
                mb_f = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(a, mfi, 0, keepdims=False),
                    mbs,
                )
                if can_be_first:
                    x0 = embed_fn(eparams, mb_f, mfi)
                    x_in = jnp.where(c == 0, x0, fwd_buf[v])
                else:
                    x_in = fwd_buf[v]
                y = chunk_fn(local_v, x_in, c, mfi)
                ys.append(y)
                slot = jnp.mod(mfi, K)
                old = jax.lax.dynamic_index_in_dim(stash[v], slot, 0, keepdims=False)
                new_stash = new_stash.at[v].set(
                    jax.lax.dynamic_update_index_in_dim(
                        new_stash[v], jnp.where(f_live, x_in, old), slot, 0
                    )
                )
                # ---- backward event: chunk c, microbatch t - (2C-1-c) ----
                m_b = t - (2 * C - 1 - c)
                b_live = (m_b >= 0) & (m_b < M)
                mbi = jnp.clip(m_b, 0, M - 1)
                mb_b = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(a, mbi, 0, keepdims=False),
                    mbs,
                )
                bslot = jnp.mod(mbi, K)
                # read the PRE-tick stash: a slot is overwritten in the same
                # tick it is read only at c=0 with K=2C-1, where the old
                # value is exactly the one wanted
                x_st = jax.lax.dynamic_index_in_dim(stash[v], bslot, 0, keepdims=False)

                def recomp(ep, lp, hp, xin):
                    if can_be_first:
                        x0r = embed_fn(ep, mb_b, mbi)
                        xr = jnp.where(c == 0, x0r, xin)
                    else:
                        xr = xin
                    yr = chunk_fn(lp, xr, c, mbi)
                    nr = (
                        head_fn(hp, yr, mb_b, mbi)
                        if can_be_last
                        else jnp.zeros((), jnp.float32)
                    )
                    return yr, nr

                (_, nr), vjp = jax.vjp(recomp, eparams, local_v, hparams, x_st)
                is_last = c == C - 1
                gy = jnp.where(is_last, jnp.zeros_like(bwd_buf[v]), bwd_buf[v])
                gn = jnp.where(is_last, 1.0, 0.0).astype(jnp.float32)
                gep, glv, ghp, gx = vjp((gy, gn))
                w = b_live.astype(jnp.float32)
                ge = _tree_axpy(ge, gep, w)
                gh = _tree_axpy(gh, ghp, w)
                gl = jax.tree.map(
                    lambda a, g, _v=v: a.at[_v].add((w * g).astype(a.dtype)),
                    gl, glv,
                )
                numer = numer + jnp.where(is_last & b_live, nr, 0.0).astype(jnp.float32)
                gxs.append(jnp.where(b_live, gx, jnp.zeros_like(gx)))
            # ---- ring sends -------------------------------------------------
            y_stack = jnp.stack(ys)  # [V, mb, ...]
            recv_f = jax.lax.ppermute(
                y_stack, AXIS_STAGES, [(i, (i + 1) % S) for i in range(S)]
            )
            # wrap on device 0: chunk vS's input is device S-1's slot v-1
            # output; slot 0 is fed by the embedding instead
            shifted_f = jnp.concatenate([jnp.zeros_like(recv_f[:1]), recv_f[:-1]], 0)
            fwd_buf = jnp.where(stage == 0, shifted_f, recv_f)
            gx_stack = jnp.stack(gxs)
            recv_b = jax.lax.ppermute(
                gx_stack, AXIS_STAGES, [(i, (i - 1) % S) for i in range(S)]
            )
            # wrap on device S-1: cotangent for chunk vS+S-1 is device 0's
            # slot v+1 pullback; the last chunk's cotangent is internal
            shifted_b = jnp.concatenate([recv_b[1:], jnp.zeros_like(recv_b[:1])], 0)
            bwd_buf = jnp.where(stage == S - 1, shifted_b, recv_b)
            return (fwd_buf, bwd_buf, new_stash, (ge, gl, gh), numer), None

        carry0 = (zbuf, zbuf, stash0, g0, jnp.zeros((), jnp.float32))
        (_, _, _, (ge, gl, gh), numer), _ = jax.lax.scan(
            tick, carry0, jnp.arange(T)
        )
        # embedding/head grads and the loss numerator are partial per stage
        # (tied-embedding contributions, reference hybrid_model
        # SharedLayerDesc allreduce).  Emitted with a leading stage axis and
        # reduced OUTSIDE the shard_map: an in-schedule psum-over-stages of
        # model-sharded grads trips an XLA partial-manual partitioner CHECK
        # (spmd_partitioner_util.cc device-group mismatch); the outer sum
        # lowers to the same allreduce through full GSPMD instead.
        numer = numer[None]
        ge = jax.tree.map(lambda a: a[None], ge)
        gh = jax.tree.map(lambda a: a[None], gh)
        gl = jax.tree.map(
            lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), gl
        )
        return numer, ge, gl, gh

    numer, ge, gl, gh = shard_map_compat.shard_map(
        pipe,
        mesh,
        in_specs=(P(), P(AXIS_STAGES), P(), P()),
        out_specs=(P(AXIS_STAGES), P(AXIS_STAGES), P(AXIS_STAGES), P(AXIS_STAGES)),
        manual_axes={AXIS_STAGES},
    )(eparams, layers, hparams, batch)
    numer = numer.sum(0)
    ge = jax.tree.map(lambda a: a.sum(0), ge)
    gh = jax.tree.map(lambda a: a.sum(0), gh)
    return numer, ge, gl, gh


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def pipeline_loss_1f1b(
    fns,
    pcfg: PipelineConfig,
    mesh,
    params,
    batch: Dict[str, jax.Array],
) -> jax.Array:
    """1F1B pipelined loss numerator: sum over tokens of nll * mask.

    ``fns = (embed_fn, chunk_fn, head_fn)``, pure functions:
      embed_fn(eparams, batch_mb, mb_index) -> x_mb          (chunk 0 input)
      chunk_fn(chunk_params, x_mb, chunk_index, mb_index) -> y_mb
      head_fn(hparams, y_mb, batch_mb, mb_index) -> numer    (scalar, fp32)

    ``params = (eparams, layers_stacked, hparams)`` are differentiable;
    ``batch`` leaves must be float arrays with leading dim = batch (cast int
    ids to float outside; cotangents for them are zero).  Gradients are
    computed during the forward schedule (see module docstring); the custom
    VJP scales them by the incoming cotangent, so this composes with
    ``jax.grad`` / the engine's value_and_grad unchanged.
    """
    numer, _, _, _ = _run_1f1b(fns, pcfg, mesh, params, batch)
    return numer


def _1f1b_fwd(fns, pcfg, mesh, params, batch):
    numer, ge, gl, gh = _run_1f1b(fns, pcfg, mesh, params, batch)
    bzeros = jax.tree.map(jnp.zeros_like, batch)
    return numer, ((ge, gl, gh), bzeros)


def _1f1b_bwd(fns, pcfg, mesh, res, gbar):
    grads, bzeros = res
    # gbar is an fp32 scalar (numer is fp32); keep cotangents in the param
    # dtype so bf16-param runs (multi_precision=False) get bf16 grads that
    # match the engine's bf16 accumulator carry instead of promoting
    return jax.tree.map(lambda g: (gbar * g).astype(g.dtype), grads), bzeros


pipeline_loss_1f1b.defvjp(_1f1b_fwd, _1f1b_bwd)
