"""Pipeline parallelism: microbatch schedule over the ``stages`` mesh axis.

TPU-native replacement for the reference's ``PipelineLayer`` runtime
(``GPTForPretrainingPipe`` hybrid_model.py:1055-1206: LayerDesc flattening,
1F1B schedule, p2p send/recv between pp ranks, tied embeddings via
SharedLayerDesc): layers are stacked on a leading axis and sharded over
``stages``; the schedule runs inside a *partially-manual* ``jax.shard_map``
— manual over ``stages`` (explicit ``ppermute`` hops between neighbour
stages, riding ICI), auto everywhere else (TP/FSDP/DP keep flowing through
GSPMD inside each stage).

Schedule: GPipe-style fill-drain over M microbatches and S stages
(T = M+S-1 ticks; bubble fraction (S-1)/T).  Memory behaves like 1F1B when
combined with full-layer rematerialisation (the default for pp configs —
same recipe as the reference's pp+recompute YAMLs).  Tied embeddings need no
SharedLayerDesc machinery: the embedding lives outside the pipelined stack,
replicated over ``stages``, and XLA psums its gradient contributions.

The backward schedule is jax.grad through the forward ``ppermute``s — the
transpose of a ppermute is the reverse ppermute, so the reverse pipeline
drains in the opposite direction automatically.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddlefleetx_tpu.parallel.mesh import AXIS_STAGES


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    num_stages: int
    num_microbatches: int


def pipelined_stack(
    layer_fn: Callable[[Any, jax.Array, jax.Array], jax.Array],
    layers_params: Any,
    x: jax.Array,
    pcfg: PipelineConfig,
    mesh,
) -> jax.Array:
    """Run a stacked-layer transformer body as a stage pipeline.

    layer_fn(local_params, x_mb, stage_index, mb_index) -> y_mb runs this
    stage's layer block (a lax.scan over the local layers); ``mb_index`` is
    the microbatch the stage is processing this tick (for per-microbatch
    dropout keys).  ``layers_params`` leaves have leading dim num_layers,
    sharded over ``stages``; x: [b, s, h].
    """
    S, M = pcfg.num_stages, pcfg.num_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible by pipeline microbatches {M}")

    in_dtype = x.dtype

    def pipe(local_layers, x):
        x = x.astype(in_dtype)  # f32 at the boundary (see cast note below)
        stage = jax.lax.axis_index(AXIS_STAGES)
        mbs = x.reshape((M, b // M) + x.shape[1:])
        T = M + S - 1

        def tick(carry, t):
            buf, out = carry
            mb_idx = jnp.minimum(t, M - 1)
            x0 = jax.lax.dynamic_index_in_dim(mbs, mb_idx, axis=0, keepdims=False)
            x_in = jnp.where(stage == 0, jnp.where(t < M, 1.0, 0.0) * x0, buf)
            # stage s processes microbatch t-s at tick t (clamped: out-of-
            # range ticks compute on garbage that is never emitted)
            mb_live = jnp.clip(t - stage, 0, M - 1)
            y = layer_fn(local_layers, x_in, stage, mb_live)
            # last stage emits microbatch t-(S-1) at tick t
            emit_idx = jnp.maximum(t - (S - 1), 0)
            emit = jnp.where((stage == S - 1) & (t >= S - 1), y, 0.0)
            prev = jax.lax.dynamic_index_in_dim(out, emit_idx, axis=0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(out, prev + emit, emit_idx, axis=0)
            buf = jax.lax.ppermute(
                y, AXIS_STAGES, [(i, (i + 1) % S) for i in range(S)]
            )
            return (buf, out), None

        buf0 = jnp.zeros_like(mbs[0])
        out0 = jnp.zeros_like(mbs)
        (_, out), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(T))
        # outputs live on the last stage only; replicate across stages so the
        # (stage-replicated) LM head can consume them everywhere.  psum in
        # fp32: XLA CPU's AllReducePromotion pass crashes on bf16 allreduce
        # (and fp32 accumulation is numerically safer anyway)
        out = jax.lax.psum(out.astype(jnp.float32), AXIS_STAGES)
        return out.reshape(x.shape)

    # cast note: activations cross the shard_map boundary in fp32 — XLA
    # CPU's AllReducePromotion pass crashes on the bf16 all-reduces this
    # boundary generates (the fwd psum above and the bwd psum that is the
    # transpose of the stage-replicated input); fp32 at the seam sidesteps
    # both and costs only a cast each way
    out = jax.shard_map(
        pipe,
        mesh=mesh,
        in_specs=(P(AXIS_STAGES), P()),
        out_specs=P(),
        axis_names={AXIS_STAGES},
        check_vma=False,
    )(layers_params, x.astype(jnp.float32))
    return out.astype(in_dtype)
