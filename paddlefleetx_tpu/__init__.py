"""PaddleFleetX-TPU: a TPU-native large-model training framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of PaddleFleetX
(reference: /root/reference): end-to-end big-model pretraining, finetuning,
evaluation, generation and deployment for language / vision / multimodal
models with hybrid parallelism (DP / TP / SP / PP / FSDP-ZeRO / MoE-EP).

Reference layer map (see SURVEY.md §1): tools -> core engine -> models/optims/
data -> distributed -> utils.  Here the same capability stack is realised as:

    tools/              CLI entry points (train / eval / export / generate)
    core/               Engine + Module protocol (train/eval loops, ckpt)
    models/             pure-JAX functional model zoo (GPT, ViT, ERNIE, ...)
    parallel/           mesh builder, sharding rules, pipeline, MoE comm
    optims/             optax-based optimizers, LR schedules, grad clip
    data/               mmap token datasets, samplers, tokenizers, C++ helpers
    ops/                Pallas TPU kernels (flash attention, fused LN, top-p)
    utils/              config (YAML + _base_ + -o overrides), logging, registry
"""

__version__ = "0.1.0"
