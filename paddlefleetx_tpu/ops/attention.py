"""Attention ops: XLA reference implementation + dispatch to Pallas flash.

TPU-native replacement for the reference's attention stack
(``MultiHeadAttention.core_attn`` single_model.py:83-200, fused
softmax-mask-triu path and the ``flash_attention`` hook
hybrid_model.py:284-301): one causal-attention entry point, implemented as
plain XLA einsum (always available, any platform) or a Pallas TPU kernel
(``ops/flash_attention.py``) selected by ``impl``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


def causal_mask_bias(seq_len: int, dtype) -> jax.Array:
    """Additive causal bias [1, 1, s, s] (triu -> -inf)."""
    mask = jnp.tril(jnp.ones((seq_len, seq_len), dtype=jnp.bool_))
    bias = jnp.where(mask, 0.0, -1e9).astype(dtype)
    return bias[None, None, :, :]


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    bias: Optional[jax.Array] = None,
    dropout_key: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    train: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Reference attention.  q,k,v: [batch, seq, heads, head_dim].

    ``scale=None`` means 1/sqrt(head_dim); pass ``scale=1.0`` for T5-style
    unscaled attention (scale folded into initialization)."""
    seq_q = q.shape[1]
    seq_k = k.shape[1]
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    # scores in fp32 for softmax stability (reference uses fused fp16 softmax
    # with max-subtract; bf16 TPU matmul accumulates fp32 natively)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    elif causal:
        scores = scores + causal_mask_bias(seq_k, scores.dtype)[:, :, -seq_q:, :]
    probs = jax.nn.softmax(scores, axis=-1)
    if train and dropout_rate > 0.0 and dropout_key is not None:
        keep = 1.0 - dropout_rate
        probs = probs * jax.random.bernoulli(dropout_key, keep, probs.shape) / keep
    probs = probs.astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "xla",
    causal: bool = True,
    bias: Optional[jax.Array] = None,
    dropout_key: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    train: bool = False,
    scale: Optional[float] = None,
    flash_block: int = 0,
    flash_bwd: str = "",
) -> jax.Array:
    """Dispatching attention entry point used by all models.

    ``flash_block`` / ``flash_bwd`` pass through to the Pallas kernels
    (0/"" = auto); surfaced as ``Model.flash_block`` / ``Model.flash_bwd``."""
    if impl == "flash" and bias is None and causal and scale is None:
        from paddlefleetx_tpu.ops.flash_attention import flash_attention, flash_supported

        if not flash_supported(q.shape[1], flash_block):
            # odd sequence lengths fall back to the XLA path (one warning)
            import warnings

            warnings.warn(
                f"flash attention unsupported for seq={q.shape[1]}; using XLA path",
                stacklevel=2,
            )
        else:
            # NB: attention-prob dropout is skipped on the flash path (the
            # reference likewise disables dropout when flash is active,
            # hybrid_model.py:284-301)
            return flash_attention(
                q, k, v, causal=True, block=flash_block, bwd_schedule=flash_bwd
            )
    out = xla_attention(
        q,
        k,
        v,
        causal=causal,
        bias=bias,
        dropout_key=dropout_key,
        dropout_rate=dropout_rate,
        train=train,
        scale=scale,
    )
    # Whenever the XLA path actually runs (configured, or flash fell back),
    # name the output so selective remat can skip the O(s^2) recompute.
    # The flash kernel instead names its lse internally ("attn_lse") and
    # re-runs one cheap fwd kernel in backward. Tagging here (not at call
    # sites) keeps the which-impl-ran decision in one place.
    return checkpoint_name(out, "attn_out")
