"""Speculative decoding: draft proposal + accept/reject verification.

Leviathan et al. 2023 ("Fast Inference from Transformers via Speculative
Decoding"): a cheap DRAFTER proposes k tokens, the target model verifies
all of them in ONE multi-token forward (the cached forward and both
decode-attention spellings already take t > 1), and a rejection rule
guarantees the output distribution is unchanged — greedy output is
token-identical to the non-speculative path by construction (accept
exactly the prefix matching the target argmax; the first mismatch is
replaced by the target's own token), and sampled output preserves the
target distribution via the residual rule for a point-mass drafter
(accept draft d w.p. p(d); on rejection sample from p with d's mass
removed and renormalized — the marginal is exactly p).

This module is the scheduler-agnostic toolbox; the decode loops that
consume it live in ``models/gpt/generation.py`` (contiguous while-loop +
paged ``decode_step_spec``) and the wiring in ``core/serving.py`` /
``core/continuous_batching.py``:

  - :class:`SpecConfig` — draft_k / drafter knobs (the ``Generation.
    speculative`` config section; part of the jit compile key, so a
    changed k retraces exactly like a changed decode strategy).
  - :func:`ngram_propose` (in-graph) / :func:`ngram_propose_host` —
    the default SELF-DRAFTING prompt-lookup drafter: find the last
    earlier occurrence of the trailing n-gram in the row's own
    prompt+output and propose the tokens that followed it.  No second
    model, no extra weights; acceptance is high exactly when decode is
    repetitive (code, tables, random-weight argmax cycles).  A wrong
    proposal costs nothing but the verify FLOPs — the accept rule
    discards it.
  - :func:`speculative_verify` — the vectorized accept/reject rule over
    one verified chunk, shared by both decode paths: per-row accepted
    prefix length, EOS handling, pad substitution for finished rows,
    and the per-slot "next pending token" candidates (target argmax for
    greedy; fresh/residual samples with per-position subkeys for
    sampling — ``ops/sampling.filtered_logits`` defines the target
    distribution the acceptance test and the residual draw share).

A draft-MODEL drafter (a small GPT sharing the tokenizer) plugs in by
generating the k proposal tokens with its own cached decode and handing
them to the same verify rule; the accept math never cares where the
proposal came from (point-mass q covers any deterministic drafter;
greedy draft models are deterministic).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.ops.sampling import filtered_logits

NEG = -1e10

DRAFTERS = ("ngram",)

# backwards-scan cap of the host prompt-lookup drafter: bounds the
# per-step host cost on long non-repetitive rows (callers may also
# slice their history to this window + needle/draft slack — the scan
# never looks further back)
NGRAM_WINDOW = 2048


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculation knobs (``Generation.speculative`` in serving configs).

    ``draft_k``: proposal length per iteration — each verify forward
    processes k+1 tokens and commits between 1 and k+1 of them.
    ``drafter``: proposal source ("ngram" = self-drafting prompt lookup).
    ``ngram``: match length of the lookup needle (2 = bigram retrieval,
    the prompt-lookup default)."""

    draft_k: int = 4
    drafter: str = "ngram"
    ngram: int = 2

    def __post_init__(self):
        if self.draft_k < 1:
            raise ValueError(f"draft_k must be >= 1, got {self.draft_k}")
        if self.drafter not in DRAFTERS:
            raise ValueError(
                f"bad drafter {self.drafter!r}; valid: {', '.join(DRAFTERS)}"
            )
        if self.ngram < 1:
            raise ValueError(f"ngram must be >= 1, got {self.ngram}")


def spec_config_from(section) -> Optional[SpecConfig]:
    """Parse a ``Generation.speculative`` config section -> SpecConfig,
    or None when speculation is disabled (absent section / draft_k 0).
    Loud on unknown drafters or invalid k — a typo must not silently
    serve the non-speculative path while the operator benchmarks "spec".
    (``kv_dtype`` lives in the same section but routes to the cache
    allocation, not here — see ``ops/decode_attention.kv_cache_dtype``.)
    """
    section = dict(section or {})
    draft_k = int(section.get("draft_k", 0) or 0)
    if draft_k == 0:
        return None
    return SpecConfig(
        draft_k=draft_k,
        drafter=str(section.get("drafter", "ngram")),
        ngram=int(section.get("ngram", 2)),
    )


# ---------------------------------------------------------------------------
# Self-drafting n-gram / prompt-lookup proposal
# ---------------------------------------------------------------------------


def ngram_propose(
    ctx: jax.Array,
    known_len: jax.Array,
    pending: jax.Array,
    k: int,
    n: int = 2,
) -> jax.Array:
    """In-graph prompt-lookup drafter (runs inside the fused decode loop).

    ``ctx`` [b, L] holds each row's prompt + committed tokens in slots
    [0, known_len); ``pending`` [b] is the already-decided next token
    (not yet in ctx).  The proposal needle is the n-gram ending at the
    pending token; the draft is the k tokens that followed the needle's
    LAST earlier occurrence.  Rows with no match (or a match whose
    continuation runs past the known region) fall back to repeating the
    pending token — the cheapest proposal that still wins on the
    single-token loops random-weight greedy decode collapses into.
    Returns [b, k] int32; a bad proposal is merely rejected downstream,
    so this function has no correctness burden beyond shape."""
    if k < 1:
        raise ValueError(f"ngram_propose needs k >= 1, got {k}")
    b, L = ctx.shape
    known_len = jnp.asarray(known_len, jnp.int32)
    idx = jnp.arange(L, dtype=jnp.int32)
    match = jnp.ones((b, L), bool)
    for j in range(n):
        shift = n - 1 - j
        if shift == 0:
            need = pending
            shifted = ctx
        else:
            gpos = jnp.clip(known_len - shift, 0, L - 1)
            need = ctx[:, gpos]
            shifted = jnp.pad(ctx, ((0, 0), (shift, 0)))[:, :L]
        match = match & (shifted == need[:, None])
    # a candidate end-position p must fit the whole needle and leave at
    # least one predictable token: n-1 <= p <= known_len - 2
    match = match & (idx >= n - 1)[None, :] & (idx <= known_len - 2)[None, :]
    has = match.any(axis=1)
    last_p = (L - 1) - jnp.argmax(
        match[:, ::-1].astype(jnp.int32), axis=1
    ).astype(jnp.int32)
    offs = jnp.arange(1, k + 1, dtype=jnp.int32)
    gidx = jnp.clip(last_p[:, None] + offs[None, :], 0, L - 1)
    cand = jnp.take_along_axis(ctx, gidx, axis=1)
    valid = has[:, None] & (last_p[:, None] + offs[None, :] <= known_len - 1)
    return jnp.where(valid, cand, pending[:, None]).astype(jnp.int32)


def ngram_propose_host(seq, k: int, n: int = 2, window: int = NGRAM_WINDOW):
    """Host-side prompt-lookup drafter (the continuous-batching scheduler
    drafts from each row's python-side prompt+tokens history between
    steps — proposals are runtime DATA fed to the compiled spec step,
    never a compile key).

    ``seq``: list of ints (prompt + generated so far).  Proposes the k
    tokens following the last earlier occurrence of the trailing
    n-gram; falls back to repeating the last token.  The backwards scan
    is capped at the last ``window`` positions so the per-step host
    cost stays bounded on long non-repetitive rows (a miss would
    otherwise walk the whole history every step, serialized with the
    device dispatch); an incremental {n-gram -> last position} index
    per row is the upgrade path if profiles ever show this cap
    mattering."""
    if k < 1:
        raise ValueError(f"ngram_propose_host needs k >= 1, got {k}")
    seq = list(seq)
    if not seq:
        return [0] * k
    last = seq[-1]
    if len(seq) > n:
        needle = seq[-n:]
        lo = max(n - 2, len(seq) - 2 - int(window))
        for p in range(len(seq) - 2, lo, -1):
            if seq[p - n + 1 : p + 1] == needle:
                out = list(seq[p + 1 : p + 1 + k])
                while len(out) < k:
                    out.append(out[-1])
                return out
    return [last] * k


# ---------------------------------------------------------------------------
# Accept/reject verification over one chunk
# ---------------------------------------------------------------------------


class SpecVerify(NamedTuple):
    """Verification of one [b, k+1] chunk = [pending, draft_0..draft_{k-1}].

    Slot j of ``logits_all`` is the target distribution for the decode
    step AFTER chunk slot j; slots are verified under the SAME processor
    chain the baseline loop applies (min-length, repetition penalty,
    forced BOS/EOS) at the step each token would occupy.

    ``real`` [b, k+1]: slot j would be committed as a real (non-pad)
    token if the commit window reaches it — the chain breaks at the
    first draft mismatch/rejection and at the first EOS.
    ``accepted`` [b]: accepted draft count (length of the real chain
    past slot 0).
    ``eos_hit`` [b, k+1]: real slots carrying EOS (the row finishes
    there once the window covers it).
    ``ok`` [b, k]: per-draft accept test (greedy: matches the processed
    argmax; sampled: u < p(draft) on the filtered target distribution).
    ``pend`` [b, k+1]: per-slot NEXT-pending candidate if the window
    ends at slot j — greedy: the processed argmax (= the corrected token
    on a mismatch, the bonus token at slot k); sampled: a residual draw
    (draft masked, renormalized) where the draft was rejected, a fresh
    draw elsewhere — per-position subkeys.
    ``w`` [b, k+1]: the chunk with baseline pad substitution applied
    (finished / post-EOS / never-alive slots -> pad_token_id), i.e. what
    the baseline loop would have emitted at those steps."""

    real: jax.Array
    accepted: jax.Array
    eos_hit: jax.Array
    ok: jax.Array
    pend: jax.Array
    w: jax.Array


def _process(logits, counts, steps, gen, forced_steps):
    """THE baseline per-step logits-processor chain — delegates to the
    single-sourced ``generation.process_step_logits`` (lazy import:
    generation imports this module at top level), so the verify-time
    acceptance distributions can never drift from the distributions the
    decode loops actually sample from."""
    from paddlefleetx_tpu.models.gpt.generation import process_step_logits

    return process_step_logits(logits, steps, counts, forced_steps, gen)


def _cat_multi(key: jax.Array, logits: jax.Array) -> jax.Array:
    """Per-position categorical with per-position subkeys:
    [b, K, v] -> [b, K].  Rides ``sample_logits``'s multi-position form
    with every filter at its identity setting — verify already filtered
    these logits, so the draw must be a bare categorical (re-applying
    top-p on filtered logits would re-truncate the renormalized
    nucleus)."""
    from paddlefleetx_tpu.ops.sampling import sample_logits

    return sample_logits(key, logits)


def speculative_verify(
    key: Optional[jax.Array],
    logits_all: jax.Array,
    chunk: jax.Array,
    base_counts: Optional[jax.Array],
    alive0: jax.Array,
    step0: jax.Array,
    gen,
    forced_steps: Optional[jax.Array] = None,
) -> SpecVerify:
    """Verify one chunk against the target logits — THE accept/reject
    rule, shared by the contiguous and paged decode paths.

    ``logits_all`` [b, k+1, v] f32: slot j = target distribution for
    step ``step0 + 1 + j`` (conditioned on chunk[:, :j+1]).
    ``chunk`` [b, k+1]: slot 0 the already-decided pending token, slots
    1..k the drafts.  ``base_counts`` [b, v] or None (None when
    repetition_penalty == 1.0): tokens emitted through step step0 - 1.
    ``alive0`` [b]: unfinished at window start.  ``step0`` scalar or [b]
    (the paged path's rows sit at different steps).  ``forced_steps``
    [b] overrides the forced-EOS firing step (paged rows carry the
    coalesce-path bucketed run end); defaults to max_dec_len - 1.

    Greedy verification is exact-match against the processed argmax —
    committed tokens are bitwise the baseline loop's.  Sampled
    verification accepts draft d with probability p(d) under the
    FILTERED target distribution (``ops/sampling.filtered_logits``) and
    the residual candidates mask d post-filter — the Leviathan
    point-mass-q rule, exact for any temperature/top-k/top-p setting."""
    greedy = gen.decode_strategy == "greedy_search"
    if not greedy and key is None:
        raise ValueError("sampled speculative_verify needs a PRNG key")
    b, K, _ = logits_all.shape
    k = K - 1
    pad = gen.pad_token_id
    eos = gen.eos_token_id
    steps0 = jnp.broadcast_to(jnp.asarray(step0, jnp.int32), (b,))
    if forced_steps is None:
        forced_steps = jnp.full((b,), gen.max_dec_len - 1, jnp.int32)
    noeos = chunk != eos
    logits_all = logits_all.astype(jnp.float32)

    def slot_pend_ok(proc, slot_key):
        """proc [b, K, v] processed logits -> (pend [b, K], ok [b, k])."""
        if greedy:
            tgt = jnp.argmax(proc, axis=-1).astype(jnp.int32)
            return tgt, chunk[:, 1:] == tgt[:, :k]
        filt = filtered_logits(
            proc, temperature=gen.temperature, top_k=gen.top_k, top_p=gen.top_p
        )
        probs = jax.nn.softmax(filt, axis=-1)
        k_acc, k_fresh, k_resid = jax.random.split(slot_key, 3)
        p_d = jnp.take_along_axis(
            probs[:, :k], chunk[:, 1:, None], axis=-1
        )[..., 0]
        ok = jax.random.uniform(k_acc, (b, k)) < p_d
        fresh = _cat_multi(k_fresh, filt).astype(jnp.int32)
        resid_logits = filt[:, :k].at[
            jnp.arange(b)[:, None], jnp.arange(k)[None, :], chunk[:, 1:]
        ].set(NEG)
        resid = _cat_multi(k_resid, resid_logits).astype(jnp.int32)
        pend = jnp.concatenate(
            [jnp.where(ok, fresh[:, :k], resid), fresh[:, k:]], axis=1
        )
        return pend, ok

    if base_counts is None or gen.repetition_penalty == 1.0:
        # vectorized: no counts feedback, every slot processed at once
        steps = steps0[:, None] + 1 + jnp.arange(K, dtype=jnp.int32)[None, :]
        proc = _process(logits_all, None, steps, gen, forced_steps[:, None])
        pend, ok = slot_pend_ok(proc, key)
    else:
        # repetition penalty consumes the counts of every PRIOR chunk
        # token (with baseline pad substitution), which depend on the
        # accept chain so far — unroll the k+1 slots sequentially
        # (k is small and static)
        counts = base_counts
        real_j = alive0
        pends, oks = [], []
        slot_keys = (
            jax.random.split(key, K) if not greedy else [None] * K
        )
        for j in range(K):
            w_j = jnp.where(real_j, chunk[:, j], pad)
            counts = counts.at[jnp.arange(b), w_j].add(1)
            steps_j = steps0 + 1 + j
            proc_j = _process(
                logits_all[:, j], counts, steps_j, gen, forced_steps
            )
            # slot-wise spelling of slot_pend_ok (proc_j is [b, v])
            if greedy:
                tgt_j = jnp.argmax(proc_j, axis=-1).astype(jnp.int32)
                pend_j = tgt_j
                ok_j = (chunk[:, j + 1] == tgt_j) if j < k else None
            else:
                filt_j = filtered_logits(
                    proc_j, temperature=gen.temperature, top_k=gen.top_k,
                    top_p=gen.top_p,
                )
                probs_j = jax.nn.softmax(filt_j, axis=-1)
                k_acc, k_fresh, k_resid = jax.random.split(slot_keys[j], 3)
                fresh_j = jax.random.categorical(
                    k_fresh, filt_j, axis=-1
                ).astype(jnp.int32)
                if j < k:
                    d_j = chunk[:, j + 1]
                    p_d = jnp.take_along_axis(
                        probs_j, d_j[:, None], axis=-1
                    )[:, 0]
                    ok_j = jax.random.uniform(k_acc, (b,)) < p_d
                    resid_j = jax.random.categorical(
                        k_resid,
                        filt_j.at[jnp.arange(b), d_j].set(NEG),
                        axis=-1,
                    ).astype(jnp.int32)
                    pend_j = jnp.where(ok_j, fresh_j, resid_j)
                else:
                    ok_j = None
                    pend_j = fresh_j
            pends.append(pend_j)
            if ok_j is not None:
                oks.append(ok_j)
                real_j = real_j & ok_j & noeos[:, j]
        pend = jnp.stack(pends, axis=1)
        ok = jnp.stack(oks, axis=1)

    cond = ok & noeos[:, :k]
    chain = jnp.cumprod(cond.astype(jnp.int32), axis=1).astype(bool)
    real = (
        jnp.concatenate([jnp.ones((b, 1), bool), chain], axis=1)
        & alive0[:, None]
    )
    accepted = chain.sum(axis=1).astype(jnp.int32)
    eos_hit = real & ~noeos
    w = jnp.where(real, chunk, pad).astype(jnp.int32)
    return SpecVerify(
        real=real, accepted=accepted, eos_hit=eos_hit, ok=ok, pend=pend, w=w
    )
