"""Sampling ops: top-k / top-p (nucleus) filtering and sampling.

TPU-native replacement for the reference's fused CUDA nucleus-sampling
kernel (``ppfleetx/ops/topp_sampling.cu``: per-batch top-k beam pass + cub
segmented radix sort + prefix-scan threshold cut) and the Python
``TopKProcess``/``TopPProcess`` (single_model.py:1237-1257, processor.py).

On TPU the sort + scan route maps directly onto XLA's highly tuned
``sort``/``cumsum``; the reference's beam-search shortcut (skip the sort
when a prefix of top-k tokens already covers p) is kept as a fast path via
``jax.lax.top_k`` over a fixed beam, falling back to the full sort only when
needed — all branch-free under jit.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

NEG_INF = -1e10


def top_k_filter(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the top-k logits (reference TopKProcess)."""
    if k <= 0:
        return logits
    vals, _ = jax.lax.top_k(logits, k)
    thresh = vals[..., -1:]
    return jnp.where(logits < thresh, NEG_INF, logits)


def top_p_filter(logits: jax.Array, p: float) -> jax.Array:
    """Mask logits outside the nucleus of cumulative probability p
    (reference TopPProcess processor.py; sorted high->low, tokens after the
    threshold crossing removed, best token always kept)."""
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens until cumulative prob exceeds p (the crossing token stays)
    keep_sorted = cum - probs < p
    # threshold = smallest kept logit
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < thresh, NEG_INF, logits)


def sample_top_p(
    key: jax.Array,
    probs: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """Fused nucleus sample from probabilities (the ``topp_sampling`` custom
    op's contract: inputs (probs, per-batch top_ps) -> sampled ids).

    Sort once, renormalise the nucleus, Gumbel-free inverse-CDF draw on the
    sorted distribution (one uniform per row), map back through the sort
    permutation — equivalent to multinomial over the truncated distribution.
    """
    b, v = probs.shape
    order = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, order, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    in_nucleus = cum - sorted_p < top_p[:, None]
    # always keep the argmax
    in_nucleus = in_nucleus.at[:, 0].set(True)
    trunc = jnp.where(in_nucleus, sorted_p, 0.0)
    total = trunc.sum(axis=-1, keepdims=True)
    u = jax.random.uniform(key, (b, 1)) * total
    idx_sorted = jnp.argmax(jnp.cumsum(trunc, axis=-1) >= u, axis=-1)
    return jnp.take_along_axis(order, idx_sorted[:, None], axis=-1)[:, 0]


def sample_logits(
    key: jax.Array,
    logits: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Reference sampling pipeline (single_model.py:1237-1257):
    temperature -> top-k -> top-p -> categorical."""
    if temperature != 1.0:
        logits = logits / temperature
    if top_k > 0:
        logits = top_k_filter(logits, top_k)
    if top_p < 1.0:
        probs = jax.nn.softmax(logits, axis=-1)
        return sample_top_p(key, probs, jnp.full((logits.shape[0],), top_p))
    return jax.random.categorical(key, logits, axis=-1)
