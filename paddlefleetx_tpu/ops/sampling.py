"""Sampling ops: top-k / top-p (nucleus) filtering and sampling.

TPU-native replacement for the reference's fused CUDA nucleus-sampling
kernel (``ppfleetx/ops/topp_sampling.cu``: per-batch top-k beam pass + cub
segmented radix sort + prefix-scan threshold cut) and the Python
``TopKProcess``/``TopPProcess`` (single_model.py:1237-1257, processor.py).

On TPU the full sort + scan route maps directly onto XLA's highly tuned
``sort``/``cumsum``; the reference kernel's beam shortcut (skip the sort
when a prefix of top-k tokens already covers p) is the DEFAULT fast path:
``lax.top_k`` over a fixed candidate count (64, the CUDA kernel's max
beam), exact whenever every row's nucleus fits the candidates, with a
``lax.cond``-guarded fallback to the full sort when one overflows — see
:func:`sample_top_p_topk`.  PFX_TOPP_K overrides the candidate count
(0 disables the fast path); invalid values fail loudly at trace time.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

NEG_INF = -1e10


def top_k_filter(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the top-k logits (reference TopKProcess)."""
    if k <= 0:
        return logits
    vals, _ = jax.lax.top_k(logits, k)
    thresh = vals[..., -1:]
    return jnp.where(logits < thresh, NEG_INF, logits)


def top_p_filter(logits: jax.Array, p: float) -> jax.Array:
    """Mask logits outside the nucleus of cumulative probability p
    (reference TopPProcess processor.py; sorted high->low, tokens after the
    threshold crossing removed, best token always kept)."""
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens until cumulative prob exceeds p (the crossing token stays)
    keep_sorted = cum - probs < p
    # threshold = smallest kept logit
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < thresh, NEG_INF, logits)


def sample_top_p(
    key: jax.Array,
    probs: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """Fused nucleus sample from probabilities (the ``topp_sampling`` custom
    op's contract: inputs (probs, per-batch top_ps) -> sampled ids).

    Sort once, renormalise the nucleus, Gumbel-free inverse-CDF draw on the
    sorted distribution (one uniform per row), map back through the sort
    permutation — equivalent to multinomial over the truncated distribution.
    """
    b, v = probs.shape
    order = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, order, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    in_nucleus = cum - sorted_p < top_p[:, None]
    # always keep the argmax
    in_nucleus = in_nucleus.at[:, 0].set(True)
    trunc = jnp.where(in_nucleus, sorted_p, 0.0)
    total = trunc.sum(axis=-1, keepdims=True)
    u = jax.random.uniform(key, (b, 1)) * total
    idx_sorted = jnp.argmax(jnp.cumsum(trunc, axis=-1) >= u, axis=-1)
    return jnp.take_along_axis(order, idx_sorted[:, None], axis=-1)[:, 0]


def _parse_prefilter_env() -> int:
    env = os.environ.get("PFX_TOPP_K") or ""
    if not env:
        return -1
    try:
        val = int(env)
    except ValueError:
        raise ValueError(
            f"PFX_TOPP_K={env!r} is not an integer; pass a positive "
            "candidate count (e.g. 64), 0 to disable the fast path, or "
            "unset it"
        ) from None
    if val < 0:
        raise ValueError(f"PFX_TOPP_K={val} must be >= 0")
    return val


def sample_top_p_topk(
    key: jax.Array,
    probs: jax.Array,
    top_p: jax.Array,
    k: int = 64,
) -> jax.Array:
    """Nucleus sample with a top-k prefilter (the ``topp_sampling.cu``
    contract: a fixed top-k beam pass first, the expensive full sort only
    when the beam does not cover p).

    ``lax.top_k(probs, k)`` returns the k best already sorted descending,
    so when the whole batch's top-k mass covers its ``top_p`` the nucleus
    lives entirely inside the k candidates: truncate/renormalize those,
    inverse-CDF draw, and map the drawn index back through the top-k
    indices — EXACT against :func:`sample_top_p` (same nucleus, same
    uniform draw, same prefix sums) while sorting k instead of the 50k
    vocab.  Rows are batched under jit, so the guard is all-rows-covered;
    any overflow row (``cum_k < p``) routes the WHOLE batch to the full
    sort via ``lax.cond`` (one runtime branch, both traced)."""
    b, v = probs.shape
    k = min(int(k), v)
    top_probs, top_idx = jax.lax.top_k(probs, k)  # sorted descending
    cum = jnp.cumsum(top_probs, axis=-1)

    def fast(_):
        in_nucleus = cum - top_probs < top_p[:, None]
        in_nucleus = in_nucleus.at[:, 0].set(True)  # always keep argmax
        trunc = jnp.where(in_nucleus, top_probs, 0.0)
        total = trunc.sum(axis=-1, keepdims=True)
        u = jax.random.uniform(key, (b, 1)) * total
        sel = jnp.argmax(jnp.cumsum(trunc, axis=-1) >= u, axis=-1)
        return jnp.take_along_axis(top_idx, sel[:, None], axis=-1)[:, 0]

    def slow(_):
        return sample_top_p(key, probs, top_p)

    covered = jnp.all(cum[:, -1] >= top_p)
    return jax.lax.cond(covered, fast, slow, operand=None)


def filtered_logits(
    logits: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """The sampling pipeline's FILTER stages only (temperature -> top-k ->
    top-p), returning the filtered logits instead of a draw.

    The speculative-decoding verify path (``ops/speculative.py``) needs
    the target DISTRIBUTION, not a sample: acceptance tests draft tokens
    against ``softmax(filtered_logits)`` and the Leviathan residual rule
    re-samples from the same filtered distribution with the rejected
    draft masked — both must see exactly the distribution the baseline
    sampler draws from, which is what these filters define
    (:func:`sample_top_p_topk` is distribution-identical to the full
    sort by construction)."""
    if temperature != 1.0:
        logits = logits / temperature
    if top_k > 0:
        logits = top_k_filter(logits, top_k)
    if top_p < 1.0:
        logits = top_p_filter(logits, top_p)
    return logits


def sample_logits(
    key: jax.Array,
    logits: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    top_p_prefilter_k: int = 64,
) -> jax.Array:
    """Reference sampling pipeline (single_model.py:1237-1257):
    temperature -> top-k -> top-p -> categorical.

    The top-p stage goes through the top-k-prefilter fast path
    (:func:`sample_top_p_topk`, ``top_p_prefilter_k`` candidates —
    PFX_TOPP_K overrides, 0 disables) so the per-step cost is a top-k
    over the vocab instead of a full argsort+cumsum; the full sort runs
    only when some row's nucleus overflows the prefilter.

    ``logits`` may be [b, vocab] (one position -> ids [b], the original
    contract, unchanged) or [b, k, vocab] (k positions -> ids [b, k]):
    the multi-position form splits ``key`` into k per-position subkeys
    and samples each position independently.  The speculative verify
    step (``ops/speculative.py``) draws its fresh/residual candidates
    through this form with the filters at identity settings — it
    filters ONCE itself via :func:`filtered_logits`, so passing
    non-default filter args there would double-filter."""
    if logits.ndim == 3:
        b, k, _ = logits.shape
        subkeys = jax.random.split(key, k)

        def one(pos_key, pos_logits):  # pos_logits [b, vocab]
            return sample_logits(
                pos_key, pos_logits, temperature=temperature, top_k=top_k,
                top_p=top_p, top_p_prefilter_k=top_p_prefilter_k,
            )

        # vmap over the position axis: per-position subkeys, independent
        # draws, [k, b] -> [b, k]
        return jax.vmap(one, in_axes=(0, 1), out_axes=1)(
            subkeys, logits
        )
    if temperature != 1.0:
        logits = logits / temperature
    if top_k > 0:
        logits = top_k_filter(logits, top_k)
    if top_p < 1.0:
        probs = jax.nn.softmax(logits, axis=-1)
        top_ps = jnp.full((logits.shape[0],), top_p)
        env_k = _parse_prefilter_env()
        k = top_p_prefilter_k if env_k < 0 else env_k
        if k <= 0:
            return sample_top_p(key, probs, top_ps)
        return sample_top_p_topk(key, probs, top_ps, k=k)
    return jax.random.categorical(key, logits, axis=-1)
