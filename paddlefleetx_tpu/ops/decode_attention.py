"""Flash-decode attention: length-aware blocked KV-cache attention.

TPU-native replacement for the decode step's attend-over-everything
``xla_attention(q, k_cache, v_cache, bias=[.,1,t,max_len])``: the cache is
preallocated at ``prompt_len + max_dec_len``, but at step ``pos`` only the
first ``pos + t`` slots hold real keys.  The dense path pays FLOPs and HBM
reads for the whole buffer every token; this op visits only the cache
blocks ``< ceil((pos + t) / block)`` and folds the causal + left-pad
(``kv_valid_from``) masks into per-block masking, so per-token cost scales
with the tokens generated so far instead of the preallocated maximum.

Online-softmax accumulation across blocks (same residual trick as the
flash forward in ``ops/flash_attention.py``): running max ``m``, running
denominator ``l``, fp32 accumulator rescaled by ``exp(m - m_new)`` per
block — bitwise layout-independent of how many blocks are visited.

Two implementations behind one entry point:

  - ``pallas``: one grid program per (batch, head); the kernel fori-loops
    over visited blocks with a runtime trip count read from a scalar
    input.  Runs on TPU; interpret mode elsewhere (tests force it).
    KNOWN LIMIT (contiguous spelling only): the BlockSpec streams the
    full [max_len, d] cache row into VMEM per program, so the length
    scaling applies to FLOPs but NOT to the HBM reads; note the partial
    last block must keep the in-kernel dslice clamp, since a grid-blocked
    tail would matmul against out-of-bounds padding (0 * NaN poisons the
    accumulator even under the mask).  The paged spelling
    (:func:`paged_decode_attention`, used by the continuous-batching
    engine) retires this: its scalar-prefetch-clamped index map DMAs
    exactly one pool block per grid step, so HBM reads scale with each
    row's real length.
  - ``lax``: the same blocked loop as ``lax.fori_loop`` +
    ``dynamic_slice`` — CPU fallback and the path used under GSPMD
    sharding (a pallas_call inside a partitioned jit would need
    shard_map; XLA partitions the lax loop for free).

Cache layout is [batch, heads, max_len, head_dim] (heads-major) so the
Pallas block tiling keeps (seq, head_dim) as the minor dims — see
``models/gpt/generation.KVCache``.

Env knobs (PFX_FLASH_* loud-parse convention — an invalid value raises
instead of silently mislabeling a chip sweep):

  PFX_DECODE_BLOCK  kv block size (default 256; positive multiple of 8)
  PFX_DECODE_ATTN   "blocked" (default) | "dense" — generation-layer
                    dispatch, read at trace time; "dense" restores the
                    attend-over-everything path for A/B benching
  PFX_KV_DTYPE      "bf16" (default: the cache stays in the model dtype)
                    | "int8" — int8 KV-cache quantization.  Quantize
                    happens ON WRITE (generation-layer scatter paths,
                    symmetric per-(slot, head) amax/127 scales stored
                    alongside the cache/arena) and dequantize IN-KERNEL
                    in every spelling here: the scores absorb the
                    per-key scale (``s *= k_scale[col]``) and the
                    probabilities absorb the per-value scale
                    (``p *= v_scale[col]``) — no dequantized cache is
                    ever materialized, so the decode step's HBM reads
                    HALVE vs bf16 (which is exactly what the
                    flash/paged kernels made the bottleneck)

Inference-only: the blocked loop has a data-dependent trip count (a
``while_loop`` under the hood), so it is not reverse-differentiable.
Training attention stays on ``ops/attention.py`` / ``ops/flash_attention``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

_DEFAULT_BLOCK = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _parse_int_env(name: str) -> int:
    env = os.environ.get(name) or "0"
    try:
        return int(env)
    except ValueError:
        raise ValueError(
            f"{name}={env!r} is not an integer; pass a positive multiple "
            f"of 8 (e.g. 256) or unset it"
        ) from None


def decode_block(max_len: int, block: int = 0) -> int:
    """Resolve the kv block size: explicit ``block`` arg, else
    PFX_DECODE_BLOCK, else {_DEFAULT_BLOCK}; clamped to ``max_len``.

    Unlike the flash block, the decode block need NOT divide the cache
    length — the last block is handled by a clamped start + dedup mask —
    but it must be a positive multiple of 8 (TPU sublane tiling), and an
    invalid override fails loudly in both spellings.  When the CLAMP
    breaks alignment (a cache shorter than the requested block and not
    itself a multiple of 8, e.g. max_len 20) the block rounds DOWN to the
    nearest multiple of 8 so the Pallas tiling invariant survives; only a
    cache shorter than 8 slots yields a sub-8 block, and
    :func:`decode_attention` routes that degenerate case to the lax
    spelling (Mosaic could not tile it)."""
    force = int(block) or _parse_int_env("PFX_DECODE_BLOCK")
    if force:
        if force < 0 or force % 8:
            raise ValueError(
                f"decode block {force} must be a positive multiple of 8 "
                "(block arg / PFX_DECODE_BLOCK)"
            )
    else:
        force = _DEFAULT_BLOCK
    clamped = min(force, max_len)
    if clamped % 8 and clamped > 8:
        clamped -= clamped % 8
    return clamped


KV_QMAX = 127.0


def kv_cache_dtype(override: str = "") -> str:
    """Resolve the KV-cache storage dtype: explicit ``override`` (the
    ``Generation.speculative.kv_dtype`` config knob), else PFX_KV_DTYPE,
    else "bf16".  "bf16" means NATIVE — the cache stays in the model
    dtype (an f32 model keeps f32; the name follows the knob contract);
    "int8" enables quantize-on-write + dequantize-in-kernel.  Loud
    parse: a typo must not silently mislabel a chip A/B as quantized."""
    raw = (override or os.environ.get("PFX_KV_DTYPE") or "bf16")
    raw = str(raw).strip().lower()
    if raw not in ("bf16", "int8"):
        raise ValueError(
            f"PFX_KV_DTYPE={raw!r}; valid: bf16 (native), int8"
        )
    return raw


def quantize_kv(x: jax.Array):
    """Symmetric per-vector int8 quantization of a K/V chunk.

    ``x`` [..., d] -> (int8 values [..., d], f32 scales [...]): one
    amax/127 scale per (slot, head) [d]-vector — finer than a per-block
    scale, so writing one token into a half-full block never forces a
    requantization of its neighbors (the scatter paths write exactly the
    new slots).  Deterministic round-to-nearest: parity suites need
    bit-stable runs.  The scale floor keeps all-zero vectors (fresh
    arena blocks) finite; their dequantized values stay exactly 0."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scl = jnp.maximum(amax / KV_QMAX, 1e-8)
    q = jnp.clip(
        jnp.round(xf / scl[..., None]), -KV_QMAX, KV_QMAX
    ).astype(jnp.int8)
    return q, scl


def decode_attn_mode() -> str:
    """PFX_DECODE_ATTN dispatch read by the generation layer at trace
    time: "blocked" (this op) or "dense" (the legacy attend-over-the-
    whole-cache path, kept for A/B rows)."""
    mode = os.environ.get("PFX_DECODE_ATTN") or "blocked"
    if mode not in ("blocked", "dense"):
        raise ValueError(
            f"PFX_DECODE_ATTN={mode!r}; valid: blocked, dense"
        )
    return mode


def blocks_visited(limit, block: int, max_len: int):
    """Number of kv blocks the kernel visits for keys [0, limit).

    ``limit`` may be traced (pos + t inside the decode loop); the result
    bounds the fori_loop trip count.  Exposed for tests asserting the
    decode step no longer touches cache blocks beyond ``pos + t``."""
    total = -(-max_len // block)
    return jnp.minimum((limit + block - 1) // block, total)


# ---------------------------------------------------------------------------
# lax fallback (CPU + GSPMD path)
# ---------------------------------------------------------------------------


def _decode_lax(q_t, k_cache, v_cache, limit, valid_from, block, scale,
                k_scale=None, v_scale=None):
    """q_t [b, n, t, d]; caches [b, n, L, d]; limit = pos + t (traced ok).

    Returns [b, n, t, d] fp32-accumulated attention over keys [vf, limit).
    With int8 caches, ``k_scale``/``v_scale`` [b, n, L] dequantize
    in-loop: per-key scales fold into the score columns and per-value
    scales into the probability columns — the cache itself streams as
    int8."""
    b, n, t, d = q_t.shape
    max_len = k_cache.shape[2]
    quant = k_scale is not None
    q_pos = limit - t + jnp.arange(t)  # global position of each query row

    m0 = jnp.full((b, n, t), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, t), jnp.float32)
    acc0 = jnp.zeros((b, n, t, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        # the last block would overrun the cache; clamp the start and mask
        # the overlap (col < j*block was handled by the previous block)
        start = jnp.maximum(jnp.minimum(j * block, max_len - block), 0)
        k = jax.lax.dynamic_slice_in_dim(k_cache, start, block, axis=2)
        v = jax.lax.dynamic_slice_in_dim(v_cache, start, block, axis=2)
        if quant:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
            ksl = jax.lax.dynamic_slice_in_dim(k_scale, start, block, axis=2)
            vsl = jax.lax.dynamic_slice_in_dim(v_scale, start, block, axis=2)
        s = scale * jnp.einsum(
            "bntd,bnkd->bntk", q_t, k, preferred_element_type=jnp.float32
        )  # [b, n, t, block]
        if quant:
            s = s * ksl[:, :, None, :]
        col = start + jnp.arange(block)  # [block]
        mask = (col[None, :] <= q_pos[:, None]) & (col[None, :] >= j * block)
        mask = mask[None, None]  # [1, 1, t, block]
        if valid_from is not None:
            mask = mask & (
                col[None, None, None, :] >= valid_from[:, None, None, None]
            )
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        pv = p * vsl[:, :, None, :] if quant else p.astype(v.dtype)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bntk,bnkd->bntd", pv, v,
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    nvisit = blocks_visited(limit, block, max_len)
    m, l, acc = jax.lax.fori_loop(0, nvisit, body, (m0, l0, acc0))
    # fully-masked query rows (left-pad positions) get 0, not NaN: they
    # feed nothing downstream (only the last, always-real row is sampled)
    return acc / jnp.maximum(l, 1e-30)[..., None]


# ---------------------------------------------------------------------------
# Pallas kernel (TPU; interpret mode in tests)
# ---------------------------------------------------------------------------


def _decode_kernel(
    q_ref, k_ref, v_ref, limit_ref, vf_ref, o_ref, *, scale, block, max_len, t
):
    q = q_ref[0, 0]  # [t, d], native dtype; dots accumulate fp32
    d = q.shape[-1]
    limit = limit_ref[0, 0]
    vf = vf_ref[0, 0, 0]
    row_pos = (limit - t) + jax.lax.broadcasted_iota(jnp.int32, (t, block), 0)

    m0 = jnp.full((t,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((t,), jnp.float32)
    acc0 = jnp.zeros((t, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        start = jnp.maximum(jnp.minimum(j * block, max_len - block), 0)
        k = k_ref[0, 0, pl.dslice(start, block), :]
        v = v_ref[0, 0, pl.dslice(start, block), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [t, block]
        col = start + jax.lax.broadcasted_iota(jnp.int32, (t, block), 1)
        mask = (col <= row_pos) & (col >= j * block) & (col >= vf)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    nvisit = blocks_visited(limit, block, max_len)
    m, l, acc = jax.lax.fori_loop(0, nvisit, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _decode_kernel_q8(
    q_ref, k_ref, v_ref, ks_ref, vs_ref, limit_ref, vf_ref, o_ref,
    *, scale, block, max_len, t
):
    """int8 spelling of :func:`_decode_kernel`: the kv refs stream the
    cache as int8 and the per-slot scales ride two [max_len] f32 rows —
    scores absorb the key scale per COLUMN, probabilities absorb the
    value scale per column, so the dequantized cache never exists and
    the block's HBM bytes are half the bf16 kernel's."""
    q = q_ref[0, 0].astype(jnp.float32)  # [t, d]
    d = q.shape[-1]
    limit = limit_ref[0, 0]
    vf = vf_ref[0, 0, 0]
    row_pos = (limit - t) + jax.lax.broadcasted_iota(jnp.int32, (t, block), 0)

    m0 = jnp.full((t,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((t,), jnp.float32)
    acc0 = jnp.zeros((t, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        start = jnp.maximum(jnp.minimum(j * block, max_len - block), 0)
        k = k_ref[0, 0, pl.dslice(start, block), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(start, block), :].astype(jnp.float32)
        ksl = ks_ref[0, 0, pl.dslice(start, block)]
        vsl = vs_ref[0, 0, pl.dslice(start, block)]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * ksl[None, :]  # [t, block]
        col = start + jax.lax.broadcasted_iota(jnp.int32, (t, block), 1)
        mask = (col <= row_pos) & (col >= j * block) & (col >= vf)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p * vsl[None, :], v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    nvisit = blocks_visited(limit, block, max_len)
    m, l, acc = jax.lax.fori_loop(0, nvisit, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _decode_pallas(q_t, k_cache, v_cache, limit, valid_from, block, scale,
                   k_scale=None, v_scale=None):
    b, n, t, d = q_t.shape
    max_len = k_cache.shape[2]
    limit_arr = jnp.full((1, 1), limit, jnp.int32)
    vf_arr = (
        jnp.zeros((b, 1, 1), jnp.int32)
        if valid_from is None
        else valid_from.astype(jnp.int32).reshape(b, 1, 1)
    )
    kv_spec = pl.BlockSpec((1, 1, max_len, d), lambda i, j: (i, j, 0, 0))
    scl_spec = pl.BlockSpec((1, 1, max_len), lambda i, j: (i, j, 0))
    if k_scale is not None:
        kernel = functools.partial(
            _decode_kernel_q8, scale=scale, block=block, max_len=max_len, t=t
        )
        return pl.pallas_call(
            kernel,
            grid=(b, n),
            in_specs=[
                pl.BlockSpec((1, 1, t, d), lambda i, j: (i, j, 0, 0)),
                kv_spec, kv_spec, scl_spec, scl_spec,
                pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
                pl.BlockSpec((1, 1, 1), lambda i, j: (i, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, t, d), lambda i, j: (i, j, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((b, n, t, d), jnp.float32),
            interpret=_interpret(),
        )(q_t, k_cache, v_cache, k_scale, v_scale, limit_arr, vf_arr)
    kernel = functools.partial(
        _decode_kernel, scale=scale, block=block, max_len=max_len, t=t
    )
    out = pl.pallas_call(
        kernel,
        grid=(b, n),
        in_specs=[
            pl.BlockSpec((1, 1, t, d), lambda i, j: (i, j, 0, 0)),
            kv_spec, kv_spec,
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, t, d), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n, t, d), jnp.float32),
        interpret=_interpret(),
    )(q_t, k_cache, v_cache, limit_arr, vf_arr)
    return out


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------


def decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    *,
    kv_valid_from: Optional[jax.Array] = None,
    block: int = 0,
    impl: str = "auto",
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Blocked KV-cache attention over keys [0, pos + t).

    q [b, t, n, d] at global positions [pos, pos+t); k_cache/v_cache
    [b, n, max_len, d] with real keys through pos+t (the current chunk
    already written).  ``kv_valid_from`` [b] masks keys before a row's
    first real token (left-padded serving buckets).  Returns [b, t, n, d].

    With an int8 cache (PFX_KV_DTYPE=int8), ``k_scale``/``v_scale``
    [b, n, max_len] carry the per-(slot, head) quantization scales and
    both spellings dequantize IN-KERNEL (scores absorb the key scale,
    probabilities the value scale) — pass both or neither.

    ``impl``: "auto" (pallas on TPU, lax elsewhere) | "pallas" | "lax".
    """
    if impl not in ("auto", "pallas", "lax"):
        raise ValueError(f"decode_attention impl {impl!r}; valid: auto, pallas, lax")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    b, t, n, d = q.shape
    max_len = k_cache.shape[2]
    bs = decode_block(max_len, block)
    scale = float(1.0 / (d**0.5))
    limit = pos + t
    q_t = q.transpose(0, 2, 1, 3)  # [b, n, t, d]
    # a sub-8 block only happens for a degenerate cache shorter than 8
    # slots (decode_block rounds down otherwise): Mosaic cannot sublane-
    # tile it, so route to the lax spelling
    use_pallas = impl == "pallas" or (impl == "auto" and not _interpret())
    if use_pallas and bs % 8 == 0:
        out = _decode_pallas(q_t, k_cache, v_cache, limit, kv_valid_from,
                             bs, scale, k_scale, v_scale)
    else:
        out = _decode_lax(q_t, k_cache, v_cache, limit, kv_valid_from,
                          bs, scale, k_scale, v_scale)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# ---------------------------------------------------------------------------
# Paged (block-table-indexed) decode attention — the continuous-batching
# serving engine's kernel (core/paged_cache.py owns the pool layout)
# ---------------------------------------------------------------------------


def _paged_lax(q_t, k_pool, v_pool, tables, positions, scale,
               k_scale=None, v_scale=None):
    """q_t [b, n, t, d]; pools [nb, n, bs, d]; tables [b, M] block ids;
    positions [b] = global slot of each row's FIRST query token (query
    qi sits at slot positions[i] + qi — t > 1 is the speculative
    multi-token verify chunk, causal within the chunk).

    Blocked online-softmax over each row's OWN block list: block j of row
    i holds key slots [j*bs, (j+1)*bs) of that row's logical cache, stored
    at pool block ``tables[i, j]``.  Query qi of row i attends over
    [0, positions[i] + qi + 1) — per-row, per-query limits, unlike
    :func:`_decode_lax`'s shared ``limit``.  Table entries beyond a row's
    limit (null-block padding) are masked by the causal bound, so their
    garbage never reaches the accumulator.  With int8 pools,
    ``k_scale``/``v_scale`` [nb, n, bs] dequantize in-loop (scores absorb
    the key scale, probabilities the value scale).
    """
    b, n, t, d = q_t.shape
    bs = k_pool.shape[2]
    quant = k_scale is not None

    m0 = jnp.full((b, n, t), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, t), jnp.float32)
    acc0 = jnp.zeros((b, n, t, d), jnp.float32)

    # each row's last needed block (its LAST query's slot): the fori
    # bound below is the BATCH max, so shorter rows clamp their gather to
    # their own last block (re-read, fully masked) — same per-row clamp
    # as the pallas index_map, keeping both spellings honestly bounded by
    # each row's real length
    last_blk = jnp.maximum(positions + t - 1, 0) // bs
    q_off = jnp.arange(t)  # query qi's slot offset within the chunk

    def body(j, carry):
        m, l, acc = carry
        jidx = jnp.minimum(j, last_blk)  # [b]
        blk = jnp.take_along_axis(tables, jidx[:, None], axis=1)[:, 0]  # [b]
        k = jnp.take(k_pool, blk, axis=0)  # [b, n, bs, d] gather
        v = jnp.take(v_pool, blk, axis=0)
        if quant:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
            ksl = jnp.take(k_scale, blk, axis=0)  # [b, n, bs]
            vsl = jnp.take(v_scale, blk, axis=0)
        s = scale * jnp.einsum(
            "bntd,bnkd->bntk", q_t, k, preferred_element_type=jnp.float32
        )  # [b, n, t, bs]
        if quant:
            s = s * ksl[:, :, None, :]
        col = j * bs + jnp.arange(bs)  # logical slot of each key column
        qpos = positions[:, None] + q_off[None, :]  # [b, t]
        mask = col[None, None, None, :] <= qpos[:, None, :, None]
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        pv = p * vsl[:, :, None, :] if quant else p.astype(v.dtype)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bntk,bnkd->bntd", pv, v,
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    nvisit = jnp.minimum(
        (jnp.max(positions) + t + bs - 1) // bs, tables.shape[1]
    )
    m, l, acc = jax.lax.fori_loop(0, nvisit, body, (m0, l0, acc0))
    # rows whose table is all-null (inactive slots, positions < 0 would
    # not occur — positions >= 0 always covers block 0) still get a
    # finite result; fully-masked rows divide by the epsilon floor
    return acc / jnp.maximum(l, 1e-30)[..., None]


def _paged_kernel(
    tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale, bs, t, ks_ref=None, vs_ref=None
):
    """One (batch, head, block) grid step.  The kv BlockSpec's index_map
    already DMA'd pool block ``tables[i, min(j, last_needed(i))]`` — the
    scalar-prefetch CLAMP: grid steps past a row's limit re-address the
    previously fetched block (no new DMA) and are fully masked here, so
    HBM traffic scales with the tokens the row actually holds, not with
    the padded table width.  ``t`` > 1 is the speculative verify chunk:
    query qi sits at slot pos + qi, causal within the chunk.  With int8
    pools the optional scale refs dequantize in-kernel: the scores
    absorb the per-key scale column-wise and the probabilities the
    per-value scale — the dequantized block never materializes."""
    i = pl.program_id(0)
    j = pl.program_id(2)
    nblk = pl.num_programs(2)
    quant = ks_ref is not None

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0, 0]  # [t, d]
    k = k_ref[0, 0]  # [bs, d] (one pool block for this head)
    v = v_ref[0, 0]
    if quant:
        q = q.astype(jnp.float32)
        k = k.astype(jnp.float32)
        v = v.astype(jnp.float32)
    pos = pos_ref[i]
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [t, bs]
    if quant:
        s = s * ks_ref[0, 0][None, :]
    col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (t, bs), 1)
    # query qi's own causal bound: slot pos + qi
    qrow = pos + jax.lax.broadcasted_iota(jnp.int32, (t, bs), 0)
    mask = col <= qrow
    s = jnp.where(mask, s, NEG_INF)

    m_prev = m_ref[:, :1]  # [t, 1] (lane-replicated store)
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_ref[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
    pv = p * vs_ref[0, 0][None, :] if quant else p.astype(v.dtype)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        pv, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nblk - 1)
    def _done():
        o_ref[0, 0] = (
            acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def _paged_pallas(q_t, k_pool, v_pool, tables, positions, scale,
                  k_scale=None, v_scale=None):
    from jax.experimental.pallas import tpu as pltpu

    b, n, t, d = q_t.shape
    bs = k_pool.shape[2]
    M = tables.shape[1]
    tables = tables.astype(jnp.int32)
    positions = positions.astype(jnp.int32)
    quant = k_scale is not None

    def kv_index(i, j, k, tables_ref, pos_ref):
        # scalar-prefetch clamp: past a row's last needed block, re-address
        # the block we already fetched — Pallas skips the DMA when the
        # index is unchanged between consecutive grid steps.  The last
        # needed block covers the chunk's LAST query slot (pos + t - 1).
        last = jnp.maximum(pos_ref[i] + (t - 1), 0) // bs
        return tables_ref[i, jnp.minimum(k, last)], j, 0, 0

    def scl_index(i, j, k, tables_ref, pos_ref):
        # same clamped pool-block address, scale tile [1, 1, bs]
        last = jnp.maximum(pos_ref[i] + (t - 1), 0) // bs
        return tables_ref[i, jnp.minimum(k, last)], j, 0

    in_specs = [
        pl.BlockSpec((1, 1, t, d), lambda i, j, k, *_: (i, j, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), kv_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
    ]
    operands = [q_t, k_pool, v_pool]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, bs), scl_index),
            pl.BlockSpec((1, 1, bs), scl_index),
        ]
        operands += [k_scale, v_scale]

        def kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                   o_ref, acc_ref, m_ref, l_ref):
            _paged_kernel(
                tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                acc_ref, m_ref, l_ref, scale=scale, bs=bs, t=t,
                ks_ref=ks_ref, vs_ref=vs_ref,
            )
    else:
        kernel = functools.partial(_paged_kernel, scale=scale, bs=bs, t=t)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, t, d), lambda i, j, k, *_: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t, d), jnp.float32),
            pltpu.VMEM((t, 128), jnp.float32),
            pltpu.VMEM((t, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, t, d), jnp.float32),
        interpret=_interpret(),
    )(tables, positions, *operands)


def paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    impl: str = "auto",
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Block-table-indexed decode attention for the paged KV cache.

    q [b, t, n, d]; pools [num_blocks, n, block, d] (one layer's arena —
    ``core/paged_cache.py``); ``block_tables`` [b, M] maps row i's logical
    block j to a pool block id; ``positions`` [b] is the slot of each
    row's FIRST query token (the chunk already written) — query qi of
    row i attends over its logical slots [0, positions[i] + qi + 1),
    causal within the chunk.  t = 1 is the plain decode step; t > 1 is
    the speculative multi-token verify chunk (k drafts + 1).  Rows are
    fully independent: each has its own length, so there is no shared
    ``limit`` and no ``kv_valid_from`` (paged rows are unpadded).
    Returns [b, t, n, d].

    With int8 pools (PFX_KV_DTYPE=int8), ``k_scale``/``v_scale``
    [num_blocks, n, block] carry the per-(slot, head) scales stored
    alongside the arena; both spellings dequantize in-kernel (the pallas
    spelling rides the same scalar-prefetch-clamped index map, so the
    scale tiles DMA with their block) — pass both or neither.

    ``impl``: "auto" (pallas on TPU, lax elsewhere) | "pallas" | "lax".
    The pallas spelling DMAs exactly one pool block per grid step with a
    scalar-prefetch-clamped index map — the HBM reads scale with each
    row's real length, retiring the known limit of `_decode_pallas`
    (which streams the whole cache row).  The lax spelling gathers via
    ``jnp.take`` (XLA partitions it freely under GSPMD).
    """
    if impl not in ("auto", "pallas", "lax"):
        raise ValueError(
            f"paged_decode_attention impl {impl!r}; valid: auto, pallas, lax"
        )
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    b, t, n, d = q.shape
    if t < 1:
        raise ValueError(f"paged_decode_attention needs t >= 1; got t={t}")
    bs = k_pool.shape[2]
    if impl == "pallas" and bs % 8:
        # an explicit pallas request must run pallas or fail LOUDLY — a
        # silent lax fallback would mislabel A/B evidence
        raise ValueError(
            f"paged block size {bs} is not a multiple of 8 (TPU sublane "
            "tiling); impl='pallas' cannot honor it — use impl='lax' or "
            "a multiple-of-8 PFX_KV_BLOCK"
        )
    scale = float(1.0 / (d**0.5))
    q_t = q.transpose(0, 2, 1, 3)  # [b, n, t, d]
    use_pallas = impl == "pallas" or (impl == "auto" and not _interpret())
    if use_pallas and bs % 8 == 0:
        out = _paged_pallas(q_t, k_pool, v_pool, block_tables, positions,
                            scale, k_scale, v_scale)
    else:
        out = _paged_lax(q_t, k_pool, v_pool, block_tables, positions,
                         scale, k_scale, v_scale)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def dense_cache_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    *,
    kv_valid_from: Optional[jax.Array] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """The legacy decode attention: attend over the ENTIRE preallocated
    cache with a materialized [., 1, t, max_len] additive bias (what
    ``_layer_with_cache`` did via ``xla_attention`` before the blocked
    kernel).  Kept verbatim-in-semantics for PFX_DECODE_ATTN=dense A/B
    benchmark rows; same [b, n, L, d] cache layout, no extra transposes,
    so a legacy row measures the old math, not a layout penalty.  An
    int8 cache is simply dequantized up front — this path exists for
    honest legacy A/B rows, not for HBM savings."""
    b, t, n, d = q.shape
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if k_scale is not None:
        # dequantize in f32 and cast the PRODUCT once: the blocked/paged
        # kernels apply scales in f32, and an A/B row comparing against
        # them must not carry extra bf16-rounded-scale error
        k_cache = (
            k_cache.astype(jnp.float32) * k_scale[..., None]
        ).astype(q.dtype)
        v_cache = (
            v_cache.astype(jnp.float32) * v_scale[..., None]
        ).astype(q.dtype)
    max_len = k_cache.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    q_pos = pos + jnp.arange(t)[:, None]
    k_pos = jnp.arange(max_len)[None, :]
    bias = jnp.where(k_pos <= q_pos, 0.0, -1e9)[None, None, :, :]  # [1,1,t,L]
    if kv_valid_from is not None:
        bias = bias + jnp.where(
            k_pos >= kv_valid_from[:, None], 0.0, -1e9
        )[:, None, None, :]
    scores = jnp.einsum(
        "btnd,bnkd->bntk", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    scores = scores + bias.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bntk,bnkd->bntd", probs, v_cache)
    return out.transpose(0, 2, 1, 3)
