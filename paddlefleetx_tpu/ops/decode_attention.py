"""Flash-decode attention: length-aware blocked KV-cache attention.

TPU-native replacement for the decode step's attend-over-everything
``xla_attention(q, k_cache, v_cache, bias=[.,1,t,max_len])``: the cache is
preallocated at ``prompt_len + max_dec_len``, but at step ``pos`` only the
first ``pos + t`` slots hold real keys.  The dense path pays FLOPs and HBM
reads for the whole buffer every token; this op visits only the cache
blocks ``< ceil((pos + t) / block)`` and folds the causal + left-pad
(``kv_valid_from``) masks into per-block masking, so per-token cost scales
with the tokens generated so far instead of the preallocated maximum.

Online-softmax accumulation across blocks (same residual trick as the
flash forward in ``ops/flash_attention.py``): running max ``m``, running
denominator ``l``, fp32 accumulator rescaled by ``exp(m - m_new)`` per
block — bitwise layout-independent of how many blocks are visited.

Two implementations behind one entry point:

  - ``pallas``: one grid program per (batch, head); the kernel fori-loops
    over visited blocks with a runtime trip count read from a scalar
    input.  Runs on TPU; interpret mode elsewhere (tests force it).
    KNOWN LIMIT: like the flash fwd kernel, the BlockSpec streams the
    full [max_len, d] cache row into VMEM per program, so the length
    scaling applies to FLOPs but NOT to the HBM reads — converting the
    kv fetch to scalar-prefetch-clamped per-block DMA (paged-attention
    style) is the chip-window follow-up; note the partial last block
    must keep the in-kernel dslice clamp, since a grid-blocked tail
    would matmul against out-of-bounds padding (0 * NaN poisons the
    accumulator even under the mask).  Until then the first chip A/B
    should also compare PFX-forced lax-vs-pallas: the lax spelling's
    ``dynamic_slice`` IS length-scaled in traffic too.
  - ``lax``: the same blocked loop as ``lax.fori_loop`` +
    ``dynamic_slice`` — CPU fallback and the path used under GSPMD
    sharding (a pallas_call inside a partitioned jit would need
    shard_map; XLA partitions the lax loop for free).

Cache layout is [batch, heads, max_len, head_dim] (heads-major) so the
Pallas block tiling keeps (seq, head_dim) as the minor dims — see
``models/gpt/generation.KVCache``.

Env knobs (PFX_FLASH_* loud-parse convention — an invalid value raises
instead of silently mislabeling a chip sweep):

  PFX_DECODE_BLOCK  kv block size (default 256; positive multiple of 8)
  PFX_DECODE_ATTN   "blocked" (default) | "dense" — generation-layer
                    dispatch, read at trace time; "dense" restores the
                    attend-over-everything path for A/B benching

Inference-only: the blocked loop has a data-dependent trip count (a
``while_loop`` under the hood), so it is not reverse-differentiable.
Training attention stays on ``ops/attention.py`` / ``ops/flash_attention``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

_DEFAULT_BLOCK = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _parse_int_env(name: str) -> int:
    env = os.environ.get(name) or "0"
    try:
        return int(env)
    except ValueError:
        raise ValueError(
            f"{name}={env!r} is not an integer; pass a positive multiple "
            f"of 8 (e.g. 256) or unset it"
        ) from None


def decode_block(max_len: int, block: int = 0) -> int:
    """Resolve the kv block size: explicit ``block`` arg, else
    PFX_DECODE_BLOCK, else {_DEFAULT_BLOCK}; clamped to ``max_len``.

    Unlike the flash block, the decode block need NOT divide the cache
    length — the last block is handled by a clamped start + dedup mask —
    but it must be a positive multiple of 8 (TPU sublane tiling), and an
    invalid override fails loudly in both spellings.  When the CLAMP
    breaks alignment (a cache shorter than the requested block and not
    itself a multiple of 8, e.g. max_len 20) the block rounds DOWN to the
    nearest multiple of 8 so the Pallas tiling invariant survives; only a
    cache shorter than 8 slots yields a sub-8 block, and
    :func:`decode_attention` routes that degenerate case to the lax
    spelling (Mosaic could not tile it)."""
    force = int(block) or _parse_int_env("PFX_DECODE_BLOCK")
    if force:
        if force < 0 or force % 8:
            raise ValueError(
                f"decode block {force} must be a positive multiple of 8 "
                "(block arg / PFX_DECODE_BLOCK)"
            )
    else:
        force = _DEFAULT_BLOCK
    clamped = min(force, max_len)
    if clamped % 8 and clamped > 8:
        clamped -= clamped % 8
    return clamped


def decode_attn_mode() -> str:
    """PFX_DECODE_ATTN dispatch read by the generation layer at trace
    time: "blocked" (this op) or "dense" (the legacy attend-over-the-
    whole-cache path, kept for A/B rows)."""
    mode = os.environ.get("PFX_DECODE_ATTN") or "blocked"
    if mode not in ("blocked", "dense"):
        raise ValueError(
            f"PFX_DECODE_ATTN={mode!r}; valid: blocked, dense"
        )
    return mode


def blocks_visited(limit, block: int, max_len: int):
    """Number of kv blocks the kernel visits for keys [0, limit).

    ``limit`` may be traced (pos + t inside the decode loop); the result
    bounds the fori_loop trip count.  Exposed for tests asserting the
    decode step no longer touches cache blocks beyond ``pos + t``."""
    total = -(-max_len // block)
    return jnp.minimum((limit + block - 1) // block, total)


# ---------------------------------------------------------------------------
# lax fallback (CPU + GSPMD path)
# ---------------------------------------------------------------------------


def _decode_lax(q_t, k_cache, v_cache, limit, valid_from, block, scale):
    """q_t [b, n, t, d]; caches [b, n, L, d]; limit = pos + t (traced ok).

    Returns [b, n, t, d] fp32-accumulated attention over keys [vf, limit).
    """
    b, n, t, d = q_t.shape
    max_len = k_cache.shape[2]
    q_pos = limit - t + jnp.arange(t)  # global position of each query row

    m0 = jnp.full((b, n, t), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, t), jnp.float32)
    acc0 = jnp.zeros((b, n, t, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        # the last block would overrun the cache; clamp the start and mask
        # the overlap (col < j*block was handled by the previous block)
        start = jnp.maximum(jnp.minimum(j * block, max_len - block), 0)
        k = jax.lax.dynamic_slice_in_dim(k_cache, start, block, axis=2)
        v = jax.lax.dynamic_slice_in_dim(v_cache, start, block, axis=2)
        s = scale * jnp.einsum(
            "bntd,bnkd->bntk", q_t, k, preferred_element_type=jnp.float32
        )  # [b, n, t, block]
        col = start + jnp.arange(block)  # [block]
        mask = (col[None, :] <= q_pos[:, None]) & (col[None, :] >= j * block)
        mask = mask[None, None]  # [1, 1, t, block]
        if valid_from is not None:
            mask = mask & (
                col[None, None, None, :] >= valid_from[:, None, None, None]
            )
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bntk,bnkd->bntd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    nvisit = blocks_visited(limit, block, max_len)
    m, l, acc = jax.lax.fori_loop(0, nvisit, body, (m0, l0, acc0))
    # fully-masked query rows (left-pad positions) get 0, not NaN: they
    # feed nothing downstream (only the last, always-real row is sampled)
    return acc / jnp.maximum(l, 1e-30)[..., None]


# ---------------------------------------------------------------------------
# Pallas kernel (TPU; interpret mode in tests)
# ---------------------------------------------------------------------------


def _decode_kernel(
    q_ref, k_ref, v_ref, limit_ref, vf_ref, o_ref, *, scale, block, max_len, t
):
    q = q_ref[0, 0]  # [t, d], native dtype; dots accumulate fp32
    d = q.shape[-1]
    limit = limit_ref[0, 0]
    vf = vf_ref[0, 0, 0]
    row_pos = (limit - t) + jax.lax.broadcasted_iota(jnp.int32, (t, block), 0)

    m0 = jnp.full((t,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((t,), jnp.float32)
    acc0 = jnp.zeros((t, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        start = jnp.maximum(jnp.minimum(j * block, max_len - block), 0)
        k = k_ref[0, 0, pl.dslice(start, block), :]
        v = v_ref[0, 0, pl.dslice(start, block), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [t, block]
        col = start + jax.lax.broadcasted_iota(jnp.int32, (t, block), 1)
        mask = (col <= row_pos) & (col >= j * block) & (col >= vf)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    nvisit = blocks_visited(limit, block, max_len)
    m, l, acc = jax.lax.fori_loop(0, nvisit, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _decode_pallas(q_t, k_cache, v_cache, limit, valid_from, block, scale):
    b, n, t, d = q_t.shape
    max_len = k_cache.shape[2]
    limit_arr = jnp.full((1, 1), limit, jnp.int32)
    vf_arr = (
        jnp.zeros((b, 1, 1), jnp.int32)
        if valid_from is None
        else valid_from.astype(jnp.int32).reshape(b, 1, 1)
    )
    kernel = functools.partial(
        _decode_kernel, scale=scale, block=block, max_len=max_len, t=t
    )
    out = pl.pallas_call(
        kernel,
        grid=(b, n),
        in_specs=[
            pl.BlockSpec((1, 1, t, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, max_len, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, max_len, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, t, d), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n, t, d), jnp.float32),
        interpret=_interpret(),
    )(q_t, k_cache, v_cache, limit_arr, vf_arr)
    return out


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------


def decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    *,
    kv_valid_from: Optional[jax.Array] = None,
    block: int = 0,
    impl: str = "auto",
) -> jax.Array:
    """Blocked KV-cache attention over keys [0, pos + t).

    q [b, t, n, d] at global positions [pos, pos+t); k_cache/v_cache
    [b, n, max_len, d] with real keys through pos+t (the current chunk
    already written).  ``kv_valid_from`` [b] masks keys before a row's
    first real token (left-padded serving buckets).  Returns [b, t, n, d].

    ``impl``: "auto" (pallas on TPU, lax elsewhere) | "pallas" | "lax".
    """
    if impl not in ("auto", "pallas", "lax"):
        raise ValueError(f"decode_attention impl {impl!r}; valid: auto, pallas, lax")
    b, t, n, d = q.shape
    max_len = k_cache.shape[2]
    bs = decode_block(max_len, block)
    scale = float(1.0 / (d**0.5))
    limit = pos + t
    q_t = q.transpose(0, 2, 1, 3)  # [b, n, t, d]
    # a sub-8 block only happens for a degenerate cache shorter than 8
    # slots (decode_block rounds down otherwise): Mosaic cannot sublane-
    # tile it, so route to the lax spelling
    use_pallas = impl == "pallas" or (impl == "auto" and not _interpret())
    if use_pallas and bs % 8 == 0:
        out = _decode_pallas(q_t, k_cache, v_cache, limit, kv_valid_from, bs, scale)
    else:
        out = _decode_lax(q_t, k_cache, v_cache, limit, kv_valid_from, bs, scale)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def dense_cache_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    *,
    kv_valid_from: Optional[jax.Array] = None,
) -> jax.Array:
    """The legacy decode attention: attend over the ENTIRE preallocated
    cache with a materialized [., 1, t, max_len] additive bias (what
    ``_layer_with_cache`` did via ``xla_attention`` before the blocked
    kernel).  Kept verbatim-in-semantics for PFX_DECODE_ATTN=dense A/B
    benchmark rows; same [b, n, L, d] cache layout, no extra transposes,
    so a legacy row measures the old math, not a layout penalty."""
    b, t, n, d = q.shape
    max_len = k_cache.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    q_pos = pos + jnp.arange(t)[:, None]
    k_pos = jnp.arange(max_len)[None, :]
    bias = jnp.where(k_pos <= q_pos, 0.0, -1e9)[None, None, :, :]  # [1,1,t,L]
    if kv_valid_from is not None:
        bias = bias + jnp.where(
            k_pos >= kv_valid_from[:, None], 0.0, -1e9
        )[:, None, None, :]
    scores = jnp.einsum(
        "btnd,bnkd->bntk", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    scores = scores + bias.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bntk,bnkd->bntd", probs, v_cache)
    return out.transpose(0, 2, 1, 3)
