"""Custom TPU ops: Pallas flash attention, fused LayerNorm, chunked CE, top-p sampling."""
