"""Custom TPU ops: Pallas flash attention, flash-decode (blocked KV-cache)
attention, fused LayerNorm, chunked CE, top-k-prefiltered top-p sampling."""
