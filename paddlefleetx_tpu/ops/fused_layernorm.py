"""Fused LayerNorm (+ optional residual add) — Pallas TPU kernel.

TPU-native replacement for the reference's fused norm ops (paddle
``FusedMultiHeadAttention``/``FusedFeedForward`` pre/post-LN fusions the
models consume, e.g. vit.py:23-115 FusedBlock; SURVEY §7.1 "fused
LN(+residual)"): one VMEM pass computes mean/rstd and writes the
normalized output, fusing the residual add that usually precedes the
norm — instead of three HBM round-trips (add, stats, scale).

Custom VJP: the backward recomputes xhat from saved (mean, rstd) and
reduces dscale/dbias on the fly — matches jax.grad of the naive form to
fp32 accuracy.  On non-TPU platforms the kernel runs in Pallas interpret
mode so the CPU-mesh test suite exercises the same code path.

API: ``fused_layer_norm(x, scale, bias, residual=None, eps=1e-5)`` over
the last dim; used as a drop-in for models' ``layer_norm(x + y, ...)``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _row_block(n_rows: int) -> int:
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n_rows % b == 0:
            return b
    return 1


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, res_ref, scale_ref, bias_ref, o_ref, mean_ref, rstd_ref, *, eps, has_res):
    x = x_ref[...].astype(jnp.float32)
    if has_res:
        x = x + res_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    y = xhat * scale_ref[...].astype(jnp.float32) + bias_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)
    # (bq, 1) lane-1 blocks: TPU tiling wants the last dim equal to the
    # array dim, same trick as the flash kernel's lse carry
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _bwd_kernel(x_ref, res_ref, scale_ref, mean_ref, rstd_ref, g_ref,
                dx_ref, dscale_ref, dbias_ref, *, has_res):
    x = x_ref[...].astype(jnp.float32)
    if has_res:
        x = x + res_ref[...].astype(jnp.float32)
    mean = mean_ref[...]  # (bq, 1)
    rstd = rstd_ref[...]
    xhat = (x - mean) * rstd
    g = g_ref[...].astype(jnp.float32)
    scale = scale_ref[...].astype(jnp.float32)
    n = x.shape[-1]
    gs = g * scale
    # dx = rstd * (gs - mean(gs) - xhat * mean(gs * xhat))
    m1 = jnp.mean(gs, axis=-1, keepdims=True)
    m2 = jnp.mean(gs * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (gs - m1 - xhat * m2)).astype(dx_ref.dtype)
    # dscale/dbias: accumulate across the sequential TPU grid into one
    # (n,)-shaped output block (block == array dims satisfies tiling)
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dscale_ref[...] = jnp.zeros_like(dscale_ref)
        dbias_ref[...] = jnp.zeros_like(dbias_ref)

    dscale_ref[...] += jnp.sum(g * xhat, axis=tuple(range(g.ndim - 1)))
    dbias_ref[...] += jnp.sum(g, axis=tuple(range(g.ndim - 1)))


# ---------------------------------------------------------------------------
# Entry + VJP
# ---------------------------------------------------------------------------


def _run_fwd(x2, res2, scale, bias, eps):
    rows, n = x2.shape
    bq = _row_block(rows)
    has_res = res2 is not None
    args = (x2,) + ((res2,) if has_res else (jnp.zeros((1, n), x2.dtype),)) + (scale, bias)
    in_specs = [
        pl.BlockSpec((bq, n), lambda i: (i, 0)),
        pl.BlockSpec((bq, n), lambda i: (i, 0)) if has_res else pl.BlockSpec((1, n), lambda i: (0, 0)),
        pl.BlockSpec((n,), lambda i: (0,)),
        pl.BlockSpec((n,), lambda i: (0,)),
    ]
    out_shapes = (
        jax.ShapeDtypeStruct((rows, n), x2.dtype),
        jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        jax.ShapeDtypeStruct((rows, 1), jnp.float32),
    )
    out_specs = (
        pl.BlockSpec((bq, n), lambda i: (i, 0)),
        pl.BlockSpec((bq, 1), lambda i: (i, 0)),
        pl.BlockSpec((bq, 1), lambda i: (i, 0)),
    )
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, has_res=has_res),
        grid=(rows // bq,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_interpret(),
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_ln(x2, res2, scale, bias, eps, has_res):
    y, _, _ = _run_fwd(x2, res2 if has_res else None, scale, bias, eps)
    return y


def _fused_ln_fwd(x2, res2, scale, bias, eps, has_res):
    y, mean, rstd = _run_fwd(x2, res2 if has_res else None, scale, bias, eps)
    return y, (x2, res2, scale, mean, rstd)


def _fused_ln_bwd(eps, has_res, saved, g):
    x2, res2, scale, mean, rstd = saved
    rows, n = x2.shape
    bq = _row_block(rows)
    args = (
        x2,
        res2 if has_res else jnp.zeros((1, n), x2.dtype),
        scale, mean, rstd, g,
    )
    in_specs = [
        pl.BlockSpec((bq, n), lambda i: (i, 0)),
        pl.BlockSpec((bq, n), lambda i: (i, 0)) if has_res else pl.BlockSpec((1, n), lambda i: (0, 0)),
        pl.BlockSpec((n,), lambda i: (0,)),
        pl.BlockSpec((bq, 1), lambda i: (i, 0)),
        pl.BlockSpec((bq, 1), lambda i: (i, 0)),
        pl.BlockSpec((bq, n), lambda i: (i, 0)),
    ]
    out_shapes = (
        jax.ShapeDtypeStruct((rows, n), x2.dtype),
        jax.ShapeDtypeStruct((n,), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32),
    )
    out_specs = (
        pl.BlockSpec((bq, n), lambda i: (i, 0)),
        pl.BlockSpec((n,), lambda i: (0,)),
        pl.BlockSpec((n,), lambda i: (0,)),
    )
    dx, dscale_p, dbias_p = pl.pallas_call(
        functools.partial(_bwd_kernel, has_res=has_res),
        grid=(rows // bq,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_interpret(),
    )(*args)
    dscale = dscale_p.astype(scale.dtype)
    dbias = dbias_p.astype(scale.dtype)
    dres = dx if has_res else None
    return dx, dres, dscale, dbias


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    residual: Optional[jax.Array] = None,
    eps: float = 1e-5,
) -> jax.Array:
    """LayerNorm over the last dim, fusing an optional residual add."""
    shape = x.shape
    n = shape[-1]
    x2 = x.reshape(-1, n)
    res2 = residual.reshape(-1, n) if residual is not None else x2  # dummy when unused
    out = _fused_ln(x2, res2, scale, bias, eps, residual is not None)
    return out.reshape(shape)
