"""Chunked softmax cross-entropy: logits never materialize.

The GPT loss tail (logits = hidden @ word.T -> fp32 softmax-CE) is the
single largest activation of the whole model: [b*s, vocab] fp32 is ~3 GB
at the bench shape, and it is the buffer that caps the per-chip batch
size.  This op streams the vocab in chunks with an online logsumexp
(fwd) and recomputes each chunk's softmax in the backward — peak memory
drops from O(N*V) to O(N*chunk), trading one extra hidden@word_c matmul
pass in the backward.

Semantics match ``gpt.model.cross_entropy`` exactly (fp32 reductions,
masked token mean).  Single-shard vocab only: under tensor parallelism
the vocab dim is model-sharded and the plain GSPMD path already handles
the reduction — callers gate on that (see gpt/model.py loss_fn).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG = jnp.float32(-1e30)


def _chunks(word: jax.Array, chunk: int) -> jax.Array:
    """[V, h] -> [nc, chunk, h], zero-padding the tail chunk (padded rows
    are masked out of the softmax by the scan bodies)."""
    v, h = word.shape
    pad = (-v) % chunk
    if pad:
        word = jnp.concatenate([word, jnp.zeros((pad, h), word.dtype)], axis=0)
    return word.reshape(-1, chunk, h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _nll(hidden2d: jax.Array, word: jax.Array, labels1d: jax.Array, chunk: int):
    """Per-token nll [N] for flattened hidden [N, h], labels [N]."""
    nll, _ = _nll_fwd(hidden2d, word, labels1d, chunk)
    return nll


def _scan_lse_picked(hidden2d, word, labels1d, chunk):
    v = word.shape[0]
    wc = _chunks(word, chunk)
    n = hidden2d.shape[0]

    def body(carry, inp):
        m, s, picked = carry
        w_c, off = inp
        # cast to the activation dtype first (bf16 MXU matmul, fp32
        # accumulate) — matching logits_from_hidden exactly
        logits = (hidden2d @ w_c.astype(hidden2d.dtype).T).astype(jnp.float32)
        cols = off + jnp.arange(chunk, dtype=jnp.int32)
        logits = jnp.where(cols[None, :] < v, logits, NEG)  # pad-tail mask
        cm = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - cm) + jnp.exp(logits - cm[:, None]).sum(axis=-1)
        local = labels1d - off
        hit = (local >= 0) & (local < chunk)
        one = jax.nn.one_hot(jnp.where(hit, local, 0), chunk, dtype=logits.dtype)
        picked = picked + jnp.where(hit, (logits * one).sum(-1), 0.0)
        return (cm, s, picked), None

    init = (jnp.full((n,), NEG), jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    offs = jnp.arange(wc.shape[0], dtype=jnp.int32) * chunk
    (m, s, picked), _ = jax.lax.scan(body, init, (wc, offs))
    lse = m + jnp.log(jnp.maximum(s, 1e-30))
    return lse, picked


def _nll_fwd(hidden2d, word, labels1d, chunk):
    lse, picked = _scan_lse_picked(hidden2d, word, labels1d, chunk)
    return lse - picked, (hidden2d, word, labels1d, lse)


def _nll_bwd(chunk, res, g):
    hidden2d, word, labels1d, lse = res
    v = word.shape[0]
    wc = _chunks(word, chunk)
    gf = g.astype(jnp.float32)

    def body(dh, inp):
        w_c, off = inp
        logits = (hidden2d @ w_c.astype(hidden2d.dtype).T).astype(jnp.float32)
        cols = off + jnp.arange(chunk, dtype=jnp.int32)
        logits = jnp.where(cols[None, :] < v, logits, NEG)
        p = jnp.exp(logits - lse[:, None])  # softmax chunk (0 at pad cols)
        local = labels1d - off
        hit = (local >= 0) & (local < chunk)
        one = jax.nn.one_hot(jnp.where(hit, local, 0), chunk, dtype=p.dtype)
        dlogits = (p - jnp.where(hit[:, None], one, 0.0)) * gf[:, None]
        # dlogits drops to the activation dtype for the two big matmuls
        # (bf16 MXU at full rate, fp32 quarters it — same finding as the
        # flash kernels); the dh CARRY stays fp32 so per-chunk rounding
        # does not compound across the vocab scan
        dlo = dlogits.astype(hidden2d.dtype)
        dh = dh + jax.lax.dot(
            dlo, w_c.astype(hidden2d.dtype),
            preferred_element_type=jnp.float32,
        )
        dw_c = jax.lax.dot(
            dlo.T, hidden2d, preferred_element_type=jnp.float32
        ).astype(word.dtype)
        return dh, dw_c

    offs = jnp.arange(wc.shape[0], dtype=jnp.int32) * chunk
    dh32 = jnp.zeros(hidden2d.shape, jnp.float32)
    dh, dwc = jax.lax.scan(body, dh32, (wc, offs))
    dword = dwc.reshape(-1, word.shape[1])[:v]
    return dh.astype(hidden2d.dtype), dword, None


_nll.defvjp(_nll_fwd, _nll_bwd)


def chunked_cross_entropy(
    hidden: jax.Array,
    word: jax.Array,
    labels: jax.Array,
    loss_mask: Optional[jax.Array] = None,
    chunk: int = 4096,
) -> jax.Array:
    """Masked-mean CE of ``hidden @ word.T`` vs labels, without the
    [b, s, V] logits buffer.  hidden [b, s, h], word [V, h], labels [b, s]."""
    b, s, h = hidden.shape
    v = word.shape[0]
    chunk = min(chunk, v)  # tail chunk is zero-padded and masked
    nll = _nll(hidden.reshape(b * s, h), word, labels.reshape(b * s), chunk)
    nll = nll.reshape(b, s)
    if loss_mask is None:
        return nll.mean()
    m = loss_mask.astype(jnp.float32)
    return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
