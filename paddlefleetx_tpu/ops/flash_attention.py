"""Causal flash attention — Pallas TPU kernel with custom VJP.

TPU-native replacement for the reference's fused attention path (fused
softmax-mask-triu in ``core_attn`` single_model.py:83-200 and the
``flash_attention`` hook hybrid_model.py:284-301): online-softmax tiling so
the [s, s] score matrix never materialises in HBM.

Layout: inputs [batch, seq, heads, head_dim] (model layout), kernels run on
[batch*heads, seq, head_dim].  Forward saves per-row logsumexp for the
backward recomputation (standard FlashAttention-2 scheme: dq swept over kv
blocks, dk/dv swept over q blocks).

On non-TPU platforms the kernels run in Pallas interpret mode (slow but
exact) so the full test suite exercises the same code path on the CPU mesh.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(seq: int, block: int = 0) -> Tuple[int, int]:
    # 512x512 measured best on v5e at seq 1024 (8.7ms vs 10.8ms at 256x256
    # and 16.2ms at 128x128 for b16/h16/d64 fwd+bwd): fewer grid programs
    # amortize K/V HBM streaming; beats the stock jax.experimental Pallas
    # flash (26.7ms) and splash (25.8ms) kernels at this shape. Seqs not
    # divisible by 512 use the largest dividing block so e.g. seq 768 keeps
    # flash support; small seqs run as one block (pre-existing behavior);
    # anything else reports unsupported and attention() falls back to XLA.
    # Model.flash_block (the ``block`` arg) or PFX_FLASH_BLOCK override the
    # ladder for chip sweeps (the bf16-dot change moves the compute/stream
    # balance, so the optimum may shift).  An invalid override fails LOUDLY
    # in BOTH spellings: silently falling back (to the ladder or the XLA
    # path) would burn a scarce tunnel-up benchmark window on mislabeled
    # data blamed on the wrong knob.
    force = int(block) or _parse_block_env("PFX_FLASH_BLOCK")
    if force:
        _check_block(force, seq, "Model.flash_block / PFX_FLASH_BLOCK")
        return force, _block_k_override(seq, force)
    for b in (512, 256, 128):
        if seq % b == 0:
            return b, _block_k_override(seq, b)
    if seq < 256 and seq % 8 == 0:
        # single-block path needs sublane alignment too: a non-multiple-
        # of-8 seq would die in Mosaic lowering, so it falls through to
        # the unsupported return below and attention() uses XLA instead
        return seq, _block_k_override(seq, seq)
    # unsupported-seq fallback: still parse + validate a set block_k
    # override FIRST so a set-but-invalid PFX_FLASH_BLOCK_K fails loudly
    # on this path too (a seq that misses the ladder, e.g. 1000, must not
    # silently drop the knob and mislabel a sweep); a VALID override is
    # then ignored along with the rest of the ladder — the XLA fallback
    # has no blocks to apply it to
    bk = _parse_block_env("PFX_FLASH_BLOCK_K")
    if bk:
        _check_block(bk, seq, "block_k; PFX_FLASH_BLOCK_K")
    return 256, 256  # does not divide seq -> flash_supported() False


def _parse_block_env(name: str) -> int:
    env = os.environ.get(name) or "0"
    try:
        return int(env)
    except ValueError:
        raise ValueError(
            f"{name}={env!r} is not an integer; pass a positive divisor "
            f"of seq (e.g. 256) or unset it"
        ) from None


def _check_block(val: int, seq: int, label: str) -> None:
    if val < 0 or seq % val:
        raise ValueError(
            f"flash block {val} must be a positive divisor of seq "
            f"{seq} ({label})"
        )
    if val % 8:
        # sublane alignment: a non-multiple-of-8 tile would surface as
        # an opaque Mosaic lowering error deep in the compile
        raise ValueError(
            f"flash block {val} must be a multiple of 8 (TPU "
            f"sublane tiling; {label})"
        )


def _block_k_override(seq: int, default_bk: int) -> int:
    """PFX_FLASH_BLOCK_K: sweep knob for an asymmetric K/V block.

    The kernels are already parameterized by block_q/block_k separately
    (causal bounds use ceil/floor divisions that hold for bq != bk); a
    larger K block amortizes K/V HBM streaming without growing the q
    tile's VMEM accumulator.  Same loud-failure contract as the q block
    (shared _check_block): an invalid override must not silently
    mislabel a chip sweep — including on the small-seq single-block
    path, where a stale exported override would otherwise be dropped."""
    bk = _parse_block_env("PFX_FLASH_BLOCK_K")
    if not bk:
        return default_bk
    _check_block(bk, seq, "block_k; PFX_FLASH_BLOCK_K")
    return bk


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_q, block_k):
    # MXU dots run in the INPUT dtype (bf16 on the model path) with fp32
    # accumulation via preferred_element_type — upcasting the operands to
    # fp32 first quarters MXU throughput (measured: the kernel pair sat at
    # 19% intra-kernel efficiency in the 03:17Z op table).  Softmax
    # statistics, rescaling, and the output accumulator stay fp32.
    qi = pl.program_id(1)
    q = q_ref[0]  # [bq, d], native dtype
    d = q.shape[-1]

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    row_ids = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :]
        v = v_ref[0, pl.dslice(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk] fp32
        col_ids = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(col_ids <= row_ids, s, NEG_INF)

        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    # causal: only kv blocks intersecting rows [qi*bq, (qi+1)*bq)
    num_kv = (qi * block_q + block_q + block_k - 1) // block_k
    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse carried as [bh, seq, 1]: TPU tiling wants the trailing block dims
    # divisible by (8, 128) or equal to the array dims — a lane dim of 1
    # satisfies the latter for this per-row scalar
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


def _flash_fwd(q, k, v, scale, block):
    bh, seq, d = q.shape
    block_q, block_k = block  # static (bq, bk) tuple
    grid = (bh, seq // block_q)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
#
# Two schedules, selected by PFX_FLASH_BWD (read at trace time):
#   split (default): FlashAttention-2 style — a dq kernel swept over kv
#     blocks and a dk/dv kernel swept over q blocks.  Each (i, j) tile
#     computes s = q@k^T and p = exp(s - lse) TWICE (once per kernel).
#   fused: one kernel, grid over kv blocks; each tile computes s/p once
#     and emits the dv/dk contributions AND accumulates the dq rows
#     in-place.  TPU Pallas grids execute sequentially, so the dq output
#     block (the full [seq, d] row slab, revisited by every j) is
#     accumulated correctly in VMEM and flushed when the bh row changes.
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, block_q, block_k):
    qi = pl.program_id(1)
    q = q_ref[0]  # native dtype; dots accumulate fp32 (see _fwd_kernel)
    do = do_ref[0]
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    d = q.shape[-1]

    row_ids = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, dq):
        k = k_ref[0, pl.dslice(j * block_k, block_k), :]
        v = v_ref[0, pl.dslice(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        col_ids = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        p = jnp.where(col_ids <= row_ids, jnp.exp(s - lse[:, None]), 0.0)
        dov = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk] fp32
        ds = p * (dov - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    num_kv = (qi * block_q + block_q + block_k - 1) // block_k
    dq = jax.lax.fori_loop(0, num_kv, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, block_q, block_k, seq
):
    kj = pl.program_id(1)
    k = k_ref[0]  # [bk, d] native dtype; dots accumulate fp32
    v = v_ref[0]
    d = k.shape[-1]

    col_ids = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        sl = pl.dslice(i * block_q, block_q)
        q = q_ref[0, sl, :]
        do = do_ref[0, sl, :]
        lse = lse_ref[0, sl, 0]
        delta = delta_ref[0, sl, 0]
        row_ids = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        p_lo, ds = _bwd_tile(q, k, v, do, lse, delta, row_ids, col_ids, scale)
        dv_new = dv + jax.lax.dot_general(
            p_lo, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    # causal: q blocks starting at or after this kv block's diagonal
    first_q = (kj * block_k) // block_q
    num_q = seq // block_q
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_q, num_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_tile(q, k, v, do, lse, delta, row_ids, col_ids, scale):
    """Shared per-(q-block, kv-block) backward tile math: recompute the
    masked softmax block from the saved lse and form ds.  Used by BOTH the
    split _dkv_kernel and the fused kernel so the mask/scaling can never
    diverge between schedules.  Returns (p_lo, ds) in the input dtype;
    dots accumulate fp32."""
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    p = jnp.where(col_ids <= row_ids, jnp.exp(s - lse[:, None]), 0.0)
    dov = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = (p * (dov - delta[:, None]) * scale).astype(q.dtype)
    return p.astype(do.dtype), ds


def _bwd_fused_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
    *, scale, block_q, block_k, seq
):
    kj = pl.program_id(1)
    k = k_ref[0]  # [bk, d] native dtype; dots accumulate fp32
    v = v_ref[0]
    d = k.shape[-1]

    # dq is the full [seq, d] row slab, revisited by every kv-block
    # program of this bh row: zero it once, at the first kv block.  The
    # slab is fp32 (out_shape below) so the cross-block read-modify-write
    # accumulation rounds once at the end, not once per kv block — same
    # fp32-carry rule as the split _dq_kernel and chunked_ce's dh.
    @pl.when(kj == 0)
    def _zero_dq():
        dq_ref[0] = jnp.zeros((seq, d), dq_ref.dtype)

    col_ids = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        sl = pl.dslice(i * block_q, block_q)
        q = q_ref[0, sl, :]
        do = do_ref[0, sl, :]
        lse = lse_ref[0, sl, 0]
        delta = delta_ref[0, sl, 0]
        row_ids = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        p_lo, ds = _bwd_tile(q, k, v, do, lse, delta, row_ids, col_ids, scale)
        dv_new = dv + jax.lax.dot_general(
            p_lo, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dq_tile = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dq_ref[0, sl, :] = dq_ref[0, sl, :] + dq_tile  # fp32 slab
        return dk_new, dv_new

    first_q = (kj * block_k) // block_q
    num_q = seq // block_q
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_q, num_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused(q, k, v, do, lse, delta, scale, block_q, block_k):
    bh, seq, d = q.shape
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_fused_kernel, scale=scale, block_q=block_q, block_k=block_k,
            seq=seq,
        ),
        grid=(bh, seq // block_k),
        in_specs=[
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, seq, 1), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, seq, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            # dq fp32: accumulated in-place across kv-block grid steps
            jax.ShapeDtypeStruct(q.shape, jnp.float32),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq.astype(q.dtype), dk, dv


def _flash_bwd(scale, block, bwd_mode, res, g):
    q, k, v, out, lse = res
    do = g
    bh, seq, d = q.shape
    block_q, block_k = block  # static (bq, bk) tuple

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[..., None]  # [bh, s, 1]

    if bwd_mode == "fused":
        return _flash_bwd_fused(q, k, v, do, lse, delta, scale, block_q, block_k)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_q=block_q, block_k=block_k),
        grid=(bh, seq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, block_q=block_q, block_k=block_k, seq=seq
        ),
        grid=(bh, seq // block_k),
        in_specs=[
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, seq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, seq, 1), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, seq, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd(q, k, v, scale, block, bwd_mode):
    out, _ = _flash_fwd(q, k, v, scale, block)
    return out


def _flash_bhsd_fwd(q, k, v, scale, block, bwd_mode):
    out, lse = _flash_fwd(q, k, v, scale, block)
    # Name lse so selective-remat policies can keep it: without a saved lse
    # the backward pass must re-run the forward kernel a SECOND time just to
    # regenerate it (observed as rematted_computation in traces). The out
    # residual is deliberately NOT name-saved: the backward's single primal
    # re-run measured faster than paying HBM for a saved copy (34.3k vs
    # 33.2k tok/s on the v5e bench).
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bwd)


def _resolve_bwd_schedule(bwd_schedule) -> str:
    mode = bwd_schedule or os.environ.get("PFX_FLASH_BWD", "split")
    if mode not in ("split", "fused"):
        # a typo must not silently A/B split-vs-split on a chip window
        raise ValueError(f"flash bwd schedule {mode!r}; valid: split, fused")
    return mode


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block: int = 0,
    bwd_schedule: str = "",
):
    """q,k,v: [batch, seq, heads, head_dim] -> [batch, seq, heads, head_dim].

    ``block`` (0 = auto: PFX_FLASH_BLOCK env, else the measured-best
    ladder) and ``bwd_schedule`` ("" = auto: PFX_FLASH_BWD env, else
    "split") surface as ``Model.flash_block`` / ``Model.flash_bwd`` —
    product knobs, not just bench sweeps."""
    if not causal:
        raise NotImplementedError("only causal flash attention")
    b, s, n, d = q.shape
    bq, bk = _block_sizes(s, block)
    if s % bq or s % bk:
        raise ValueError(
            f"flash_attention needs seq divisible by block size {bq}, got {s}; "
            "pad the sequence or use attn_impl='xla'"
        )
    scale = float(1.0 / (d**0.5))
    mode = _resolve_bwd_schedule(bwd_schedule)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * n, s, d)

    out = _flash_bhsd(to_bh(q), to_bh(k), to_bh(v), scale, (bq, bk), mode)
    return out.reshape(b, n, s, d).transpose(0, 2, 1, 3)


def flash_supported(seq: int, block: int = 0) -> bool:
    """True when the kernel's block tiling divides ``seq`` (dispatch helper).

    With an explicit ``block`` this raises (loudly) on invalid values
    rather than reporting unsupported — see _block_sizes."""
    bq, bk = _block_sizes(seq, block)
    return seq % bq == 0 and seq % bk == 0
