"""Shared plumbing for the HF checkpoint converters (gpt/t5/debertav2/vit/
ernie convert.py modules): torch-or-numpy leaf extraction, backbone-prefix
detection, and per-layer stacking.  One copy — a dtype or safetensors fix
lands everywhere at once.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import numpy as np


def to_numpy(v) -> np.ndarray:
    """torch tensor or array-like -> fp32 numpy."""
    return np.asarray(
        v.detach().cpu().numpy() if hasattr(v, "detach") else v
    ).astype(np.float32)


def detect_prefix(sd: Dict, candidates: Sequence[str]) -> str:
    """First candidate prefix ('' always matches last) present in the keys —
    classification/pretraining wrappers nest the backbone under one."""
    names = list(sd.keys())
    for p in candidates:
        if p and any(n.startswith(p) for n in names):
            return p
    return ""


def make_getter(sd: Dict, prefix: str = "") -> Callable[[str], np.ndarray]:
    """get(name): prefer the prefixed key, fall back to the bare one."""

    def get(name: str) -> np.ndarray:
        key = prefix + name if prefix + name in sd else name
        return to_numpy(sd[key])

    return get


def make_stacker(get: Callable[[str], np.ndarray], num_layers: int):
    """stack(fmt): per-layer tensors -> one leading-L array, with optional
    torch->native transpose and reshape."""

    def stack(fmt: str, reshape: Optional[tuple] = None, transpose: bool = False):
        arrs = []
        for i in range(num_layers):
            a = get(fmt.format(i=i))
            if transpose:
                a = a.T
            arrs.append(a.reshape(reshape) if reshape is not None else a)
        return np.stack(arrs)

    return stack
