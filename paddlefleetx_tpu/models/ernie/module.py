"""ERNIE engine modules (reference ErnieModule / ErnieSeqClsModule,
ppfleetx/models/language_model/ernie/ernie_module.py:120+)."""

from __future__ import annotations

from paddlefleetx_tpu.core.module import BasicModule
from paddlefleetx_tpu.models.ernie import model as ernie
from paddlefleetx_tpu.models.ernie.config import ErnieConfig
from paddlefleetx_tpu.utils.registry import MODULES


def _config_from(cfg) -> ErnieConfig:
    model_cfg = dict(cfg.Model)
    model_cfg.pop("module", None)
    model_cfg.pop("name", None)
    from paddlefleetx_tpu.core.module import resolve_model_dtype

    resolve_model_dtype(cfg, model_cfg)
    # reference knob alias: with_nsp_loss toggles the NSP head+loss
    # (ErniePretrainingCriterion single_model.py:598)
    if "with_nsp_loss" in model_cfg:
        model_cfg.setdefault("binary_head", bool(model_cfg.pop("with_nsp_loss")))
    return ErnieConfig.from_config(model_cfg)


@MODULES.register("ErnieModule")
class ErnieModule(BasicModule):
    """MLM+NSP pretraining."""

    def __init__(self, cfg):
        self.config = _config_from(cfg)
        self.tokens_per_sample = self.config.max_position_embeddings
        seq = cfg.get("Data", {}).get("Train", {}).get("dataset", {}).get("max_seq_len")
        if seq:
            self.tokens_per_sample = int(seq)

    def init_params(self, key):
        return ernie.init(self.config, key)

    def logical_axes(self):
        return ernie.ernie_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        return ernie.pretrain_loss(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )

    def export_spec(self):
        import jax.numpy as jnp

        cfg = self.config

        def fwd(params, input_ids):
            seq_out, pooled = ernie.encode(params, input_ids, cfg, train=False)
            return ernie.pretrain_logits(params, seq_out, pooled, cfg)[0]

        return fwd, (jnp.zeros((1, self.tokens_per_sample), jnp.int32),)


@MODULES.register("ErnieSeqClsModule")
class ErnieSeqClsModule(ErnieModule):
    """Sequence-classification finetune (GLUE-style)."""

    def __init__(self, cfg):
        super().__init__(cfg)
        self.metric_cfg = dict(cfg.Model.get("metric", {}) or {})

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        logits = ernie.cls_forward(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )
        return ernie.cls_loss(logits, batch["labels"])

    def export_spec(self):
        import jax.numpy as jnp

        cfg = self.config

        def fwd(params, input_ids):
            return ernie.cls_forward(params, {"input_ids": input_ids}, cfg, train=False)

        return fwd, (jnp.zeros((1, self.tokens_per_sample), jnp.int32),)

    # metric streaming (consumed by Engine.evaluate)
    def predict_fn(self, params, batch, *, ctx=None):
        return ernie.cls_forward(params, batch, self.config, ctx=ctx, train=False)

    def build_metric(self):
        from paddlefleetx_tpu.models.metrics import Accuracy, build_metric

        if self.metric_cfg.get("eval"):
            return build_metric(self.metric_cfg["eval"])
        return Accuracy()
