"""HF ERNIE checkpoint -> native param tree (same role as gpt/convert.py).

transformers ``ErnieModel`` (the nghuyong ERNIE 1.0/3.0 ports) is the same
post-LN BERT-style encoder as the reference's paddle ERNIE; torch Linear
weights are [out, in] — kernels transpose, separate q/k/v pack into the
fused qkv kernel.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from paddlefleetx_tpu.models.ernie.config import ErnieConfig


def hf_ernie_config(hf_cfg, **overrides) -> ErnieConfig:
    act = getattr(hf_cfg, "hidden_act", "gelu")
    if act != "gelu":
        raise ValueError(f"unsupported hidden_act {act!r}")
    if abs(float(getattr(hf_cfg, "layer_norm_eps", 1e-12)) - 1e-12) > 1e-15:
        raise ValueError(
            f"unsupported layer_norm_eps {hf_cfg.layer_norm_eps} (model uses 1e-12)"
        )
    if getattr(hf_cfg, "use_task_id", False):
        raise ValueError("task_type embeddings (use_task_id) not supported")
    kw = dict(
        vocab_size=int(hf_cfg.vocab_size),
        hidden_size=int(hf_cfg.hidden_size),
        num_layers=int(hf_cfg.num_hidden_layers),
        num_attention_heads=int(hf_cfg.num_attention_heads),
        ffn_hidden_size=int(hf_cfg.intermediate_size),
        max_position_embeddings=int(hf_cfg.max_position_embeddings),
        type_vocab_size=int(getattr(hf_cfg, "type_vocab_size", 2)),
        pad_token_id=int(getattr(hf_cfg, "pad_token_id", 0)),
        gelu_approximate=False,
    )
    kw.update(overrides)
    return ErnieConfig(**kw)


def convert_hf_ernie_state_dict(sd: Dict, cfg: ErnieConfig) -> Dict:
    """torch/HF ``ErnieModel`` / ``ErnieForPreTraining`` state dict ->
    stacked param tree (``ernie.`` prefixes handled; MLM/NSP heads map
    when present, otherwise fresh zero heads are emitted)."""

    from paddlefleetx_tpu.models.convert_common import (
        detect_prefix,
        make_getter,
        make_stacker,
    )

    get = make_getter(sd, detect_prefix(sd, ("ernie.",)))

    h, nh, hd, L = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim, cfg.num_layers

    def qkv_stack(kind):
        ks, bs = [], []
        for i in range(L):
            base = f"encoder.layer.{i}.attention.self.{kind}"
            ks.append(get(base + ".weight").T.reshape(h, nh, hd))
            bs.append(get(base + ".bias").reshape(nh, hd))
        return np.stack(ks), np.stack(bs)

    qk, qb = qkv_stack("query")
    kk, kb = qkv_stack("key")
    vk, vb = qkv_stack("value")

    stack = make_stacker(get, L)

    params = {
        "embeddings": {
            "word": get("embeddings.word_embeddings.weight"),
            "position": get("embeddings.position_embeddings.weight"),
            "token_type": get("embeddings.token_type_embeddings.weight"),
            "ln": {
                "scale": get("embeddings.LayerNorm.weight"),
                "bias": get("embeddings.LayerNorm.bias"),
            },
        },
        "layers": {
            "attn": {
                "qkv_kernel": np.stack([qk, kk, vk], axis=2),
                "qkv_bias": np.stack([qb, kb, vb], axis=1),
                "out_kernel": stack(
                    "encoder.layer.{i}.attention.output.dense.weight",
                    (nh, hd, h), transpose=True,
                ),
                "out_bias": stack("encoder.layer.{i}.attention.output.dense.bias"),
            },
            "ln_1": {
                "scale": stack("encoder.layer.{i}.attention.output.LayerNorm.weight"),
                "bias": stack("encoder.layer.{i}.attention.output.LayerNorm.bias"),
            },
            "mlp": {
                "fc_in_kernel": stack(
                    "encoder.layer.{i}.intermediate.dense.weight", transpose=True
                ),
                "fc_in_bias": stack("encoder.layer.{i}.intermediate.dense.bias"),
                "fc_out_kernel": stack(
                    "encoder.layer.{i}.output.dense.weight", transpose=True
                ),
                "fc_out_bias": stack("encoder.layer.{i}.output.dense.bias"),
            },
            "ln_2": {
                "scale": stack("encoder.layer.{i}.output.LayerNorm.weight"),
                "bias": stack("encoder.layer.{i}.output.LayerNorm.bias"),
            },
        },
        "pooler": {
            "kernel": get("pooler.dense.weight").T,
            "bias": get("pooler.dense.bias"),
        },
    }
    # pretrain heads (ErnieForPreTraining: cls.predictions / cls.seq_relationship
    # live at the top level, outside the "ernie." backbone prefix)
    if "cls.predictions.transform.dense.weight" in sd:
        params["mlm"] = {
            "transform_kernel": get("cls.predictions.transform.dense.weight").T,
            "transform_bias": get("cls.predictions.transform.dense.bias"),
            "ln": {
                "scale": get("cls.predictions.transform.LayerNorm.weight"),
                "bias": get("cls.predictions.transform.LayerNorm.bias"),
            },
            "decoder_bias": get("cls.predictions.bias"),
        }
        params["nsp"] = {
            "kernel": get("cls.seq_relationship.weight").T,
            "bias": get("cls.seq_relationship.bias"),
        }
    else:
        params["mlm"] = {
            "transform_kernel": np.zeros((h, h), np.float32),
            "transform_bias": np.zeros((h,), np.float32),
            "ln": {"scale": np.ones((h,), np.float32), "bias": np.zeros((h,), np.float32)},
            "decoder_bias": np.zeros((cfg.vocab_size,), np.float32),
        }
        params["nsp"] = {
            "kernel": np.zeros((h, 2), np.float32),
            "bias": np.zeros((2,), np.float32),
        }
    if cfg.num_classes:
        params["cls_head"] = {
            "kernel": np.zeros((h, cfg.num_classes), np.float32),
            "bias": np.zeros((cfg.num_classes,), np.float32),
        }
    return params
