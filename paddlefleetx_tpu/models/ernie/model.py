"""ERNIE — BERT-style bidirectional encoder, pure-JAX functional.

Covers the reference's ErnieModel / ErnieForPretraining /
ErnieForSequenceClassification and their hybrid/pipe variants
(ppfleetx/models/language_model/ernie/dygraph/single_model.py:131,464,647;
hybrid_model.py:88,796): one definition, parallelism by logical-axis
annotation (TP shards heads/ffn/vocab exactly like GPT; the stacked
``layers`` axis is what pipeline stage-sharding partitions).

Architecture: word+position+token-type embeddings -> LayerNorm -> dropout;
N *post-LN* encoder blocks (LN after residual — BERT convention, unlike
GPT's pre-LN); tanh pooler on [CLS]; heads:
  - MLM: dense+gelu+LN transform, decoder tied to word embeddings + bias
    (ErnieLMPredictionHead single_model.py:401-441)
  - NSP/SOP: binary classifier on pooled output (ErniePretrainingHeads :443)
Pretraining loss = masked-token CE (ignore label -1) + NSP CE
(ErniePretrainingCriterion :591-644).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    dropout,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    stack_spec_tree,
    zeros_init,
)
from paddlefleetx_tpu.models.ernie.config import ErnieConfig
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, layer_norm, _constrain
from paddlefleetx_tpu.ops.attention import attention


# ---------------------------------------------------------------------------
# Parameter specs
# ---------------------------------------------------------------------------


def _layer_specs(cfg: ErnieConfig) -> Dict[str, Any]:
    h, nh, hd, ffn = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim, cfg.ffn_hidden_size
    w = normal_init(cfg.initializer_range)
    return {
        "attn": {
            "qkv_kernel": ParamSpec((h, 3, nh, hd), ("embed", None, "heads", "kv"), w),
            "qkv_bias": ParamSpec((3, nh, hd), (None, "heads", "kv"), zeros_init()),
            "out_kernel": ParamSpec((nh, hd, h), ("heads", "kv", "embed"), w),
            "out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "ln_1": {  # post-attention LN
            "scale": ParamSpec((h,), ("embed",), ones_init()),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "mlp": {
            "fc_in_kernel": ParamSpec((h, ffn), ("embed", "mlp"), w),
            "fc_in_bias": ParamSpec((ffn,), ("mlp",), zeros_init()),
            "fc_out_kernel": ParamSpec((ffn, h), ("mlp", "embed"), w),
            "fc_out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "ln_2": {  # post-FFN LN
            "scale": ParamSpec((h,), ("embed",), ones_init()),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
    }


def ernie_specs(cfg: ErnieConfig) -> Dict[str, Any]:
    h = cfg.hidden_size
    w = normal_init(cfg.initializer_range)
    specs: Dict[str, Any] = {
        "embeddings": {
            "word": ParamSpec((cfg.vocab_size, h), ("vocab", "embed"), w),
            "position": ParamSpec((cfg.max_position_embeddings, h), ("table", "embed"), w),
            "token_type": ParamSpec((cfg.type_vocab_size, h), ("table", "embed"), w),
            "ln": {
                "scale": ParamSpec((h,), ("embed",), ones_init()),
                "bias": ParamSpec((h,), ("embed",), zeros_init()),
            },
        },
        "layers": stack_spec_tree(_layer_specs(cfg), cfg.num_layers),
        "pooler": {
            "kernel": ParamSpec((h, h), ("embed", None), w),
            "bias": ParamSpec((h,), (None,), zeros_init()),
        },
        "mlm": {
            "transform_kernel": ParamSpec((h, h), ("embed", None), w),
            "transform_bias": ParamSpec((h,), (None,), zeros_init()),
            "ln": {
                "scale": ParamSpec((h,), ("embed",), ones_init()),
                "bias": ParamSpec((h,), ("embed",), zeros_init()),
            },
            # decoder weight is tied to embeddings.word; only the bias is new
            "decoder_bias": ParamSpec((cfg.vocab_size,), ("vocab",), zeros_init()),
        },
    }
    if cfg.binary_head:
        specs["nsp"] = {
            "kernel": ParamSpec((h, 2), ("embed", None), w),
            "bias": ParamSpec((2,), (None,), zeros_init()),
        }
    specs["cls_head"] = {
        "kernel": ParamSpec((h, cfg.num_classes), ("embed", None), w),
        "bias": ParamSpec((cfg.num_classes,), (None,), zeros_init()),
    }
    return specs


def init(cfg: ErnieConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, ernie_specs(cfg))


def ernie_logical_axes(cfg: ErnieConfig) -> Dict[str, Any]:
    return logical_axes(ernie_specs(cfg))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _attention_block(p, x, bias, cfg: ErnieConfig, ctx, key, train):
    dtype = x.dtype
    k_attn, k_resid = (jax.random.split(key) if key is not None else (None, None))
    qkv = jnp.einsum("bsh,htnd->bstnd", x, p["qkv_kernel"].astype(dtype))
    qkv = qkv + p["qkv_bias"].astype(dtype)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _constrain(ctx, q, ("batch", None, "heads", "kv"))

    def core(q, k, v, dk):
        return attention(
            q, k, v,
            impl=cfg.attn_impl,
            causal=False,
            bias=bias,
            dropout_key=dk,
            dropout_rate=cfg.attention_probs_dropout_prob,
            train=train,
        )

    if cfg.use_recompute and cfg.recompute_granularity == "core_attn":
        core = jax.checkpoint(core)
    out = core(q, k, v, k_attn)
    out = jnp.einsum("bsnd,ndh->bsh", out, p["out_kernel"].astype(dtype))
    out = out + p["out_bias"].astype(dtype)
    return dropout(k_resid, out, cfg.hidden_dropout_prob, train)


def _encoder_layer(p, x, bias, cfg: ErnieConfig, ctx, key, train):
    """Post-LN encoder block: LN(x + attn(x)); LN(x + ffn(x))."""
    k_attn, k_mlp = (jax.random.split(key) if key is not None else (None, None))
    dtype = x.dtype

    x = x + _attention_block(p["attn"], x, bias, cfg, ctx, k_attn, train)
    x = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"], eps=1e-12)
    x = _constrain(ctx, x, ("batch", "seq", "embed"))

    h = x @ p["mlp"]["fc_in_kernel"].astype(dtype) + p["mlp"]["fc_in_bias"].astype(dtype)
    h = _constrain(ctx, h, ("batch", None, "mlp"))
    h = jax.nn.gelu(h, approximate=cfg.gelu_approximate)
    h = h @ p["mlp"]["fc_out_kernel"].astype(dtype) + p["mlp"]["fc_out_bias"].astype(dtype)
    h = dropout(k_mlp, h, cfg.hidden_dropout_prob, train)
    x = layer_norm(x + h, p["ln_2"]["scale"], p["ln_2"]["bias"], eps=1e-12)
    return _constrain(ctx, x, ("batch", "seq", "embed"))


def encode(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: ErnieConfig,
    *,
    token_type_ids: Optional[jax.Array] = None,
    position_ids: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """-> (sequence_output [b,s,h], pooled_output [b,h]).

    ``attention_mask``: [b, s] with 1 = attend, 0 = padding (reference
    derives it from pad_token_id when absent, single_model.py:241-330)."""
    dtype = jnp.dtype(cfg.dtype)
    b, s = input_ids.shape
    if position_ids is None:
        position_ids = jnp.arange(s, dtype=jnp.int32)[None, :]
    if token_type_ids is None:
        token_type_ids = jnp.zeros((b, s), jnp.int32)
    if attention_mask is None:
        attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.float32)

    k_embed, k_layers = (
        jax.random.split(dropout_key) if dropout_key is not None else (None, None)
    )

    emb = params["embeddings"]
    x = (
        emb["word"].astype(dtype)[input_ids]
        + emb["position"].astype(dtype)[position_ids]
        + emb["token_type"].astype(dtype)[token_type_ids]
    )
    x = layer_norm(x, emb["ln"]["scale"], emb["ln"]["bias"], eps=1e-12)
    x = _constrain(ctx, x, ("batch", "seq", "embed"))
    x = dropout(k_embed, x, cfg.hidden_dropout_prob, train)

    # additive padding bias [b, 1, 1, s]
    bias = (1.0 - attention_mask.astype(jnp.float32)) * -1e9
    bias = bias[:, None, None, :]

    def body(carry, inp):
        params_l, idx = inp
        k = jax.random.fold_in(k_layers, idx) if k_layers is not None else None
        out = _encoder_layer(params_l, carry, bias, cfg, ctx, k, train)
        return out, None

    body_fn = body
    if cfg.use_recompute and cfg.recompute_granularity == "full":
        body_fn = jax.checkpoint(body)
    x, _ = jax.lax.scan(body_fn, x, (params["layers"], jnp.arange(cfg.num_layers)))

    pooled = jnp.tanh(
        x[:, 0] @ params["pooler"]["kernel"].astype(dtype)
        + params["pooler"]["bias"].astype(dtype)
    )
    return x, pooled


def _mlm_transform(params: Dict[str, Any], sequence_output: jax.Array, cfg: ErnieConfig):
    """dense + gelu + LN transform before the tied decoder matmul."""
    dtype = sequence_output.dtype
    p = params["mlm"]
    h = sequence_output @ p["transform_kernel"].astype(dtype) + p["transform_bias"].astype(dtype)
    h = jax.nn.gelu(h, approximate=cfg.gelu_approximate)
    return layer_norm(h, p["ln"]["scale"], p["ln"]["bias"], eps=1e-12)


def pretrain_logits(
    params: Dict[str, Any], sequence_output: jax.Array, pooled: jax.Array, cfg: ErnieConfig,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """-> (mlm logits [b,s,v], nsp logits [b,2] or None)."""
    dtype = sequence_output.dtype
    p = params["mlm"]
    h = _mlm_transform(params, sequence_output, cfg)
    word = params["embeddings"]["word"].astype(dtype)
    logits = jnp.einsum("bsh,vh->bsv", h, word) + p["decoder_bias"].astype(dtype)
    logits = _constrain(ctx, logits, ("batch", "seq", "vocab"))
    nsp = None
    if cfg.binary_head and "nsp" in params:
        nsp = pooled @ params["nsp"]["kernel"].astype(dtype) + params["nsp"]["bias"].astype(dtype)
    return logits, nsp


def _token_ce(logits: jax.Array, labels: jax.Array, ignore_index: int = -1) -> jax.Array:
    """Mean CE over labels != ignore_index, fp32."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (lse - picked) * valid.astype(jnp.float32)
    return jnp.sum(nll) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)


def _pipeline_pretrain_loss(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: ErnieConfig,
    ctx: ShardingCtx,
    dropout_key: Optional[jax.Array],
) -> jax.Array:
    """ERNIE pretrain loss under the 1F1B pipeline schedule (reference
    ErnieForPretrainingPipe, ernie/dygraph/hybrid_model.py:796).

    Unlike causal GPT, every encoder layer needs the padding mask; the
    schedule threads one activation tensor between stages, so the mask
    rides along as an extra trailing feature column ([b, s, h+1]) and each
    stage slices it back off.  Per-microbatch losses are normalized
    microbatch-locally and averaged — the same semantics as the engine's
    gradient-accumulation loop."""
    from paddlefleetx_tpu.parallel.pipeline import (
        interleave_permutation,
        pipeline_loss_1f1b,
    )

    pcfg = ctx.pipeline
    S, V = pcfg.num_stages, pcfg.num_virtual_stages
    C = S * V
    if cfg.num_layers % C:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by {S} stages x {V} virtual"
        )
    pc = cfg.num_layers // C
    dtype = jnp.dtype(cfg.dtype)

    k_embed, k_layers = (
        jax.random.split(dropout_key) if dropout_key is not None else (None, None)
    )

    b, s = batch["input_ids"].shape
    input_ids = batch["input_ids"]
    attention_mask = batch.get("attention_mask")
    if attention_mask is None:
        attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.float32)
    fbatch = {
        "input_ids": input_ids.astype(jnp.float32),
        "token_type_ids": (
            batch.get("token_type_ids")
            if batch.get("token_type_ids") is not None
            else jnp.zeros((b, s), jnp.int32)
        ).astype(jnp.float32),
        "attention_mask": attention_mask.astype(jnp.float32),
        "masked_lm_labels": batch["masked_lm_labels"].astype(jnp.float32),
    }
    if "next_sentence_label" in batch:
        fbatch["next_sentence_label"] = batch["next_sentence_label"].astype(jnp.float32)
    M = pcfg.num_microbatches

    def embed_fn(eparams, mb, mbi):
        ids = mb["input_ids"].astype(jnp.int32)
        tt = mb["token_type_ids"].astype(jnp.int32)
        pos = jnp.arange(s, dtype=jnp.int32)[None, :]
        x = (
            eparams["word"].astype(dtype)[ids]
            + eparams["position"].astype(dtype)[pos]
            + eparams["token_type"].astype(dtype)[tt]
        )
        x = layer_norm(x, eparams["ln"]["scale"], eparams["ln"]["bias"], eps=1e-12)
        k = jax.random.fold_in(k_embed, mbi) if k_embed is not None else None
        x = dropout(k, x, cfg.hidden_dropout_prob, True)
        return jnp.concatenate([x, mb["attention_mask"].astype(dtype)[..., None]], -1)

    def chunk_fn(chunk_params, xm_mb, c, mbi):
        x_mb, mask = xm_mb[..., :-1], xm_mb[..., -1]
        bias = ((1.0 - mask.astype(jnp.float32)) * -1e9)[:, None, None, :]

        def sbody(carry, inp):
            params_l, local_idx = inp
            k = (
                jax.random.fold_in(
                    jax.random.fold_in(k_layers, c * pc + local_idx), mbi
                )
                if k_layers is not None
                else None
            )
            out = _encoder_layer(params_l, carry, bias, cfg, ctx, k, True)
            return out, None

        # same dispatch as encode(): whole-layer checkpoint only for "full"
        # (core_attn's inner checkpoint already lives in _encoder_layer)
        if cfg.use_recompute and cfg.recompute_granularity == "full":
            sbody = jax.checkpoint(sbody)
        x_mb, _ = jax.lax.scan(sbody, x_mb, (chunk_params, jnp.arange(pc)))
        return jnp.concatenate([x_mb, mask[..., None].astype(x_mb.dtype)], -1)

    def head_fn(hparams, ym_mb, mb, mbi):
        y = ym_mb[..., :-1]
        pooled = jnp.tanh(
            y[:, 0] @ hparams["pooler"]["kernel"].astype(y.dtype)
            + hparams["pooler"]["bias"].astype(y.dtype)
        )
        hp = {
            "mlm": hparams["mlm"],
            "embeddings": {"word": hparams["word"]},
        }
        if "nsp" in hparams:
            hp["nsp"] = hparams["nsp"]
        mlm_logits, nsp_logits = pretrain_logits(hp, y, pooled, cfg, ctx)
        from paddlefleetx_tpu.models.common import one_hot_token_nll

        labels_t = mb["masked_lm_labels"].astype(jnp.int32)
        valid = (labels_t != -1).astype(jnp.float32)
        safe = jnp.where(labels_t != -1, labels_t, 0)
        nll = one_hot_token_nll(mlm_logits, safe)
        loss = jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)
        if nsp_logits is not None and "next_sentence_label" in mb:
            nsp = nsp_logits.astype(jnp.float32)
            labels = mb["next_sentence_label"].astype(jnp.int32).reshape(-1)
            nsp_nll = jax.nn.logsumexp(nsp, -1) - jnp.take_along_axis(
                nsp, labels[:, None], axis=-1
            )[:, 0]
            loss = loss + nsp_nll.mean()
        return loss / M

    layers_params = params["layers"]
    if V > 1:
        perm = interleave_permutation(cfg.num_layers, S, V)
        layers_params = jax.tree.map(lambda a: jnp.take(a, perm, axis=0), layers_params)

    hparams = {
        "pooler": params["pooler"],
        "mlm": params["mlm"],
        "word": params["embeddings"]["word"],
    }
    if cfg.binary_head and "nsp" in params:
        hparams["nsp"] = params["nsp"]
    return pipeline_loss_1f1b(
        (embed_fn, chunk_fn, head_fn),
        pcfg,
        ctx.mesh,
        (params["embeddings"], layers_params, hparams),
        fbatch,
    )


def pretrain_loss(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: ErnieConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = True,
) -> jax.Array:
    """batch: input_ids, token_type_ids, attention_mask?, masked_lm_labels
    (-1 for unmasked), next_sentence_label [b] (optional).

    loss = MLM CE + NSP CE (ErniePretrainingCriterion single_model.py:631-644)."""
    if (
        train
        and ctx is not None
        and ctx.pipeline is not None
        and ctx.pipeline.num_stages > 1
    ):
        return _pipeline_pretrain_loss(params, batch, cfg, ctx, dropout_key)
    seq_out, pooled = encode(
        params,
        batch["input_ids"],
        cfg,
        token_type_ids=batch.get("token_type_ids"),
        attention_mask=batch.get("attention_mask"),
        ctx=ctx,
        dropout_key=dropout_key,
        train=train,
    )
    vocab_sharded = False
    if ctx is not None:
        from paddlefleetx_tpu.parallel.mesh import AXIS_MODEL

        vocab_sharded = ctx.mesh.shape.get(AXIS_MODEL, 1) > 1
    if cfg.use_chunked_ce and not vocab_sharded:
        # stream the 40k vocab through the CE (ops/chunked_ce.py); the
        # decoder bias folds in via a ones-column on hidden / bias-column
        # on the tied word matrix, so logits match pretrain_logits exactly
        from paddlefleetx_tpu.ops.chunked_ce import chunked_cross_entropy

        h = _mlm_transform(params, seq_out, cfg)
        ones = jnp.ones(h.shape[:-1] + (1,), h.dtype)
        h1 = jnp.concatenate([h, ones], axis=-1)
        word = params["embeddings"]["word"]
        w1 = jnp.concatenate(
            [word, params["mlm"]["decoder_bias"][:, None].astype(word.dtype)], axis=-1
        )
        labels_t = batch["masked_lm_labels"]
        valid = (labels_t != -1).astype(jnp.float32)
        safe = jnp.where(labels_t != -1, labels_t, 0)
        loss = chunked_cross_entropy(h1, w1, safe, valid, chunk=cfg.ce_chunk_size)
        mlm_logits = None
        _, nsp_logits = (None, None)
        if cfg.binary_head and "nsp" in params:
            dtype = seq_out.dtype
            nsp_logits = (
                pooled @ params["nsp"]["kernel"].astype(dtype)
                + params["nsp"]["bias"].astype(dtype)
            )
    else:
        mlm_logits, nsp_logits = pretrain_logits(params, seq_out, pooled, cfg, ctx)
        loss = _token_ce(mlm_logits, batch["masked_lm_labels"])
    if nsp_logits is not None and "next_sentence_label" in batch:
        nsp = nsp_logits.astype(jnp.float32)
        labels = batch["next_sentence_label"].reshape(-1)
        nsp_nll = jax.nn.logsumexp(nsp, -1) - jnp.take_along_axis(
            nsp, labels[:, None], axis=-1
        )[:, 0]
        loss = loss + jnp.mean(nsp_nll)
    return loss


def cls_forward(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: ErnieConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """Sequence classification logits [b, num_classes]
    (ErnieForSequenceClassification single_model.py:647-700)."""
    k_enc, k_cls = (
        jax.random.split(dropout_key) if dropout_key is not None else (None, None)
    )
    _, pooled = encode(
        params,
        batch["input_ids"],
        cfg,
        token_type_ids=batch.get("token_type_ids"),
        attention_mask=batch.get("attention_mask"),
        ctx=ctx,
        dropout_key=k_enc,
        train=train,
    )
    pooled = dropout(k_cls, pooled, cfg.hidden_dropout_prob, train)
    p = params["cls_head"]
    return pooled @ p["kernel"].astype(pooled.dtype) + p["bias"].astype(pooled.dtype)


def cls_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, -1)
    picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - picked)
