"""ERNIE model family (reference ppfleetx/models/language_model/ernie/)."""

from paddlefleetx_tpu.models.ernie.config import ErnieConfig  # noqa: F401
from paddlefleetx_tpu.models.ernie.model import (  # noqa: F401
    cls_forward,
    cls_loss,
    encode,
    ernie_logical_axes,
    ernie_specs,
    init,
    pretrain_logits,
    pretrain_loss,
)
