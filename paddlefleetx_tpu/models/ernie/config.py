"""ERNIE model configuration (reference ErnieModel kwargs,
ppfleetx/models/language_model/ernie/dygraph/single_model.py:131-241)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    ffn_hidden_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 4
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    pad_token_id: int = 0
    num_classes: int = 2  # sequence-classification head width
    dtype: str = "bfloat16"
    attn_impl: str = "xla"
    # tanh-approx gelu (TPU default); HF/exact-erf checkpoints set False
    gelu_approximate: bool = True
    use_recompute: bool = False
    recompute_granularity: str = "full"
    binary_head: bool = True
    # chunked softmax-CE for the MLM loss (ops/chunked_ce.py); ignored
    # under vocab (model-axis) sharding and in the 1F1B pipeline head
    use_chunked_ce: bool = False
    ce_chunk_size: int = 4096

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_attention_heads == 0
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "ErnieConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @property
    def np_dtype(self):
        return jnp.dtype(self.dtype)
