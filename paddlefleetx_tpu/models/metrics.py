"""Evaluation metrics for finetuning (reference
ppfleetx/models/language_model/metrics.py:31,180,305,445 — AccuracyAndF1,
Mcc, PearsonAndSpearman, MultiLabelsMetric — same update/accumulate/reset
streaming protocol, implemented in numpy on host; predictions stream out of
jitted eval steps as arrays)."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from paddlefleetx_tpu.utils.registry import Registry

METRICS = Registry("metric")


class Metric:
    """Streaming metric: update(preds, labels) per batch; accumulate() -> value(s)."""

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__


@METRICS.register("Accuracy")
class Accuracy(Metric):
    def __init__(self, **_):
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim > 1:
            preds = preds.argmax(-1)
        labels = np.asarray(labels).reshape(preds.shape)
        self._correct += int((preds == labels).sum())
        self._total += preds.size

    def accumulate(self) -> float:
        return self._correct / max(self._total, 1)

    def reset(self):
        self._correct = 0
        self._total = 0


@METRICS.register("AccuracyAndF1")
class AccuracyAndF1(Metric):
    """Binary accuracy + precision/recall/F1 (reference metrics.py:31-178;
    positive class = ``pos_label``).  accumulate() returns
    (acc, precision, recall, f1, (acc+f1)/2) like the reference."""

    def __init__(self, pos_label: int = 1, **_):
        self.pos_label = pos_label
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim > 1:
            preds = preds.argmax(-1)
        labels = np.asarray(labels).reshape(preds.shape)
        pos = preds == self.pos_label
        true = labels == self.pos_label
        self.tp += int((pos & true).sum())
        self.fp += int((pos & ~true).sum())
        self.fn += int((~pos & true).sum())
        self.tn += int((~pos & ~true).sum())

    def accumulate(self) -> Tuple[float, float, float, float, float]:
        total = self.tp + self.fp + self.fn + self.tn
        acc = (self.tp + self.tn) / max(total, 1)
        precision = self.tp / max(self.tp + self.fp, 1)
        recall = self.tp / max(self.tp + self.fn, 1)
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall > 0
            else 0.0
        )
        return acc, precision, recall, f1, (acc + f1) / 2

    def reset(self):
        self.tp = self.fp = self.fn = self.tn = 0


@METRICS.register("Mcc")
class Mcc(Metric):
    """Matthews correlation coefficient (reference metrics.py:180-302)."""

    def __init__(self, **_):
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim > 1:
            preds = preds.argmax(-1)
        labels = np.asarray(labels).reshape(preds.shape)
        pos = preds == 1
        true = labels == 1
        self.tp += int((pos & true).sum())
        self.fp += int((pos & ~true).sum())
        self.fn += int((~pos & true).sum())
        self.tn += int((~pos & ~true).sum())

    def accumulate(self) -> float:
        num = self.tp * self.tn - self.fp * self.fn
        den = (
            (self.tp + self.fp)
            * (self.tp + self.fn)
            * (self.tn + self.fp)
            * (self.tn + self.fn)
        )
        return num / np.sqrt(den) if den > 0 else 0.0

    def reset(self):
        self.tp = self.fp = self.fn = self.tn = 0


@METRICS.register("PearsonAndSpearman")
class PearsonAndSpearman(Metric):
    """Regression correlations (reference metrics.py:305-441).  accumulate()
    -> (pearson, spearman, mean)."""

    def __init__(self, **_):
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds, np.float64).reshape(-1)
        labels = np.asarray(labels, np.float64).reshape(-1)
        self.preds.append(preds)
        self.labels.append(labels)

    def accumulate(self) -> Tuple[float, float, float]:
        p = np.concatenate(self.preds) if self.preds else np.zeros(0)
        l = np.concatenate(self.labels) if self.labels else np.zeros(0)
        if len(p) < 2:
            return 0.0, 0.0, 0.0
        pearson = float(np.corrcoef(p, l)[0, 1])
        spearman = float(np.corrcoef(_rank(p), _rank(l))[0, 1])
        return pearson, spearman, (pearson + spearman) / 2

    def reset(self):
        self.preds = []
        self.labels = []


def _rank(x: np.ndarray) -> np.ndarray:
    """Average ranks (ties get mean rank), matching scipy.stats.rankdata."""
    order = np.argsort(x, kind="stable")
    ranks = np.empty(len(x), np.float64)
    ranks[order] = np.arange(1, len(x) + 1)
    # average tied groups
    sorted_x = x[order]
    i = 0
    while i < len(x):
        j = i
        while j + 1 < len(x) and sorted_x[j + 1] == sorted_x[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = ranks[order[i : j + 1]].mean()
        i = j + 1
    return ranks


@METRICS.register("MultiLabelsMetric")
class MultiLabelsMetric(Metric):
    """Multi-class precision/recall/F1 with micro/macro averaging
    (reference metrics.py:445-688)."""

    def __init__(self, num_labels: int, **_):
        assert num_labels > 1
        self.num_labels = num_labels
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim > 1:
            preds = preds.argmax(-1)
        labels = np.asarray(labels).reshape(preds.shape)
        for c in range(self.num_labels):
            pos = preds == c
            true = labels == c
            self.tp[c] += int((pos & true).sum())
            self.fp[c] += int((pos & ~true).sum())
            self.fn[c] += int((~pos & true).sum())

    def accumulate(self, average: Optional[str] = None, pos_label: int = 1):
        def prf(tp, fp, fn):
            p = tp / max(tp + fp, 1)
            r = tp / max(tp + fn, 1)
            f = 2 * p * r / (p + r) if p + r > 0 else 0.0
            return p, r, f

        if average == "micro":
            return prf(self.tp.sum(), self.fp.sum(), self.fn.sum())
        if average == "macro":
            per = [prf(self.tp[c], self.fp[c], self.fn[c]) for c in range(self.num_labels)]
            arr = np.asarray(per)
            return tuple(arr.mean(0))
        if average is None:
            return prf(self.tp[pos_label], self.fp[pos_label], self.fn[pos_label])
        raise ValueError(f"unknown average {average!r}")

    def reset(self):
        self.tp = np.zeros(self.num_labels, np.int64)
        self.fp = np.zeros(self.num_labels, np.int64)
        self.fn = np.zeros(self.num_labels, np.int64)


def build_metric(cfg) -> Metric:
    cfg = dict(cfg)
    name = cfg.pop("name")
    return METRICS.get(name)(**cfg)


def format_metric(m: Metric) -> Dict[str, float]:
    """Flatten accumulate() output into a {name: value} dict for logging."""
    val = m.accumulate()
    if isinstance(val, dict):
        return {k: float(v) for k, v in val.items()}
    if isinstance(val, tuple):
        if isinstance(m, AccuracyAndF1):
            keys = ("acc", "precision", "recall", "f1", "acc_and_f1")
        elif isinstance(m, PearsonAndSpearman):
            keys = ("pearson", "spearman", "corr")
        else:
            keys = tuple(f"v{i}" for i in range(len(val)))
        return {k: float(v) for k, v in zip(keys, val)}
    return {m.name.lower() if isinstance(m.name, str) else "metric": float(val)}
