"""Shared functional-model machinery.

Models in this framework are pure functions over explicit parameter pytrees.
Each parameter is declared once as a :class:`ParamSpec` carrying its shape,
*logical* sharding axes (see ``parallel.sharding``) and initializer; the same
spec tree yields the init function, the logical-axis tree for pjit, and
abstract shapes for checkpoint restoration.  This replaces the reference's
nn.Layer modules + per-class parallel variants (single_model / hybrid_model /
auto_model triplication) with one definition sharded by annotation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Initializer = Callable[[jax.Array, Tuple[int, ...], Any], jax.Array]


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    shape: Tuple[int, ...]
    logical: Tuple[Optional[str], ...]
    init: Initializer
    dtype: Any = jnp.float32

    def __post_init__(self):
        assert len(self.shape) == len(self.logical), (self.shape, self.logical)


def normal_init(stddev: float) -> Initializer:
    def f(key, shape, dtype):
        return stddev * jax.random.normal(key, shape, dtype)

    return f


def zeros_init() -> Initializer:
    return lambda key, shape, dtype: jnp.zeros(shape, dtype)


def ones_init() -> Initializer:
    return lambda key, shape, dtype: jnp.ones(shape, dtype)


def _is_spec(x: Any) -> bool:
    return isinstance(x, ParamSpec)


def init_params(key: jax.Array, specs: Any) -> Any:
    """Initialize a param pytree from a spec tree (one key fold per leaf)."""
    leaves, treedef = jax.tree.flatten(specs, is_leaf=_is_spec)
    keys = jax.random.split(key, len(leaves))
    arrs = [s.init(k, s.shape, s.dtype) for s, k in zip(leaves, keys)]
    return jax.tree.unflatten(treedef, arrs)


def logical_axes(specs: Any) -> Any:
    """Pytree of logical-axis tuples matching the param pytree."""
    return jax.tree.map(lambda s: s.logical, specs, is_leaf=_is_spec)


def abstract_params(specs: Any) -> Any:
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), specs, is_leaf=_is_spec
    )


def stack_specs(spec: ParamSpec, n: int, axis_name: Optional[str] = "layers") -> ParamSpec:
    """Add a leading stacked dim (for lax.scan-over-layers param layout)."""
    return ParamSpec(
        shape=(n,) + spec.shape,
        logical=(axis_name,) + spec.logical,
        init=_vmap_init(spec.init, n),
        dtype=spec.dtype,
    )


def _vmap_init(init: Initializer, n: int) -> Initializer:
    def f(key, shape, dtype):
        keys = jax.random.split(key, n)
        return jax.vmap(lambda k: init(k, shape[1:], dtype))(keys)

    return f


def stack_spec_tree(specs: Any, n: int, axis_name: Optional[str] = "layers") -> Any:
    return jax.tree.map(
        lambda s: stack_specs(s, n, axis_name), specs, is_leaf=_is_spec
    )


def count_params(params: Any) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def cast_floating(tree: Any, dtype: Any) -> Any:
    """Cast floating leaves (activations/compute copies of params)."""
    def c(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(c, tree)


def dropout(key: Optional[jax.Array], x: jax.Array, rate: float, train: bool) -> jax.Array:
    if not train or rate == 0.0 or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def one_hot_token_nll(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-token negative log-likelihood, fp32, via a one-hot contraction.

    NOT take_along_axis: the scatter transpose of a gather over a
    model-sharded vocab dim trips an XLA partial-manual partitioner CHECK
    inside pipelined shard_maps; the one-hot contraction's transpose is a
    plain (psum-able) broadcast-multiply.  Used by the GPT and ERNIE 1F1B
    pipeline heads."""
    lg = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lg, axis=-1)
    picked = jnp.sum(lg * jax.nn.one_hot(labels, lg.shape[-1], dtype=lg.dtype), -1)
    return lse - picked
