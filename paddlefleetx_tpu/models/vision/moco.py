"""MoCo v1/v2 momentum-contrast pretraining, functional.

Reference: ppfleetx/models/vision_model/moco/moco.py (MoCo :94-246,
MoCoV2Projector :50, MoCoClassifier :70).  Mapping to the functional design:

  base encoder params        -> trainable ``params``
  momentum encoder params    -> ``extra['momentum']`` (EMA-updated, no grads)
  queue / queue_ptr buffers  -> ``extra['queue']`` / ``extra['ptr']``
  BN running stats (both)    -> ``extra['bn']`` / ``extra['bn_m']``

The reference's cross-GPU machinery maps as:
  concat_all_gather (moco.py:35-46)  -> nothing: under pjit the batch IS
    global, so keys enqueued per step are already the full global batch
  _batch_shuffle_ddp (:162-187)      -> dropped: shuffle-BN exists to defeat
    leakage through PER-DEVICE BN statistics; our _batch_norm reduces over
    the full global batch (SimCLR-style "Global BN"), whose statistics are
    permutation-invariant, so a shuffle would be a mathematical no-op and
    the leakage it guards against cannot occur in the first place
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    init_params,
    logical_axes as spec_logical_axes,
    normal_init,
    zeros_init,
)
from paddlefleetx_tpu.models.vision import resnet


@dataclasses.dataclass(frozen=True)
class MoCoConfig:
    depth: int = 50
    dim: int = 128  # output embedding dim
    K: int = 65536  # queue length
    m: float = 0.999  # momentum coefficient
    T: float = 0.07  # softmax temperature
    v2: bool = False  # v2 = extra MLP projector (MoCoV2Projector)
    # loss_fn runs once per micro-batch; with grad accumulation the EMA is
    # applied accumulate_steps times per optimizer step, so use m^(1/accum)
    # per call to keep the per-step momentum exactly m (reference applies it
    # once per step, moco.py:135-144)
    ema_substeps: int = 1
    dtype: Any = jnp.float32

    @classmethod
    def from_config(cls, cfg: Dict[str, Any]) -> "MoCoConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in cfg.items() if k in known}
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        return cls(**kw)

    @property
    def backbone(self) -> resnet.ResNetConfig:
        return resnet.ResNetConfig(depth=self.depth, num_classes=0, dtype=self.dtype)


def _encoder_param_specs(cfg: MoCoConfig) -> Dict[str, Any]:
    f = cfg.backbone.num_features
    specs: Dict[str, Any] = {"backbone": resnet.param_specs(cfg.backbone)}
    if cfg.v2:
        specs["proj"] = {
            "kernel": ParamSpec((f, f), (None, None), normal_init(1.0 / math.sqrt(f))),
            "bias": ParamSpec((f,), (None,), zeros_init()),
        }
    # MoCoClassifier: normal(std=0.01) fc (moco.py:82-86)
    specs["cls"] = {
        "kernel": ParamSpec((f, cfg.dim), (None, None), normal_init(0.01)),
        "bias": ParamSpec((cfg.dim,), (None,), zeros_init()),
    }
    return specs


def param_specs(cfg: MoCoConfig) -> Dict[str, Any]:
    return _encoder_param_specs(cfg)


def extra_specs(cfg: MoCoConfig) -> Dict[str, Any]:
    enc = _encoder_param_specs(cfg)

    def queue_init(key, shape, dtype):
        q = jax.random.normal(key, shape, dtype)  # randn, L2-normalized cols
        return q / jnp.linalg.norm(q, axis=0, keepdims=True)

    return {
        "momentum": enc,  # initialized == base (copied at init, moco.py:124-127)
        "queue": ParamSpec((cfg.dim, cfg.K), (None, None), queue_init),
        "ptr": ParamSpec((), (), lambda k, s, d: jnp.zeros(s, d), dtype=jnp.int32),
        "bn": resnet.state_specs(cfg.backbone),
        "bn_m": resnet.state_specs(cfg.backbone),
    }


def init(cfg: MoCoConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, param_specs(cfg))


def init_extra(cfg: MoCoConfig, key: jax.Array, params: Dict[str, Any]) -> Dict[str, Any]:
    """Momentum branch starts as an exact copy of the base (moco.py:124-127)."""
    extra = init_params(key, extra_specs(cfg))
    extra["momentum"] = jax.tree.map(lambda p: p, params)
    return extra


def _l2_normalize(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Row-normalize with a NaN-SAFE gradient: ``x / (||x|| + eps)``
    differentiates ``||x||`` whose gradient at x == 0 is 0/0 = NaN —
    exactly what a degenerate zero embedding produces (constant images
    through global-batch BN collapse to the zero feature at 1x1 spatial,
    and then one poisoned row NaNs the whole batch's gradient).
    ``x * rsqrt(sum(x^2) + eps)`` is the same map away from zero but its
    gradient at zero is finite (rsqrt(eps) * I)."""
    return x * jax.lax.rsqrt(jnp.sum(x * x, axis=1, keepdims=True) + eps)


def _encode(
    enc_params: Dict[str, Any],
    bn_state: Dict[str, Any],
    images: jax.Array,
    cfg: MoCoConfig,
    train: bool,
) -> Tuple[jax.Array, Dict[str, Any]]:
    feats, new_bn = resnet.features(
        enc_params["backbone"], bn_state, images, cfg.backbone, train=train
    )
    feats = feats.astype(jnp.float32)
    if cfg.v2:
        p = enc_params["proj"]
        feats = jax.nn.relu(feats @ p["kernel"].astype(jnp.float32) + p["bias"])
    c = enc_params["cls"]
    out = feats @ c["kernel"].astype(jnp.float32) + c["bias"]
    return out, new_bn


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: MoCoConfig,
    extra: Dict[str, Any],
    *,
    dropout_key: Optional[jax.Array] = None,
    train: bool = True,
) -> Tuple[jax.Array, Dict[str, Any]]:
    """InfoNCE over (query, key) views (reference forward moco.py:209-246)."""
    img_q, img_k = batch["img_q"], batch["img_k"]
    n = img_q.shape[0]

    # queries
    q, new_bn = _encode(params, extra["bn"], img_q, cfg, train)
    q = _l2_normalize(q)

    # momentum encoder update (EMA, no grad — moco.py:135-144)
    m_eff = cfg.m ** (1.0 / max(cfg.ema_substeps, 1))
    new_momentum = jax.tree.map(
        lambda m, b: m_eff * m + (1.0 - m_eff) * jax.lax.stop_gradient(b),
        extra["momentum"],
        params,
    )

    # keys via momentum encoder. No shuffle-BN (see module docstring):
    # global-batch BN statistics are permutation-invariant.
    k, new_bn_m = _encode(new_momentum, extra["bn_m"], img_k, cfg, train)
    k = jax.lax.stop_gradient(k)
    k = _l2_normalize(k)

    # logits: positives Nx1 against paired key, negatives NxK against queue
    l_pos = jnp.sum(q * k, axis=1, keepdims=True)
    # queue is a buffer, not a parameter: no gradient flows into it
    l_neg = q @ jax.lax.stop_gradient(extra["queue"])
    logits = jnp.concatenate([l_pos, l_neg], axis=1) / cfg.T
    loss = -jnp.mean(jax.nn.log_softmax(logits, axis=-1)[:, 0])

    # dequeue+enqueue at ptr (K % N == 0 keeps slices aligned, moco.py:146-159)
    new_queue = jax.lax.dynamic_update_slice(
        extra["queue"], k.T, (jnp.int32(0), extra["ptr"])
    )
    new_ptr = (extra["ptr"] + n) % cfg.K
    new_extra = {
        "momentum": new_momentum,
        "queue": jax.lax.stop_gradient(new_queue),
        "ptr": new_ptr,
        "bn": new_bn,
        "bn_m": new_bn_m,
    }
    if not train:
        new_extra = extra
    return loss, new_extra


def moco_logical_axes(cfg: MoCoConfig):
    return spec_logical_axes(param_specs(cfg))


def moco_extra_logical_axes(cfg: MoCoConfig):
    return spec_logical_axes(extra_specs(cfg))
