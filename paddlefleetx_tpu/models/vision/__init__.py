"""Vision model family: ResNet backbones, MoCo v1/v2 contrastive pretrain,
vision losses and metrics.

Reference surface: ppfleetx/models/vision_model/{resnet,moco,loss,metrics}
(resnet re-exported from paddle.vision — here implemented natively,
NHWC + XLA convs for the TPU MXU).
"""

from paddlefleetx_tpu.models.vision import loss, metrics, moco, resnet  # noqa: F401
