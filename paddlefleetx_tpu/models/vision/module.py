"""Vision module adapters: MoCo pretrain, MoCo linear probe, ResNet cls.

Reference: ppfleetx/models/vision_model/moco_module.py (MOCOModule :32,
MOCOClsModule :117) and general_classification_module.py.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.core.module import BasicModule, resolve_model_dtype
from paddlefleetx_tpu.models.common import (
    ParamSpec,
    init_params,
    logical_axes as spec_logical_axes,
    normal_init,
    zeros_init,
)
from paddlefleetx_tpu.models.vision import loss as vloss, moco, resnet
from paddlefleetx_tpu.utils.registry import MODULES


def _model_cfg(cfg) -> Dict[str, Any]:
    model_cfg = dict(cfg.Model)
    model_cfg.pop("module", None)
    model_cfg.pop("name", None)
    resolve_model_dtype(cfg, model_cfg)
    return model_cfg


@MODULES.register("MOCOModule")
class MOCOModule(BasicModule):
    """MoCo v1/v2 contrastive pretraining (moco_module.py:32-114)."""

    has_extra_state = True

    def __init__(self, cfg):
        mc = _model_cfg(cfg)
        mc["ema_substeps"] = int(cfg.Engine.get("accumulate_steps", 1))
        self.config = moco.MoCoConfig.from_config(mc)
        gbs = int(cfg.Global.global_batch_size)
        assert self.config.K % gbs == 0, (
            f"queue K={self.config.K} must be divisible by global batch {gbs} "
            "(reference moco.py:153)"
        )
        self.tokens_per_sample = 1  # ips = images/s

    def init_params(self, key):
        return moco.init(self.config, key)

    def init_extra(self, key, params):
        return moco.init_extra(self.config, key, params)

    def logical_axes(self):
        return moco.moco_logical_axes(self.config)

    def extra_logical_axes(self):
        return moco.moco_extra_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, extra=None, dropout_key=None, train=True):
        return moco.loss_fn(
            params, batch, self.config, extra, dropout_key=dropout_key, train=train
        )


@MODULES.register("MOCOClsModule")
class MOCOClsModule(BasicModule):
    """Linear probe on a frozen MoCo backbone (moco_module.py:117-240):
    backbone params + BN stats live in `extra` (never updated, BN uses
    global running stats — _freeze_backbone :144-152); only the fc head
    trains."""

    has_extra_state = True

    def __init__(self, cfg):
        mc = _model_cfg(cfg)
        self.num_classes = int(mc.get("num_classes", 1000))
        self.backbone_cfg = resnet.ResNetConfig.from_config(
            {**mc, "num_classes": 0}
        )
        self.pretrained = mc.get("pretrained")
        f = self.backbone_cfg.num_features
        self._head_specs = {
            "kernel": ParamSpec((f, self.num_classes), (None, None), normal_init(0.01)),
            "bias": ParamSpec((self.num_classes,), (None,), zeros_init()),
        }
        self.tokens_per_sample = 1

    def init_params(self, key):
        return init_params(key, self._head_specs)

    def init_extra(self, key, params):
        return {
            "backbone": init_params(key, resnet.param_specs(self.backbone_cfg)),
            "bn": init_params(key, resnet.state_specs(self.backbone_cfg)),
        }

    def logical_axes(self):
        return spec_logical_axes(self._head_specs)

    def extra_logical_axes(self):
        return {
            "backbone": spec_logical_axes(resnet.param_specs(self.backbone_cfg)),
            "bn": spec_logical_axes(resnet.state_specs(self.backbone_cfg)),
        }

    def post_init_state(self, engine, state):
        """Install the pretrained MoCo base encoder from `Model.pretrained`
        (an Engine checkpoint dir from MOCOModule pretraining; reference
        loads `base_encoder.0.*` weights, moco_module.py:160-180)."""
        if not self.pretrained:
            return state
        import orbax.checkpoint as ocp
        import os

        path = os.path.abspath(self.pretrained)
        assert os.path.exists(path), f"{path} does not exist (moco_module.py:163)"
        restored = ocp.StandardCheckpointer().restore(os.path.join(path, "state"))
        state.extra = dict(state.extra)
        state.extra["backbone"] = jax.tree.map(
            jnp.asarray, restored["params"]["backbone"]
        )
        state.extra["bn"] = jax.tree.map(jnp.asarray, restored["extra"]["bn"])
        return state

    def loss_fn(self, params, batch, *, ctx=None, extra=None, dropout_key=None, train=True):
        feats, _ = resnet.features(
            extra["backbone"], extra["bn"], batch["images"], self.backbone_cfg,
            train=False,  # frozen BN: always global stats
        )
        feats = jax.lax.stop_gradient(feats).astype(jnp.float32)
        logits = feats @ params["kernel"].astype(jnp.float32) + params["bias"]
        loss = vloss.ce_loss(logits, batch["labels"])
        return loss, extra


@MODULES.register("ResNetModule")
class ResNetModule(BasicModule):
    """Supervised ResNet classification (reference resolves resnet through
    GeneralClsModule + vision factory)."""

    has_extra_state = True

    def __init__(self, cfg):
        mc = _model_cfg(cfg)
        self.config = resnet.ResNetConfig.from_config(mc)
        self.label_smoothing = mc.get("label_smoothing")
        self.tokens_per_sample = 1

    def init_params(self, key):
        return init_params(key, resnet.param_specs(self.config))

    def init_extra(self, key, params):
        return init_params(key, resnet.state_specs(self.config))

    def logical_axes(self):
        return spec_logical_axes(resnet.param_specs(self.config))

    def extra_logical_axes(self):
        return spec_logical_axes(resnet.state_specs(self.config))

    def loss_fn(self, params, batch, *, ctx=None, extra=None, dropout_key=None, train=True):
        logits, new_bn = resnet.forward(
            params, extra, batch["images"], self.config, train=train
        )
        loss = vloss.ce_loss(logits, batch["labels"], self.label_smoothing)
        return loss, (new_bn if train else extra)
