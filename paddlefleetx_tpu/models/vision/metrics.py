"""Vision metrics (reference ppfleetx/models/vision_model/metrics/accuracy.py
TopkAcc :19-43 — top-1/top-5 accuracy over logits)."""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp


def topk_acc(
    logits: jax.Array, labels: jax.Array, topk: Sequence[int] = (1, 5)
) -> Dict[str, jax.Array]:
    labels = labels.reshape(-1)
    k_max = max(topk)
    _, pred = jax.lax.top_k(logits, k_max)  # [b, k_max]
    hit = pred == labels[:, None]
    out = {}
    for k in topk:
        out[f"top{k}"] = jnp.mean(jnp.any(hit[:, :k], axis=-1).astype(jnp.float32))
    return out
