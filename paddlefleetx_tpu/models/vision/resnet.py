"""Functional ResNet (18/34/50/101/152) in NHWC for the TPU MXU.

The reference consumes ``paddle.vision.models.resnet`` re-exported through
ppfleetx/models/vision_model/resnet/__init__.py:16-23; behavior matched here:
7x7/2 stem + 3x3/2 maxpool, 4 stages, BasicBlock (<50) / Bottleneck (>=50),
stride-2 downsample convs, global average pool, optional fc head.

BatchNorm running statistics are *state*, not params — threaded through the
engine's ``extra`` slot (Paddle keeps them as buffers).  Batch statistics are
computed over the GLOBAL (sharded) batch: under pjit the mean/var reductions
psum over the data axis, i.e. SyncBN semantics for free.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import ParamSpec, normal_init, ones_init, zeros_init

# depth -> (block kind, per-stage block counts)
ARCHS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}

STAGE_WIDTHS = (64, 128, 256, 512)
BN_MOMENTUM = 0.9  # paddle BatchNorm default momentum


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000  # 0 = feature extractor (no fc)
    in_channels: int = 3
    dtype: Any = jnp.float32

    @classmethod
    def from_config(cls, cfg: Dict[str, Any]) -> "ResNetConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in cfg.items() if k in known}
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        return cls(**kw)

    @property
    def block(self) -> str:
        return ARCHS[self.depth][0]

    @property
    def stage_blocks(self) -> Tuple[int, ...]:
        return ARCHS[self.depth][1]

    @property
    def expansion(self) -> int:
        return 1 if self.block == "basic" else 4

    @property
    def num_features(self) -> int:
        return STAGE_WIDTHS[-1] * self.expansion


def _he_init(fan_out_scale: Tuple[int, ...] = ()) -> Any:
    """Kaiming-normal on fan_out (conv default in paddle.vision resnet)."""

    def f(key, shape, dtype):
        kh, kw, _, cout = shape
        std = math.sqrt(2.0 / (kh * kw * cout))
        return std * jax.random.normal(key, shape, dtype)

    return f


def _conv_spec(kh: int, kw: int, cin: int, cout: int) -> ParamSpec:
    return ParamSpec((kh, kw, cin, cout), (None, None, None, None), _he_init())


def _bn_param_specs(c: int) -> Dict[str, ParamSpec]:
    return {
        "scale": ParamSpec((c,), (None,), ones_init()),
        "bias": ParamSpec((c,), (None,), zeros_init()),
    }


def _bn_state_specs(c: int) -> Dict[str, ParamSpec]:
    return {
        "mean": ParamSpec((c,), (None,), zeros_init()),
        "var": ParamSpec((c,), (None,), ones_init()),
    }


def _block_channels(cfg: ResNetConfig):
    """Yield (cin, width, cout, stride) per block, flattened over stages."""
    cin = 64
    for stage, (width, n) in enumerate(zip(STAGE_WIDTHS, cfg.stage_blocks)):
        for b in range(n):
            stride = 2 if (stage > 0 and b == 0) else 1
            cout = width * cfg.expansion
            yield stage, b, cin, width, cout, stride
            cin = cout


def param_specs(cfg: ResNetConfig) -> Dict[str, Any]:
    specs: Dict[str, Any] = {
        "stem": {"conv": _conv_spec(7, 7, cfg.in_channels, 64), "bn": _bn_param_specs(64)}
    }
    blocks = []
    for stage, b, cin, width, cout, stride in _block_channels(cfg):
        if cfg.block == "basic":
            blk = {
                "conv1": _conv_spec(3, 3, cin, width),
                "bn1": _bn_param_specs(width),
                "conv2": _conv_spec(3, 3, width, cout),
                "bn2": _bn_param_specs(cout),
            }
        else:
            blk = {
                "conv1": _conv_spec(1, 1, cin, width),
                "bn1": _bn_param_specs(width),
                "conv2": _conv_spec(3, 3, width, width),
                "bn2": _bn_param_specs(width),
                "conv3": _conv_spec(1, 1, width, cout),
                "bn3": _bn_param_specs(cout),
            }
        if stride != 1 or cin != cout:
            blk["down_conv"] = _conv_spec(1, 1, cin, cout)
            blk["down_bn"] = _bn_param_specs(cout)
        blocks.append(blk)
    specs["blocks"] = blocks
    if cfg.num_classes:
        f = cfg.num_features
        specs["fc"] = {
            "kernel": ParamSpec(
                (f, cfg.num_classes), ("embed", None), normal_init(1.0 / math.sqrt(f))
            ),
            "bias": ParamSpec((cfg.num_classes,), (None,), zeros_init()),
        }
    return specs


def state_specs(cfg: ResNetConfig) -> Dict[str, Any]:
    specs: Dict[str, Any] = {"stem": {"bn": _bn_state_specs(64)}}
    blocks = []
    for stage, b, cin, width, cout, stride in _block_channels(cfg):
        if cfg.block == "basic":
            blk = {"bn1": _bn_state_specs(width), "bn2": _bn_state_specs(cout)}
        else:
            blk = {
                "bn1": _bn_state_specs(width),
                "bn2": _bn_state_specs(width),
                "bn3": _bn_state_specs(cout),
            }
        if stride != 1 or cin != cout:
            blk["down_bn"] = _bn_state_specs(cout)
        blocks.append(blk)
    specs["blocks"] = blocks
    return specs


# ----------------------------------------------------------------------
def _conv(x: jax.Array, kernel: jax.Array, stride: int, dtype) -> jax.Array:
    kh = kernel.shape[0]
    pad = (kh - 1) // 2
    return jax.lax.conv_general_dilated(
        x.astype(dtype),
        kernel.astype(dtype),
        window_strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batch_norm(
    x: jax.Array,
    p: Dict[str, jax.Array],
    s: Dict[str, jax.Array],
    train: bool,
    eps: float = 1e-5,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    if train:
        mean = jnp.mean(x.astype(jnp.float32), axis=(0, 1, 2))
        var = jnp.var(x.astype(jnp.float32), axis=(0, 1, 2))
        new_s = {
            "mean": BN_MOMENTUM * s["mean"] + (1 - BN_MOMENTUM) * mean,
            "var": BN_MOMENTUM * s["var"] + (1 - BN_MOMENTUM) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jax.lax.rsqrt(var + eps)
    y = (x - mean.astype(x.dtype)) * (inv * p["scale"]).astype(x.dtype) + p[
        "bias"
    ].astype(x.dtype)
    return y, new_s


def features(
    params: Dict[str, Any],
    state: Dict[str, Any],
    images: jax.Array,
    cfg: ResNetConfig,
    train: bool = False,
) -> Tuple[jax.Array, Dict[str, Any]]:
    """images [b, H, W, C] -> pooled features [b, num_features] + new BN state."""
    dtype = cfg.dtype
    new_state: Dict[str, Any] = {"stem": {}, "blocks": []}
    x = _conv(images, params["stem"]["conv"], 2, dtype)
    x, new_state["stem"]["bn"] = _batch_norm(
        x, params["stem"]["bn"], state["stem"]["bn"], train
    )
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x,
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(1, 3, 3, 1),
        window_strides=(1, 2, 2, 1),
        padding=((0, 0), (1, 1), (1, 1), (0, 0)),
    )

    for blk_idx, (stage, b, cin, width, cout, stride) in enumerate(
        _block_channels(cfg)
    ):
        p, s = params["blocks"][blk_idx], state["blocks"][blk_idx]
        ns: Dict[str, Any] = {}
        identity = x
        if cfg.block == "basic":
            y = _conv(x, p["conv1"], stride, dtype)
            y, ns["bn1"] = _batch_norm(y, p["bn1"], s["bn1"], train)
            y = jax.nn.relu(y)
            y = _conv(y, p["conv2"], 1, dtype)
            y, ns["bn2"] = _batch_norm(y, p["bn2"], s["bn2"], train)
        else:
            y = _conv(x, p["conv1"], 1, dtype)
            y, ns["bn1"] = _batch_norm(y, p["bn1"], s["bn1"], train)
            y = jax.nn.relu(y)
            y = _conv(y, p["conv2"], stride, dtype)
            y, ns["bn2"] = _batch_norm(y, p["bn2"], s["bn2"], train)
            y = jax.nn.relu(y)
            y = _conv(y, p["conv3"], 1, dtype)
            y, ns["bn3"] = _batch_norm(y, p["bn3"], s["bn3"], train)
        if "down_conv" in p:
            identity = _conv(x, p["down_conv"], stride, dtype)
            identity, ns["down_bn"] = _batch_norm(
                identity, p["down_bn"], s["down_bn"], train
            )
        x = jax.nn.relu(y + identity)
        new_state["blocks"].append(ns)

    feats = jnp.mean(x, axis=(1, 2))  # global average pool
    return feats, new_state


def forward(
    params: Dict[str, Any],
    state: Dict[str, Any],
    images: jax.Array,
    cfg: ResNetConfig,
    train: bool = False,
) -> Tuple[jax.Array, Dict[str, Any]]:
    """Full classifier forward -> logits [b, num_classes] (fp32) + new state."""
    feats, new_state = features(params, state, images, cfg, train)
    fc = params["fc"]
    logits = feats.astype(jnp.float32) @ fc["kernel"].astype(jnp.float32) + fc["bias"]
    return logits, new_state
