"""Vision losses (reference ppfleetx/models/vision_model/loss/cross_entropy.py).

``ce_loss``  = CELoss: softmax CE, optional label smoothing, accepts int
labels or soft-label distributions (:25-61).
``vit_ce_loss`` = ViTCELoss: sigmoid (binary CE over one-hot) with
ViT-style additive smoothing ``y*(1-eps)+eps`` (:64-93).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _one_hot_if_needed(labels: jax.Array, num_classes: int) -> jax.Array:
    if labels.ndim >= 1 and labels.shape[-1] == num_classes and jnp.issubdtype(
        labels.dtype, jnp.floating
    ):
        return labels
    return jax.nn.one_hot(labels.reshape(-1), num_classes, dtype=jnp.float32)


def ce_loss(
    logits: jax.Array, labels: jax.Array, epsilon: Optional[float] = None
) -> jax.Array:
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    target = _one_hot_if_needed(labels, num_classes)
    if epsilon is not None:
        # paddle F.label_smooth: y*(1-eps) + eps/num_classes
        target = target * (1.0 - epsilon) + epsilon / num_classes
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.mean(-jnp.sum(target * logp, axis=-1))


def vit_ce_loss(
    logits: jax.Array, labels: jax.Array, epsilon: Optional[float] = None
) -> jax.Array:
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    target = _one_hot_if_needed(labels, num_classes)
    if epsilon is not None:
        target = target * (1.0 - epsilon) + epsilon
    per_class = jnp.maximum(logits, 0) - logits * target + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )
    return jnp.mean(jnp.sum(per_class, axis=-1))
