"""HF GPT-2 checkpoint -> native param tree.

The reference ships pretrained-weight download/convert tooling
(utils/download.py + per-model checkpoint loaders); the TPU-native
equivalent imports the ubiquitous HuggingFace GPT-2 format, so a user
switching frameworks can bring standard weights.  Mapping notes:

- HF ``Conv1D`` weights are already [in, out] — no transpose needed.
- ``c_attn`` packs q|k|v along the output dim: [h, 3h] reshapes to
  [h, 3, nh, hd], matching the fused qkv einsum ``bsh,htnd->bstnd``.
- activations (gelu tanh-approx) and LN eps (1e-5) already agree.
- the LM head is tied to the word embedding in both implementations.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from paddlefleetx_tpu.models.gpt.config import GPTConfig


def hf_gpt2_config(hf_cfg, **overrides) -> GPTConfig:
    """GPTConfig from a transformers GPT2Config.

    Raises on variants the native model hardcodes differently — a silent
    convert would produce wrong logits with no error anywhere downstream.
    """
    act = getattr(hf_cfg, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(f"unsupported activation_function {act!r} (need gelu_new)")
    eps = float(getattr(hf_cfg, "layer_norm_epsilon", 1e-5))
    if abs(eps - 1e-5) > 1e-12:
        raise ValueError(f"unsupported layer_norm_epsilon {eps} (model hardcodes 1e-5)")
    n_inner = getattr(hf_cfg, "n_inner", None)
    if n_inner is not None and int(n_inner) != 4 * int(hf_cfg.n_embd):
        raise ValueError(f"unsupported n_inner {n_inner} (need 4*n_embd)")
    if getattr(hf_cfg, "scale_attn_by_inverse_layer_idx", False):
        raise ValueError("scale_attn_by_inverse_layer_idx not supported")
    if getattr(hf_cfg, "reorder_and_upcast_attn", False):
        raise ValueError("reorder_and_upcast_attn not supported")
    kw = dict(
        vocab_size=int(hf_cfg.vocab_size),
        hidden_size=int(hf_cfg.n_embd),
        num_layers=int(hf_cfg.n_layer),
        num_attention_heads=int(hf_cfg.n_head),
        max_position_embeddings=int(hf_cfg.n_positions),
    )
    kw.update(overrides)
    return GPTConfig(**kw)


def convert_hf_gpt2_state_dict(
    sd: Dict[str, "np.ndarray"], cfg: GPTConfig, pad_vocab_to: Optional[int] = None
) -> Dict:
    """torch/HF ``GPT2LMHeadModel.state_dict()`` -> stacked param tree.

    ``sd`` values may be torch tensors or numpy arrays.  ``pad_vocab_to``
    grows the embedding with zero rows (MXU-friendly multiples of 128); the
    model config must then use the padded vocab_size.
    """

    from paddlefleetx_tpu.models.convert_common import make_getter, make_stacker

    get = make_getter(sd)

    h, L = cfg.hidden_size, cfg.num_layers
    nh, hd = cfg.num_attention_heads, cfg.head_dim

    word = get("transformer.wte.weight").astype(np.float32)
    if pad_vocab_to is not None:
        if pad_vocab_to < word.shape[0]:
            raise ValueError(f"pad_vocab_to {pad_vocab_to} < vocab {word.shape[0]}")
        pad = np.zeros((pad_vocab_to - word.shape[0], h), np.float32)
        word = np.concatenate([word, pad], axis=0)
    if word.shape[0] != cfg.vocab_size:
        raise ValueError(
            f"config vocab_size {cfg.vocab_size} != embedding rows {word.shape[0]}"
        )

    stack = make_stacker(get, L)

    params = {
        "embeddings": {
            "word": word,
            "position": get("transformer.wpe.weight").astype(np.float32),
        },
        "layers": {
            "ln_1": {
                "scale": stack("transformer.h.{i}.ln_1.weight"),
                "bias": stack("transformer.h.{i}.ln_1.bias"),
            },
            "attn": {
                "qkv_kernel": stack("transformer.h.{i}.attn.c_attn.weight", (h, 3, nh, hd)),
                "qkv_bias": stack("transformer.h.{i}.attn.c_attn.bias", (3, nh, hd)),
                "out_kernel": stack("transformer.h.{i}.attn.c_proj.weight", (nh, hd, h)),
                "out_bias": stack("transformer.h.{i}.attn.c_proj.bias"),
            },
            "ln_2": {
                "scale": stack("transformer.h.{i}.ln_2.weight"),
                "bias": stack("transformer.h.{i}.ln_2.bias"),
            },
            "mlp": {
                "fc_in_kernel": stack("transformer.h.{i}.mlp.c_fc.weight"),
                "fc_in_bias": stack("transformer.h.{i}.mlp.c_fc.bias"),
                "fc_out_kernel": stack("transformer.h.{i}.mlp.c_proj.weight"),
                "fc_out_bias": stack("transformer.h.{i}.mlp.c_proj.bias"),
            },
        },
        "final_ln": {
            "scale": get("transformer.ln_f.weight").astype(np.float32),
            "bias": get("transformer.ln_f.bias").astype(np.float32),
        },
    }
    return params
