"""Mixture-of-Experts FFN: gating, capacity, dispatch/combine.

TPU-native consolidation of the reference's TWO MoE stacks
(fastmoe-style ``MoELayer`` models/language_model/moe/ — alltoall
MoEScatter/MoEGather + per-expert loop; deepspeed-style ``moe_exp/``
sharded_moe.py:300-379 — TopKGate with capacity factor, token dropping,
load-balance aux loss): one fixed-capacity dense formulation.

Shape discipline (SURVEY §7.3: "MoE capacity/token-drop numerics under jit
need a fixed-capacity dense formulation"): dispatch/combine are dense
[tokens, experts, capacity] einsum masks — no dynamic shapes; dropped
tokens fall out of the mask.  The expert dim is sharded over the expert
group (``data``×``fsdp``×``sep``, mirroring HybridCommGroupForMoE's fused
dp×mp group, comm_groups.py:149-153), so XLA inserts the alltoall the
reference issues manually in MoEScatter/MoEGather (moe/comm_ops.py:28,74).

Gates: ``naive`` (top-k renormalised, no aux), ``gshard`` (top-2 +
load-balance aux), ``switch`` (top-1 + aux) — reference gate/*.py and
sharded_moe.py TopKGate.

Grad-clip parity note: the reference needs ``ClipGradForMOEByGlobalNorm``
(optims/grad_clip.py:27-156) to allreduce expert-param norms over the moe
group because expert params differ per rank; under GSPMD the param pytree
is global, so plain optax.clip_by_global_norm already computes the same
global norm.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import ParamSpec, normal_init, zeros_init


def moe_layer_specs(cfg) -> Dict[str, Any]:
    """Expert-parallel FFN param specs (drop-in for the dense 'mlp' subtree)."""
    h, ffn, E = cfg.hidden_size, cfg.ffn_hidden_size, cfg.num_experts
    w = normal_init(cfg.initializer_range)
    return {
        "gate_kernel": ParamSpec((h, E), ("embed", None), w),
        "fc_in_kernel": ParamSpec((E, h, ffn), ("expert", "embed", "mlp"), w),
        "fc_in_bias": ParamSpec((E, ffn), ("expert", "mlp"), zeros_init()),
        "fc_out_kernel": ParamSpec((E, ffn, h), ("expert", "mlp", "embed"), w),
        "fc_out_bias": ParamSpec((E, h), ("expert", "embed"), zeros_init()),
    }


def _top_k_positions(expert_mask: jax.Array) -> jax.Array:
    """Position of each (token, choice) inside its expert's capacity buffer.

    expert_mask: [N, k, E] one-hot.  Rank-0 choices get priority over rank-1
    (GShard policy): positions count down the flattened (k-major) order.
    Returns [N, k, E] int positions (-1 where not dispatched)."""
    n, k, e = expert_mask.shape
    flat = expert_mask.transpose(1, 0, 2).reshape(k * n, e)
    pos_flat = jnp.cumsum(flat, axis=0) * flat - 1  # -1 where mask==0
    return pos_flat.reshape(k, n, e).transpose(1, 0, 2).astype(jnp.int32)


def effective_top_k(gate_type: str, top_k: int) -> int:
    """switch is top-1 and gshard top-2 by definition (reference gate/*.py)."""
    return {"switch": 1, "gshard": 2}.get(gate_type, top_k)


def gate_and_dispatch(
    x: jax.Array,  # [N, h] tokens
    gate_logits: jax.Array,  # [N, E]
    num_experts: int,
    top_k: int,
    capacity: int,
    gate_type: str,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """-> (combine [N, E, C], dispatch bool [N, E, C], aux_loss scalar)."""
    n = x.shape[0]
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_k = effective_top_k(gate_type, top_k)

    top_w, top_idx = jax.lax.top_k(probs, top_k)  # [N, k]
    if gate_type in ("gshard", "switch"):
        # load-balance aux (GShard eq.: E * sum_e fraction_tokens_e * mean_prob_e)
        top1_mask = jax.nn.one_hot(top_idx[:, 0], num_experts)
        density = top1_mask.mean(axis=0)
        density_proxy = probs.mean(axis=0)
        aux = num_experts * jnp.sum(density * density_proxy)
    else:
        aux = jnp.zeros((), jnp.float32)

    if top_k > 1:
        # renormalise among chosen experts (GShard top-2)
        top_w = top_w / jnp.maximum(top_w.sum(axis=-1, keepdims=True), 1e-9)
    # top-1 (switch) keeps the RAW gate prob: scaling the expert output by it
    # is the router's only task-loss gradient path (Switch Transformer)

    expert_mask = jax.nn.one_hot(top_idx, num_experts, dtype=jnp.float32)  # [N,k,E]
    pos = _top_k_positions(expert_mask)  # [N,k,E]
    keep = (pos >= 0) & (pos < capacity)
    pos = jnp.where(keep, pos, 0)

    cap_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [N,k,E,C]
    cap_onehot = cap_onehot * keep[..., None] * expert_mask[..., None]
    combine = jnp.einsum("nk,nkec->nec", top_w, cap_onehot)
    dispatch = combine > 0
    return combine, dispatch, aux


def moe_mlp_block(
    p: Dict[str, Any],
    x: jax.Array,  # [b, s, h]
    cfg,
    ctx,
    key,
    train: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel FFN.  Returns (out [b,s,h], aux loss scalar)."""
    from paddlefleetx_tpu.models.common import dropout
    from paddlefleetx_tpu.models.gpt.model import _constrain

    dtype = x.dtype
    b, s, h = x.shape
    E = cfg.num_experts
    k = effective_top_k(cfg.moe_gate, cfg.moe_top_k)
    tokens = x.reshape(b * s, h)
    n = b * s
    capacity = max(int(math.ceil(n * k * cfg.moe_capacity_factor / E)), 4)

    gate_logits = tokens.astype(jnp.float32) @ p["gate_kernel"].astype(jnp.float32)
    combine, dispatch, aux = gate_and_dispatch(
        tokens, gate_logits, E, k, capacity, cfg.moe_gate
    )

    # dispatch: [E, C, h] expert inputs (alltoall inserted by XLA when the
    # expert axis sharding differs from the token axis sharding)
    expert_in = jnp.einsum("nec,nh->ech", dispatch.astype(dtype), tokens)
    expert_in = _constrain(ctx, expert_in, ("expert", None, "embed"))

    def ffn(e_in, kern_in, b_in, kern_out, b_out):
        y = e_in @ kern_in.astype(dtype) + b_in.astype(dtype)
        y = jax.nn.gelu(y, approximate=True)
        return y @ kern_out.astype(dtype) + b_out.astype(dtype)

    expert_out = jax.vmap(ffn)(
        expert_in,
        p["fc_in_kernel"],
        p["fc_in_bias"],
        p["fc_out_kernel"],
        p["fc_out_bias"],
    )
    expert_out = _constrain(ctx, expert_out, ("expert", None, "embed"))

    out = jnp.einsum("nec,ech->nh", combine.astype(dtype), expert_out)
    out = out.reshape(b, s, h)
    out = dropout(key, out, cfg.hidden_dropout_prob, train)
    return out, aux.astype(jnp.float32)
