"""GPT model hyperparameter config.

Field vocabulary matches the reference's GPT YAML ``Model`` block
(ppfleetx/configs/nlp/gpt/pretrain_gpt_base.yaml and
models/language_model/gpt/dygraph/single_model.py:608 ``GPTModel.__init__``),
so reference configs translate 1:1.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_attention_heads: int = 16
    ffn_hidden_size: Optional[int] = None  # defaults to 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    # recompute (reference recompute_granularity full/full_attn/core_attn,
    # single_model.py:320-405; "selective" is TPU-native: saves the expensive
    # matmul outputs by name and recomputes only cheap elementwise ops)
    use_recompute: bool = False
    recompute_granularity: str = "full"
    # comma-separated checkpoint names kept live under "selective"
    # (qkv | attn_out | attn_lse | mlp_hidden); empty = measured-best default
    recompute_names: str = ""
    # fused LayerNorm Pallas kernel (ops/fused_layernorm.py) instead of the
    # jnp composite (reference consumes paddle fused norm ops, vit.py:23-115)
    use_fused_ln: bool = False
    # chunked softmax-CE (ops/chunked_ce.py): streams the vocab so the
    # [b,s,V] fp32 logits buffer never materializes — the HBM lever for
    # bigger per-chip batches.  Ignored under vocab (model-axis) sharding
    # (the GSPMD path owns that reduction) and under pipeline parallelism
    # (the 1F1B head computes per-microbatch logits, already 1/M the size).
    use_chunked_ce: bool = False
    ce_chunk_size: int = 4096
    # fused qkv projection (reference fuse_attn_qkv, hybrid_model.py:153)
    fuse_attn_qkv: bool = True
    # attention implementation: "xla" (jnp reference) | "flash" (Pallas kernel)
    attn_impl: str = "xla"
    # flash kernel tile size (0 = auto: PFX_FLASH_BLOCK env, else the
    # measured-best ladder in ops/flash_attention._block_sizes)
    flash_block: int = 0
    # flash backward schedule: "" = auto (PFX_FLASH_BWD env, else "split");
    # "fused" = single-kernel dq+dk+dv (computes each softmax tile once)
    flash_bwd: str = ""
    # unroll factor for the scan over layers (lax.scan unroll=N): trades
    # compile time + code size for removing the scan-boundary stacking
    # copies the profiler shows at ~4% of step time (chip_day op table).
    # 1 = rolled (default); must divide num_layers
    scan_unroll: int = 1
    # ring attention inner K-block (attn_impl="ring"): bounds the per-ring-
    # step score buffer to [s_local, ring_chunk_k]; 0 = unchunked
    ring_chunk_k: int = 1024
    # Megatron sequence parallelism: activations sharded on seq over `model`
    sequence_parallel: bool = False
    # compute dtype for activations (params/optimizer stay fp32)
    dtype: str = "bfloat16"
    # MoE (0 or 1 = dense; >1 enables expert-parallel FFN, reference
    # single_model.py:480-492 num_experts)
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.2
    moe_gate: str = "gshard"  # naive | gshard | switch
    moe_aux_loss_weight: float = 0.01

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            object.__setattr__(self, "ffn_hidden_size", 4 * self.hidden_size)
        if self.hidden_size % self.num_attention_heads:
            raise ValueError("num_attention_heads must divide hidden_size")
        if self.recompute_granularity not in ("full", "selective", "full_attn", "core_attn"):
            raise ValueError(f"bad recompute_granularity {self.recompute_granularity}")
        raw = self.recompute_names
        parts = raw if isinstance(raw, (list, tuple)) else str(raw).split(",")
        names = tuple(str(n).strip() for n in parts if str(n).strip())
        bad = set(names) - {"qkv", "attn_out", "attn_lse", "mlp_hidden"}
        if bad:
            raise ValueError(
                f"bad recompute_names {sorted(bad)}; "
                "valid: qkv, attn_out, attn_lse, mlp_hidden"
            )
        if names and self.recompute_granularity != "selective":
            raise ValueError(
                "recompute_names only applies to recompute_granularity='selective'"
            )
        if self.scan_unroll < 1 or self.num_layers % self.scan_unroll:
            raise ValueError(
                f"scan_unroll {self.scan_unroll} must be >=1 and divide "
                f"num_layers {self.num_layers}"
            )
        if self.flash_bwd not in ("", "split", "fused"):
            raise ValueError(
                f"flash_bwd {self.flash_bwd!r}; valid: '' (auto), split, fused"
            )
        object.__setattr__(self, "recompute_names", ",".join(names))

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def recompute_name_tuple(self) -> Tuple[str, ...]:
        """Normalized selective-remat save-set; empty = measured-best default."""
        return tuple(n for n in self.recompute_names.split(",") if n)

    @staticmethod
    def from_config(model_cfg) -> "GPTConfig":
        """Build from a YAML ``Model`` section (unknown keys ignored)."""
        fields = {f.name for f in dataclasses.fields(GPTConfig)}
        kwargs = {k: v for k, v in dict(model_cfg).items() if k in fields}
        return GPTConfig(**kwargs)


# Reference model sizes (projects/gpt/docs, configs/nlp/gpt/*.yaml)
PRESETS = {
    "gpt-345M": dict(hidden_size=1024, num_layers=24, num_attention_heads=16),
    "gpt-1.3B": dict(hidden_size=2048, num_layers=24, num_attention_heads=16),
    "gpt-6.7B": dict(hidden_size=4096, num_layers=32, num_attention_heads=32),
    "gpt-13B": dict(hidden_size=5120, num_layers=40, num_attention_heads=40),
    "gpt-175B": dict(hidden_size=12288, num_layers=96, num_attention_heads=96),
}


def preset(name: str, **overrides) -> GPTConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name}; known: {sorted(PRESETS)}")
    return GPTConfig(**{**PRESETS[name], **overrides})
