"""GPT decoder-only LM — pure-JAX functional, sharded by annotation.

One model definition covers the reference's four GPT variants (single-device
``GPTModel`` single_model.py:608, TP/SP ``GPTModelHybrid`` hybrid_model.py:739,
pipeline ``GPTForPretrainingPipe`` hybrid_model.py:1055, auto-parallel
``GPTModelAuto`` auto_model.py:514): parallelism comes from the logical-axis
annotations on :func:`gpt_specs` + the active sharding rules, not from
separate classes.

Architecture (matches reference GPTModel): learned word+position embeddings,
pre-LayerNorm transformer decoder blocks (fused-qkv attention, gelu MLP),
final LayerNorm, logits via tied word-embedding matmul
(``parallel_matmul``, hybrid_model.py:66-87), masked-mean token
cross-entropy (``GPTPretrainingCriterion`` single_model.py:819).

Layers are stacked on a leading ``layers`` axis and executed with
``lax.scan`` (compile-time O(1) in depth; the ``layers`` axis is what
pipeline stage-sharding partitions).  Recompute granularities full /
full_attn / core_attn (reference single_model.py:320-405) map to
``jax.checkpoint`` placement.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    dropout,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    stack_spec_tree,
    zeros_init,
)
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.ops.attention import attention


@dataclasses.dataclass(frozen=True)
class ShardingCtx:
    """Optional activation-sharding context (mesh + logical rules).

    ``pipeline`` switches the transformer stack from plain scan-over-layers
    to the stage-pipelined schedule (parallel/pipeline.py)."""

    mesh: Any
    rules: Tuple[Tuple[str, Any], ...]
    pipeline: Any = None  # Optional[PipelineConfig]
    # global token positions of the (possibly permuted) sequence, [s];
    # consumed by ring attention so balanced layouts (zigzag_permutation)
    # mask causally by TRUE token order.  None = contiguous arange.
    attn_positions: Any = None

    def constrain(self, x: jax.Array, logical: Tuple[Optional[str], ...]) -> jax.Array:
        from paddlefleetx_tpu.parallel.sharding import with_logical_constraint

        return with_logical_constraint(x, logical, self.rules, self.mesh)


def _constrain(ctx: Optional[ShardingCtx], x: jax.Array, logical) -> jax.Array:
    return ctx.constrain(x, logical) if ctx is not None else x


# ---------------------------------------------------------------------------
# Parameter specs
# ---------------------------------------------------------------------------


def _layer_specs(cfg: GPTConfig) -> Dict[str, Any]:
    h, nh, hd, ffn = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim, cfg.ffn_hidden_size
    w = normal_init(cfg.initializer_range)
    specs: Dict[str, Any] = {
        "ln_1": {
            "scale": ParamSpec((h,), ("embed",), ones_init()),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "attn": {
            "qkv_kernel": ParamSpec((h, 3, nh, hd), ("embed", None, "heads", "kv"), w),
            "qkv_bias": ParamSpec((3, nh, hd), (None, "heads", "kv"), zeros_init()),
            "out_kernel": ParamSpec((nh, hd, h), ("heads", "kv", "embed"), w),
            "out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "ln_2": {
            "scale": ParamSpec((h,), ("embed",), ones_init()),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "mlp": {
            "fc_in_kernel": ParamSpec((h, ffn), ("embed", "mlp"), w),
            "fc_in_bias": ParamSpec((ffn,), ("mlp",), zeros_init()),
            "fc_out_kernel": ParamSpec((ffn, h), ("mlp", "embed"), w),
            "fc_out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
    }
    if cfg.num_experts > 1:
        from paddlefleetx_tpu.models.gpt.moe import moe_layer_specs

        specs["mlp"] = moe_layer_specs(cfg)
    return specs


def gpt_specs(cfg: GPTConfig) -> Dict[str, Any]:
    w = normal_init(cfg.initializer_range)
    return {
        "embeddings": {
            "word": ParamSpec((cfg.vocab_size, cfg.hidden_size), ("vocab", "embed"), w),
            "position": ParamSpec(
                (cfg.max_position_embeddings, cfg.hidden_size), ("table", "embed"), w
            ),
        },
        "layers": stack_spec_tree(_layer_specs(cfg), cfg.num_layers),
        "final_ln": {
            "scale": ParamSpec((cfg.hidden_size,), ("embed",), ones_init()),
            "bias": ParamSpec((cfg.hidden_size,), ("embed",), zeros_init()),
        },
    }


def init(cfg: GPTConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, gpt_specs(cfg))


def gpt_logical_axes(cfg: GPTConfig) -> Dict[str, Any]:
    return logical_axes(gpt_specs(cfg))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def layer_norm(
    x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5, fused: bool = False
):
    if fused:
        from paddlefleetx_tpu.ops.fused_layernorm import fused_layer_norm

        return fused_layer_norm(x, scale, bias, eps=eps)
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(dtype)


def _layer_remat(cfg: GPTConfig, fn):
    """Wrap a per-layer scan body in jax.checkpoint per recompute granularity.

    "full" saves only layer-boundary activations (reference recompute
    single_model.py:320-405); "selective" additionally saves a tunable set
    of named activations (default qkv + attn_out + attn_lse) so the
    backward pass skips the expensive recomputes — the TPU-native middle
    ground the reference lacks."""
    if not cfg.use_recompute:
        return fn
    if cfg.recompute_granularity == "full":
        return jax.checkpoint(fn)
    if cfg.recompute_granularity == "selective":
        # The save-set trades HBM residency+traffic against recompute FLOPs;
        # qkv+attn_out+attn_lse measured fastest on v5e (saving mlp_hidden
        # costs 3GB of HBM round-trips per step for a 0.7ms matmul re-run)
        names = cfg.recompute_name_tuple or ("qkv", "attn_out", "attn_lse")
        if cfg.attn_impl == "flash" and "attn_out" in names and "attn_lse" not in names:
            # on the flash path the attention residual is the kernel's lse,
            # not the (primal) output — honor the user's "save attention"
            # intent instead of silently saving nothing
            names = names + ("attn_lse",)
        policy = jax.checkpoint_policies.save_only_these_names(*names)
        return jax.checkpoint(fn, policy=policy)
    return fn


def _attention_block(
    p: Dict[str, Any],
    x: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx],
    key: Optional[jax.Array],
    train: bool,
) -> jax.Array:
    """Fused-qkv causal self-attention.  x: [b, s, h] -> [b, s, h]."""
    dtype = x.dtype
    k_attn, k_resid = (jax.random.split(key) if key is not None else (None, None))

    # qkv: [b, s, 3, nh, hd]  (column-parallel: nh sharded over `model`)
    qkv = jnp.einsum("bsh,htnd->bstnd", x, p["qkv_kernel"].astype(dtype))
    qkv = qkv + p["qkv_bias"].astype(dtype)[None, None]
    qkv = checkpoint_name(qkv, "qkv")
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    if cfg.attn_impl == "ring" and ctx is not None:
        # context parallelism: seq stays sep-sharded; K/V rotate the ring.
        # NB: attention-prob dropout is skipped here, like the flash path
        # (reference disables dropout under flash too, hybrid_model.py:284)
        from paddlefleetx_tpu.parallel.ring_attention import ring_attention

        q = _constrain(ctx, q, ("batch", "seq", "heads", "kv"))
        chunk_k = int(getattr(cfg, "ring_chunk_k", 1024)) or None
        pos = ctx.attn_positions
        if cfg.use_recompute and cfg.recompute_granularity == "core_attn":
            ring = jax.checkpoint(
                lambda q, k, v, mesh=ctx.mesh: ring_attention(
                    q, k, v, mesh, causal=True, chunk_k=chunk_k, positions=pos
                )
            )
            out = ring(q, k, v)
        else:
            out = ring_attention(
                q, k, v, ctx.mesh, causal=True, chunk_k=chunk_k, positions=pos
            )
        out = checkpoint_name(out, "attn_out")
        out = jnp.einsum("bsnd,ndh->bsh", out, p["out_kernel"].astype(dtype))
        out = out + p["out_bias"].astype(dtype)
        return dropout(k_resid, out, cfg.hidden_dropout_prob, train)

    # Ulysses/TP reshard: heads spread over (model, sep), seq gathered
    q = _constrain(ctx, q, ("batch", None, "heads", "kv"))

    def core(q, k, v, dk):
        return attention(
            q,
            k,
            v,
            impl=cfg.attn_impl,
            causal=True,
            dropout_key=dk,
            dropout_rate=cfg.attention_probs_dropout_prob,
            train=train,
            flash_block=cfg.flash_block,
            flash_bwd=cfg.flash_bwd,
        )

    if cfg.use_recompute and cfg.recompute_granularity == "core_attn":
        core = jax.checkpoint(core, static_argnums=())
    out = core(q, k, v, k_attn)  # [b, s, nh, hd]

    # row-parallel output projection: contraction over sharded heads -> psum
    out = jnp.einsum("bsnd,ndh->bsh", out, p["out_kernel"].astype(dtype))
    out = out + p["out_bias"].astype(dtype)
    out = dropout(k_resid, out, cfg.hidden_dropout_prob, train)
    return out


def _mlp_block(
    p: Dict[str, Any],
    x: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx],
    key: Optional[jax.Array],
    train: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (out, moe_aux_loss); aux is 0 for the dense FFN."""
    if cfg.num_experts > 1:
        from paddlefleetx_tpu.models.gpt.moe import moe_mlp_block

        return moe_mlp_block(p, x, cfg, ctx, key, train)
    dtype = x.dtype
    h = x @ p["fc_in_kernel"].astype(dtype) + p["fc_in_bias"].astype(dtype)
    h = _constrain(ctx, h, ("batch", None, "mlp"))
    h = checkpoint_name(h, "mlp_hidden")
    h = jax.nn.gelu(h, approximate=True)
    h = h @ p["fc_out_kernel"].astype(dtype) + p["fc_out_bias"].astype(dtype)
    h = dropout(key, h, cfg.hidden_dropout_prob, train)
    return h, jnp.zeros((), jnp.float32)


def _decoder_layer(
    p: Dict[str, Any],
    x: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx],
    key: Optional[jax.Array],
    train: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Pre-LN decoder block (reference TransformerDecoderLayer
    single_model.py:406: x + attn(ln(x)); x + mlp(ln(x)))."""
    k_attn, k_mlp = (jax.random.split(key) if key is not None else (None, None))

    def attn_part(p, x, k):
        y = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"], fused=cfg.use_fused_ln)
        y = _constrain(ctx, y, ("batch", "seq", "embed"))
        return _attention_block(p["attn"], y, cfg, ctx, k, train)

    if cfg.use_recompute and cfg.recompute_granularity == "full_attn":
        attn_part = jax.checkpoint(attn_part)

    x = x + attn_part(p, x, k_attn)
    x = _constrain(ctx, x, ("batch", "seq", "embed"))

    y = layer_norm(x, p["ln_2"]["scale"], p["ln_2"]["bias"], fused=cfg.use_fused_ln)
    y, aux = _mlp_block(p["mlp"], y, cfg, ctx, k_mlp, train)
    x = x + y
    return _constrain(ctx, x, ("batch", "seq", "embed")), aux


def transformer_stack(
    layers_params: Dict[str, Any],
    x: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx],
    key: Optional[jax.Array],
    train: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Stacked-layer body: lax.scan (accumulating MoE aux losses), or the
    stage pipeline when enabled.  Returns (hidden, aux_loss_sum)."""

    if ctx is not None and ctx.pipeline is not None and ctx.pipeline.num_stages > 1:
        if cfg.num_experts > 1:
            # reference parity: MoE requires pp==1 (HybridCommGroupForMoE
            # asserts, comm_groups.py:150)
            raise NotImplementedError("MoE with pipeline parallelism unsupported")
        from paddlefleetx_tpu.parallel.pipeline import pipelined_stack

        S = ctx.pipeline.num_stages
        if cfg.num_layers % S:
            raise ValueError(f"num_layers {cfg.num_layers} not divisible by stages {S}")
        per_stage = cfg.num_layers // S

        def stage_fn(local_params, x_mb, stage, mb):
            def sbody(carry, inp):
                params_l, local_idx = inp
                # dropout key folds on the GLOBAL layer index AND the
                # microbatch index — each microbatch must draw its own mask
                k = (
                    jax.random.fold_in(
                        jax.random.fold_in(key, stage * per_stage + local_idx), mb
                    )
                    if key is not None
                    else None
                )
                out, _aux = _decoder_layer(params_l, carry, cfg, ctx, k, train)
                return out, None

            sbody_fn = _layer_remat(cfg, sbody)
            x_mb, _ = jax.lax.scan(
                sbody_fn, x_mb, (local_params, jnp.arange(per_stage))
            )
            return x_mb

        return (
            pipelined_stack(stage_fn, layers_params, x, ctx.pipeline, ctx.mesh),
            jnp.zeros((), jnp.float32),
        )

    def body(carry, inp):
        x, aux_sum = carry
        params_l, idx = inp
        k = jax.random.fold_in(key, idx) if key is not None else None
        out, aux = _decoder_layer(params_l, x, cfg, ctx, k, train)
        return (out, aux_sum + aux), None

    body_fn = _layer_remat(cfg, body)

    (x, aux), _ = jax.lax.scan(
        body_fn,
        (x, jnp.zeros((), jnp.float32)),
        (layers_params, jnp.arange(cfg.num_layers)),
        unroll=cfg.scan_unroll,
    )
    return x, aux


def _embed(
    params: Dict[str, Any],
    input_ids: jax.Array,
    position_ids: Optional[jax.Array],
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx],
    key: Optional[jax.Array],
    train: bool,
) -> jax.Array:
    """Word + position embedding with embedding dropout -> [b, s, h]."""
    dtype = jnp.dtype(cfg.dtype)
    s = input_ids.shape[1]
    if position_ids is None:
        position_ids = jnp.arange(s, dtype=jnp.int32)[None, :]
    word = params["word"].astype(dtype)
    pos = params["position"].astype(dtype)
    x = word[input_ids] + pos[position_ids]
    x = _constrain(ctx, x, ("batch", "seq", "embed"))
    return dropout(key, x, cfg.hidden_dropout_prob, train)


def forward_hidden(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: GPTConfig,
    *,
    position_ids: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Token ids [b, s] -> (final hidden [b, s, h], moe aux loss sum)."""
    k_embed, k_layers = (
        jax.random.split(dropout_key) if dropout_key is not None else (None, None)
    )
    x = _embed(params["embeddings"], input_ids, position_ids, cfg, ctx, k_embed, train)

    x, aux = transformer_stack(params["layers"], x, cfg, ctx, k_layers, train)
    x = layer_norm(
        x, params["final_ln"]["scale"], params["final_ln"]["bias"], fused=cfg.use_fused_ln
    )
    return _constrain(ctx, x, ("batch", "seq", "embed")), aux


def logits_from_hidden(
    params: Dict[str, Any], hidden: jax.Array, ctx: Optional[ShardingCtx] = None
) -> jax.Array:
    """Tied-embedding LM head (reference parallel_matmul hybrid_model.py:66)."""
    word = params["embeddings"]["word"].astype(hidden.dtype)
    logits = jnp.einsum("bsh,vh->bsv", hidden, word)
    return _constrain(ctx, logits, ("batch", "seq", "vocab"))


def forward(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: GPTConfig,
    *,
    position_ids: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    hidden, _ = forward_hidden(
        params,
        input_ids,
        cfg,
        position_ids=position_ids,
        ctx=ctx,
        dropout_key=dropout_key,
        train=train,
    )
    return logits_from_hidden(params, hidden, ctx)


# ---------------------------------------------------------------------------
# Loss
# ---------------------------------------------------------------------------


def cross_entropy(
    logits: jax.Array, labels: jax.Array, loss_mask: Optional[jax.Array] = None
) -> jax.Array:
    """Masked-mean token CE in fp32 (GPTPretrainingCriterion single_model.py:819).

    Under TP the ``vocab`` dim of logits is model-sharded; the logsumexp and
    label gather partition cleanly (XLA inserts the psum the reference's
    ParallelCrossEntropy issues manually, hybrid_model.py:951).
    """
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - picked
    if loss_mask is None:
        return jnp.mean(nll)
    loss_mask = loss_mask.astype(jnp.float32)
    return jnp.sum(nll * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)


def _pipeline_train_loss(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: GPTConfig,
    ctx: ShardingCtx,
    dropout_key: Optional[jax.Array],
) -> jax.Array:
    """Training loss under pipeline parallelism via the 1F1B schedule.

    Embedding, per-chunk layer blocks, and the head+CE all run inside the
    schedule (parallel/pipeline.py); this function just adapts the GPT
    pieces to the (embed_fn, chunk_fn, head_fn) contract and divides the
    returned numerator by the global mask sum (reference
    GPTPretrainingCriterion masked mean, single_model.py:819)."""
    from paddlefleetx_tpu.parallel.pipeline import (
        interleave_permutation,
        pipeline_loss_1f1b,
    )

    if cfg.num_experts > 1:
        raise NotImplementedError("MoE with pipeline parallelism unsupported")
    pcfg = ctx.pipeline
    S, V = pcfg.num_stages, pcfg.num_virtual_stages
    C = S * V
    if cfg.num_layers % C:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by {S} stages x {V} virtual"
        )
    pc = cfg.num_layers // C

    k_embed, k_layers = (
        jax.random.split(dropout_key) if dropout_key is not None else (None, None)
    )

    # batch leaves enter the custom-vjp pipeline as floats (ids < 2^24 are
    # exact in f32; zero cotangents) and are cast back inside the fns
    bsz, seq = batch["tokens"].shape
    fbatch = {
        "tokens": batch["tokens"].astype(jnp.float32),
        "labels": batch["labels"].astype(jnp.float32),
    }
    loss_mask = batch.get("loss_mask")
    fbatch["loss_mask"] = (
        jnp.ones((bsz, seq), jnp.float32)
        if loss_mask is None
        else loss_mask.astype(jnp.float32)
    )
    if batch.get("position_ids") is not None:
        fbatch["position_ids"] = batch["position_ids"].astype(jnp.float32)

    def embed_fn(eparams, mb, mbi):
        toks = mb["tokens"].astype(jnp.int32)
        pos_ids = (
            mb["position_ids"].astype(jnp.int32) if "position_ids" in mb else None
        )
        k = jax.random.fold_in(k_embed, mbi) if k_embed is not None else None
        return _embed(eparams, toks, pos_ids, cfg, ctx, k, True)

    def chunk_fn(chunk_params, x_mb, c, mbi):
        def sbody(carry, inp):
            params_l, local_idx = inp
            # semantic layer index: params are pre-permuted so execution
            # chunk c holds semantic layers [c*pc, (c+1)*pc) — key folding
            # matches the single-device scan exactly
            k = (
                jax.random.fold_in(jax.random.fold_in(k_layers, c * pc + local_idx), mbi)
                if k_layers is not None
                else None
            )
            out, _aux = _decoder_layer(params_l, carry, cfg, ctx, k, True)
            return out, None

        sbody_fn = _layer_remat(cfg, sbody)
        x_mb, _ = jax.lax.scan(sbody_fn, x_mb, (chunk_params, jnp.arange(pc)))
        return x_mb

    def head_fn(hparams, y_mb, mb, mbi):
        y = layer_norm(
            y_mb, hparams["final_ln"]["scale"], hparams["final_ln"]["bias"],
            fused=cfg.use_fused_ln,
        )
        y = _constrain(ctx, y, ("batch", "seq", "embed"))
        word = hparams["word"].astype(y.dtype)
        logits = jnp.einsum("bsh,vh->bsv", y, word)
        logits = _constrain(ctx, logits, ("batch", "seq", "vocab")).astype(jnp.float32)
        labels = mb["labels"].astype(jnp.int32)
        from paddlefleetx_tpu.models.common import one_hot_token_nll

        return jnp.sum(one_hot_token_nll(logits, labels) * mb["loss_mask"])

    layers_params = params["layers"]
    if V > 1:
        # NOTE: this per-step permutation crosses stage-shard boundaries
        # (one all-to-all of the layer stack each way per step).  Storing
        # params pre-permuted would amortize it but ties checkpoint layout
        # to the pipeline config (Megatron's choice); revisit if V>1 runs
        # become bandwidth-bound.
        perm = interleave_permutation(cfg.num_layers, S, V)
        layers_params = jax.tree.map(lambda a: jnp.take(a, perm, axis=0), layers_params)

    eparams = params["embeddings"]
    hparams = {"final_ln": params["final_ln"], "word": params["embeddings"]["word"]}
    numer = pipeline_loss_1f1b(
        (embed_fn, chunk_fn, head_fn),
        pcfg,
        ctx.mesh,
        (eparams, layers_params, hparams),
        fbatch,
    )
    return numer / jnp.maximum(jnp.sum(fbatch["loss_mask"]), 1.0)


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: GPTConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = True,
) -> jax.Array:
    """batch: tokens [b,s], labels [b,s], loss_mask [b,s], position_ids opt.

    MoE models add the load-balance aux loss scaled by moe_aux_loss_weight
    (reference sharded_moe.py l_aux handling)."""
    if (
        train
        and ctx is not None
        and ctx.pipeline is not None
        and ctx.pipeline.num_stages > 1
    ):
        return _pipeline_train_loss(params, batch, cfg, ctx, dropout_key)
    hidden, aux = forward_hidden(
        params,
        batch["tokens"],
        cfg,
        position_ids=batch.get("position_ids"),
        ctx=ctx,
        dropout_key=dropout_key,
        train=train,
    )
    from paddlefleetx_tpu.parallel.mesh import AXIS_MODEL

    vocab_sharded = ctx is not None and ctx.mesh.shape.get(AXIS_MODEL, 1) > 1
    if cfg.use_chunked_ce and not vocab_sharded:
        from paddlefleetx_tpu.ops.chunked_ce import chunked_cross_entropy

        loss = chunked_cross_entropy(
            hidden,
            params["embeddings"]["word"],
            batch["labels"],
            batch.get("loss_mask"),
            chunk=cfg.ce_chunk_size,
        )
    else:
        logits = logits_from_hidden(params, hidden, ctx)
        loss = cross_entropy(logits, batch["labels"], batch.get("loss_mask"))
    if cfg.num_experts > 1:
        loss = loss + cfg.moe_aux_loss_weight * aux
    return loss
