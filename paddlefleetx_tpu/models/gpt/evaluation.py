"""GPT zero-shot evaluation module (reference GPTEvalModule
language_module.py:600-735): WikiText perplexity over overlapping windows
and LAMBADA last-word accuracy, driven by the LM_Eval_Dataset /
Lambada_Eval_Dataset (data/gpt_dataset.py)."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.core.module import BasicModule, resolve_model_dtype
from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.metrics import METRICS, Metric
from paddlefleetx_tpu.utils.registry import MODULES


@METRICS.register("LMEval")
class LMEvalMetric(Metric):
    """Accumulates (masked nll sum, mask count, all-correct count, seqs):
    exact corpus PPL + sequence accuracy from one stream (reference tracks
    total_score/total_tokens the same way)."""

    def __init__(self, **_):
        self.reset()

    def update(self, preds, labels=None):
        # preds: [b, 3] rows (nll_sum, mask_count, all_correct)
        preds = np.asarray(preds)
        self.nll += float(preds[:, 0].sum())
        self.tokens += float(preds[:, 1].sum())
        self.correct += float(preds[:, 2].sum())
        self.seqs += preds.shape[0]

    def accumulate(self) -> Dict[str, float]:
        ppl = float(np.exp(min(self.nll / max(self.tokens, 1.0), 20.0)))
        return {
            "ppl": ppl,
            "acc": self.correct / max(self.seqs, 1),
            "tokens": self.tokens,
        }

    def reset(self):
        self.nll = 0.0
        self.tokens = 0.0
        self.correct = 0.0
        self.seqs = 0


@MODULES.register("GPTEvalModule")
class GPTEvalModule(BasicModule):
    def __init__(self, cfg):
        model_cfg = dict(cfg.Model)
        model_cfg.pop("module", None)
        model_cfg.pop("name", None)
        resolve_model_dtype(cfg, model_cfg)
        self.config = GPTConfig.from_config(model_cfg)
        self.tokens_per_sample = self.config.max_position_embeddings

    def init_params(self, key):
        return gpt.init(self.config, key)

    def logical_axes(self):
        return gpt.gpt_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=False):
        return gpt.loss_fn(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=False
        )

    def predict_fn(self, params, batch, *, ctx=None):
        """-> [b, 3] rows (masked nll sum, mask count, all-masked-correct)."""
        logits = gpt.forward(
            params,
            batch["tokens"],
            self.config,
            position_ids=batch.get("position_ids"),
            ctx=ctx,
            train=False,
        ).astype(jnp.float32)
        labels = batch["labels"]
        mask = batch["loss_mask"].astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        nll = (lse - picked) * mask
        correct = (jnp.argmax(logits, axis=-1) == labels) | (mask == 0)
        all_correct = jnp.all(correct, axis=-1).astype(jnp.float32)
        return jnp.stack([nll.sum(-1), mask.sum(-1), all_correct], axis=-1)

    def build_metric(self):
        return LMEvalMetric()
