"""GPT sequence-classification finetuning (reference
GPTForSequenceClassification single_model.py:856-897 + GPTFinetuneModule
language_module.py:228-488).

The classifier scores the hidden state of the LAST real token of each
sequence (decoder-only convention; the reference gathers by position of the
final non-pad token).  Loss: CE for classification tasks, MSE for the STS-B
regression task (reference loss config paddle.nn.loss.* dispatch)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.core.module import BasicModule, resolve_model_dtype
from paddlefleetx_tpu.models.common import ParamSpec, init_params, logical_axes, normal_init, zeros_init
from paddlefleetx_tpu.models.gpt import model as gpt
from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.utils.registry import MODULES


def seqcls_specs(cfg: GPTConfig, num_classes: int) -> Dict[str, Any]:
    specs = gpt.gpt_specs(cfg)
    specs["score"] = {
        "kernel": ParamSpec(
            (cfg.hidden_size, num_classes), ("embed", None), normal_init(cfg.initializer_range)
        ),
        "bias": ParamSpec((num_classes,), (None,), zeros_init()),
    }
    return specs


def seqcls_forward(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: GPTConfig,
    *,
    ctx=None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """-> logits [b, num_classes]; batch needs tokens + cls_position."""
    hidden, _ = gpt.forward_hidden(
        params,
        batch["tokens"],
        cfg,
        position_ids=batch.get("position_ids"),
        ctx=ctx,
        dropout_key=dropout_key,
        train=train,
    )
    # gather the last real token's hidden state per sequence
    pos = batch["cls_position"].astype(jnp.int32)  # [b]
    picked = jnp.take_along_axis(hidden, pos[:, None, None], axis=1)[:, 0]  # [b, h]
    p = params["score"]
    return picked @ p["kernel"].astype(picked.dtype) + p["bias"].astype(picked.dtype)


@MODULES.register("GPTFinetuneModule")
class GPTFinetuneModule(BasicModule):
    """GLUE-style finetune: CE (classification) or MSE (regression) on the
    last-token classifier; eval metric built from ``Model.metric``."""

    def __init__(self, cfg):
        model_cfg = dict(cfg.Model)
        model_cfg.pop("module", None)
        model_cfg.pop("name", None)
        self.loss_cfg = dict(model_cfg.pop("loss", {}) or {})
        self.metric_cfg = dict(model_cfg.pop("metric", {}) or {})
        self.num_classes = int(model_cfg.pop("num_classes", 2))
        resolve_model_dtype(cfg, model_cfg)
        self.config = GPTConfig.from_config(model_cfg)
        self.tokens_per_sample = (
            int(cfg.get("Data", {}).get("Train", {}).get("dataset", {}).get("max_seq_len", 0))
            or self.config.max_position_embeddings
        )
        train_loss = self.loss_cfg.get("train", {}).get("name", "CrossEntropyLoss")
        self.regression = train_loss in ("MSELoss", "mse") or self.num_classes == 1

    def init_params(self, key):
        return init_params(key, seqcls_specs(self.config, self.num_classes))

    def logical_axes(self):
        return logical_axes(seqcls_specs(self.config, self.num_classes))

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        logits = seqcls_forward(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )
        labels = batch["labels"]
        if self.regression:
            return jnp.mean(jnp.square(logits[:, 0].astype(jnp.float32) - labels))
        logits = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, -1)
        picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - picked)

    # ---- metric protocol (consumed by Engine.evaluate) -----------------
    def predict_fn(self, params, batch, *, ctx=None):
        logits = seqcls_forward(params, batch, self.config, ctx=ctx, train=False)
        return logits[:, 0] if self.regression else logits

    def build_metric(self):
        from paddlefleetx_tpu.models.metrics import build_metric

        if self.metric_cfg.get("eval"):
            return build_metric(self.metric_cfg["eval"])
        return None
