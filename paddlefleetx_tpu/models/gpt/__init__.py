"""GPT decoder-only family: model, generation, MoE, finetune, eval."""
