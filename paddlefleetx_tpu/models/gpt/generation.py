"""GPT autoregressive generation: KV-cache decode + logits processors.

Reference: ``GPTForGeneration`` (single_model.py:898-1320 — prepare inputs,
logits processors, per-token sample loop with incremental KV-cache decode)
and ``processor.py`` (LogitsProcessorList etc.).

TPU-native shape discipline: the reference's dynamic Python while-loop
becomes a static ``lax.scan`` over ``max_dec_len`` slots with an
``unfinished`` flag (padded static shapes; XLA traces one step).  The KV
cache is a preallocated [layers, b, max_len, heads, head_dim] pair updated
with ``dynamic_update_slice``; prefill packs the prompt in one forward.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.gpt.model import layer_norm
from paddlefleetx_tpu.ops.attention import xla_attention
from paddlefleetx_tpu.ops.sampling import sample_logits


class KVCache(NamedTuple):
    k: jax.Array  # [layers, b, max_len, heads, head_dim]
    v: jax.Array


def init_cache(cfg: GPTConfig, batch: int, max_len: int, dtype=None) -> KVCache:
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, batch, max_len, cfg.num_attention_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# Cache-aware forward (shares weights with model.gpt_specs; the training
# forward in model.py stays cache-free)
# ---------------------------------------------------------------------------


def _layer_with_cache(
    p: Dict[str, Any],
    x: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    cfg: GPTConfig,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder layer over x [b, t, h] writing K/V at offset ``pos``.

    Attends over cache[:pos+t] (left-padded garbage masked by position).
    """
    dtype = x.dtype
    b, t, h = x.shape
    max_len = k_cache.shape[1]

    y = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"])
    qkv = jnp.einsum("bsh,htnd->bstnd", y, p["attn"]["qkv_kernel"].astype(dtype))
    qkv = qkv + p["attn"]["qkv_bias"].astype(dtype)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))

    # bias: query i (global pos+i) attends keys j <= pos+i, j < pos+t valid
    q_pos = pos + jnp.arange(t)[:, None]
    k_pos = jnp.arange(max_len)[None, :]
    bias = jnp.where(k_pos <= q_pos, 0.0, -1e9)[None, None, :, :]  # [1,1,t,max]

    attn_out = xla_attention(q, k_cache, v_cache, causal=False, bias=bias)
    attn_out = jnp.einsum(
        "bsnd,ndh->bsh", attn_out, p["attn"]["out_kernel"].astype(dtype)
    ) + p["attn"]["out_bias"].astype(dtype)
    x = x + attn_out

    y = layer_norm(x, p["ln_2"]["scale"], p["ln_2"]["bias"])
    mp = p["mlp"]
    y = y @ mp["fc_in_kernel"].astype(dtype) + mp["fc_in_bias"].astype(dtype)
    y = jax.nn.gelu(y, approximate=True)
    y = y @ mp["fc_out_kernel"].astype(dtype) + mp["fc_out_bias"].astype(dtype)
    return x + y, k_cache, v_cache


def forward_cached(
    params: Dict[str, Any],
    tokens: jax.Array,
    cache: KVCache,
    pos: jax.Array,
    cfg: GPTConfig,
) -> Tuple[jax.Array, KVCache]:
    """tokens [b, t] at positions [pos, pos+t) -> (logits [b, t, v], cache)."""
    dtype = jnp.dtype(cfg.dtype)
    b, t = tokens.shape
    word = params["embeddings"]["word"].astype(dtype)
    pe = params["embeddings"]["position"].astype(dtype)
    positions = pos + jnp.arange(t)
    x = word[tokens] + pe[positions][None, :, :]

    def body(x, inp):
        p_l, kc, vc = inp
        x, kc, vc = _layer_with_cache(p_l, x, kc, vc, pos, cfg)
        return x, (kc, vc)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"])
    logits = jnp.einsum("bsh,vh->bsv", x, word)
    return logits, KVCache(ks, vs)


# ---------------------------------------------------------------------------
# Logits processors (reference processor.py)
# ---------------------------------------------------------------------------


def apply_repetition_penalty(logits, generated_mask_counts, penalty: float):
    """Divide positive / multiply negative logits of already-generated tokens
    (reference RepetitionPenaltyLogitsProcessor)."""
    if penalty == 1.0:
        return logits
    seen = generated_mask_counts > 0
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def apply_min_length(logits, cur_len, min_len: int, eos_token_id: int):
    """Suppress EOS before min_length (reference MinLengthLogitsProcessor)."""
    if min_len <= 0:
        return logits
    return jnp.where(
        (cur_len < min_len)[..., None]
        & (jnp.arange(logits.shape[-1]) == eos_token_id)[None, :],
        -1e10,
        logits,
    )


# ---------------------------------------------------------------------------
# Generation loop
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Reference GPTForGeneration config surface (single_model.py:898-960)."""

    max_dec_len: int = 64
    min_dec_len: int = 1
    decode_strategy: str = "sampling"  # sampling | greedy_search
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: int = 50256
    pad_token_id: int = 0


def generate(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: GPTConfig,
    gen: GenerationConfig,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """input_ids [b, prompt_len] (right-aligned, no padding) ->
    generated ids [b, max_dec_len] (eos/pad-filled after finish)."""
    if cfg.num_experts > 1:
        raise NotImplementedError("KV-cache generation for MoE models unsupported")
    b, prompt_len = input_ids.shape
    max_len = prompt_len + gen.max_dec_len
    if max_len > cfg.max_position_embeddings:
        raise ValueError(
            f"prompt_len {prompt_len} + max_dec_len {gen.max_dec_len} exceeds "
            f"max_position_embeddings {cfg.max_position_embeddings}"
        )
    if key is None:
        key = jax.random.key(0)

    cache = init_cache(cfg, b, max_len)
    vocab = cfg.vocab_size
    token_counts0 = jnp.zeros((b, vocab), jnp.int32).at[
        jnp.arange(b)[:, None], input_ids
    ].add(1)

    # prefill: cache K/V for the prompt; its last-row logits seed the loop
    logits, cache = forward_cached(params, input_ids, cache, jnp.int32(0), cfg)
    last_logits = logits[:, -1, :].astype(jnp.float32)

    class Carry(NamedTuple):
        cache: KVCache
        logits: jax.Array  # [b, vocab] — logits of the position to sample
        pos: jax.Array
        unfinished: jax.Array  # [b] bool
        token_counts: jax.Array
        key: jax.Array

    def step(carry: Carry, i):
        logits = apply_min_length(
            carry.logits, jnp.full((b,), i), gen.min_dec_len, gen.eos_token_id
        )
        logits = apply_repetition_penalty(
            logits, carry.token_counts, gen.repetition_penalty
        )
        key, sub = jax.random.split(carry.key)
        if gen.decode_strategy == "greedy_search":
            nxt = jnp.argmax(logits, axis=-1)
        else:
            nxt = sample_logits(
                sub, logits, temperature=gen.temperature, top_k=gen.top_k, top_p=gen.top_p
            )
        nxt = jnp.where(carry.unfinished, nxt, gen.pad_token_id)
        unfinished = carry.unfinished & (nxt != gen.eos_token_id)
        counts = carry.token_counts.at[jnp.arange(b), nxt].add(1)
        new_logits, cache = forward_cached(
            params, nxt[:, None], carry.cache, carry.pos, cfg
        )
        new_carry = Carry(
            cache=cache,
            logits=new_logits[:, -1, :].astype(jnp.float32),
            pos=carry.pos + 1,
            unfinished=unfinished,
            token_counts=counts,
            key=key,
        )
        return new_carry, nxt

    carry0 = Carry(
        cache=cache,
        logits=last_logits,
        pos=jnp.int32(prompt_len),
        unfinished=jnp.ones((b,), bool),
        token_counts=token_counts0,
        key=key,
    )
    carry, tokens = jax.lax.scan(step, carry0, jnp.arange(gen.max_dec_len))
    return tokens.T  # [b, max_dec_len]
