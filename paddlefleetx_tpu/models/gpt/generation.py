"""GPT autoregressive generation: KV-cache decode + logits processors.

Reference: ``GPTForGeneration`` (single_model.py:898-1320 — prepare inputs,
logits processors, per-token sample loop with incremental KV-cache decode)
and ``processor.py`` (LogitsProcessorList etc.).

TPU-native shape discipline: the reference's dynamic Python while-loop
becomes a bounded ``lax.while_loop`` over ``max_dec_len`` slots with an
``unfinished`` flag (padded static shapes; XLA traces one step) that exits
as soon as every row has emitted EOS; ``PFX_DECODE_SCAN=1`` restores the
fixed-trip ``lax.scan`` (trace-shape debugging; beam search keeps scan).
The KV cache is a preallocated [layers, b, heads, max_len, head_dim] pair
(heads-major so the flash-decode kernel's block tiling keeps (seq, dim)
minor — ``ops/decode_attention.py``) updated with ``dynamic_update_slice``;
prefill packs the prompt in one forward.  The decode step attends only
over cache blocks ``< ceil((pos+t)/block)``, not the whole buffer; set
PFX_DECODE_ATTN=dense for the legacy attend-over-everything path.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, _constrain, layer_norm
from paddlefleetx_tpu.ops.decode_attention import (
    decode_attention,
    decode_attn_mode,
    dense_cache_attention,
    kv_cache_dtype,
    paged_decode_attention,
    quantize_kv,
)
from paddlefleetx_tpu.ops.sampling import filtered_logits, sample_logits
from paddlefleetx_tpu.ops.speculative import (
    SpecConfig,
    ngram_propose,
    speculative_verify,
)


class KVCache(NamedTuple):
    """Contiguous decode cache.  ``k``/``v`` are [layers, b, heads,
    max_len, head_dim] in the model dtype — or int8 under
    PFX_KV_DTYPE=int8, in which case ``k_scale``/``v_scale`` [layers, b,
    heads, max_len] carry the per-(slot, head) quantization scales
    written alongside every cache update (quantize-on-write,
    dequantize-in-kernel — ``ops/decode_attention``)."""

    k: jax.Array  # [layers, b, heads, max_len, head_dim]
    v: jax.Array
    k_scale: Optional[jax.Array] = None  # [layers, b, heads, max_len]
    v_scale: Optional[jax.Array] = None


def init_cache(
    cfg: GPTConfig, batch: int, max_len: int, dtype=None, kv_dtype: str = ""
) -> KVCache:
    """``kv_dtype``: "" resolves PFX_KV_DTYPE (the serving path passes the
    ``Generation.speculative.kv_dtype`` config value through); "bf16"
    keeps the cache in the model dtype, "int8" allocates the quantized
    pair plus its scale planes (HBM bytes per slot halve vs bf16)."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, batch, cfg.num_attention_heads, max_len, cfg.head_dim)
    if kv_cache_dtype(kv_dtype) == "int8":
        sshape = shape[:-1]
        return KVCache(
            jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
            jnp.zeros(sshape, jnp.float32), jnp.zeros(sshape, jnp.float32),
        )
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# Cache-aware forward (shares weights with model.gpt_specs; the training
# forward in model.py stays cache-free)
# ---------------------------------------------------------------------------


def _layer_with_cache(
    p: Dict[str, Any],
    x: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
    kv_valid_from: Optional[jax.Array] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
):
    """One decoder layer over x [b, t, h] writing K/V at offset ``pos``.

    Attends over cache[:pos+t] via the length-aware blocked kernel
    (``ops/decode_attention``): only cache blocks up to ceil((pos+t)/block)
    are visited, with the causal + ``kv_valid_from`` left-pad masks folded
    into per-block masking.  PFX_DECODE_ATTN=dense restores the legacy
    materialized-bias attend-over-the-whole-buffer path (A/B benching).
    Under TP serving (reference GPTForGenerationHybrid hybrid_model.py:1209)
    the qkv/cache/attention stay ``heads``-sharded over the model axis and
    the output projection row-psum is inserted by GSPMD; the sharded path
    uses the lax spelling of the blocked loop (GSPMD partitions it freely,
    a pallas_call would need shard_map).
    """
    dtype = x.dtype
    b, t, h = x.shape

    y = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"])
    qkv = jnp.einsum("bsh,htnd->bstnd", y, p["attn"]["qkv_kernel"].astype(dtype))
    qkv = qkv + p["attn"]["qkv_bias"].astype(dtype)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _constrain(ctx, q, ("batch", None, "heads", "kv"))

    # cache layout [b, heads, max_len, head_dim]: transpose the (small)
    # step chunk, never the cache.  Under int8 the chunk quantizes HERE
    # (quantize-on-write) and the scale planes update alongside — the
    # kernels below dequantize in-kernel, so the cache only ever streams
    # as int8.
    kc = k.transpose(0, 2, 1, 3)
    vc = v.transpose(0, 2, 1, 3)
    if k_scale is not None:
        kq, ks = quantize_kv(kc)
        vq, vs = quantize_kv(vc)
        k_cache = jax.lax.dynamic_update_slice(k_cache, kq, (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, vq, (0, 0, pos, 0))
        k_scale = jax.lax.dynamic_update_slice(k_scale, ks, (0, 0, pos))
        v_scale = jax.lax.dynamic_update_slice(v_scale, vs, (0, 0, pos))
        k_scale = _constrain(ctx, k_scale, ("batch", "heads", None))
        v_scale = _constrain(ctx, v_scale, ("batch", "heads", None))
    else:
        k_cache = jax.lax.dynamic_update_slice(k_cache, kc, (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, vc, (0, 0, pos, 0))
    k_cache = _constrain(ctx, k_cache, ("batch", "heads", None, "kv"))
    v_cache = _constrain(ctx, v_cache, ("batch", "heads", None, "kv"))

    if decode_attn_mode() == "dense":
        attn_out = dense_cache_attention(
            q, k_cache, v_cache, pos, kv_valid_from=kv_valid_from,
            k_scale=k_scale, v_scale=v_scale,
        )
    else:
        attn_out = decode_attention(
            q, k_cache, v_cache, pos, kv_valid_from=kv_valid_from,
            impl="lax" if ctx is not None else "auto",
            k_scale=k_scale, v_scale=v_scale,
        )
    attn_out = jnp.einsum(
        "bsnd,ndh->bsh", attn_out, p["attn"]["out_kernel"].astype(dtype)
    ) + p["attn"]["out_bias"].astype(dtype)
    x = x + attn_out

    y = layer_norm(x, p["ln_2"]["scale"], p["ln_2"]["bias"])
    mp = p["mlp"]
    y = y @ mp["fc_in_kernel"].astype(dtype) + mp["fc_in_bias"].astype(dtype)
    y = jax.nn.gelu(y, approximate=True)
    y = y @ mp["fc_out_kernel"].astype(dtype) + mp["fc_out_bias"].astype(dtype)
    return x + y, k_cache, v_cache, k_scale, v_scale


def forward_cached(
    params: Dict[str, Any],
    tokens: jax.Array,
    cache: KVCache,
    pos: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
    position_ids: Optional[jax.Array] = None,
    kv_valid_from: Optional[jax.Array] = None,
) -> Tuple[jax.Array, KVCache]:
    """tokens [b, t] at positions [pos, pos+t) -> (logits [b, t, v], cache).

    ``position_ids`` [b, t] overrides the default pos+arange(t) position
    embedding indices and ``kv_valid_from`` [b] masks cache keys before a
    row's first real token — together they implement left-padded serving
    buckets (each row's real prompt right-aligned at the same width)."""
    dtype = jnp.dtype(cfg.dtype)
    b, t = tokens.shape
    word = params["embeddings"]["word"].astype(dtype)
    pe = params["embeddings"]["position"].astype(dtype)
    if position_ids is None:
        x = word[tokens] + pe[pos + jnp.arange(t)][None, :, :]
    else:
        x = word[tokens] + pe[position_ids]
    x = _constrain(ctx, x, ("batch", None, "embed"))

    quant = cache.k_scale is not None
    if quant:
        def body(x, inp):
            p_l, kc, vc, ksl, vsl = inp
            x, kc, vc, ksl, vsl = _layer_with_cache(
                p_l, x, kc, vc, pos, cfg, ctx, kv_valid_from, ksl, vsl
            )
            return x, (kc, vc, ksl, vsl)

        xs = (params["layers"], cache.k, cache.v, cache.k_scale, cache.v_scale)
        x, (ks, vs, kss, vss) = jax.lax.scan(body, x, xs)
        out_cache = KVCache(ks, vs, kss, vss)
    else:
        def body(x, inp):
            p_l, kc, vc = inp
            x, kc, vc, _, _ = _layer_with_cache(
                p_l, x, kc, vc, pos, cfg, ctx, kv_valid_from
            )
            return x, (kc, vc)

        x, (ks, vs) = jax.lax.scan(body, x, (params["layers"], cache.k, cache.v))
        out_cache = KVCache(ks, vs)
    x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"])
    logits = jnp.einsum("bsh,vh->bsv", x, word)
    return _constrain(ctx, logits, ("batch", None, "vocab")), out_cache


# ---------------------------------------------------------------------------
# Logits processors (reference processor.py)
# ---------------------------------------------------------------------------


def apply_repetition_penalty(logits, generated_mask_counts, penalty: float):
    """Divide positive / multiply negative logits of already-generated tokens
    (reference RepetitionPenaltyLogitsProcessor)."""
    if penalty == 1.0:
        return logits
    seen = generated_mask_counts > 0
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def apply_min_length(logits, cur_len, min_len: int, eos_token_id: int):
    """Suppress EOS before min_length (reference MinLengthLogitsProcessor)."""
    if min_len <= 0:
        return logits
    return jnp.where(
        (cur_len < min_len)[..., None]
        & (jnp.arange(logits.shape[-1]) == eos_token_id)[None, :],
        -1e10,
        logits,
    )


def apply_forced_token(logits, step, force_at_step: int, token_id: int):
    """Force a specific token at a given decode step (reference
    ForcedBOSTokenLogitsProcessor / ForcedEOSTokenLogitsProcessor)."""
    if token_id < 0:
        return logits
    forced = jnp.full_like(logits, -1e10).at[..., token_id].set(0.0)
    return jnp.where(step == force_at_step, forced, logits)


def apply_hamming_diversity(logits, current_tokens, group_start: int, penalty: float):
    """Penalize tokens already chosen by EARLIER beam groups at this step
    (reference HammingDiversityLogitsProcessor): logits [gb, v];
    current_tokens [gb] holds this step's choices for groups processed so
    far (entries >= group_start are not yet decided and are masked off)."""
    if penalty == 0.0:
        return logits
    vocab = logits.shape[-1]
    decided = jnp.arange(current_tokens.shape[0]) < group_start
    counts = jnp.zeros((vocab,), logits.dtype).at[current_tokens].add(
        decided.astype(logits.dtype)
    )
    return logits - penalty * counts[None, :]


# ---------------------------------------------------------------------------
# Generation loop
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Reference GPTForGeneration config surface (single_model.py:898-960)."""

    max_dec_len: int = 64
    min_dec_len: int = 1
    decode_strategy: str = "sampling"  # sampling | greedy_search | beam_search
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: int = 50256
    pad_token_id: int = 0
    # beam search (reference BeamSearchScorer + processor.py)
    num_beams: int = 4
    length_penalty: float = 1.0
    # diverse (group) beam search: HammingDiversityLogitsProcessor
    num_beam_groups: int = 1
    diversity_penalty: float = 0.0
    # ForcedBOS/ForcedEOS processors (-1 = disabled)
    forced_bos_token_id: int = -1
    forced_eos_token_id: int = -1

    def __post_init__(self):
        if self.decode_strategy not in ("sampling", "greedy_search", "beam_search"):
            raise ValueError(
                f"bad decode_strategy {self.decode_strategy!r}; "
                "valid: sampling, greedy_search, beam_search"
            )


def decode_loop_mode() -> str:
    """PFX_DECODE_SCAN: "1" restores the fixed-trip ``lax.scan`` decode
    loop (trace-shape debugging; also what beam search always uses), "0"/
    unset selects the early-exit ``lax.while_loop``.  Loud parse — a typo
    must not silently A/B while-vs-while on a chip window."""
    env = os.environ.get("PFX_DECODE_SCAN") or "0"
    if env not in ("0", "1"):
        raise ValueError(f"PFX_DECODE_SCAN={env!r}; valid: 0, 1")
    return "scan" if env == "1" else "while"


def _left_pad_prefill(prompt_len: int, prompt_lens: Optional[jax.Array]):
    """(pad_len [b], prefill position ids [b, P]) for left-padded buckets;
    (None, None) on the unpadded path."""
    if prompt_lens is None:
        return None, None
    pad_len = jnp.int32(prompt_len) - prompt_lens
    pos_ids = jnp.maximum(jnp.arange(prompt_len)[None, :] - pad_len[:, None], 0)
    return pad_len, pos_ids


def bucket_len(longest: int, multiple: int) -> int:
    """THE prompt-bucket formula (next multiple of ``multiple``).

    Single-sourced on purpose: ``pad_prompts`` (the padding itself),
    ``GenerationServer.warmup`` (bucket validation), and the serve-layer
    coalesce key (tools/serve.py ``plan_request``) must all agree on the
    padded width — a drifted copy would silently key fresh compiles for
    coalesced traffic."""
    return ((int(longest) + int(multiple) - 1) // int(multiple)) * int(multiple)


def pad_prompts(prompts, pad_token_id: int, multiple: int = 64):
    """Left-pad a list of variable-length prompts to a shared bucketed
    width (``bucket_len``): serving compiles once per BUCKET, not once
    per prompt length (VERDICT r1 weak #4).

    Returns (padded [b, P] int32 array, prompt_lens [b])."""
    import numpy as np

    P = bucket_len(max(len(p) for p in prompts), multiple)
    out = np.full((len(prompts), P), pad_token_id, np.int32)
    lens = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        out[i, P - len(p):] = p
        lens[i] = len(p)
    return jnp.asarray(out), jnp.asarray(lens)


def generate(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: GPTConfig,
    gen: GenerationConfig,
    key: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
    prompt_lens: Optional[jax.Array] = None,
    cache: Optional[KVCache] = None,
    return_cache: bool = False,
    spec: Optional[SpecConfig] = None,
    return_spec_stats: bool = False,
) -> jax.Array:
    """input_ids [b, prompt_len] -> generated ids [b, max_dec_len]
    (eos/pad-filled after finish).

    Without ``prompt_lens`` the prompts are taken as right-aligned and
    unpadded.  With ``prompt_lens`` [b], rows are LEFT-padded to a shared
    width (see :func:`pad_prompts`): padded key slots are masked out of
    attention and position ids start at the first real token — the shape
    (and therefore the compiled artifact) depends only on the bucket.

    Pass ``ctx`` to serve on a mesh: the KV cache and attention stay
    heads-sharded over the model axis (TP serving parity with the
    reference's GPTForGenerationHybrid, hybrid_model.py:1209).

    ``cache``: optionally pass a preallocated ``init_cache(cfg, b,
    prompt_len + max_dec_len)`` buffer instead of allocating inside the
    trace — a caller jitting generate can then DONATE it
    (``donate_argnums``) so the per-step ``dynamic_update_slice`` writes
    in place instead of copying the pair each step.  A donated cache is
    CONSUMED: the caller must not touch it after the call.  Donation only
    aliases an input to an OUTPUT buffer, so pair it with
    ``return_cache=True`` — the returned final cache occupies the donated
    buffer and can be donated straight back on the next same-shape call
    (``core/serving.py`` keeps a per-bucket pool doing exactly that;
    stale tail slots are safe because the blocked kernel never visits
    blocks beyond ``pos + t``).

    ``return_cache``: return ``(tokens, final KVCache)`` instead of
    tokens (sampling/greedy only).

    ``spec``: a :class:`~paddlefleetx_tpu.ops.speculative.SpecConfig`
    routes sampling/greedy decode through the speculative while-loop
    (:func:`_generate_speculative`): draft k tokens per iteration,
    verify them in ONE t=k+1 forward, commit the accepted prefix —
    greedy output is token-identical to the plain loop by construction.
    The cache needs ``spec.draft_k`` slack slots past ``prompt_len +
    max_dec_len`` (the verify chunk's rejected tail overruns before the
    rewind); a caller-provided cache must include them.
    ``return_spec_stats`` appends an ``(proposed, accepted)`` int32 pair
    to the return tuple (acceptance telemetry)."""
    if cfg.num_experts > 1:
        raise NotImplementedError("KV-cache generation for MoE models unsupported")
    if return_spec_stats and spec is None:
        raise ValueError("return_spec_stats needs a SpecConfig")
    b, prompt_len = input_ids.shape
    max_len = prompt_len + gen.max_dec_len
    cache_len = max_len + (spec.draft_k if spec is not None else 0)
    if max_len > cfg.max_position_embeddings:
        # with prompt_lens, position ids are bounded by the REAL lengths,
        # not the bucket width: only reject when the real positions
        # overflow (or the bound cannot be known, i.e. traced lengths)
        real_bound = None
        if prompt_lens is not None:
            try:
                real_bound = int(jax.numpy.max(prompt_lens)) + gen.max_dec_len
            except jax.errors.ConcretizationTypeError:
                real_bound = None  # traced lengths: bucket-width bound applies
        if real_bound is None or real_bound > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt_len {prompt_len} + max_dec_len {gen.max_dec_len} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}"
            )
    if key is None:
        key = jax.random.key(0)
    if gen.decode_strategy == "beam_search":
        if cache is not None or return_cache:
            raise ValueError(
                "cache donation/return is not supported for beam_search (the "
                "beam loop reorders the cache by parent each step)"
            )
        if spec is not None:
            raise ValueError(
                "speculative decoding is not supported for beam_search "
                "(the beam loop reorders the cache by parent each step)"
            )
        return beam_search(params, input_ids, cfg, gen, ctx=ctx, prompt_lens=prompt_lens)
    if spec is not None and decode_loop_mode() == "scan":
        raise ValueError(
            "speculative decoding needs the early-exit while-loop decode "
            "(variable tokens per iteration); unset PFX_DECODE_SCAN"
        )

    pad_len, prefill_pos_ids = _left_pad_prefill(prompt_len, prompt_lens)
    if cache is None:
        cache = init_cache(cfg, b, cache_len)
    else:
        want = (cfg.num_layers, b, cfg.num_attention_heads, cache_len,
                cfg.head_dim)
        if cache.k.shape != want:
            raise ValueError(
                f"provided cache shape {cache.k.shape} != required {want} "
                f"(prompt {prompt_len} + max_dec_len {gen.max_dec_len}"
                + (f" + draft_k {spec.draft_k}" if spec is not None else "")
                + ")"
            )
    if spec is not None:
        return _generate_speculative(
            params, input_ids, cfg, gen, spec, key, ctx, prompt_lens,
            pad_len, prefill_pos_ids, cache, return_cache, return_spec_stats,
        )
    vocab = cfg.vocab_size
    valid = (
        jnp.ones((b, prompt_len), jnp.int32)
        if pad_len is None
        else (jnp.arange(prompt_len)[None, :] >= pad_len[:, None]).astype(jnp.int32)
    )
    token_counts0 = jnp.zeros((b, vocab), jnp.int32).at[
        jnp.arange(b)[:, None], input_ids
    ].add(valid)

    # prefill: cache K/V for the prompt; its last-row logits seed the loop
    logits, cache = forward_cached(
        params, input_ids, cache, jnp.int32(0), cfg, ctx,
        position_ids=prefill_pos_ids, kv_valid_from=pad_len,
    )
    last_logits = logits[:, -1, :].astype(jnp.float32)

    class Carry(NamedTuple):
        cache: KVCache
        logits: jax.Array  # [b, vocab] — logits of the position to sample
        pos: jax.Array
        unfinished: jax.Array  # [b] bool
        token_counts: jax.Array
        key: jax.Array

    def step(carry: Carry, i):
        logits = apply_min_length(
            carry.logits, jnp.full((b,), i), gen.min_dec_len, gen.eos_token_id
        )
        logits = apply_repetition_penalty(
            logits, carry.token_counts, gen.repetition_penalty
        )
        logits = apply_forced_token(logits, i, 0, gen.forced_bos_token_id)
        logits = apply_forced_token(
            logits, i, gen.max_dec_len - 1, gen.forced_eos_token_id
        )
        key, sub = jax.random.split(carry.key)
        if gen.decode_strategy == "greedy_search":
            nxt = jnp.argmax(logits, axis=-1)
        else:
            nxt = sample_logits(
                sub, logits, temperature=gen.temperature, top_k=gen.top_k, top_p=gen.top_p
            )
        nxt = jnp.where(carry.unfinished, nxt, gen.pad_token_id)
        unfinished = carry.unfinished & (nxt != gen.eos_token_id)
        counts = carry.token_counts.at[jnp.arange(b), nxt].add(1)
        step_pos_ids = (
            (prompt_lens + i)[:, None] if prompt_lens is not None else None
        )
        new_logits, cache = forward_cached(
            params, nxt[:, None], carry.cache, carry.pos, cfg, ctx,
            position_ids=step_pos_ids, kv_valid_from=pad_len,
        )
        new_carry = Carry(
            cache=cache,
            logits=new_logits[:, -1, :].astype(jnp.float32),
            pos=carry.pos + 1,
            unfinished=unfinished,
            token_counts=counts,
            key=key,
        )
        return new_carry, nxt

    carry0 = Carry(
        cache=cache,
        logits=last_logits,
        pos=jnp.int32(prompt_len),
        unfinished=jnp.ones((b,), bool),
        token_counts=token_counts0,
        key=key,
    )
    if decode_loop_mode() == "scan":
        carry, tokens = jax.lax.scan(step, carry0, jnp.arange(gen.max_dec_len))
        tokens = tokens.T  # [b, max_dec_len]
        return (tokens, carry.cache) if return_cache else tokens

    # early-exit while_loop: the scan runs all max_dec_len steps even after
    # every row emitted EOS (each a full forward over the batch); the while
    # loop stops as soon as nothing is unfinished.  Token-for-token parity
    # with the scan: the buffer starts pad-filled, and the scan likewise
    # emits pad for every step after all rows finish (nxt is forced to
    # pad_token_id once unfinished is False), so skipped slots are
    # identical — asserted by tests/test_generation.py.
    tokens0 = jnp.full((b, gen.max_dec_len), gen.pad_token_id, jnp.int32)

    def loop_cond(st):
        carry, i, _ = st
        return (i < gen.max_dec_len) & jnp.any(carry.unfinished)

    def loop_body(st):
        carry, i, tokens = st
        new_carry, nxt = step(carry, i)
        tokens = jax.lax.dynamic_update_slice(tokens, nxt[:, None], (0, i))
        return new_carry, i + 1, tokens

    carry, _, tokens = jax.lax.while_loop(
        loop_cond, loop_body, (carry0, jnp.int32(0), tokens0)
    )
    return (tokens, carry.cache) if return_cache else tokens  # [b, max_dec_len]


# ---------------------------------------------------------------------------
# Speculative decode loop (contiguous path).  Leviathan et al. 2023 via
# ops/speculative.py: each iteration forwards a [pending, draft_0..k-1]
# chunk (t = k+1) through the SAME cached forward the plain loop uses,
# verifies the drafts against the target's own processed logits, and
# commits the batch-min accepted prefix + the pending token — between 1
# and k+1 tokens per forward instead of exactly 1.
# ---------------------------------------------------------------------------


def _generate_speculative(
    params, input_ids, cfg, gen, spec: SpecConfig, key, ctx, prompt_lens,
    pad_len, prefill_pos_ids, cache, return_cache, return_spec_stats,
):
    """The speculative spelling of generate()'s early-exit while loop.

    Commit discipline: per iteration every row verifies its own k drafts,
    but the batch commits the MINIMUM accepted length m across unfinished
    rows (the contiguous cache writes one shared [b, t] chunk at a
    scalar position, so rows cannot advance independently — the paged
    path's :func:`decode_step_spec` does true per-row commit).  Each
    row's committed tokens are a verified prefix of its own acceptance,
    so greedy output stays token-identical to the plain loop; rows that
    accepted beyond m simply re-verify the surplus next iteration.  Rows
    that hit EOS inside their accepted prefix stop constraining the
    minimum (they are done — pad-substitution covers their tail).

    Cache rewind: the chunk writes K/V at [pos, pos+k]; only [pos,
    pos+m] are committed.  The next iteration's chunk starts at
    pos+m+1 and spans k+1 slots, so every stale slot is rewritten
    BEFORE any attention visits it — the same stale-tail argument as
    the donated serving pool (docs/decode_path.md).  The cache carries
    ``draft_k`` slack slots past prompt+max_dec_len for the final
    iteration's overrun; overrun position ids clamp to the embedding
    table (those slots are never committed)."""
    b, prompt_len = input_ids.shape
    k = spec.draft_k
    K = k + 1
    DEC = gen.max_dec_len
    vocab = cfg.vocab_size
    use_counts = gen.repetition_penalty != 1.0
    greedy = gen.decode_strategy == "greedy_search"
    if key is None:
        key = jax.random.key(0)

    valid = (
        jnp.ones((b, prompt_len), jnp.int32)
        if pad_len is None
        else (jnp.arange(prompt_len)[None, :] >= pad_len[:, None]).astype(jnp.int32)
    )
    token_counts0 = jnp.zeros((b, vocab), jnp.int32).at[
        jnp.arange(b)[:, None], input_ids
    ].add(valid)

    logits, cache = forward_cached(
        params, input_ids, cache, jnp.int32(0), cfg, ctx,
        position_ids=prefill_pos_ids, kv_valid_from=pad_len,
    )
    last_logits = logits[:, -1, :].astype(jnp.float32)

    # pending_0 = the baseline loop's step-0 token, sampled through the
    # identical (single-sourced) processor chain
    p0 = process_step_logits(
        last_logits, jnp.zeros((b,), jnp.int32), token_counts0,
        jnp.full((b,), DEC - 1, jnp.int32), gen,
    )
    key, sub0 = jax.random.split(key)
    if greedy:
        pending0 = jnp.argmax(p0, axis=-1).astype(jnp.int32)
    else:
        pending0 = sample_logits(
            sub0, p0, temperature=gen.temperature, top_k=gen.top_k,
            top_p=gen.top_p,
        ).astype(jnp.int32)

    class SpecCarry(NamedTuple):
        cache: KVCache
        pending: jax.Array    # [b] token for step `emitted`
        pos: jax.Array        # cache slot where pending will be written
        emitted: jax.Array    # committed tokens so far (shared)
        unfinished: jax.Array
        token_counts: jax.Array
        key: jax.Array
        tokens: jax.Array     # [b, DEC + k + 1] (k+1 write slack)
        proposed: jax.Array   # drafted tokens (acceptance telemetry)
        accepted: jax.Array   # committed drafted tokens

    tokens0 = jnp.full((b, DEC + K), gen.pad_token_id, jnp.int32)

    def loop_cond(st: SpecCarry):
        return (st.emitted < DEC) & jnp.any(st.unfinished)

    def loop_body(st: SpecCarry):
        emitted = st.emitted
        # self-draft from the row's own prompt + committed output
        ctx_buf = jnp.concatenate([input_ids, st.tokens], axis=1)
        draft = ngram_propose(
            ctx_buf, prompt_len + emitted, st.pending, k, n=spec.ngram
        )
        chunk = jnp.concatenate([st.pending[:, None], draft], axis=1)

        # ONE t=k+1 forward verifies the whole chunk; overrun position
        # ids clamp to the embedding table (never committed)
        base = (
            prompt_lens if prompt_lens is not None
            else jnp.full((b,), prompt_len, jnp.int32)
        )
        pos_ids = jnp.clip(
            base[:, None] + emitted + jnp.arange(K)[None, :],
            0, cfg.max_position_embeddings - 1,
        )
        logits_all, cache = forward_cached(
            params, chunk, st.cache, st.pos, cfg, ctx,
            position_ids=pos_ids, kv_valid_from=pad_len,
        )

        key, sub = jax.random.split(st.key)
        sv = speculative_verify(
            sub, logits_all.astype(jnp.float32), chunk,
            st.token_counts if use_counts else None,
            st.unfinished, emitted, gen,
        )

        # batch-min commit: rows finished before the window, or finished
        # BY it (EOS inside their accepted prefix), stop constraining
        constraint = jnp.where(
            ~st.unfinished | sv.eos_hit.any(axis=1), k, sv.accepted
        )
        m = jnp.minimum(jnp.min(constraint), DEC - 1 - emitted)

        jmask = jnp.arange(K) <= m  # [K]
        window = jnp.where(jmask[None, :], sv.w, gen.pad_token_id)
        tokens = jax.lax.dynamic_update_slice(st.tokens, window, (0, emitted))
        counts = st.token_counts.at[jnp.arange(b)[:, None], sv.w].add(
            jmask[None, :].astype(jnp.int32)
        )
        unfinished = st.unfinished & ~(sv.eos_hit & jmask[None, :]).any(axis=1)

        # next pending = the token for step emitted + m + 1: the already-
        # accepted surplus draft when the row out-accepted the batch, else
        # the verify candidate (correction / residual / bonus)
        m_col = jnp.full((b, 1), m, jnp.int32)
        beyond = sv.accepted > m
        from_chunk = jnp.take_along_axis(
            chunk, jnp.minimum(m_col + 1, k), axis=1
        )[:, 0]
        from_pend = jnp.take_along_axis(sv.pend, m_col, axis=1)[:, 0]
        pending = jnp.where(
            unfinished,
            jnp.where(beyond, from_chunk, from_pend),
            gen.pad_token_id,
        ).astype(jnp.int32)

        n_alive = st.unfinished.sum().astype(jnp.int32)
        return SpecCarry(
            cache=cache,
            pending=pending,
            pos=st.pos + m + 1,
            emitted=emitted + m + 1,
            unfinished=unfinished,
            token_counts=counts,
            key=key,
            tokens=tokens,
            proposed=st.proposed + k * n_alive,
            accepted=st.accepted + m * n_alive,
        )

    st0 = SpecCarry(
        cache=cache,
        pending=pending0,
        pos=jnp.int32(prompt_len),
        emitted=jnp.int32(0),
        unfinished=jnp.ones((b,), bool),
        token_counts=token_counts0,
        key=key,
        tokens=tokens0,
        proposed=jnp.int32(0),
        accepted=jnp.int32(0),
    )
    st = jax.lax.while_loop(loop_cond, loop_body, st0)
    tokens = st.tokens[:, :DEC]
    out = (tokens,)
    if return_cache:
        out = out + (st.cache,)
    if return_spec_stats:
        out = out + ((st.proposed, st.accepted),)
    return out if len(out) > 1 else tokens


# ---------------------------------------------------------------------------
# Paged decode: block-pool KV cache + the step-wise entry the
# continuous-batching scheduler drives (core/continuous_batching.py).
# The contiguous generate() above runs ONE request set to completion
# inside a fused loop; these functions instead expose ONE decode step
# over a batch of INDEPENDENT rows (own positions, own budgets, own
# block tables into a shared arena), so the host scheduler can admit and
# evict rows at every step boundary.
# ---------------------------------------------------------------------------


class PagedPools(NamedTuple):
    """The paged KV arena: [layers, num_blocks, heads, block, head_dim]
    (heads-major within a block, matching KVCache's tiling rationale).
    Block 0 is the NULL block — never allocated to a sequence; inactive
    batch rows route their writes there (core/paged_cache.py).  Under
    PFX_KV_DTYPE=int8 the arrays are int8 and ``k_scale``/``v_scale``
    [layers, num_blocks, heads, block] carry per-(slot, head) scale
    tiles stored alongside the arena — each pool block owns its
    [heads, block] scale tile, DMA'd with it by the pallas kernel's
    clamped index map."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None


def init_paged_pools(
    cfg: GPTConfig, num_blocks: int, block: int, dtype=None,
    kv_dtype: str = "",
) -> PagedPools:
    shape = (cfg.num_layers, num_blocks, cfg.num_attention_heads, block,
             cfg.head_dim)
    if kv_cache_dtype(kv_dtype) == "int8":
        sshape = shape[:-1]
        return PagedPools(
            jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
            jnp.zeros(sshape, jnp.float32), jnp.zeros(sshape, jnp.float32),
        )
    dtype = dtype or jnp.dtype(cfg.dtype)
    return PagedPools(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


class PagedRows(NamedTuple):
    """Per-row decode state the scheduler threads through decode_step.

    ``positions`` is each row's NEXT write slot (= real prompt length +
    tokens generated so far); ``gen_steps`` counts generated tokens;
    ``max_news`` is the per-row decode budget (runtime data, NOT a
    compile key — unlike the contiguous path, a new max_tokens value
    never keys a retrace); ``forced_steps`` is the per-row step index
    where ``forced_eos_token_id`` fires — the CONTIGUOUS path's bucketed
    run end (`core/serving.plan_decode`'s ``run - 1``), not the raw
    budget, so forced-EOS output stays token-identical to the coalesce
    path (whose forced step usually lands beyond the trimmed output);
    ``logits`` are the pending next-token logits the next step samples
    from; ``counts`` back repetition penalty.

    ``reject`` (speculative path only, else None): the draft token id
    the last iteration's verify REJECTED at exactly the carried logits'
    position, or -1.  Sampled decode masks it out of the filtered
    distribution before drawing — the Leviathan residual rule carried
    across the step boundary; greedy ignores it (the argmax already
    differs from a rejected draft)."""

    logits: jax.Array        # [B, vocab] f32
    counts: jax.Array        # [B, vocab] int32
    positions: jax.Array     # [B] int32
    gen_steps: jax.Array     # [B] int32
    max_news: jax.Array      # [B] int32
    active: jax.Array        # [B] bool
    forced_steps: jax.Array  # [B] int32
    reject: Optional[jax.Array] = None  # [B] int32 (-1 = none)


def _paged_layer_step(
    p: Dict[str, Any],
    x: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    blk: jax.Array,
    off: jax.Array,
    tables: jax.Array,
    positions: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
):
    """One decoder layer over x [b, t, h]: write each of the t chunk
    tokens' K/V at pool slot (blk[i, j], off[i, j]) per row (t > 1 is
    the speculative verify chunk), then block-table paged attention with
    per-query causal bounds.  Under int8 the chunk quantizes on write
    and the per-slot scales land in the arena's scale planes."""
    dtype = x.dtype
    b, t, _ = x.shape
    n = cfg.num_attention_heads

    y = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"])
    qkv = jnp.einsum("bsh,htnd->bstnd", y, p["attn"]["qkv_kernel"].astype(dtype))
    qkv = qkv + p["attn"]["qkv_bias"].astype(dtype)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _constrain(ctx, q, ("batch", None, "heads", "kv"))

    # scatter the [b, t, n, d] chunk into each row's blocks: rows own
    # disjoint blocks and a row's t slots are distinct, so the only index
    # collisions are inactive/overrun rows' null-block writes
    # (garbage-on-garbage, never read)
    idx_b = blk[:, :, None]                  # [b, t, 1]
    idx_n = jnp.arange(n)[None, None, :]     # [1, 1, n]
    idx_o = off[:, :, None]
    if k_scale is not None:
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        k_pool = k_pool.at[idx_b, idx_n, idx_o, :].set(kq)
        v_pool = v_pool.at[idx_b, idx_n, idx_o, :].set(vq)
        k_scale = k_scale.at[idx_b, idx_n, idx_o].set(ks)
        v_scale = v_scale.at[idx_b, idx_n, idx_o].set(vs)
    else:
        k_pool = k_pool.at[idx_b, idx_n, idx_o, :].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[idx_b, idx_n, idx_o, :].set(v.astype(v_pool.dtype))

    attn_out = paged_decode_attention(
        q, k_pool, v_pool, tables, positions,
        impl="lax" if ctx is not None else "auto",
        k_scale=k_scale, v_scale=v_scale,
    )
    attn_out = jnp.einsum(
        "bsnd,ndh->bsh", attn_out, p["attn"]["out_kernel"].astype(dtype)
    ) + p["attn"]["out_bias"].astype(dtype)
    x = x + attn_out

    y = layer_norm(x, p["ln_2"]["scale"], p["ln_2"]["bias"])
    mp = p["mlp"]
    y = y @ mp["fc_in_kernel"].astype(dtype) + mp["fc_in_bias"].astype(dtype)
    y = jax.nn.gelu(y, approximate=True)
    y = y @ mp["fc_out_kernel"].astype(dtype) + mp["fc_out_bias"].astype(dtype)
    return x + y, k_pool, v_pool, k_scale, v_scale


def paged_forward_step(
    params: Dict[str, Any],
    tokens: jax.Array,
    pools: PagedPools,
    block_tables: jax.Array,
    positions: jax.Array,
    active: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
    n_valid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, PagedPools]:
    """tokens [B] or [B, t] at per-row slots positions..positions+t-1 ->
    (logits [B, t, v] f32, pools).  t = 1 is the plain decode step;
    t > 1 is the speculative verify chunk (causal within the chunk).
    Inactive rows still run (fixed shape) but write to the null block
    and their logits are garbage the caller ignores.  Chunk slots past a
    row's block-table allocation gather the NULL padding entry, so a
    near-budget verify overrun can never alias another row's blocks
    (the engine also reserves draft_k slack — belt and braces).

    ``n_valid`` [B] (chunked prefill) null-routes each row's chunk slots
    >= its real token count: a padded tail chunk's junk positions can
    wrap onto REAL slots of the row's last allocated block after the
    table-width clamp, so pad K/V must never be written anywhere."""
    if tokens.ndim == 1:
        tokens = tokens[:, None]
    B, t = tokens.shape
    dtype = jnp.dtype(cfg.dtype)
    word = params["embeddings"]["word"].astype(dtype)
    pe = params["embeddings"]["position"].astype(dtype)
    # per-slot positions; clamp inactive rows' (stale) and overrun
    # slots' embedding indices into the table
    pos_t = positions[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    pos_emb = jnp.clip(
        jnp.where(active[:, None], pos_t, 0),
        0, cfg.max_position_embeddings - 1,
    )
    x = word[tokens] + pe[pos_emb]  # [B, t, h]
    x = _constrain(ctx, x, ("batch", None, "embed"))

    bs = pools.k.shape[3]
    blk_log = jnp.clip(pos_t // bs, 0, block_tables.shape[1] - 1)
    blk = jnp.take_along_axis(block_tables, blk_log, axis=1)  # [B, t]
    blk = jnp.where(active[:, None], blk, 0)  # inactive rows -> null block
    if n_valid is not None:  # pad chunk slots -> null block
        blk = jnp.where(
            jnp.arange(t, dtype=jnp.int32)[None, :] < n_valid[:, None],
            blk, 0,
        )
    off = pos_t % bs

    quant = pools.k_scale is not None
    if quant:
        def body(x, inp):
            p_l, kp, vp, ksl, vsl = inp
            x, kp, vp, ksl, vsl = _paged_layer_step(
                p_l, x, kp, vp, blk, off, block_tables, positions, cfg, ctx,
                ksl, vsl,
            )
            return x, (kp, vp, ksl, vsl)

        xs = (params["layers"], pools.k, pools.v, pools.k_scale, pools.v_scale)
        x, (ks, vs, kss, vss) = jax.lax.scan(body, x, xs)
        out_pools = PagedPools(ks, vs, kss, vss)
    else:
        def body(x, inp):
            p_l, kp, vp = inp
            x, kp, vp, _, _ = _paged_layer_step(
                p_l, x, kp, vp, blk, off, block_tables, positions, cfg, ctx
            )
            return x, (kp, vp)

        x, (ks, vs) = jax.lax.scan(
            body, x, (params["layers"], pools.k, pools.v)
        )
        out_pools = PagedPools(ks, vs)
    x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"])
    logits = jnp.einsum("bsh,vh->bsv", x, word)
    logits = _constrain(ctx, logits, ("batch", None, "vocab"))
    return logits.astype(jnp.float32), out_pools


def paged_prefill(
    params: Dict[str, Any],
    prompt: jax.Array,
    prompt_len: jax.Array,
    pools: PagedPools,
    table_row: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[PagedPools, jax.Array, jax.Array]:
    """Prefill ONE row's prompt into its pool blocks (prefill-on-admit).

    ``prompt`` [1, P] is RIGHT-padded to the bucket (real tokens at
    [0, prompt_len); pad junk after) — unlike the contiguous serving
    path's left padding, paged rows are unpadded in their logical cache,
    so real token i lives at slot i and positions need no offset.  The
    prompt runs through the contiguous ``forward_cached`` prefill (causal
    masking makes the real rows' math exactly the unpadded computation),
    then the temp cache is repacked block-wise into the arena at
    ``table_row`` [PB] (PB * block >= P).  Pad-slot junk K/V land in the
    row's own blocks past ``prompt_len`` and are overwritten by decode
    steps before any attention limit reaches them — the same stale-tail
    argument as the donated contiguous pool.

    Returns (pools, last real token's logits [v] f32, prompt token
    counts [v] for repetition penalty)."""
    P = int(prompt.shape[1])
    layers = cfg.num_layers
    n = cfg.num_attention_heads
    d = cfg.head_dim
    PB = int(table_row.shape[0])
    bs = int(pools.k.shape[3])
    L = PB * bs
    if L < P:
        raise ValueError(
            f"table_row covers {PB}x{bs}={L} slots < prompt bucket {P}"
        )
    # the temp prefill cache is NATIVE dtype even when the arena is int8:
    # the prompt's self-attention runs at full precision and the K/V
    # quantize ONCE on the repack below (decode then reads the same
    # quantized prompt keys whether speculating or not)
    cache = init_cache(cfg, 1, L, kv_dtype="bf16")
    pos_ids = jnp.arange(P, dtype=jnp.int32)[None, :]
    logits, cache = forward_cached(
        params, prompt, cache, jnp.int32(0), cfg, ctx, position_ids=pos_ids
    )
    last = jax.lax.dynamic_index_in_dim(
        logits[0], prompt_len - 1, axis=0, keepdims=False
    ).astype(jnp.float32)
    # repack [layers, 1, n, L, d] -> per-block [layers, PB, n, bs, d]
    def pack(c):
        return c[:, 0].reshape(layers, n, PB, bs, d).transpose(0, 2, 1, 3, 4)

    counts = jnp.zeros((cfg.vocab_size,), jnp.int32).at[prompt[0]].add(
        (jnp.arange(P) < prompt_len).astype(jnp.int32)
    )
    if pools.k_scale is not None:
        kq, ksl = quantize_kv(pack(cache.k))
        vq, vsl = quantize_kv(pack(cache.v))
        return PagedPools(
            pools.k.at[:, table_row].set(kq),
            pools.v.at[:, table_row].set(vq),
            pools.k_scale.at[:, table_row].set(ksl),
            pools.v_scale.at[:, table_row].set(vsl),
        ), last, counts
    k_pool = pools.k.at[:, table_row].set(pack(cache.k).astype(pools.k.dtype))
    v_pool = pools.v.at[:, table_row].set(pack(cache.v).astype(pools.v.dtype))
    return PagedPools(k_pool, v_pool), last, counts


def paged_chunk_prefill(
    params: Dict[str, Any],
    tokens: jax.Array,
    pools: PagedPools,
    table_row: jax.Array,
    position: jax.Array,
    n_valid: jax.Array,
    last_idx: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[PagedPools, jax.Array]:
    """Prefill ONE row's next chunk of prompt tokens directly against the
    paged arena: ``tokens`` [1, t] land at slots position..position+t-1
    of the row's ``table_row`` blocks, attending over everything already
    in them — which is exactly what makes this the prefix-reuse and
    chunked-prefill spelling (docs/serving.md): the already-cached
    prefix (shared blocks) and earlier chunks are simply THERE, so only
    the unmatched suffix ever runs through the model.  Rides
    :func:`paged_forward_step`'s multi-token path (the speculative
    verify chunk machinery), so a chunk admission compiles into the same
    bounded (t, table-width) family as decode steps — no monolithic
    full-prompt prefill compile for a prompt that is mostly cached.

    Pad slots past the real chunk (``tokens[0, j]`` for j >= ``n_valid``)
    NULL-ROUTE their K/V writes outright: a near-capacity tail chunk's
    pad positions can alias real slots of the row's last block modulo
    the block size, so unlike `paged_prefill`'s bucket junk they must
    never land in the row's blocks at all.  Returns (pools, logits of
    chunk slot ``last_idx`` [v] f32 — the last REAL prompt token's
    logits on the final chunk)."""
    logits, pools = paged_forward_step(
        params, tokens, pools, table_row[None, :], position[None],
        jnp.ones((1,), bool), cfg, ctx, n_valid=n_valid[None],
    )
    last = jax.lax.dynamic_index_in_dim(
        logits[0], last_idx, axis=0, keepdims=False
    ).astype(jnp.float32)
    return pools, last


def prefix_token_counts(prompt_ids, vocab_size: int) -> "np.ndarray":
    """Host-side repetition-penalty seed counts for a prompt — the exact
    integer bincount `paged_prefill` computes in-graph, computed on host
    for admissions that skip the monolithic prefill (prefix hits /
    chunked prompts)."""
    import numpy as np

    return np.bincount(
        np.asarray(list(prompt_ids), np.int64), minlength=int(vocab_size)
    ).astype(np.int32)


def gather_kv_blocks(pools: PagedPools, table) -> Dict[str, "np.ndarray"]:
    """Copy one row's arena blocks to host for the KV-handoff payload:
    ``{"k", "v"[, "k_scale", "v_scale"]}`` with k/v shaped
    [layers, len(table), heads, block, dim] in the ARENA dtype (int8
    blocks ship with their per-(slot, head) scale planes — the decode
    replica adopts the quantized values bit-exactly instead of paying a
    second quantization error).  Host-side indexing, not a jit: handoff
    happens once per request at the prefill/decode boundary, never on
    the per-token hot path."""
    import numpy as np

    idx = jnp.asarray(table, jnp.int32)
    out = {"k": np.asarray(pools.k[:, idx]), "v": np.asarray(pools.v[:, idx])}
    if pools.k_scale is not None:
        out["k_scale"] = np.asarray(pools.k_scale[:, idx])
        out["v_scale"] = np.asarray(pools.v_scale[:, idx])
    return out


def scatter_kv_blocks(pools: PagedPools, table, blocks) -> PagedPools:
    """Adopt exported blocks into this arena at ``table`` (the adopting
    row's first ``len(table)`` allocated blocks).  The caller validates
    compatibility first (`core/paged_cache.check_handoff_meta`); this
    helper still refuses a dtype or per-block-shape mismatch loudly —
    scattering mistyped bytes would corrupt a live arena."""
    want = {"k", "v"} | (
        {"k_scale", "v_scale"} if pools.k_scale is not None else set()
    )
    if set(blocks) != want:
        raise ValueError(
            f"handoff arrays {sorted(blocks)} != arena arrays {sorted(want)}"
        )
    idx = jnp.asarray(table, jnp.int32)
    new = {}
    for name in sorted(want):
        pool = getattr(pools, name)
        arr = blocks[name]
        if str(arr.dtype) != str(pool.dtype):
            raise ValueError(
                f"handoff {name} dtype {arr.dtype} != arena {pool.dtype}"
            )
        if tuple(arr.shape) != (pool.shape[0], len(table)) + pool.shape[2:]:
            raise ValueError(
                f"handoff {name} shape {tuple(arr.shape)} does not cover "
                f"{len(table)} blocks of arena {tuple(pool.shape)}"
            )
        new[name] = pool.at[:, idx].set(jnp.asarray(arr))
    return PagedPools(
        new["k"], new["v"], new.get("k_scale"), new.get("v_scale")
    )


def process_step_logits(logits, steps, counts, forced_steps, gen):
    """THE per-step logits-processor chain (min-length -> repetition
    penalty -> forced BOS/EOS), shape-agnostic: ``logits`` [..., v] with
    ``steps``/``forced_steps`` matching the leading dims (per-row on the
    paged path, per-slot on the speculative verify chunk).
    Single-sourced on purpose: :func:`decode_step`,
    :func:`decode_step_spec`'s pending-token sampling, the speculative
    prefill seed, and `ops/speculative.speculative_verify` must all stay
    BITWISE identical or the greedy token-identity contract silently
    drifts.  ``counts`` None skips repetition penalty (callers pass None
    exactly when the penalty is 1.0)."""
    logits = apply_min_length(logits, steps, gen.min_dec_len, gen.eos_token_id)
    if counts is not None:
        logits = apply_repetition_penalty(logits, counts, gen.repetition_penalty)
    if gen.forced_bos_token_id >= 0:
        forced = jnp.full_like(logits, -1e10).at[
            ..., gen.forced_bos_token_id].set(0.0)
        logits = jnp.where((steps == 0)[..., None], forced, logits)
    if gen.forced_eos_token_id >= 0:
        forced = jnp.full_like(logits, -1e10).at[
            ..., gen.forced_eos_token_id].set(0.0)
        logits = jnp.where((steps == forced_steps)[..., None], forced, logits)
    return logits


def decode_step(
    params: Dict[str, Any],
    pools: PagedPools,
    block_tables: jax.Array,
    rows: PagedRows,
    cfg: GPTConfig,
    gen: GenerationConfig,
    key: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[jax.Array, PagedPools, PagedRows]:
    """ONE iteration-level decode step over the running batch.

    Samples each active row's next token from its pending logits through
    the same processor chain as :func:`generate` (min-length, repetition
    penalty, forced BOS/EOS — all per-row: rows sit at different steps),
    writes the token's K/V at the row's current slot, and returns the
    refreshed pending logits.  Greedy rows are token-identical to the
    contiguous path; sampling rows draw from per-step subkeys (a
    different, but deterministic, stream).  Returns (sampled tokens [B],
    pools, rows')."""
    B, vocab = rows.logits.shape
    i = rows.gen_steps
    logits = process_step_logits(
        rows.logits, i, rows.counts, rows.forced_steps, gen
    )
    if gen.decode_strategy == "greedy_search":
        nxt = jnp.argmax(logits, axis=-1)
    else:
        if key is None:
            raise ValueError("sampling decode_step needs a PRNG key")
        nxt = sample_logits(
            key, logits, temperature=gen.temperature, top_k=gen.top_k,
            top_p=gen.top_p,
        )
    nxt = jnp.where(rows.active, nxt, gen.pad_token_id)
    counts = rows.counts.at[jnp.arange(B), nxt].add(
        rows.active.astype(jnp.int32)
    )
    finished = rows.active & (
        (nxt == gen.eos_token_id) | (i + 1 >= rows.max_news)
    )
    new_logits, pools = paged_forward_step(
        params, nxt, pools, block_tables, rows.positions, rows.active,
        cfg, ctx,
    )
    act = rows.active.astype(jnp.int32)
    new_rows = PagedRows(
        logits=new_logits[:, 0],
        counts=counts,
        positions=rows.positions + act,
        gen_steps=i + act,
        max_news=rows.max_news,
        active=rows.active & ~finished,
        forced_steps=rows.forced_steps,
    )
    return nxt, pools, new_rows


def decode_step_spec(
    params: Dict[str, Any],
    pools: PagedPools,
    block_tables: jax.Array,
    rows: PagedRows,
    drafts: jax.Array,
    cfg: GPTConfig,
    gen: GenerationConfig,
    key: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[jax.Array, jax.Array, PagedPools, PagedRows]:
    """ONE speculative iteration over the running batch — the paged
    spelling of :func:`_generate_speculative`'s body, with TRUE per-row
    commit (each row owns its positions, so accepted lengths never
    constrain each other; accepted length is runtime DATA, not a compile
    key — ``drafts`` [B, k] are host-proposed runtime data too).

    Per row: sample the pending token t0 from ``rows.logits`` through
    exactly :func:`decode_step`'s processor chain (greedy rows are
    bitwise the baseline), forward the [t0, draft_0..k-1] chunk in ONE
    t=k+1 dispatch (writing K/V at positions..positions+k), verify the
    drafts with :func:`~paddlefleetx_tpu.ops.speculative.
    speculative_verify`, and commit t0 plus the accepted prefix —
    truncated by the per-row budget.  Rejected-tail K/V slots are
    rewritten by the next iteration's chunk before any attention visits
    them (positions advance only by the committed count: the per-row
    position REWIND; block tables are untouched — rows reserved their
    full capacity, plus draft_k slack, at admission).

    Returns (window [B, k+1] committed tokens — pad past each row's
    count, ncommit [B] int32 in [0, k+1] (0 only for inactive rows),
    pools, rows').  ``rows'.logits`` carries the RAW target logits at
    each row's last committed position; ``rows'.reject`` the residual
    mask for the next sample (sampling mode; see :class:`PagedRows`)."""
    B, vocab = rows.logits.shape
    k = int(drafts.shape[1])
    K = k + 1
    i = rows.gen_steps
    greedy = gen.decode_strategy == "greedy_search"
    use_counts = gen.repetition_penalty != 1.0
    if not greedy and key is None:
        raise ValueError("sampling decode_step_spec needs a PRNG key")

    # --- t0: the baseline decode_step sampling rule on pending logits
    logits = process_step_logits(
        rows.logits, i, rows.counts, rows.forced_steps, gen
    )
    if greedy:
        t0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        key_verify = key
    else:
        key, key_t0, key_verify = (
            jax.random.split(key, 3)
        )
        filt = filtered_logits(
            logits, temperature=gen.temperature, top_k=gen.top_k,
            top_p=gen.top_p,
        )
        if rows.reject is not None:
            # residual rule carried across the step boundary: mask the
            # draft the last verify rejected at THIS position (post-
            # filter, so the renormalized nucleus is the exact residual)
            hit = rows.reject >= 0
            safe = jnp.clip(rows.reject, 0, vocab - 1)
            filt = jnp.where(
                hit[:, None]
                & (jnp.arange(vocab)[None, :] == safe[:, None]),
                -1e10, filt,
            )
        t0 = jax.random.categorical(key_t0, filt, axis=-1).astype(jnp.int32)
    nxt0 = jnp.where(rows.active, t0, gen.pad_token_id)
    chunk = jnp.concatenate([nxt0[:, None], drafts.astype(jnp.int32)], axis=1)

    # --- ONE t=k+1 verify forward
    logits_all, pools = paged_forward_step(
        params, chunk, pools, block_tables, rows.positions, rows.active,
        cfg, ctx,
    )
    sv = speculative_verify(
        key_verify, logits_all, chunk,
        rows.counts if use_counts else None,
        rows.active, i, gen, forced_steps=rows.forced_steps,
    )

    # --- per-row commit: the accepted prefix cut by the decode budget
    budget_ok = (i[:, None] + jnp.arange(K)[None, :]) < rows.max_news[:, None]
    valid = sv.real & budget_ok
    ncommit = valid.sum(axis=1).astype(jnp.int32)
    window = jnp.where(valid, sv.w, gen.pad_token_id)
    jmask = (jnp.arange(K)[None, :] < ncommit[:, None]).astype(jnp.int32)
    counts = rows.counts.at[jnp.arange(B)[:, None], window].add(jmask)

    eos_fin = (sv.eos_hit & valid).any(axis=1)
    budget_fin = (i + ncommit) >= rows.max_news
    finished = rows.active & (eos_fin | budget_fin)

    # --- carry the RAW logits at each row's last committed position
    sel = jnp.clip(ncommit - 1, 0, k)[:, None, None]
    new_logits = jnp.take_along_axis(logits_all, sel, axis=1)[:, 0]
    new_logits = jnp.where(rows.active[:, None], new_logits, rows.logits)

    # --- residual mask: a MISMATCH rejection at exactly the carried slot
    a = sv.accepted
    a_cl = jnp.clip(a, 0, k - 1)
    ok_at_a = jnp.take_along_axis(sv.ok, a_cl[:, None], axis=1)[:, 0]
    real_at_a = jnp.take_along_axis(sv.real, a[:, None], axis=1)[:, 0]
    mism = (a < k) & real_at_a & ~ok_at_a
    rej_draft = jnp.take_along_axis(drafts, a_cl[:, None], axis=1)[:, 0]
    reject = jnp.where(
        mism & (ncommit == a + 1) & rows.active & ~finished,
        rej_draft.astype(jnp.int32), jnp.int32(-1),
    )

    new_rows = PagedRows(
        logits=new_logits,
        counts=counts,
        positions=rows.positions + ncommit,
        gen_steps=i + ncommit,
        max_news=rows.max_news,
        active=rows.active & ~finished,
        forced_steps=rows.forced_steps,
        reject=reject,
    )
    return window, ncommit, pools, new_rows


# ---------------------------------------------------------------------------
# Beam search (reference single_model.py:1190-1320 beam strategy +
# BeamSearchScorer; diverse groups via HammingDiversityLogitsProcessor)
# ---------------------------------------------------------------------------


def _length_penalty(length, alpha: float):
    return jnp.power(length.astype(jnp.float32), alpha)


def beam_search(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: GPTConfig,
    gen: GenerationConfig,
    ctx: Optional[ShardingCtx] = None,
    prompt_lens: Optional[jax.Array] = None,
) -> jax.Array:
    """Static-shape beam search: [b, prompt_len] -> [b, max_dec_len].

    K = num_beams alive beams per prompt plus a K-slot finished pool;
    each step takes the top 2*Kg candidates per beam group (Kg = K /
    num_beam_groups), routes EOS continuations into the finished pool with
    length penalty, keeps the best Kg non-EOS continuations alive, and
    reorders the KV cache by parent beam.  ``diversity_penalty`` applies
    the Hamming penalty against earlier groups' same-step choices.
    Repetition penalty is not applied on the beam path (matching the
    reference beam strategy's processor set)."""
    b, prompt_len = input_ids.shape
    K, G = gen.num_beams, gen.num_beam_groups
    if K % G:
        raise ValueError(f"num_beams {K} not divisible by num_beam_groups {G}")
    Kg = K // G
    vocab = cfg.vocab_size
    # length validated by generate() before dispatch
    max_len = prompt_len + gen.max_dec_len

    # prefill ONCE per prompt, then repeat the cache/logits K-fold (all
    # beams share the prompt; re-running the forward K times would be
    # K x the prefill FLOPs for identical results)
    pad_len, prefill_pos_ids = _left_pad_prefill(prompt_len, prompt_lens)
    # beam reorders the cache by parent each step and rebuilds it here —
    # always native dtype (int8 KV quant covers the sampling/greedy
    # serving paths, not beam)
    cache = init_cache(cfg, b, max_len, kv_dtype="bf16")
    logits, cache = forward_cached(
        params, input_ids, cache, jnp.int32(0), cfg, ctx,
        position_ids=prefill_pos_ids, kv_valid_from=pad_len,
    )
    cache = KVCache(
        jnp.repeat(cache.k, K, axis=1), jnp.repeat(cache.v, K, axis=1)
    )
    logits0 = jnp.repeat(logits[:, -1, :].astype(jnp.float32), K, axis=0)
    pad_len_flat = jnp.repeat(pad_len, K, axis=0) if pad_len is not None else None
    lens_flat = (
        jnp.repeat(prompt_lens, K, axis=0) if prompt_lens is not None else None
    )

    NEG = jnp.float32(-1e9)
    # only each group's first beam is live at step 0 (avoids duplicates)
    init_scores = jnp.where(
        (jnp.arange(K) % Kg) == 0, 0.0, NEG
    )[None].repeat(b, 0)  # [b, K]

    def _pin_beam(x, logical):
        """Pin beam bookkeeping to batch-sharded/replicated-elsewhere.

        jax-0.4.37 GSPMD mis-partitions the beam scan under TP: the scan
        carry's bookkeeping arrays (derived from vocab-sharded logits via
        top_k/gather chains) can leave the loop marked partial-over-`model`
        while each shard actually holds the full value, and the consumer's
        combining all-reduce then multiplies token ids by mp_degree
        (observed: every emitted token exactly 2x under mp=2; the same
        ops unrolled OUTSIDE lax.scan partition correctly).  Explicitly
        constraining the carry each step keeps the sharding the partitioner
        propagates identical to what the values actually are.  These are
        [b, K]-sized arrays — replication is free."""
        if ctx is None:
            return x
        return ctx.constrain(x, logical)

    class Beams(NamedTuple):
        cache: KVCache
        logits: jax.Array  # [b*K, v]
        scores: jax.Array  # [b, K] cumulative alive logprobs
        seqs: jax.Array  # [b, K, max_dec]
        fin_scores: jax.Array  # [b, K]
        fin_seqs: jax.Array  # [b, K, max_dec]
        pos: jax.Array

    def step(st: Beams, i):
        logp = jax.nn.log_softmax(st.logits, axis=-1).reshape(b, K, vocab)
        logp = apply_min_length(
            logp.reshape(b * K, vocab), jnp.full((b * K,), i),
            gen.min_dec_len, gen.eos_token_id,
        ).reshape(b, K, vocab)
        logp = apply_forced_token(
            logp.reshape(b * K, vocab), i, 0, gen.forced_bos_token_id
        ).reshape(b, K, vocab)
        logp = apply_forced_token(
            logp.reshape(b * K, vocab), i, gen.max_dec_len - 1,
            gen.forced_eos_token_id,
        ).reshape(b, K, vocab)

        new_scores = st.scores
        fin_scores, fin_seqs = st.fin_scores, st.fin_seqs
        chosen_tok = jnp.zeros((b, K), jnp.int32)
        chosen_parent = jnp.zeros((b, K), jnp.int32)
        step_tokens = jnp.full((b, K), -1, jnp.int32)  # for Hamming penalty

        for g in range(G):  # static, G small
            sl = slice(g * Kg, (g + 1) * Kg)
            glogp = logp[:, sl]  # [b, Kg, v]
            if gen.diversity_penalty > 0.0 and g > 0:
                glogp = jax.vmap(
                    lambda lg, cur: apply_hamming_diversity(
                        lg, cur, g * Kg, gen.diversity_penalty
                    )
                )(glogp, step_tokens)
            cand = (st.scores[:, sl, None] + glogp).reshape(b, Kg * vocab)
            top_s, top_i = jax.lax.top_k(cand, 2 * Kg)  # [b, 2Kg]
            tok = top_i % vocab
            parent = top_i // vocab + g * Kg  # flat beam index
            is_eos = tok == gen.eos_token_id

            # finished pool: EOS continuations scored with length penalty
            f_cand = jnp.where(is_eos, top_s / _length_penalty(
                jnp.full((b, 2 * Kg), i + 1), gen.length_penalty
            ), NEG)
            # candidate finished sequences = parent's seq + eos at i
            parent_seqs = jnp.take_along_axis(
                st.seqs, parent[..., None], axis=1
            )  # [b, 2Kg, max_dec]
            f_seqs = jax.vmap(
                lambda ps, tk: ps.at[:, i].set(tk)
            )(parent_seqs, tok)
            all_f_scores = jnp.concatenate([fin_scores, f_cand], axis=1)
            all_f_seqs = jnp.concatenate([fin_seqs, f_seqs], axis=1)
            keep_s, keep_i = jax.lax.top_k(all_f_scores, K)
            fin_scores = keep_s
            fin_seqs = jnp.take_along_axis(all_f_seqs, keep_i[..., None], axis=1)

            # alive: best Kg non-EOS continuations
            alive_s = jnp.where(is_eos, NEG, top_s)
            a_s, a_i = jax.lax.top_k(alive_s, Kg)  # indices into 2Kg
            a_tok = jnp.take_along_axis(tok, a_i, axis=1)
            a_parent = jnp.take_along_axis(parent, a_i, axis=1)
            new_scores = new_scores.at[:, sl].set(a_s)
            chosen_tok = chosen_tok.at[:, sl].set(a_tok)
            chosen_parent = chosen_parent.at[:, sl].set(a_parent)
            step_tokens = step_tokens.at[:, sl].set(a_tok)

        # reorder sequences/caches by parent beam, then append tokens
        new_seqs = jnp.take_along_axis(st.seqs, chosen_parent[..., None], axis=1)
        new_seqs = jax.vmap(lambda s, t: s.at[:, i].set(t))(new_seqs, chosen_tok)
        flat_parent = (
            jnp.arange(b)[:, None] * K + chosen_parent
        ).reshape(-1)  # [b*K]
        cache = KVCache(
            jnp.take(st.cache.k, flat_parent, axis=1),
            jnp.take(st.cache.v, flat_parent, axis=1),
        )
        step_pos_ids = (
            (lens_flat + i)[:, None] if lens_flat is not None else None
        )
        new_logits, cache = forward_cached(
            params, chosen_tok.reshape(b * K, 1), cache, st.pos, cfg, ctx,
            position_ids=step_pos_ids, kv_valid_from=pad_len_flat,
        )
        return Beams(
            cache=cache,
            logits=new_logits[:, -1, :].astype(jnp.float32),
            scores=_pin_beam(new_scores, ("batch", None)),
            seqs=_pin_beam(new_seqs, ("batch", None, None)),
            fin_scores=_pin_beam(fin_scores, ("batch", None)),
            fin_seqs=_pin_beam(fin_seqs, ("batch", None, None)),
            pos=st.pos + 1,
        ), None

    st0 = Beams(
        cache=cache,
        logits=logits0,
        scores=init_scores,
        seqs=jnp.full((b, K, gen.max_dec_len), gen.pad_token_id, jnp.int32),
        fin_scores=jnp.full((b, K), NEG),
        fin_seqs=jnp.full((b, K, gen.max_dec_len), gen.pad_token_id, jnp.int32),
        pos=jnp.int32(prompt_len),
    )
    st, _ = jax.lax.scan(step, st0, jnp.arange(gen.max_dec_len))

    # merge still-alive beams (scored at full length) into the pool
    alive_final = st.scores / _length_penalty(
        jnp.full((b, K), gen.max_dec_len), gen.length_penalty
    )
    all_scores = jnp.concatenate([st.fin_scores, alive_final], axis=1)
    all_seqs = jnp.concatenate([st.fin_seqs, st.seqs], axis=1)
    best = jnp.argmax(all_scores, axis=1)
    return jnp.take_along_axis(all_seqs, best[:, None, None], axis=1)[:, 0]
