"""GPT autoregressive generation: KV-cache decode + logits processors.

Reference: ``GPTForGeneration`` (single_model.py:898-1320 — prepare inputs,
logits processors, per-token sample loop with incremental KV-cache decode)
and ``processor.py`` (LogitsProcessorList etc.).

TPU-native shape discipline: the reference's dynamic Python while-loop
becomes a bounded ``lax.while_loop`` over ``max_dec_len`` slots with an
``unfinished`` flag (padded static shapes; XLA traces one step) that exits
as soon as every row has emitted EOS; ``PFX_DECODE_SCAN=1`` restores the
fixed-trip ``lax.scan`` (trace-shape debugging; beam search keeps scan).
The KV cache is a preallocated [layers, b, heads, max_len, head_dim] pair
(heads-major so the flash-decode kernel's block tiling keeps (seq, dim)
minor — ``ops/decode_attention.py``) updated with ``dynamic_update_slice``;
prefill packs the prompt in one forward.  The decode step attends only
over cache blocks ``< ceil((pos+t)/block)``, not the whole buffer; set
PFX_DECODE_ATTN=dense for the legacy attend-over-everything path.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.gpt.config import GPTConfig
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, _constrain, layer_norm
from paddlefleetx_tpu.ops.decode_attention import (
    decode_attention,
    decode_attn_mode,
    dense_cache_attention,
    paged_decode_attention,
)
from paddlefleetx_tpu.ops.sampling import sample_logits


class KVCache(NamedTuple):
    k: jax.Array  # [layers, b, heads, max_len, head_dim]
    v: jax.Array


def init_cache(cfg: GPTConfig, batch: int, max_len: int, dtype=None) -> KVCache:
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, batch, cfg.num_attention_heads, max_len, cfg.head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# Cache-aware forward (shares weights with model.gpt_specs; the training
# forward in model.py stays cache-free)
# ---------------------------------------------------------------------------


def _layer_with_cache(
    p: Dict[str, Any],
    x: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
    kv_valid_from: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder layer over x [b, t, h] writing K/V at offset ``pos``.

    Attends over cache[:pos+t] via the length-aware blocked kernel
    (``ops/decode_attention``): only cache blocks up to ceil((pos+t)/block)
    are visited, with the causal + ``kv_valid_from`` left-pad masks folded
    into per-block masking.  PFX_DECODE_ATTN=dense restores the legacy
    materialized-bias attend-over-the-whole-buffer path (A/B benching).
    Under TP serving (reference GPTForGenerationHybrid hybrid_model.py:1209)
    the qkv/cache/attention stay ``heads``-sharded over the model axis and
    the output projection row-psum is inserted by GSPMD; the sharded path
    uses the lax spelling of the blocked loop (GSPMD partitions it freely,
    a pallas_call would need shard_map).
    """
    dtype = x.dtype
    b, t, h = x.shape

    y = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"])
    qkv = jnp.einsum("bsh,htnd->bstnd", y, p["attn"]["qkv_kernel"].astype(dtype))
    qkv = qkv + p["attn"]["qkv_bias"].astype(dtype)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _constrain(ctx, q, ("batch", None, "heads", "kv"))

    # cache layout [b, heads, max_len, head_dim]: transpose the (small)
    # step chunk, never the cache
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k.transpose(0, 2, 1, 3), (0, 0, pos, 0)
    )
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.transpose(0, 2, 1, 3), (0, 0, pos, 0)
    )
    k_cache = _constrain(ctx, k_cache, ("batch", "heads", None, "kv"))
    v_cache = _constrain(ctx, v_cache, ("batch", "heads", None, "kv"))

    if decode_attn_mode() == "dense":
        attn_out = dense_cache_attention(
            q, k_cache, v_cache, pos, kv_valid_from=kv_valid_from
        )
    else:
        attn_out = decode_attention(
            q, k_cache, v_cache, pos, kv_valid_from=kv_valid_from,
            impl="lax" if ctx is not None else "auto",
        )
    attn_out = jnp.einsum(
        "bsnd,ndh->bsh", attn_out, p["attn"]["out_kernel"].astype(dtype)
    ) + p["attn"]["out_bias"].astype(dtype)
    x = x + attn_out

    y = layer_norm(x, p["ln_2"]["scale"], p["ln_2"]["bias"])
    mp = p["mlp"]
    y = y @ mp["fc_in_kernel"].astype(dtype) + mp["fc_in_bias"].astype(dtype)
    y = jax.nn.gelu(y, approximate=True)
    y = y @ mp["fc_out_kernel"].astype(dtype) + mp["fc_out_bias"].astype(dtype)
    return x + y, k_cache, v_cache


def forward_cached(
    params: Dict[str, Any],
    tokens: jax.Array,
    cache: KVCache,
    pos: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
    position_ids: Optional[jax.Array] = None,
    kv_valid_from: Optional[jax.Array] = None,
) -> Tuple[jax.Array, KVCache]:
    """tokens [b, t] at positions [pos, pos+t) -> (logits [b, t, v], cache).

    ``position_ids`` [b, t] overrides the default pos+arange(t) position
    embedding indices and ``kv_valid_from`` [b] masks cache keys before a
    row's first real token — together they implement left-padded serving
    buckets (each row's real prompt right-aligned at the same width)."""
    dtype = jnp.dtype(cfg.dtype)
    b, t = tokens.shape
    word = params["embeddings"]["word"].astype(dtype)
    pe = params["embeddings"]["position"].astype(dtype)
    if position_ids is None:
        x = word[tokens] + pe[pos + jnp.arange(t)][None, :, :]
    else:
        x = word[tokens] + pe[position_ids]
    x = _constrain(ctx, x, ("batch", None, "embed"))

    def body(x, inp):
        p_l, kc, vc = inp
        x, kc, vc = _layer_with_cache(p_l, x, kc, vc, pos, cfg, ctx, kv_valid_from)
        return x, (kc, vc)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"])
    logits = jnp.einsum("bsh,vh->bsv", x, word)
    return _constrain(ctx, logits, ("batch", None, "vocab")), KVCache(ks, vs)


# ---------------------------------------------------------------------------
# Logits processors (reference processor.py)
# ---------------------------------------------------------------------------


def apply_repetition_penalty(logits, generated_mask_counts, penalty: float):
    """Divide positive / multiply negative logits of already-generated tokens
    (reference RepetitionPenaltyLogitsProcessor)."""
    if penalty == 1.0:
        return logits
    seen = generated_mask_counts > 0
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def apply_min_length(logits, cur_len, min_len: int, eos_token_id: int):
    """Suppress EOS before min_length (reference MinLengthLogitsProcessor)."""
    if min_len <= 0:
        return logits
    return jnp.where(
        (cur_len < min_len)[..., None]
        & (jnp.arange(logits.shape[-1]) == eos_token_id)[None, :],
        -1e10,
        logits,
    )


def apply_forced_token(logits, step, force_at_step: int, token_id: int):
    """Force a specific token at a given decode step (reference
    ForcedBOSTokenLogitsProcessor / ForcedEOSTokenLogitsProcessor)."""
    if token_id < 0:
        return logits
    forced = jnp.full_like(logits, -1e10).at[..., token_id].set(0.0)
    return jnp.where(step == force_at_step, forced, logits)


def apply_hamming_diversity(logits, current_tokens, group_start: int, penalty: float):
    """Penalize tokens already chosen by EARLIER beam groups at this step
    (reference HammingDiversityLogitsProcessor): logits [gb, v];
    current_tokens [gb] holds this step's choices for groups processed so
    far (entries >= group_start are not yet decided and are masked off)."""
    if penalty == 0.0:
        return logits
    vocab = logits.shape[-1]
    decided = jnp.arange(current_tokens.shape[0]) < group_start
    counts = jnp.zeros((vocab,), logits.dtype).at[current_tokens].add(
        decided.astype(logits.dtype)
    )
    return logits - penalty * counts[None, :]


# ---------------------------------------------------------------------------
# Generation loop
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Reference GPTForGeneration config surface (single_model.py:898-960)."""

    max_dec_len: int = 64
    min_dec_len: int = 1
    decode_strategy: str = "sampling"  # sampling | greedy_search | beam_search
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: int = 50256
    pad_token_id: int = 0
    # beam search (reference BeamSearchScorer + processor.py)
    num_beams: int = 4
    length_penalty: float = 1.0
    # diverse (group) beam search: HammingDiversityLogitsProcessor
    num_beam_groups: int = 1
    diversity_penalty: float = 0.0
    # ForcedBOS/ForcedEOS processors (-1 = disabled)
    forced_bos_token_id: int = -1
    forced_eos_token_id: int = -1

    def __post_init__(self):
        if self.decode_strategy not in ("sampling", "greedy_search", "beam_search"):
            raise ValueError(
                f"bad decode_strategy {self.decode_strategy!r}; "
                "valid: sampling, greedy_search, beam_search"
            )


def decode_loop_mode() -> str:
    """PFX_DECODE_SCAN: "1" restores the fixed-trip ``lax.scan`` decode
    loop (trace-shape debugging; also what beam search always uses), "0"/
    unset selects the early-exit ``lax.while_loop``.  Loud parse — a typo
    must not silently A/B while-vs-while on a chip window."""
    env = os.environ.get("PFX_DECODE_SCAN") or "0"
    if env not in ("0", "1"):
        raise ValueError(f"PFX_DECODE_SCAN={env!r}; valid: 0, 1")
    return "scan" if env == "1" else "while"


def _left_pad_prefill(prompt_len: int, prompt_lens: Optional[jax.Array]):
    """(pad_len [b], prefill position ids [b, P]) for left-padded buckets;
    (None, None) on the unpadded path."""
    if prompt_lens is None:
        return None, None
    pad_len = jnp.int32(prompt_len) - prompt_lens
    pos_ids = jnp.maximum(jnp.arange(prompt_len)[None, :] - pad_len[:, None], 0)
    return pad_len, pos_ids


def bucket_len(longest: int, multiple: int) -> int:
    """THE prompt-bucket formula (next multiple of ``multiple``).

    Single-sourced on purpose: ``pad_prompts`` (the padding itself),
    ``GenerationServer.warmup`` (bucket validation), and the serve-layer
    coalesce key (tools/serve.py ``plan_request``) must all agree on the
    padded width — a drifted copy would silently key fresh compiles for
    coalesced traffic."""
    return ((int(longest) + int(multiple) - 1) // int(multiple)) * int(multiple)


def pad_prompts(prompts, pad_token_id: int, multiple: int = 64):
    """Left-pad a list of variable-length prompts to a shared bucketed
    width (``bucket_len``): serving compiles once per BUCKET, not once
    per prompt length (VERDICT r1 weak #4).

    Returns (padded [b, P] int32 array, prompt_lens [b])."""
    import numpy as np

    P = bucket_len(max(len(p) for p in prompts), multiple)
    out = np.full((len(prompts), P), pad_token_id, np.int32)
    lens = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        out[i, P - len(p):] = p
        lens[i] = len(p)
    return jnp.asarray(out), jnp.asarray(lens)


def generate(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: GPTConfig,
    gen: GenerationConfig,
    key: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
    prompt_lens: Optional[jax.Array] = None,
    cache: Optional[KVCache] = None,
    return_cache: bool = False,
) -> jax.Array:
    """input_ids [b, prompt_len] -> generated ids [b, max_dec_len]
    (eos/pad-filled after finish).

    Without ``prompt_lens`` the prompts are taken as right-aligned and
    unpadded.  With ``prompt_lens`` [b], rows are LEFT-padded to a shared
    width (see :func:`pad_prompts`): padded key slots are masked out of
    attention and position ids start at the first real token — the shape
    (and therefore the compiled artifact) depends only on the bucket.

    Pass ``ctx`` to serve on a mesh: the KV cache and attention stay
    heads-sharded over the model axis (TP serving parity with the
    reference's GPTForGenerationHybrid, hybrid_model.py:1209).

    ``cache``: optionally pass a preallocated ``init_cache(cfg, b,
    prompt_len + max_dec_len)`` buffer instead of allocating inside the
    trace — a caller jitting generate can then DONATE it
    (``donate_argnums``) so the per-step ``dynamic_update_slice`` writes
    in place instead of copying the pair each step.  A donated cache is
    CONSUMED: the caller must not touch it after the call.  Donation only
    aliases an input to an OUTPUT buffer, so pair it with
    ``return_cache=True`` — the returned final cache occupies the donated
    buffer and can be donated straight back on the next same-shape call
    (``core/serving.py`` keeps a per-bucket pool doing exactly that;
    stale tail slots are safe because the blocked kernel never visits
    blocks beyond ``pos + t``).

    ``return_cache``: return ``(tokens, final KVCache)`` instead of
    tokens (sampling/greedy only)."""
    if cfg.num_experts > 1:
        raise NotImplementedError("KV-cache generation for MoE models unsupported")
    b, prompt_len = input_ids.shape
    max_len = prompt_len + gen.max_dec_len
    if max_len > cfg.max_position_embeddings:
        # with prompt_lens, position ids are bounded by the REAL lengths,
        # not the bucket width: only reject when the real positions
        # overflow (or the bound cannot be known, i.e. traced lengths)
        real_bound = None
        if prompt_lens is not None:
            try:
                real_bound = int(jax.numpy.max(prompt_lens)) + gen.max_dec_len
            except jax.errors.ConcretizationTypeError:
                real_bound = None  # traced lengths: bucket-width bound applies
        if real_bound is None or real_bound > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt_len {prompt_len} + max_dec_len {gen.max_dec_len} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}"
            )
    if key is None:
        key = jax.random.key(0)
    if gen.decode_strategy == "beam_search":
        if cache is not None or return_cache:
            raise ValueError(
                "cache donation/return is not supported for beam_search (the "
                "beam loop reorders the cache by parent each step)"
            )
        return beam_search(params, input_ids, cfg, gen, ctx=ctx, prompt_lens=prompt_lens)

    pad_len, prefill_pos_ids = _left_pad_prefill(prompt_len, prompt_lens)
    if cache is None:
        cache = init_cache(cfg, b, max_len)
    else:
        want = (cfg.num_layers, b, cfg.num_attention_heads, max_len, cfg.head_dim)
        if cache.k.shape != want:
            raise ValueError(
                f"provided cache shape {cache.k.shape} != required {want} "
                f"(prompt {prompt_len} + max_dec_len {gen.max_dec_len})"
            )
    vocab = cfg.vocab_size
    valid = (
        jnp.ones((b, prompt_len), jnp.int32)
        if pad_len is None
        else (jnp.arange(prompt_len)[None, :] >= pad_len[:, None]).astype(jnp.int32)
    )
    token_counts0 = jnp.zeros((b, vocab), jnp.int32).at[
        jnp.arange(b)[:, None], input_ids
    ].add(valid)

    # prefill: cache K/V for the prompt; its last-row logits seed the loop
    logits, cache = forward_cached(
        params, input_ids, cache, jnp.int32(0), cfg, ctx,
        position_ids=prefill_pos_ids, kv_valid_from=pad_len,
    )
    last_logits = logits[:, -1, :].astype(jnp.float32)

    class Carry(NamedTuple):
        cache: KVCache
        logits: jax.Array  # [b, vocab] — logits of the position to sample
        pos: jax.Array
        unfinished: jax.Array  # [b] bool
        token_counts: jax.Array
        key: jax.Array

    def step(carry: Carry, i):
        logits = apply_min_length(
            carry.logits, jnp.full((b,), i), gen.min_dec_len, gen.eos_token_id
        )
        logits = apply_repetition_penalty(
            logits, carry.token_counts, gen.repetition_penalty
        )
        logits = apply_forced_token(logits, i, 0, gen.forced_bos_token_id)
        logits = apply_forced_token(
            logits, i, gen.max_dec_len - 1, gen.forced_eos_token_id
        )
        key, sub = jax.random.split(carry.key)
        if gen.decode_strategy == "greedy_search":
            nxt = jnp.argmax(logits, axis=-1)
        else:
            nxt = sample_logits(
                sub, logits, temperature=gen.temperature, top_k=gen.top_k, top_p=gen.top_p
            )
        nxt = jnp.where(carry.unfinished, nxt, gen.pad_token_id)
        unfinished = carry.unfinished & (nxt != gen.eos_token_id)
        counts = carry.token_counts.at[jnp.arange(b), nxt].add(1)
        step_pos_ids = (
            (prompt_lens + i)[:, None] if prompt_lens is not None else None
        )
        new_logits, cache = forward_cached(
            params, nxt[:, None], carry.cache, carry.pos, cfg, ctx,
            position_ids=step_pos_ids, kv_valid_from=pad_len,
        )
        new_carry = Carry(
            cache=cache,
            logits=new_logits[:, -1, :].astype(jnp.float32),
            pos=carry.pos + 1,
            unfinished=unfinished,
            token_counts=counts,
            key=key,
        )
        return new_carry, nxt

    carry0 = Carry(
        cache=cache,
        logits=last_logits,
        pos=jnp.int32(prompt_len),
        unfinished=jnp.ones((b,), bool),
        token_counts=token_counts0,
        key=key,
    )
    if decode_loop_mode() == "scan":
        carry, tokens = jax.lax.scan(step, carry0, jnp.arange(gen.max_dec_len))
        tokens = tokens.T  # [b, max_dec_len]
        return (tokens, carry.cache) if return_cache else tokens

    # early-exit while_loop: the scan runs all max_dec_len steps even after
    # every row emitted EOS (each a full forward over the batch); the while
    # loop stops as soon as nothing is unfinished.  Token-for-token parity
    # with the scan: the buffer starts pad-filled, and the scan likewise
    # emits pad for every step after all rows finish (nxt is forced to
    # pad_token_id once unfinished is False), so skipped slots are
    # identical — asserted by tests/test_generation.py.
    tokens0 = jnp.full((b, gen.max_dec_len), gen.pad_token_id, jnp.int32)

    def loop_cond(st):
        carry, i, _ = st
        return (i < gen.max_dec_len) & jnp.any(carry.unfinished)

    def loop_body(st):
        carry, i, tokens = st
        new_carry, nxt = step(carry, i)
        tokens = jax.lax.dynamic_update_slice(tokens, nxt[:, None], (0, i))
        return new_carry, i + 1, tokens

    carry, _, tokens = jax.lax.while_loop(
        loop_cond, loop_body, (carry0, jnp.int32(0), tokens0)
    )
    return (tokens, carry.cache) if return_cache else tokens  # [b, max_dec_len]


# ---------------------------------------------------------------------------
# Paged decode: block-pool KV cache + the step-wise entry the
# continuous-batching scheduler drives (core/continuous_batching.py).
# The contiguous generate() above runs ONE request set to completion
# inside a fused loop; these functions instead expose ONE decode step
# over a batch of INDEPENDENT rows (own positions, own budgets, own
# block tables into a shared arena), so the host scheduler can admit and
# evict rows at every step boundary.
# ---------------------------------------------------------------------------


class PagedPools(NamedTuple):
    """The paged KV arena: [layers, num_blocks, heads, block, head_dim]
    (heads-major within a block, matching KVCache's tiling rationale).
    Block 0 is the NULL block — never allocated to a sequence; inactive
    batch rows route their writes there (core/paged_cache.py)."""

    k: jax.Array
    v: jax.Array


def init_paged_pools(
    cfg: GPTConfig, num_blocks: int, block: int, dtype=None
) -> PagedPools:
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, num_blocks, cfg.num_attention_heads, block,
             cfg.head_dim)
    return PagedPools(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


class PagedRows(NamedTuple):
    """Per-row decode state the scheduler threads through decode_step.

    ``positions`` is each row's NEXT write slot (= real prompt length +
    tokens generated so far); ``gen_steps`` counts generated tokens;
    ``max_news`` is the per-row decode budget (runtime data, NOT a
    compile key — unlike the contiguous path, a new max_tokens value
    never keys a retrace); ``forced_steps`` is the per-row step index
    where ``forced_eos_token_id`` fires — the CONTIGUOUS path's bucketed
    run end (`core/serving.plan_decode`'s ``run - 1``), not the raw
    budget, so forced-EOS output stays token-identical to the coalesce
    path (whose forced step usually lands beyond the trimmed output);
    ``logits`` are the pending next-token logits the next step samples
    from; ``counts`` back repetition penalty."""

    logits: jax.Array        # [B, vocab] f32
    counts: jax.Array        # [B, vocab] int32
    positions: jax.Array     # [B] int32
    gen_steps: jax.Array     # [B] int32
    max_news: jax.Array      # [B] int32
    active: jax.Array        # [B] bool
    forced_steps: jax.Array  # [B] int32


def _paged_layer_step(
    p: Dict[str, Any],
    x: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    blk: jax.Array,
    off: jax.Array,
    tables: jax.Array,
    positions: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder layer over x [b, 1, h]: write this step's K/V at pool
    slot (blk[i], off[i]) per row, then block-table paged attention."""
    dtype = x.dtype
    b = x.shape[0]
    n = cfg.num_attention_heads

    y = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"])
    qkv = jnp.einsum("bsh,htnd->bstnd", y, p["attn"]["qkv_kernel"].astype(dtype))
    qkv = qkv + p["attn"]["qkv_bias"].astype(dtype)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _constrain(ctx, q, ("batch", None, "heads", "kv"))

    # scatter the [b, n, d] step chunk into each row's current block:
    # rows own disjoint blocks, so the only index collisions are inactive
    # rows' null-block writes (garbage-on-garbage, never read)
    idx_b = blk[:, None]
    idx_n = jnp.arange(n)[None, :]
    idx_o = off[:, None]
    k_pool = k_pool.at[idx_b, idx_n, idx_o, :].set(k[:, 0].astype(k_pool.dtype))
    v_pool = v_pool.at[idx_b, idx_n, idx_o, :].set(v[:, 0].astype(v_pool.dtype))

    attn_out = paged_decode_attention(
        q, k_pool, v_pool, tables, positions,
        impl="lax" if ctx is not None else "auto",
    )
    attn_out = jnp.einsum(
        "bsnd,ndh->bsh", attn_out, p["attn"]["out_kernel"].astype(dtype)
    ) + p["attn"]["out_bias"].astype(dtype)
    x = x + attn_out

    y = layer_norm(x, p["ln_2"]["scale"], p["ln_2"]["bias"])
    mp = p["mlp"]
    y = y @ mp["fc_in_kernel"].astype(dtype) + mp["fc_in_bias"].astype(dtype)
    y = jax.nn.gelu(y, approximate=True)
    y = y @ mp["fc_out_kernel"].astype(dtype) + mp["fc_out_bias"].astype(dtype)
    return x + y, k_pool, v_pool


def paged_forward_step(
    params: Dict[str, Any],
    tokens: jax.Array,
    pools: PagedPools,
    block_tables: jax.Array,
    positions: jax.Array,
    active: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[jax.Array, PagedPools]:
    """tokens [B] at per-row slots ``positions`` -> (logits [B, v] f32,
    pools).  Inactive rows still run (fixed shape) but write to the null
    block and their logits are garbage the caller ignores."""
    dtype = jnp.dtype(cfg.dtype)
    word = params["embeddings"]["word"].astype(dtype)
    pe = params["embeddings"]["position"].astype(dtype)
    # clamp inactive rows' embedding index: an evicted slot may carry a
    # stale position beyond the table
    pos_emb = jnp.where(active, positions, 0)
    x = word[tokens][:, None, :] + pe[pos_emb][:, None, :]  # [B, 1, h]
    x = _constrain(ctx, x, ("batch", None, "embed"))

    bs = pools.k.shape[3]
    blk_log = jnp.clip(positions // bs, 0, block_tables.shape[1] - 1)
    blk = jnp.take_along_axis(block_tables, blk_log[:, None], axis=1)[:, 0]
    blk = jnp.where(active, blk, 0)  # inactive rows -> null block
    off = positions % bs

    def body(x, inp):
        p_l, kp, vp = inp
        x, kp, vp = _paged_layer_step(
            p_l, x, kp, vp, blk, off, block_tables, positions, cfg, ctx
        )
        return x, (kp, vp)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"], pools.k, pools.v))
    x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"])
    logits = jnp.einsum("bsh,vh->bsv", x, word)
    logits = _constrain(ctx, logits, ("batch", None, "vocab"))
    return logits[:, -1, :].astype(jnp.float32), PagedPools(ks, vs)


def paged_prefill(
    params: Dict[str, Any],
    prompt: jax.Array,
    prompt_len: jax.Array,
    pools: PagedPools,
    table_row: jax.Array,
    cfg: GPTConfig,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[PagedPools, jax.Array, jax.Array]:
    """Prefill ONE row's prompt into its pool blocks (prefill-on-admit).

    ``prompt`` [1, P] is RIGHT-padded to the bucket (real tokens at
    [0, prompt_len); pad junk after) — unlike the contiguous serving
    path's left padding, paged rows are unpadded in their logical cache,
    so real token i lives at slot i and positions need no offset.  The
    prompt runs through the contiguous ``forward_cached`` prefill (causal
    masking makes the real rows' math exactly the unpadded computation),
    then the temp cache is repacked block-wise into the arena at
    ``table_row`` [PB] (PB * block >= P).  Pad-slot junk K/V land in the
    row's own blocks past ``prompt_len`` and are overwritten by decode
    steps before any attention limit reaches them — the same stale-tail
    argument as the donated contiguous pool.

    Returns (pools, last real token's logits [v] f32, prompt token
    counts [v] for repetition penalty)."""
    P = int(prompt.shape[1])
    layers = cfg.num_layers
    n = cfg.num_attention_heads
    d = cfg.head_dim
    PB = int(table_row.shape[0])
    bs = int(pools.k.shape[3])
    L = PB * bs
    if L < P:
        raise ValueError(
            f"table_row covers {PB}x{bs}={L} slots < prompt bucket {P}"
        )
    cache = init_cache(cfg, 1, L)
    pos_ids = jnp.arange(P, dtype=jnp.int32)[None, :]
    logits, cache = forward_cached(
        params, prompt, cache, jnp.int32(0), cfg, ctx, position_ids=pos_ids
    )
    last = jax.lax.dynamic_index_in_dim(
        logits[0], prompt_len - 1, axis=0, keepdims=False
    ).astype(jnp.float32)
    # repack [layers, 1, n, L, d] -> per-block [layers, PB, n, bs, d]
    def pack(c):
        return c[:, 0].reshape(layers, n, PB, bs, d).transpose(0, 2, 1, 3, 4)

    k_pool = pools.k.at[:, table_row].set(pack(cache.k).astype(pools.k.dtype))
    v_pool = pools.v.at[:, table_row].set(pack(cache.v).astype(pools.v.dtype))
    counts = jnp.zeros((cfg.vocab_size,), jnp.int32).at[prompt[0]].add(
        (jnp.arange(P) < prompt_len).astype(jnp.int32)
    )
    return PagedPools(k_pool, v_pool), last, counts


def decode_step(
    params: Dict[str, Any],
    pools: PagedPools,
    block_tables: jax.Array,
    rows: PagedRows,
    cfg: GPTConfig,
    gen: GenerationConfig,
    key: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
) -> Tuple[jax.Array, PagedPools, PagedRows]:
    """ONE iteration-level decode step over the running batch.

    Samples each active row's next token from its pending logits through
    the same processor chain as :func:`generate` (min-length, repetition
    penalty, forced BOS/EOS — all per-row: rows sit at different steps),
    writes the token's K/V at the row's current slot, and returns the
    refreshed pending logits.  Greedy rows are token-identical to the
    contiguous path; sampling rows draw from per-step subkeys (a
    different, but deterministic, stream).  Returns (sampled tokens [B],
    pools, rows')."""
    B, vocab = rows.logits.shape
    i = rows.gen_steps
    logits = apply_min_length(rows.logits, i, gen.min_dec_len, gen.eos_token_id)
    logits = apply_repetition_penalty(logits, rows.counts, gen.repetition_penalty)
    if gen.forced_bos_token_id >= 0:
        forced = jnp.full_like(logits, -1e10).at[
            ..., gen.forced_bos_token_id].set(0.0)
        logits = jnp.where((i == 0)[:, None], forced, logits)
    if gen.forced_eos_token_id >= 0:
        forced = jnp.full_like(logits, -1e10).at[
            ..., gen.forced_eos_token_id].set(0.0)
        logits = jnp.where((i == rows.forced_steps)[:, None], forced, logits)
    if gen.decode_strategy == "greedy_search":
        nxt = jnp.argmax(logits, axis=-1)
    else:
        if key is None:
            raise ValueError("sampling decode_step needs a PRNG key")
        nxt = sample_logits(
            key, logits, temperature=gen.temperature, top_k=gen.top_k,
            top_p=gen.top_p,
        )
    nxt = jnp.where(rows.active, nxt, gen.pad_token_id)
    counts = rows.counts.at[jnp.arange(B), nxt].add(
        rows.active.astype(jnp.int32)
    )
    finished = rows.active & (
        (nxt == gen.eos_token_id) | (i + 1 >= rows.max_news)
    )
    new_logits, pools = paged_forward_step(
        params, nxt, pools, block_tables, rows.positions, rows.active,
        cfg, ctx,
    )
    act = rows.active.astype(jnp.int32)
    new_rows = PagedRows(
        logits=new_logits,
        counts=counts,
        positions=rows.positions + act,
        gen_steps=i + act,
        max_news=rows.max_news,
        active=rows.active & ~finished,
        forced_steps=rows.forced_steps,
    )
    return nxt, pools, new_rows


# ---------------------------------------------------------------------------
# Beam search (reference single_model.py:1190-1320 beam strategy +
# BeamSearchScorer; diverse groups via HammingDiversityLogitsProcessor)
# ---------------------------------------------------------------------------


def _length_penalty(length, alpha: float):
    return jnp.power(length.astype(jnp.float32), alpha)


def beam_search(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: GPTConfig,
    gen: GenerationConfig,
    ctx: Optional[ShardingCtx] = None,
    prompt_lens: Optional[jax.Array] = None,
) -> jax.Array:
    """Static-shape beam search: [b, prompt_len] -> [b, max_dec_len].

    K = num_beams alive beams per prompt plus a K-slot finished pool;
    each step takes the top 2*Kg candidates per beam group (Kg = K /
    num_beam_groups), routes EOS continuations into the finished pool with
    length penalty, keeps the best Kg non-EOS continuations alive, and
    reorders the KV cache by parent beam.  ``diversity_penalty`` applies
    the Hamming penalty against earlier groups' same-step choices.
    Repetition penalty is not applied on the beam path (matching the
    reference beam strategy's processor set)."""
    b, prompt_len = input_ids.shape
    K, G = gen.num_beams, gen.num_beam_groups
    if K % G:
        raise ValueError(f"num_beams {K} not divisible by num_beam_groups {G}")
    Kg = K // G
    vocab = cfg.vocab_size
    # length validated by generate() before dispatch
    max_len = prompt_len + gen.max_dec_len

    # prefill ONCE per prompt, then repeat the cache/logits K-fold (all
    # beams share the prompt; re-running the forward K times would be
    # K x the prefill FLOPs for identical results)
    pad_len, prefill_pos_ids = _left_pad_prefill(prompt_len, prompt_lens)
    cache = init_cache(cfg, b, max_len)
    logits, cache = forward_cached(
        params, input_ids, cache, jnp.int32(0), cfg, ctx,
        position_ids=prefill_pos_ids, kv_valid_from=pad_len,
    )
    cache = KVCache(
        jnp.repeat(cache.k, K, axis=1), jnp.repeat(cache.v, K, axis=1)
    )
    logits0 = jnp.repeat(logits[:, -1, :].astype(jnp.float32), K, axis=0)
    pad_len_flat = jnp.repeat(pad_len, K, axis=0) if pad_len is not None else None
    lens_flat = (
        jnp.repeat(prompt_lens, K, axis=0) if prompt_lens is not None else None
    )

    NEG = jnp.float32(-1e9)
    # only each group's first beam is live at step 0 (avoids duplicates)
    init_scores = jnp.where(
        (jnp.arange(K) % Kg) == 0, 0.0, NEG
    )[None].repeat(b, 0)  # [b, K]

    class Beams(NamedTuple):
        cache: KVCache
        logits: jax.Array  # [b*K, v]
        scores: jax.Array  # [b, K] cumulative alive logprobs
        seqs: jax.Array  # [b, K, max_dec]
        fin_scores: jax.Array  # [b, K]
        fin_seqs: jax.Array  # [b, K, max_dec]
        pos: jax.Array

    def step(st: Beams, i):
        logp = jax.nn.log_softmax(st.logits, axis=-1).reshape(b, K, vocab)
        logp = apply_min_length(
            logp.reshape(b * K, vocab), jnp.full((b * K,), i),
            gen.min_dec_len, gen.eos_token_id,
        ).reshape(b, K, vocab)
        logp = apply_forced_token(
            logp.reshape(b * K, vocab), i, 0, gen.forced_bos_token_id
        ).reshape(b, K, vocab)
        logp = apply_forced_token(
            logp.reshape(b * K, vocab), i, gen.max_dec_len - 1,
            gen.forced_eos_token_id,
        ).reshape(b, K, vocab)

        new_scores = st.scores
        fin_scores, fin_seqs = st.fin_scores, st.fin_seqs
        chosen_tok = jnp.zeros((b, K), jnp.int32)
        chosen_parent = jnp.zeros((b, K), jnp.int32)
        step_tokens = jnp.full((b, K), -1, jnp.int32)  # for Hamming penalty

        for g in range(G):  # static, G small
            sl = slice(g * Kg, (g + 1) * Kg)
            glogp = logp[:, sl]  # [b, Kg, v]
            if gen.diversity_penalty > 0.0 and g > 0:
                glogp = jax.vmap(
                    lambda lg, cur: apply_hamming_diversity(
                        lg, cur, g * Kg, gen.diversity_penalty
                    )
                )(glogp, step_tokens)
            cand = (st.scores[:, sl, None] + glogp).reshape(b, Kg * vocab)
            top_s, top_i = jax.lax.top_k(cand, 2 * Kg)  # [b, 2Kg]
            tok = top_i % vocab
            parent = top_i // vocab + g * Kg  # flat beam index
            is_eos = tok == gen.eos_token_id

            # finished pool: EOS continuations scored with length penalty
            f_cand = jnp.where(is_eos, top_s / _length_penalty(
                jnp.full((b, 2 * Kg), i + 1), gen.length_penalty
            ), NEG)
            # candidate finished sequences = parent's seq + eos at i
            parent_seqs = jnp.take_along_axis(
                st.seqs, parent[..., None], axis=1
            )  # [b, 2Kg, max_dec]
            f_seqs = jax.vmap(
                lambda ps, tk: ps.at[:, i].set(tk)
            )(parent_seqs, tok)
            all_f_scores = jnp.concatenate([fin_scores, f_cand], axis=1)
            all_f_seqs = jnp.concatenate([fin_seqs, f_seqs], axis=1)
            keep_s, keep_i = jax.lax.top_k(all_f_scores, K)
            fin_scores = keep_s
            fin_seqs = jnp.take_along_axis(all_f_seqs, keep_i[..., None], axis=1)

            # alive: best Kg non-EOS continuations
            alive_s = jnp.where(is_eos, NEG, top_s)
            a_s, a_i = jax.lax.top_k(alive_s, Kg)  # indices into 2Kg
            a_tok = jnp.take_along_axis(tok, a_i, axis=1)
            a_parent = jnp.take_along_axis(parent, a_i, axis=1)
            new_scores = new_scores.at[:, sl].set(a_s)
            chosen_tok = chosen_tok.at[:, sl].set(a_tok)
            chosen_parent = chosen_parent.at[:, sl].set(a_parent)
            step_tokens = step_tokens.at[:, sl].set(a_tok)

        # reorder sequences/caches by parent beam, then append tokens
        new_seqs = jnp.take_along_axis(st.seqs, chosen_parent[..., None], axis=1)
        new_seqs = jax.vmap(lambda s, t: s.at[:, i].set(t))(new_seqs, chosen_tok)
        flat_parent = (
            jnp.arange(b)[:, None] * K + chosen_parent
        ).reshape(-1)  # [b*K]
        cache = KVCache(
            jnp.take(st.cache.k, flat_parent, axis=1),
            jnp.take(st.cache.v, flat_parent, axis=1),
        )
        step_pos_ids = (
            (lens_flat + i)[:, None] if lens_flat is not None else None
        )
        new_logits, cache = forward_cached(
            params, chosen_tok.reshape(b * K, 1), cache, st.pos, cfg, ctx,
            position_ids=step_pos_ids, kv_valid_from=pad_len_flat,
        )
        return Beams(
            cache=cache,
            logits=new_logits[:, -1, :].astype(jnp.float32),
            scores=new_scores,
            seqs=new_seqs,
            fin_scores=fin_scores,
            fin_seqs=fin_seqs,
            pos=st.pos + 1,
        ), None

    st0 = Beams(
        cache=cache,
        logits=logits0,
        scores=init_scores,
        seqs=jnp.full((b, K, gen.max_dec_len), gen.pad_token_id, jnp.int32),
        fin_scores=jnp.full((b, K), NEG),
        fin_seqs=jnp.full((b, K, gen.max_dec_len), gen.pad_token_id, jnp.int32),
        pos=jnp.int32(prompt_len),
    )
    st, _ = jax.lax.scan(step, st0, jnp.arange(gen.max_dec_len))

    # merge still-alive beams (scored at full length) into the pool
    alive_final = st.scores / _length_penalty(
        jnp.full((b, K), gen.max_dec_len), gen.length_penalty
    )
    all_scores = jnp.concatenate([st.fin_scores, alive_final], axis=1)
    all_seqs = jnp.concatenate([st.fin_seqs, st.seqs], axis=1)
    best = jnp.argmax(all_scores, axis=1)
    return jnp.take_along_axis(all_seqs, best[:, None, None], axis=1)[:, 0]
