"""Imagen: NHWC UNets + continuous-time diffusion + text encoders."""

from paddlefleetx_tpu.models.multimodal.imagen.imagen import (  # noqa: F401
    ImagenConfig,
    UnetConfig,
)
