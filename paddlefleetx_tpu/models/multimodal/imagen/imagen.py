"""Imagen — cascaded text-to-image diffusion (base + SR unets).

TPU-native re-design of the reference ImagenModel
(ppfleetx/models/multimodal_model/imagen/modeling.py:138-950: p_losses,
q_sample around :600-700, sample loop :750-900, ImagenCriterion :94;
unet presets :36-92).  The reference trains ONE unet of the cascade per
run (unet_number); same contract here.

Text conditioning: the reference embeds captions with a frozen T5 or
DebertaV2 encoder inside the model (imagen_text2im_64_debertav2 :977).
Here the loss takes precomputed ``text_embeds``/``text_mask`` from the
batch, or — when an encoder param tree is supplied via ``extra`` — runs
the frozen encoder on ``input_ids`` inside the step (stop-gradient, so
the encoder never trains; it rides the Engine's non-gradient state).

Sampling: DDPM ancestral sampling over descending continuous-time pairs
with classifier-free guidance (two-pass cond/uncond), dynamic clipping of
x0 to [-1, 1]; SR stages get the previous stage's output, resized and
noise-augmented, as conditioning.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.multimodal.imagen import unet as unet_lib
from paddlefleetx_tpu.models.multimodal.imagen.diffusion import (
    GaussianDiffusionContinuousTimes,
)
from paddlefleetx_tpu.models.multimodal.imagen.unet import UnetConfig


@dataclasses.dataclass(frozen=True)
class ImagenConfig:
    unets: Tuple[Dict[str, Any], ...] = (
        dict(dim=128, dim_mults=(1, 2, 4), layer_attns=(False, False, True),
             layer_cross_attns=(False, True, True)),
    )
    image_sizes: Tuple[int, ...] = (64,)
    text_embed_dim: int = 512
    timesteps: int = 1000
    noise_schedules: Tuple[str, ...] = ("cosine",)
    cond_drop_prob: float = 0.1
    pred_objective: str = "noise"  # or "v"
    p2_loss_weight_gamma: float = 0.0  # 0 = plain MSE (reference default)
    p2_loss_weight_k: float = 1.0
    lowres_noise_schedule: str = "linear"
    lowres_max_aug_time: float = 0.999
    # which unet this run trains, 1-based like the reference unet_number
    unet_number: int = 1
    channels: int = 3
    dtype: str = "float32"

    def __post_init__(self):
        assert len(self.unets) == len(self.image_sizes)

    def unet_config(self, i: int) -> UnetConfig:
        d = dict(self.unets[i])
        d.setdefault("text_embed_dim", self.text_embed_dim)
        d.setdefault("channels", self.channels)
        d.setdefault("dtype", self.dtype)
        d["lowres_cond"] = i > 0
        return UnetConfig.from_config(d)

    def scheduler(self, i: int) -> GaussianDiffusionContinuousTimes:
        sched = self.noise_schedules[min(i, len(self.noise_schedules) - 1)]
        return GaussianDiffusionContinuousTimes(sched, self.timesteps)

    @property
    def train_index(self) -> int:
        return self.unet_number - 1

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "ImagenConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        for k in ("unets", "image_sizes", "noise_schedules"):
            if k in kw and isinstance(kw[k], list):
                kw[k] = tuple(kw[k])
        return cls(**kw)


# ---------------------------------------------------------------------------
# Params (for the unet being trained)
# ---------------------------------------------------------------------------


def init(cfg: ImagenConfig, key: jax.Array) -> Dict[str, Any]:
    return unet_lib.init(cfg.unet_config(cfg.train_index), key)


def imagen_logical_axes(cfg: ImagenConfig) -> Dict[str, Any]:
    return unet_lib.unet_logical_axes(cfg.unet_config(cfg.train_index))


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def resize_image_to(images: jax.Array, size: int) -> jax.Array:
    """(reference utils.py:177-193) bilinear resize, NHWC."""
    b, h, w, c = images.shape
    if h == size:
        return images
    return jax.image.resize(images, (b, size, size, c), method="bilinear")


def normalize_neg_one_to_one(img: jax.Array) -> jax.Array:
    return img * 2.0 - 1.0


def unnormalize_zero_to_one(img: jax.Array) -> jax.Array:
    return (img + 1.0) * 0.5


# ---------------------------------------------------------------------------
# Training loss (one unet of the cascade)
# ---------------------------------------------------------------------------


def p_losses(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: ImagenConfig,
    key: jax.Array,
    *,
    train: bool = True,
) -> jax.Array:
    """MSE on the noise (or v) prediction for the configured unet.

    batch: images [b,H,W,C] in [0,1]; text_embeds [b,L,D]; text_mask [b,L].
    """
    i = cfg.train_index
    ucfg = cfg.unet_config(i)
    sched = cfg.scheduler(i)
    images = batch["images"]
    b = images.shape[0]

    k_t, k_noise, k_drop, k_aug, k_aug_noise = jax.random.split(key, 5)
    x0 = normalize_neg_one_to_one(resize_image_to(images, cfg.image_sizes[i]))
    t = sched.sample_random_times(k_t, b)
    noise = jax.random.normal(k_noise, x0.shape, x0.dtype)
    x_t, log_snr, _ = sched.q_sample(x0, t, noise)

    lowres_img = lowres_aug_t = None
    if i > 0:
        low_sched = GaussianDiffusionContinuousTimes(cfg.lowres_noise_schedule, cfg.timesteps)
        lowres = resize_image_to(images, cfg.image_sizes[i - 1])
        lowres = normalize_neg_one_to_one(resize_image_to(lowres, cfg.image_sizes[i]))
        # noise-conditioning augmentation: one aug level per batch row
        lowres_aug_t = jax.random.uniform(k_aug, (b,), maxval=cfg.lowres_max_aug_time)
        aug_noise = jax.random.normal(k_aug_noise, lowres.shape, lowres.dtype)
        lowres_img, _, _ = low_sched.q_sample(lowres, lowres_aug_t, aug_noise)

    cond_drop = None
    if train and cfg.cond_drop_prob > 0:
        cond_drop = jax.random.bernoulli(k_drop, cfg.cond_drop_prob, (b,))

    pred = unet_lib.forward(
        params, x_t, t, ucfg,
        text_embeds=batch.get("text_embeds"),
        text_mask=batch.get("text_mask"),
        cond_drop_mask=cond_drop,
        lowres_cond_img=lowres_img,
        lowres_aug_time=lowres_aug_t,
    )
    if cfg.pred_objective == "v":
        target = sched.calculate_v(x0, t, noise)
    else:
        target = noise
    err = jnp.square(pred - target.astype(pred.dtype))
    loss = err.mean(axis=tuple(range(1, err.ndim)))  # per-sample
    if cfg.p2_loss_weight_gamma > 0:
        # (k + snr)^-gamma  (Imagen/P2 weighting)
        snr = jnp.exp(log_snr)
        loss = loss * (cfg.p2_loss_weight_k + snr) ** -cfg.p2_loss_weight_gamma
    return loss.mean()


# ---------------------------------------------------------------------------
# Sampling (full cascade; pass the params of every unet)
# ---------------------------------------------------------------------------


def p_sample_loop(
    params: Dict[str, Any],
    shape: Tuple[int, ...],
    cfg: ImagenConfig,
    unet_index: int,
    key: jax.Array,
    *,
    text_embeds: Optional[jax.Array],
    text_mask: Optional[jax.Array],
    guidance_scale: float = 5.0,
    lowres_img: Optional[jax.Array] = None,
    lowres_aug_t: Optional[jax.Array] = None,
) -> jax.Array:
    """DDPM ancestral sampling for one unet.  Returns x0 in [-1, 1]."""
    ucfg = cfg.unet_config(unet_index)
    sched = cfg.scheduler(unet_index)
    times = sched.get_times()  # [T+1] descending
    b = shape[0]

    def guided_eps(x, t_vec):
        cond = unet_lib.forward(
            params, x, t_vec, ucfg,
            text_embeds=text_embeds, text_mask=text_mask,
            cond_drop_mask=jnp.zeros((b,), bool),
            lowres_cond_img=lowres_img, lowres_aug_time=lowres_aug_t,
        )
        if guidance_scale == 1.0 or text_embeds is None:
            return cond
        null = unet_lib.forward(
            params, x, t_vec, ucfg,
            text_embeds=text_embeds, text_mask=text_mask,
            cond_drop_mask=jnp.ones((b,), bool),
            lowres_cond_img=lowres_img, lowres_aug_time=lowres_aug_t,
        )
        return null + guidance_scale * (cond - null)

    def step(carry, idx):
        x, k = carry
        t = jnp.full((b,), times[idx])
        s = jnp.full((b,), times[idx + 1])
        pred = guided_eps(x, t)
        if cfg.pred_objective == "v":
            x0 = sched.predict_start_from_v(x, t, pred)
        else:
            x0 = sched.predict_start_from_noise(x, t, pred)
        x0 = jnp.clip(x0, -1.0, 1.0)
        mean, log_var = sched.q_posterior(x0, x, t, s)
        k, k_z = jax.random.split(k)
        z = jax.random.normal(k_z, x.shape, x.dtype)
        nonzero = (idx < sched.num_timesteps - 1).astype(x.dtype)
        x = mean + nonzero * jnp.exp(0.5 * log_var) * z
        return (x, k), None

    key, k_init = jax.random.split(key)
    x = jax.random.normal(k_init, shape, jnp.float32)
    (x, _), _ = jax.lax.scan(step, (x, key), jnp.arange(sched.num_timesteps))
    return jnp.clip(x, -1.0, 1.0)


def sample(
    all_params: Sequence[Dict[str, Any]],
    cfg: ImagenConfig,
    key: jax.Array,
    *,
    text_embeds: jax.Array,
    text_mask: Optional[jax.Array] = None,
    batch_size: Optional[int] = None,
    guidance_scale: float = 5.0,
    stop_at_unet_number: Optional[int] = None,
) -> jax.Array:
    """Run the full cascade.  Returns images in [0, 1]."""
    b = batch_size or text_embeds.shape[0]
    img = None
    n_stages = (
        min(stop_at_unet_number, len(all_params))
        if stop_at_unet_number
        else len(all_params)
    )
    low_sched = GaussianDiffusionContinuousTimes(cfg.lowres_noise_schedule, cfg.timesteps)
    for i in range(n_stages):
        key, k_stage, k_aug = jax.random.split(key, 3)
        size = cfg.image_sizes[i]
        lowres_img = lowres_aug_t = None
        if i > 0:
            # sample-time aug level is fixed low (reference uses 0.2-ish)
            lowres_aug_t = jnp.full((b,), 0.2)
            up = resize_image_to(img, size)
            lowres_img, _, _ = low_sched.q_sample(
                up, lowres_aug_t, jax.random.normal(k_aug, up.shape, up.dtype)
            )
        img = p_sample_loop(
            all_params[i], (b, size, size, cfg.channels), cfg, i, k_stage,
            text_embeds=text_embeds, text_mask=text_mask,
            guidance_scale=guidance_scale,
            lowres_img=lowres_img, lowres_aug_t=lowres_aug_t,
        )
    return unnormalize_zero_to_one(img)
