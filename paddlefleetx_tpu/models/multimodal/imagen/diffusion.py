"""Continuous-time Gaussian diffusion (Imagen flavor).

Re-design of the reference GaussianDiffusionContinuousTimes
(ppfleetx/models/multimodal_model/imagen/utils.py:384-481) with its two
log-SNR noise schedules (beta_linear_log_snr :370, alpha_cosine_log_snr
:374, log_snr_to_alpha_sigma :380).  Everything is a pure function of
continuous time t in [0, 1]; sampling discretizes t uniformly.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp


def beta_linear_log_snr(t: jax.Array) -> jax.Array:
    return -jnp.log(jnp.expm1(1e-4 + 10.0 * t * t))


def alpha_cosine_log_snr(t: jax.Array, s: float = 0.008) -> jax.Array:
    # -log(cos^{-2}(pi/2 * (t+s)/(1+s)) - 1)
    c = jnp.cos((t + s) / (1 + s) * math.pi * 0.5) ** -2
    return -jnp.log(jnp.clip(c - 1.0, 1e-5, None))


def log_snr_to_alpha_sigma(log_snr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return jnp.sqrt(jax.nn.sigmoid(log_snr)), jnp.sqrt(jax.nn.sigmoid(-log_snr))


class GaussianDiffusionContinuousTimes:
    """Stateless schedule helper (cheap to construct anywhere)."""

    def __init__(self, noise_schedule: str = "cosine", num_timesteps: int = 1000):
        if noise_schedule == "linear":
            self.log_snr = beta_linear_log_snr
        elif noise_schedule == "cosine":
            self.log_snr = alpha_cosine_log_snr
        else:
            raise ValueError(f"unknown noise schedule {noise_schedule}")
        self.num_timesteps = num_timesteps

    # -- forward process ----------------------------------------------------

    def sample_random_times(self, key: jax.Array, batch: int) -> jax.Array:
        return jax.random.uniform(key, (batch,), minval=0.0, maxval=1.0)

    def q_sample(
        self, x0: jax.Array, t: jax.Array, noise: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """x_t = alpha_t x0 + sigma_t eps.  t: [b]. Returns (x_t, log_snr, alpha)."""
        log_snr = self.log_snr(t)
        pad = (slice(None),) + (None,) * (x0.ndim - 1)
        alpha, sigma = log_snr_to_alpha_sigma(log_snr)
        x_t = alpha[pad] * x0 + sigma[pad] * noise
        return x_t, log_snr, alpha

    # -- reverse process ----------------------------------------------------

    def get_times(self) -> jax.Array:
        """[T+1] descending times 1 -> 0 (pairs (t, s) slide along this)."""
        return jnp.linspace(1.0, 0.0, self.num_timesteps + 1)

    def q_posterior(
        self, x0: jax.Array, x_t: jax.Array, t: jax.Array, s: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """Mean/log-variance of q(x_s | x_t, x0) for s < t
        (reference q_posterior utils.py:428-447)."""
        log_snr_t = self.log_snr(t)
        log_snr_s = self.log_snr(s)
        pad = (slice(None),) + (None,) * (x0.ndim - 1)
        alpha_t, sigma_t = log_snr_to_alpha_sigma(log_snr_t)
        alpha_s, sigma_s = log_snr_to_alpha_sigma(log_snr_s)
        # c = -expm1(log_snr_t - log_snr_s)  (variance-preserving transition)
        c = -jnp.expm1(log_snr_t - log_snr_s)
        mean = alpha_s[pad] * (x_t * (1 - c)[pad] / jnp.maximum(alpha_t, 1e-8)[pad] + c[pad] * x0)
        var = (sigma_s ** 2) * c
        return mean, jnp.log(jnp.clip(var, 1e-20, None))[pad]

    def predict_start_from_noise(self, x_t: jax.Array, t: jax.Array, noise: jax.Array) -> jax.Array:
        log_snr = self.log_snr(t)
        pad = (slice(None),) + (None,) * (x_t.ndim - 1)
        alpha, sigma = log_snr_to_alpha_sigma(log_snr)
        return (x_t - sigma[pad] * noise) / jnp.maximum(alpha[pad], 1e-8)

    def predict_start_from_v(self, x_t: jax.Array, t: jax.Array, v: jax.Array) -> jax.Array:
        log_snr = self.log_snr(t)
        pad = (slice(None),) + (None,) * (x_t.ndim - 1)
        alpha, sigma = log_snr_to_alpha_sigma(log_snr)
        return alpha[pad] * x_t - sigma[pad] * v

    def calculate_v(self, x0: jax.Array, t: jax.Array, noise: jax.Array) -> jax.Array:
        log_snr = self.log_snr(t)
        pad = (slice(None),) + (None,) * (x0.ndim - 1)
        alpha, sigma = log_snr_to_alpha_sigma(log_snr)
        return alpha[pad] * noise - sigma[pad] * x0
