"""Imagen efficient U-Net — pure-JAX functional.

TPU-native re-design of the reference Unet
(ppfleetx/models/multimodal_model/imagen/unet.py: Block :382, ResnetBlock
:400, CrossAttention :464, Attention :201, TransformerBlock :723,
SinusoidalPosEmb :350, Upsample/Downsample :304/:342, Unet :858).  The
reference's nn.Layer graph becomes one spec tree + one forward function;
NHWC layout throughout (TPU conv-friendly), bf16 compute with fp32 norms.

Conditioning path (Unet.forward semantics):
  time -> sinusoidal -> MLP -> t (time_cond)     [b, time_dim]
       -> linear -> num_time_tokens cond tokens  [b, n_t, cond_dim]
  text_embeds -> linear -> cond tokens, masked-mean -> hidden added to t
  classifier-free guidance: per-sample drop mask swaps text cond tokens /
  pooled hidden for learned null embeddings (prob_mask_like utils.py:207)
  SR unets additionally get the lowres image (channel-concat) + a second
  time embedding for the lowres noise-aug level.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    zeros_init,
)


@dataclasses.dataclass(frozen=True)
class UnetConfig:
    dim: int = 128
    dim_mults: Tuple[int, ...] = (1, 2, 4)
    channels: int = 3
    num_resnet_blocks: int = 2
    layer_attns: Tuple[bool, ...] = (False, False, True)
    layer_cross_attns: Tuple[bool, ...] = (False, True, True)
    text_embed_dim: int = 512
    cond_dim: Optional[int] = None  # default = dim
    num_time_tokens: int = 2
    attn_heads: int = 8
    attn_head_dim: int = 32
    lowres_cond: bool = False  # SR unets condition on the upsampled lowres img
    groups: int = 8
    init_kernel: int = 7
    dtype: str = "float32"

    def __post_init__(self):
        assert len(self.dim_mults) == len(self.layer_attns) == len(self.layer_cross_attns)

    @property
    def cdim(self) -> int:
        return self.cond_dim or self.dim

    @property
    def time_dim(self) -> int:
        return self.dim * 4

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "UnetConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        for k in ("dim_mults", "layer_attns", "layer_cross_attns"):
            if k in kw and isinstance(kw[k], list):
                kw[k] = tuple(kw[k])
        return cls(**kw)


# ---------------------------------------------------------------------------
# Spec helpers
# ---------------------------------------------------------------------------

_W = normal_init(0.02)


def _conv(kh, kw, cin, cout):
    return ParamSpec((kh, kw, cin, cout), (None, None, None, "embed"), _W)


def _lin(cin, cout, logical=("embed", "mlp")):
    return ParamSpec((cin, cout), logical, _W)


def _bias(c, logical=("embed",)):
    return ParamSpec((c,), logical, zeros_init())


def _gn(c):
    return {"scale": ParamSpec((c,), ("embed",), ones_init()),
            "bias": ParamSpec((c,), ("embed",), zeros_init())}


def _resnet_specs(cin, cout, time_dim, cond_dim, groups, attn_heads=8, attn_head_dim=32):
    specs = {
        "gn1": _gn(cin),
        "conv1": _conv(3, 3, cin, cout),
        "conv1_b": _bias(cout),
        "time_kernel": _lin(time_dim, cout * 2),
        "time_bias": _bias(cout * 2),
        "gn2": _gn(cout),
        "conv2": _conv(3, 3, cout, cout),
        "conv2_b": _bias(cout),
    }
    if cin != cout:
        specs["res_conv"] = _conv(1, 1, cin, cout)
        specs["res_conv_b"] = _bias(cout)
    if cond_dim is not None:
        # cross-attention conditioning inside the block (reference
        # ResnetBlock cross_attn, unet.py:400-462)
        specs["xattn"] = _xattn_specs(cout, cond_dim, attn_heads, attn_head_dim)
    return specs


def _xattn_specs(dim, ctx_dim, heads, head_dim):
    inner = heads * head_dim
    return {
        "norm": _gn(dim),
        "q_kernel": ParamSpec((dim, inner), ("embed", "mlp"), _W),
        "k_kernel": ParamSpec((ctx_dim, inner), ("embed", "mlp"), _W),
        "v_kernel": ParamSpec((ctx_dim, inner), ("embed", "mlp"), _W),
        "out_kernel": ParamSpec((inner, dim), ("mlp", "embed"), _W),
        "out_bias": _bias(dim),
    }


def _selfattn_specs(dim, heads, head_dim):
    inner = heads * head_dim
    return {
        "norm": _gn(dim),
        "qkv_kernel": ParamSpec((dim, 3 * inner), ("embed", "mlp"), _W),
        "out_kernel": ParamSpec((inner, dim), ("mlp", "embed"), _W),
        "out_bias": _bias(dim),
        "ff_norm": _gn(dim),
        "ff_in": ParamSpec((dim, dim * 2), ("embed", "mlp"), _W),
        "ff_in_b": _bias(dim * 2, ("mlp",)),
        "ff_out": ParamSpec((dim * 2, dim), ("mlp", "embed"), _W),
        "ff_out_b": _bias(dim),
    }


def unet_specs(cfg: UnetConfig) -> Dict[str, Any]:
    dims = [cfg.dim * m for m in cfg.dim_mults]
    in_ch = cfg.channels * (2 if cfg.lowres_cond else 1)
    td, cd = cfg.time_dim, cfg.cdim
    k = cfg.init_kernel
    specs: Dict[str, Any] = {
        "init_conv": _conv(k, k, in_ch, cfg.dim),
        "init_conv_b": _bias(cfg.dim),
        "time_mlp": {
            "fc1": _lin(cfg.dim, td), "fc1_b": _bias(td, ("mlp",)),
            "fc2": _lin(td, td, ("mlp", "embed")), "fc2_b": _bias(td, ("mlp",)),
        },
        "time_tokens": _lin(td, cd * cfg.num_time_tokens),
        "time_tokens_b": _bias(cd * cfg.num_time_tokens, ("mlp",)),
        "text_to_cond": _lin(cfg.text_embed_dim, cd),
        "text_to_cond_b": _bias(cd, ("mlp",)),
        "text_hidden": _lin(cd, td),
        "text_hidden_b": _bias(td, ("mlp",)),
        "null_text_embed": ParamSpec((1, 1, cd), (None, None, "embed"), _W),
        "null_text_hidden": ParamSpec((1, td), (None, "embed"), _W),
        "final_gn": _gn(cfg.dim),
        "final_conv": _conv(3, 3, cfg.dim, cfg.channels),
        "final_conv_b": _bias(cfg.channels),
    }
    if cfg.lowres_cond:
        specs["lowres_time_mlp"] = {
            "fc1": _lin(cfg.dim, td), "fc1_b": _bias(td, ("mlp",)),
            "fc2": _lin(td, td, ("mlp", "embed")), "fc2_b": _bias(td, ("mlp",)),
        }

    downs, ups = {}, {}
    n = len(dims)
    for i in range(n):
        cin = cfg.dim if i == 0 else dims[i - 1]
        cout = dims[i]
        xcd = cd if cfg.layer_cross_attns[i] else None
        stage = {
            "init_block": _resnet_specs(cin, cout, td, xcd, cfg.groups, cfg.attn_heads, cfg.attn_head_dim),
            "blocks": [
                _resnet_specs(cout, cout, td, None, cfg.groups)
                for _ in range(cfg.num_resnet_blocks)
            ],
        }
        if cfg.layer_attns[i]:
            stage["attn"] = _selfattn_specs(cout, cfg.attn_heads, cfg.attn_head_dim)
        if i < n - 1:
            stage["down"] = _conv(4, 4, cout, cout)
            stage["down_b"] = _bias(cout)
        downs[f"stage_{i}"] = stage
    specs["downs"] = downs

    specs["mid"] = {
        "block1": _resnet_specs(dims[-1], dims[-1], td, cd, cfg.groups, cfg.attn_heads, cfg.attn_head_dim),
        "attn": _selfattn_specs(dims[-1], cfg.attn_heads, cfg.attn_head_dim),
        "block2": _resnet_specs(dims[-1], dims[-1], td, cd, cfg.groups, cfg.attn_heads, cfg.attn_head_dim),
    }

    for i in reversed(range(n)):
        cout = dims[i]
        cskip = dims[i]  # skip from the matching down stage
        cup = dims[i + 1] if i < n - 1 else dims[-1]
        xcd = cd if cfg.layer_cross_attns[i] else None
        stage = {
            "init_block": _resnet_specs(cup + cskip, cout, td, xcd, cfg.groups, cfg.attn_heads, cfg.attn_head_dim),
            "blocks": [
                _resnet_specs(cout, cout, td, None, cfg.groups)
                for _ in range(cfg.num_resnet_blocks)
            ],
        }
        if cfg.layer_attns[i]:
            stage["attn"] = _selfattn_specs(cout, cfg.attn_heads, cfg.attn_head_dim)
        if i > 0:
            stage["up"] = _conv(3, 3, cout, cout * 4)  # pixel-shuffle upsample
            stage["up_b"] = _bias(cout * 4)
        ups[f"stage_{i}"] = stage
    specs["ups"] = ups
    return specs


def init(cfg: UnetConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, unet_specs(cfg))


def unet_logical_axes(cfg: UnetConfig) -> Dict[str, Any]:
    return logical_axes(unet_specs(cfg))


# ---------------------------------------------------------------------------
# Forward pieces
# ---------------------------------------------------------------------------


def group_norm(x: jax.Array, p: Dict[str, jax.Array], groups: int, eps: float = 1e-5):
    """NHWC (or N,L,C) group norm, fp32 statistics."""
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    shape = xf.shape
    c = shape[-1]
    g = min(groups, c)
    while c % g:
        g -= 1
    xg = xf.reshape(shape[:-1] + (g, c // g))
    mean = xg.mean(axis=tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,), keepdims=True)
    var = xg.var(axis=tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    xf = xg.reshape(shape)
    return (xf * p["scale"] + p["bias"]).astype(orig_dtype)


def _conv2d(x, kernel, bias=None, stride=1):
    y = jax.lax.conv_general_dilated(
        x, kernel, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + bias if bias is not None else y


def sinusoidal_embedding(t: jax.Array, dim: int) -> jax.Array:
    """(reference SinusoidalPosEmb unet.py:350-361)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half) / (half - 1))
    args = t[:, None] * freqs[None]
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


def _time_mlp(p, t_sin):
    h = t_sin @ p["fc1"] + p["fc1_b"]
    h = jax.nn.silu(h)
    return h @ p["fc2"] + p["fc2_b"]


def _cross_attention(p, x, context, context_mask, heads, head_dim):
    """x: [b, h, w, c] attends to context [b, l, cd]."""
    b, hh, ww, c = x.shape
    xn = group_norm(x, p["norm"], 1)  # LayerNorm-ish (1 group over channels)
    q = (xn.reshape(b, hh * ww, c) @ p["q_kernel"]).reshape(b, hh * ww, heads, head_dim)
    k = (context @ p["k_kernel"]).reshape(b, -1, heads, head_dim)
    v = (context @ p["v_kernel"]).reshape(b, -1, heads, head_dim)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(head_dim)
    if context_mask is not None:
        scores = scores + jnp.where(context_mask[:, None, None, :].astype(bool), 0.0, -1e9)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, hh * ww, heads * head_dim)
    out = out @ p["out_kernel"] + p["out_bias"]
    return x + out.reshape(b, hh, ww, c)


def _self_attention(p, x, heads, head_dim):
    """TransformerBlock: attn + ff over flattened pixels."""
    b, hh, ww, c = x.shape
    inner = heads * head_dim
    xn = group_norm(x, p["norm"], 1).reshape(b, hh * ww, c)
    qkv = xn @ p["qkv_kernel"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, -1, heads, head_dim)
    k = k.reshape(b, -1, heads, head_dim)
    v = v.reshape(b, -1, heads, head_dim)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(scores / math.sqrt(head_dim), axis=-1).astype(x.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, -1, inner)
    x = x + (out @ p["out_kernel"] + p["out_bias"]).reshape(b, hh, ww, c)
    xn = group_norm(x, p["ff_norm"], 1)
    y = jax.nn.gelu(xn @ p["ff_in"] + p["ff_in_b"], approximate=True)
    return x + (y @ p["ff_out"] + p["ff_out_b"])


def _resnet_block(p, x, t, cond_tokens, cond_mask, groups, attn_heads=8, attn_head_dim=32):
    """(reference ResnetBlock unet.py:400-462): gn-silu-conv, time
    scale/shift on the second norm, optional cross-attn conditioning."""
    h = jax.nn.silu(group_norm(x, p["gn1"], groups))
    h = _conv2d(h, p["conv1"], p["conv1_b"])
    ts = jax.nn.silu(t) @ p["time_kernel"] + p["time_bias"]
    scale, shift = jnp.split(ts, 2, axis=-1)
    h = group_norm(h, p["gn2"], groups)
    h = h * (1 + scale[:, None, None, :]) + shift[:, None, None, :]
    h = jax.nn.silu(h)
    if "xattn" in p and cond_tokens is not None:
        h = _cross_attention(p["xattn"], h, cond_tokens, cond_mask, attn_heads, attn_head_dim)
    h = _conv2d(h, p["conv2"], p["conv2_b"])
    if "res_conv" in p:
        x = _conv2d(x, p["res_conv"], p["res_conv_b"])
    return h + x


def _pixel_shuffle(x, factor=2):
    b, h, w, c = x.shape
    c_out = c // (factor * factor)
    x = x.reshape(b, h, w, factor, factor, c_out)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, h * factor, w * factor, c_out)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def forward(
    params: Dict[str, Any],
    x: jax.Array,  # [b, H, W, C] in [-1, 1]
    time: jax.Array,  # [b] continuous in [0, 1]
    cfg: UnetConfig,
    *,
    text_embeds: Optional[jax.Array] = None,  # [b, L, text_embed_dim]
    text_mask: Optional[jax.Array] = None,  # [b, L]
    cond_drop_mask: Optional[jax.Array] = None,  # [b] True -> DROP text cond
    lowres_cond_img: Optional[jax.Array] = None,
    lowres_aug_time: Optional[jax.Array] = None,
) -> jax.Array:
    """Predict noise eps for x_t.  Returns [b, H, W, C]."""
    dtype = jnp.dtype(cfg.dtype)
    if dtype != jnp.float32:
        # AMP contract (core/engine.py fwd_params comment): the MODEL casts
        # fp32 params to the compute dtype per use, so fp32 masters stay on
        # the optimizer side and main_grad=False's pre-cast no-ops here.
        # One tree-cast at entry covers every conv/attn weight below;
        # group_norm still computes its statistics in fp32 regardless.
        params = jax.tree.map(
            lambda w: w.astype(dtype) if w.dtype == jnp.float32 else w, params
        )
    b = x.shape[0]
    x = x.astype(dtype)
    if cfg.lowres_cond:
        assert lowres_cond_img is not None
        x = jnp.concatenate([x, lowres_cond_img.astype(dtype)], axis=-1)

    # time conditioning
    t = _time_mlp(params["time_mlp"], sinusoidal_embedding(time, cfg.dim).astype(dtype))
    if cfg.lowres_cond and lowres_aug_time is not None:
        t = t + _time_mlp(
            params["lowres_time_mlp"], sinusoidal_embedding(lowres_aug_time, cfg.dim).astype(dtype)
        )
    time_tokens = (t @ params["time_tokens"] + params["time_tokens_b"]).reshape(
        b, cfg.num_time_tokens, cfg.cdim
    )

    # text conditioning + classifier-free dropout
    cond_tokens = time_tokens
    cond_mask = jnp.ones((b, cfg.num_time_tokens), jnp.int32)
    if text_embeds is not None:
        text_cond = text_embeds.astype(dtype) @ params["text_to_cond"] + params["text_to_cond_b"]
        if text_mask is None:
            text_mask = jnp.ones(text_embeds.shape[:2], jnp.int32)
        if cond_drop_mask is not None:
            keep = ~cond_drop_mask
            text_cond = jnp.where(
                keep[:, None, None], text_cond, params["null_text_embed"].astype(dtype)
            )
            text_mask = jnp.where(keep[:, None], text_mask, jnp.ones_like(text_mask))
        # pooled text -> added to time cond
        denom = jnp.maximum(text_mask.sum(axis=1, keepdims=True), 1).astype(dtype)
        pooled = (text_cond * text_mask[..., None].astype(dtype)).sum(axis=1) / denom
        text_hidden = jax.nn.silu(pooled @ params["text_hidden"] + params["text_hidden_b"])
        if cond_drop_mask is not None:
            text_hidden = jnp.where(
                (~cond_drop_mask)[:, None], text_hidden, params["null_text_hidden"].astype(dtype)
            )
        t = t + text_hidden
        cond_tokens = jnp.concatenate([time_tokens, text_cond], axis=1)
        cond_mask = jnp.concatenate([cond_mask, text_mask.astype(jnp.int32)], axis=1)

    x = _conv2d(x, params["init_conv"], params["init_conv_b"])

    n = len(cfg.dim_mults)
    skips = []
    for i in range(n):
        sp = params["downs"][f"stage_{i}"]
        ct = cond_tokens if cfg.layer_cross_attns[i] else None
        x = _resnet_block(sp["init_block"], x, t, ct, cond_mask, cfg.groups, cfg.attn_heads, cfg.attn_head_dim)
        for bp in sp["blocks"]:
            x = _resnet_block(bp, x, t, None, None, cfg.groups, cfg.attn_heads, cfg.attn_head_dim)
        if cfg.layer_attns[i]:
            x = _self_attention(sp["attn"], x, cfg.attn_heads, cfg.attn_head_dim)
        skips.append(x)
        if i < n - 1:
            x = _conv2d(x, sp["down"], sp["down_b"], stride=2)

    mp = params["mid"]
    x = _resnet_block(mp["block1"], x, t, cond_tokens, cond_mask, cfg.groups, cfg.attn_heads, cfg.attn_head_dim)
    x = _self_attention(mp["attn"], x, cfg.attn_heads, cfg.attn_head_dim)
    x = _resnet_block(mp["block2"], x, t, cond_tokens, cond_mask, cfg.groups, cfg.attn_heads, cfg.attn_head_dim)

    for i in reversed(range(n)):
        sp = params["ups"][f"stage_{i}"]
        x = jnp.concatenate([x, skips[i]], axis=-1)
        ct = cond_tokens if cfg.layer_cross_attns[i] else None
        x = _resnet_block(sp["init_block"], x, t, ct, cond_mask, cfg.groups, cfg.attn_heads, cfg.attn_head_dim)
        for bp in sp["blocks"]:
            x = _resnet_block(bp, x, t, None, None, cfg.groups, cfg.attn_heads, cfg.attn_head_dim)
        if cfg.layer_attns[i]:
            x = _self_attention(sp["attn"], x, cfg.attn_heads, cfg.attn_head_dim)
        if i > 0:
            x = _pixel_shuffle(_conv2d(x, sp["up"], sp["up_b"]))

    x = jax.nn.silu(group_norm(x, params["final_gn"], cfg.groups))
    return _conv2d(x, params["final_conv"], params["final_conv_b"]).astype(jnp.float32)
