"""Multimodal engine modules (reference multimodal_module.py ImagenModule;
CLIPModule added — the reference's clip package is an empty stub)."""

from __future__ import annotations

from paddlefleetx_tpu.core.module import BasicModule, resolve_model_dtype
from paddlefleetx_tpu.models.multimodal import clip as clip_model
from paddlefleetx_tpu.models.multimodal.clip import CLIPConfig
from paddlefleetx_tpu.utils.registry import MODULES


@MODULES.register("CLIPModule")
class CLIPModule(BasicModule):
    """Contrastive image-text pretraining."""

    def __init__(self, cfg):
        model_cfg = dict(cfg.Model)
        model_cfg.pop("module", None)
        model_cfg.pop("name", None)
        resolve_model_dtype(cfg, model_cfg)
        self.config = CLIPConfig.from_config(model_cfg)
        self.tokens_per_sample = self.config.max_text_len

    def init_params(self, key):
        return clip_model.init(self.config, key)

    def logical_axes(self):
        return clip_model.clip_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        return clip_model.clip_loss(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )
