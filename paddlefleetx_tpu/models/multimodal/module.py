"""Multimodal engine modules (reference multimodal_module.py ImagenModule;
CLIPModule added — the reference's clip package is an empty stub)."""

from __future__ import annotations

from paddlefleetx_tpu.core.module import BasicModule, resolve_model_dtype
from paddlefleetx_tpu.models.multimodal import clip as clip_model
from paddlefleetx_tpu.models.multimodal.clip import CLIPConfig
from paddlefleetx_tpu.utils.registry import MODULES


@MODULES.register("ImagenModule")
class ImagenModule(BasicModule):
    """Text-to-image diffusion: trains ONE unet of the cascade
    (reference ImagenModule multimodal_module.py + ImagenModel.forward
    unet_number contract).

    Text conditioning: batches may carry precomputed ``text_embeds`` /
    ``text_mask``; otherwise a FROZEN text encoder (T5 or DebertaV2,
    random-init unless restored from a checkpoint) rides the Engine's
    non-gradient ``extra`` state and embeds ``input_ids`` on the fly."""

    has_extra_state = True

    def __init__(self, cfg):
        from paddlefleetx_tpu.models.multimodal.imagen.imagen import ImagenConfig

        model_cfg = dict(cfg.Model)
        model_cfg.pop("module", None)
        model_cfg.pop("name", None)
        resolve_model_dtype(cfg, model_cfg)
        self.text_encoder_cfg = model_cfg.pop("text_encoder", None)
        self.config = ImagenConfig.from_config(model_cfg)
        self.tokens_per_sample = self.config.image_sizes[self.config.train_index] ** 2
        # resolve the frozen text-encoder family ONCE: (config, init,
        # logical_axes, encode) — every other method goes through these
        self._enc_cfg = self._enc_init = self._enc_axes = self._enc_encode = None
        if self.text_encoder_cfg:
            name = self.text_encoder_cfg.get("name", "t5")
            if name == "t5":
                from paddlefleetx_tpu.models.t5 import model as t5
                from paddlefleetx_tpu.models.t5.config import T5Config

                self._enc_cfg = T5Config.from_config(dict(self.text_encoder_cfg))
                self._enc_init, self._enc_axes = t5.init, t5.t5_logical_axes
                self._enc_encode = t5.encode
            elif name == "debertav2":
                from paddlefleetx_tpu.models.debertav2 import model as dbv2
                from paddlefleetx_tpu.models.debertav2.config import DebertaV2Config

                self._enc_cfg = DebertaV2Config.from_config(dict(self.text_encoder_cfg))
                self._enc_init, self._enc_axes = dbv2.init, dbv2.debertav2_logical_axes
                self._enc_encode = dbv2.encode
            else:
                raise ValueError(f"unknown text encoder {name}")

    def init_params(self, key):
        from paddlefleetx_tpu.models.multimodal.imagen import imagen

        return imagen.init(self.config, key)

    def logical_axes(self):
        from paddlefleetx_tpu.models.multimodal.imagen import imagen

        return imagen.imagen_logical_axes(self.config)

    def init_extra(self, key, params):
        if self._enc_init is None:
            return {}
        return {"text_encoder": self._enc_init(self._enc_cfg, key)}

    def extra_logical_axes(self):
        if self._enc_axes is None:
            return {}
        return {"text_encoder": self._enc_axes(self._enc_cfg)}

    def _embed_text(self, extra, batch):
        import jax

        ids = batch["input_ids"]
        enc = jax.tree.map(jax.lax.stop_gradient, extra["text_encoder"])
        emb = self._enc_encode(enc, ids, self._enc_cfg)
        mask = (ids != self._enc_cfg.pad_token_id).astype("int32")
        return emb, mask

    def loss_fn(self, params, batch, *, ctx=None, extra=None, dropout_key=None, train=True):
        import jax

        from paddlefleetx_tpu.models.multimodal.imagen import imagen

        if "text_embeds" not in batch and extra and "text_encoder" in extra:
            emb, mask = self._embed_text(extra, batch)
            batch = {**batch, "text_embeds": emb, "text_mask": mask}
        key = dropout_key if dropout_key is not None else jax.random.key(0)
        loss = imagen.p_losses(params, batch, self.config, key, train=train)
        return loss, extra


@MODULES.register("CLIPModule")
class CLIPModule(BasicModule):
    """Contrastive image-text pretraining."""

    def __init__(self, cfg):
        model_cfg = dict(cfg.Model)
        model_cfg.pop("module", None)
        model_cfg.pop("name", None)
        resolve_model_dtype(cfg, model_cfg)
        self.config = CLIPConfig.from_config(model_cfg)
        self.tokens_per_sample = self.config.max_text_len

    def init_params(self, key):
        return clip_model.init(self.config, key)

    def logical_axes(self):
        return clip_model.clip_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        return clip_model.clip_loss(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )
