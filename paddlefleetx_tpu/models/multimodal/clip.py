"""CLIP — contrastive language-image pretraining, functional.

The reference ships only a stub clip package
(ppfleetx/models/multimodal_model/clip/__init__.py is empty — SURVEY §2.3
"partial"); this is a complete implementation to close that gap the TPU way:

  - vision tower: the existing ViT (models/vit) with its classification
    head re-purposed as the image->embedding projection
  - text tower: compact pre-LN causal transformer; the sequence feature is
    taken at each sample's last non-pad token (CLIP's "EOT pooling")
  - symmetric InfoNCE over the GLOBAL batch: under pjit the batch axis is
    already global, so the cross-device feature all_gather that a
    NCCL implementation needs (same pattern as MoCo concat_all_gather,
    reference moco.py:35-46) is implied by the sharding — logits_per_image
    = scale * img @ txt.T directly
  - learnable temperature stored as log scale, clamped at 100 (CLIP paper)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    stack_spec_tree,
    zeros_init,
)
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, _constrain, layer_norm
from paddlefleetx_tpu.models.vit import model as vit
from paddlefleetx_tpu.models.vit.model import ViTConfig
from paddlefleetx_tpu.ops.attention import attention


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    projection_dim: int = 512
    # vision tower (ViT-B/16 by default)
    image_size: int = 224
    patch_size: int = 16
    vision_hidden_size: int = 768
    vision_layers: int = 12
    vision_heads: int = 12
    # text tower
    vocab_size: int = 49408
    max_text_len: int = 77
    text_hidden_size: int = 512
    text_layers: int = 12
    text_heads: int = 8
    pad_token_id: int = 0
    logit_scale_init: float = math.log(1.0 / 0.07)
    initializer_range: float = 0.02
    dropout_prob: float = 0.0
    dtype: str = "bfloat16"
    attn_impl: str = "xla"

    @property
    def vision_config(self) -> ViTConfig:
        return ViTConfig(
            image_size=self.image_size,
            patch_size=self.patch_size,
            num_classes=self.projection_dim,  # head == projection
            hidden_size=self.vision_hidden_size,
            num_layers=self.vision_layers,
            num_attention_heads=self.vision_heads,
            hidden_dropout_prob=self.dropout_prob,
            attention_probs_dropout_prob=self.dropout_prob,
            initializer_range=self.initializer_range,
            dtype=self.dtype,
        )

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "CLIPConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


# ---------------------------------------------------------------------------
# Text tower specs
# ---------------------------------------------------------------------------


def _text_layer_specs(cfg: CLIPConfig) -> Dict[str, Any]:
    h = cfg.text_hidden_size
    nh = cfg.text_heads
    hd = h // nh
    ffn = 4 * h
    w = normal_init(cfg.initializer_range)
    return {
        "ln_1": {"scale": ParamSpec((h,), ("embed",), ones_init()),
                 "bias": ParamSpec((h,), ("embed",), zeros_init())},
        "attn": {
            "qkv_kernel": ParamSpec((h, 3, nh, hd), ("embed", None, "heads", "kv"), w),
            "qkv_bias": ParamSpec((3, nh, hd), (None, "heads", "kv"), zeros_init()),
            "out_kernel": ParamSpec((nh, hd, h), ("heads", "kv", "embed"), w),
            "out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "ln_2": {"scale": ParamSpec((h,), ("embed",), ones_init()),
                 "bias": ParamSpec((h,), ("embed",), zeros_init())},
        "mlp": {
            "fc_in_kernel": ParamSpec((h, ffn), ("embed", "mlp"), w),
            "fc_in_bias": ParamSpec((ffn,), ("mlp",), zeros_init()),
            "fc_out_kernel": ParamSpec((ffn, h), ("mlp", "embed"), w),
            "fc_out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
    }


def clip_specs(cfg: CLIPConfig) -> Dict[str, Any]:
    h = cfg.text_hidden_size
    w = normal_init(cfg.initializer_range)
    return {
        "vision": vit.vit_specs(cfg.vision_config),
        "text": {
            "token_embedding": ParamSpec((cfg.vocab_size, h), ("vocab", "embed"), w),
            "pos_embedding": ParamSpec((cfg.max_text_len, h), (None, "embed"), w),
            "layers": stack_spec_tree(_text_layer_specs(cfg), cfg.text_layers),
            "final_ln": {"scale": ParamSpec((h,), ("embed",), ones_init()),
                         "bias": ParamSpec((h,), ("embed",), zeros_init())},
            "projection": ParamSpec((h, cfg.projection_dim), ("embed", None), w),
        },
        "logit_scale": ParamSpec(
            (), (), lambda key, shape, dtype: jnp.asarray(cfg.logit_scale_init, dtype)
        ),
    }


def init(cfg: CLIPConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, clip_specs(cfg))


def clip_logical_axes(cfg: CLIPConfig) -> Dict[str, Any]:
    return logical_axes(clip_specs(cfg))


# ---------------------------------------------------------------------------
# Towers
# ---------------------------------------------------------------------------


def encode_image(
    params: Dict[str, Any],
    images: jax.Array,
    cfg: CLIPConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """-> L2-normalized image embeddings [b, projection_dim]."""
    emb = vit.forward(
        params["vision"], images, cfg.vision_config,
        ctx=ctx, dropout_key=dropout_key, train=train,
    )
    return emb / (jnp.linalg.norm(emb.astype(jnp.float32), axis=-1, keepdims=True) + 1e-8).astype(emb.dtype)


def encode_text(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: CLIPConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """-> L2-normalized text embeddings [b, projection_dim] (EOT pooling)."""
    tp = params["text"]
    dtype = jnp.dtype(cfg.dtype)
    b, s = input_ids.shape
    x = tp["token_embedding"][input_ids].astype(dtype) + tp["pos_embedding"][:s][None].astype(dtype)
    x = _constrain(ctx, x, ("batch", "seq", "embed"))

    nh = cfg.text_heads
    hd = cfg.text_hidden_size // nh

    def block(carry, inp):
        h, idx = carry
        lp = inp
        key = (
            jax.random.fold_in(dropout_key, idx) if dropout_key is not None else None
        )
        h = _constrain(ctx, h, ("batch", "seq", "embed"))
        xn = layer_norm(h, lp["ln_1"]["scale"], lp["ln_1"]["bias"])
        qkv = jnp.einsum("bsd,dthk->bsthk", xn, lp["attn"]["qkv_kernel"]) + lp["attn"]["qkv_bias"]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        y = attention(
            q, k, v, impl=cfg.attn_impl, causal=True,
            dropout_key=key, dropout_rate=cfg.dropout_prob, train=train,
        )
        y = jnp.einsum("bshk,hkd->bsd", y, lp["attn"]["out_kernel"]) + lp["attn"]["out_bias"]
        h = h + y
        xn = layer_norm(h, lp["ln_2"]["scale"], lp["ln_2"]["bias"])
        y = jax.nn.gelu(xn @ lp["mlp"]["fc_in_kernel"] + lp["mlp"]["fc_in_bias"], approximate=True)
        y = y @ lp["mlp"]["fc_out_kernel"] + lp["mlp"]["fc_out_bias"]
        return (h + y, idx + 1), None

    (x, _), _ = jax.lax.scan(block, (x, jnp.int32(0)), tp["layers"], length=cfg.text_layers)
    x = layer_norm(x, tp["final_ln"]["scale"], tp["final_ln"]["bias"])

    # EOT pooling: feature at each sample's last non-pad position
    lengths = jnp.sum((input_ids != cfg.pad_token_id).astype(jnp.int32), axis=1)
    eot = jnp.clip(lengths - 1, 0, s - 1)
    feat = jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]
    emb = feat @ tp["projection"].astype(feat.dtype)
    return emb / (jnp.linalg.norm(emb.astype(jnp.float32), axis=-1, keepdims=True) + 1e-8).astype(emb.dtype)


def forward(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: CLIPConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    ki = kt = None
    if dropout_key is not None:
        ki, kt = jax.random.split(dropout_key)
    img = encode_image(params, batch["images"], cfg, ctx=ctx, dropout_key=ki, train=train)
    txt = encode_text(params, batch["input_ids"], cfg, ctx=ctx, dropout_key=kt, train=train)
    # straight-through clamp at ln(100): value is clipped but the gradient
    # passes through, so the parameter stays trainable at the boundary
    # (OpenAI CLIP clamps the param post-step; a plain min() would zero the
    # gradient and freeze the temperature once it crossed the cap)
    ls = params["logit_scale"]
    ls = ls - jax.lax.stop_gradient(jnp.maximum(ls - math.log(100.0), 0.0))
    return img, txt, jnp.exp(ls)


def clip_loss(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: CLIPConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = True,
) -> jax.Array:
    """Symmetric InfoNCE over the global batch."""
    img, txt, scale = forward(
        params, batch, cfg, ctx=ctx, dropout_key=dropout_key, train=train
    )
    logits = (scale * img @ txt.T).astype(jnp.float32)  # [b, b]
    labels = jnp.arange(logits.shape[0])
    li = -jnp.mean(jnp.take_along_axis(jax.nn.log_softmax(logits, axis=1), labels[:, None], axis=1))
    lt = -jnp.mean(jnp.take_along_axis(jax.nn.log_softmax(logits, axis=0), labels[None, :], axis=0))
    return 0.5 * (li + lt)
