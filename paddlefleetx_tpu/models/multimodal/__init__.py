"""Multimodal families: Imagen text-to-image, CLIP dual encoder."""
