"""Vision Transformer family."""

from paddlefleetx_tpu.models.vit.model import (  # noqa: F401
    PRESETS,
    ViTConfig,
    cls_loss,
    forward,
    init,
    interpolate_pos_embed,
    top_k_accuracy,
    vit_logical_axes,
    vit_specs,
)
