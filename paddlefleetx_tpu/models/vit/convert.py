"""HF ViT checkpoint -> native param tree (same role as gpt/convert.py).

Mapping notes:
- separate HF q/k/v Linears pack into the fused qkv kernel [h, 3, nh, hd]
  (torch Linear weights are [out, in] — transpose first).
- the Conv2d patch projection [h, C, ps, ps] becomes the matmul kernel
  [ps*ps*C, h] matching patchify()'s (ph, pw, C) flatten order.
- HF uses exact-erf gelu: the emitted config sets gelu_approximate False.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from paddlefleetx_tpu.models.vit.model import ViTConfig


def hf_vit_config(hf_cfg, num_classes: int = 0, **overrides) -> ViTConfig:
    act = getattr(hf_cfg, "hidden_act", "gelu")
    if act != "gelu":
        raise ValueError(f"unsupported hidden_act {act!r}")
    kw = dict(
        image_size=int(hf_cfg.image_size),
        patch_size=int(hf_cfg.patch_size),
        in_channels=int(getattr(hf_cfg, "num_channels", 3)),
        hidden_size=int(hf_cfg.hidden_size),
        num_layers=int(hf_cfg.num_hidden_layers),
        num_attention_heads=int(hf_cfg.num_attention_heads),
        ffn_hidden_size=int(hf_cfg.intermediate_size),
        num_classes=int(num_classes),
        gelu_approximate=False,
        layer_norm_eps=float(getattr(hf_cfg, "layer_norm_eps", 1e-12)),
    )
    kw.update(overrides)
    return ViTConfig(**kw)


def convert_hf_vit_state_dict(sd: Dict, cfg: ViTConfig) -> Dict:
    """torch/HF ``ViTModel``/``ViTForImageClassification`` state dict ->
    stacked param tree.  Keys may carry a ``vit.`` prefix (classification
    models); the classifier head maps when num_classes matches."""

    from paddlefleetx_tpu.models.convert_common import (
        detect_prefix,
        make_getter,
        make_stacker,
    )

    get = make_getter(sd, detect_prefix(sd, ("vit.",)))

    h, nh, hd = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
    L, ps, C = cfg.num_layers, cfg.patch_size, cfg.in_channels

    def qkv_stack(kind):
        ks, bs = [], []
        for i in range(L):
            base = f"encoder.layer.{i}.attention.attention.{kind}"
            ks.append(get(base + ".weight").T.reshape(h, nh, hd))
            bs.append(get(base + ".bias").reshape(nh, hd))
        return np.stack(ks), np.stack(bs)

    qk, qb = qkv_stack("query")
    kk, kb = qkv_stack("key")
    vk, vb = qkv_stack("value")

    stack = make_stacker(get, L)

    params = {
        "cls_token": get("embeddings.cls_token"),
        "pos_embed": get("embeddings.position_embeddings"),
        "patch_embed": {
            # [h, C, ph, pw] -> (ph, pw, C, h) -> [ps*ps*C, h]
            "kernel": get("embeddings.patch_embeddings.projection.weight")
            .transpose(2, 3, 1, 0)
            .reshape(ps * ps * C, h),
            "bias": get("embeddings.patch_embeddings.projection.bias"),
        },
        "layers": {
            "ln_1": {
                "scale": stack("encoder.layer.{i}.layernorm_before.weight"),
                "bias": stack("encoder.layer.{i}.layernorm_before.bias"),
            },
            "attn": {
                "qkv_kernel": np.stack([qk, kk, vk], axis=2),  # [L, h, 3, nh, hd]
                "qkv_bias": np.stack([qb, kb, vb], axis=1),    # [L, 3, nh, hd]
                "out_kernel": stack(
                    "encoder.layer.{i}.attention.output.dense.weight",
                    (nh, hd, h), transpose=True,
                ),
                "out_bias": stack("encoder.layer.{i}.attention.output.dense.bias"),
            },
            "ln_2": {
                "scale": stack("encoder.layer.{i}.layernorm_after.weight"),
                "bias": stack("encoder.layer.{i}.layernorm_after.bias"),
            },
            "mlp": {
                "fc_in_kernel": stack(
                    "encoder.layer.{i}.intermediate.dense.weight", transpose=True
                ),
                "fc_in_bias": stack("encoder.layer.{i}.intermediate.dense.bias"),
                "fc_out_kernel": stack(
                    "encoder.layer.{i}.output.dense.weight", transpose=True
                ),
                "fc_out_bias": stack("encoder.layer.{i}.output.dense.bias"),
            },
        },
        "final_ln": {
            "scale": get("layernorm.weight"),
            "bias": get("layernorm.bias"),
        },
    }
    if cfg.num_classes:
        if "classifier.weight" in sd:
            head_w = get("classifier.weight")
            if head_w.shape[0] != cfg.num_classes:
                raise ValueError(
                    f"checkpoint classifier has {head_w.shape[0]} labels, "
                    f"config num_classes is {cfg.num_classes}"
                )
            params["head"] = {"kernel": head_w.T, "bias": get("classifier.bias")}
        else:
            # backbone-only checkpoint converted for finetuning: fresh head
            # (zeros — the first optimizer steps learn it from the frozen-ish
            # pretrained features, standard linear-probe init)
            params["head"] = {
                "kernel": np.zeros((h, cfg.num_classes), np.float32),
                "bias": np.zeros((cfg.num_classes,), np.float32),
            }
    return params
