"""Vision Transformer (reference ppfleetx/models/vision_model/vit/vit.py).

Covers the reference surface: patch embedding, class token, learned position
embeddings, pre-LN encoder blocks, optional representation layer ("pre_logits")
and classification head (vit.py:54-166); position-embedding interpolation for
resolution changes (:282-308).  The reference's ``FusedBlock``
(FusedMultiHeadAttention/FusedFeedForward, vit.py:23-80) corresponds to the
same fused compute XLA emits for these einsum blocks — there is one block
definition here, no fused/unfused duality (checkpoint conversion moot).

Sharding uses the same logical vocabulary as GPT (heads/mlp over ``model``,
batch over data axes), so all parallel layouts apply unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    dropout,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    stack_spec_tree,
    zeros_init,
)
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, _constrain, layer_norm
from paddlefleetx_tpu.ops.attention import attention


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    in_channels: int = 3
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    ffn_hidden_size: Optional[int] = None
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    use_recompute: bool = False
    attn_impl: str = "xla"  # bidirectional: flash (causal-only) not applicable
    # tanh-approx gelu is the TPU default; HF ViT checkpoints use exact erf
    gelu_approximate: bool = True
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    # "token": use cls token; "mean": global average pool (reference global_pool)
    pool: str = "token"
    representation_size: Optional[int] = None

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            object.__setattr__(self, "ffn_hidden_size", 4 * self.hidden_size)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def from_config(model_cfg) -> "ViTConfig":
        fields = {f.name for f in dataclasses.fields(ViTConfig)}
        return ViTConfig(**{k: v for k, v in dict(model_cfg).items() if k in fields})


PRESETS = {
    "ViT-B/16": dict(hidden_size=768, num_layers=12, num_attention_heads=12, patch_size=16),
    "ViT-L/16": dict(hidden_size=1024, num_layers=24, num_attention_heads=16, patch_size=16),
    "ViT-H/14": dict(hidden_size=1280, num_layers=32, num_attention_heads=16, patch_size=14),
}


def _encoder_layer_specs(cfg: ViTConfig) -> Dict[str, Any]:
    h, nh, hd, ffn = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim, cfg.ffn_hidden_size
    w = normal_init(cfg.initializer_range)
    return {
        "ln_1": {"scale": ParamSpec((h,), ("embed",), ones_init()),
                 "bias": ParamSpec((h,), ("embed",), zeros_init())},
        "attn": {
            "qkv_kernel": ParamSpec((h, 3, nh, hd), ("embed", None, "heads", "kv"), w),
            "qkv_bias": ParamSpec((3, nh, hd), (None, "heads", "kv"), zeros_init()),
            "out_kernel": ParamSpec((nh, hd, h), ("heads", "kv", "embed"), w),
            "out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "ln_2": {"scale": ParamSpec((h,), ("embed",), ones_init()),
                 "bias": ParamSpec((h,), ("embed",), zeros_init())},
        "mlp": {
            "fc_in_kernel": ParamSpec((h, ffn), ("embed", "mlp"), w),
            "fc_in_bias": ParamSpec((ffn,), ("mlp",), zeros_init()),
            "fc_out_kernel": ParamSpec((ffn, h), ("mlp", "embed"), w),
            "fc_out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
    }


def vit_specs(cfg: ViTConfig) -> Dict[str, Any]:
    h = cfg.hidden_size
    w = normal_init(cfg.initializer_range)
    p = cfg.patch_size
    specs: Dict[str, Any] = {
        "patch_embed": {
            "kernel": ParamSpec(
                (p * p * cfg.in_channels, h), (None, "embed"), w
            ),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "cls_token": ParamSpec((1, 1, h), (None, None, "embed"), zeros_init()),
        "pos_embed": ParamSpec((1, cfg.num_patches + 1, h), (None, None, "embed"), w),
        "layers": stack_spec_tree(_encoder_layer_specs(cfg), cfg.num_layers),
        "final_ln": {"scale": ParamSpec((h,), ("embed",), ones_init()),
                     "bias": ParamSpec((h,), ("embed",), zeros_init())},
    }
    if cfg.num_classes:
        specs["head"] = {
            "kernel": ParamSpec((h, cfg.num_classes), ("embed", "vocab"), w),
            "bias": ParamSpec((cfg.num_classes,), ("vocab",), zeros_init()),
        }
    if cfg.representation_size:
        specs["pre_logits"] = {
            "kernel": ParamSpec((h, cfg.representation_size), ("embed", "mlp"), w),
            "bias": ParamSpec((cfg.representation_size,), ("mlp",), zeros_init()),
        }
        specs["head"]["kernel"] = ParamSpec(
            (cfg.representation_size, cfg.num_classes), ("mlp", "vocab"), w
        )
    return specs


def init(cfg: ViTConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, vit_specs(cfg))


def vit_logical_axes(cfg: ViTConfig) -> Dict[str, Any]:
    return logical_axes(vit_specs(cfg))


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """[b, H, W, C] -> [b, (H/p)*(W/p), p*p*C] (conv-as-reshape: the patch
    projection is a matmul on unfolded patches — MXU-friendly, identical to
    the reference's Conv2d stride=p patch embed)."""
    b, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(b, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, patch * patch * C)


def interpolate_pos_embed(pos_embed: jax.Array, new_num_patches: int) -> jax.Array:
    """Bilinear-resize grid position embeddings for a new resolution
    (reference vit.py:282-308)."""
    cls_pe, grid_pe = pos_embed[:, :1], pos_embed[:, 1:]
    old = int(grid_pe.shape[1] ** 0.5)
    new = int(new_num_patches**0.5)
    if old == new:
        return pos_embed
    grid = grid_pe.reshape(1, old, old, -1)
    grid = jax.image.resize(grid, (1, new, new, grid.shape[-1]), "bilinear")
    return jnp.concatenate([cls_pe, grid.reshape(1, new * new, -1)], axis=1)


def _encoder_layer(p, x, cfg: ViTConfig, ctx, key, train):
    k_attn, k_resid, k_mlp = (
        jax.random.split(key, 3) if key is not None else (None, None, None)
    )
    dtype = x.dtype

    y = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"], eps=cfg.layer_norm_eps)
    qkv = jnp.einsum("bsh,htnd->bstnd", y, p["attn"]["qkv_kernel"].astype(dtype))
    qkv = qkv + p["attn"]["qkv_bias"].astype(dtype)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _constrain(ctx, q, ("batch", None, "heads", "kv"))
    out = attention(
        q, k, v, impl="xla", causal=False,
        dropout_key=k_attn, dropout_rate=cfg.attention_probs_dropout_prob, train=train,
    )
    out = jnp.einsum("bsnd,ndh->bsh", out, p["attn"]["out_kernel"].astype(dtype))
    out = out + p["attn"]["out_bias"].astype(dtype)
    x = x + dropout(k_resid, out, cfg.hidden_dropout_prob, train)

    y = layer_norm(x, p["ln_2"]["scale"], p["ln_2"]["bias"], eps=cfg.layer_norm_eps)
    mp = p["mlp"]
    y = y @ mp["fc_in_kernel"].astype(dtype) + mp["fc_in_bias"].astype(dtype)
    y = jax.nn.gelu(y, approximate=cfg.gelu_approximate)
    y = y @ mp["fc_out_kernel"].astype(dtype) + mp["fc_out_bias"].astype(dtype)
    x = x + dropout(k_mlp, y, cfg.hidden_dropout_prob, train)
    return _constrain(ctx, x, ("batch", None, "embed"))


def forward(
    params: Dict[str, Any],
    images: jax.Array,  # [b, H, W, C] float
    cfg: ViTConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """-> logits [b, num_classes]."""
    dtype = jnp.dtype(cfg.dtype)
    x = patchify(images.astype(dtype), cfg.patch_size)
    x = x @ params["patch_embed"]["kernel"].astype(dtype) + params["patch_embed"][
        "bias"
    ].astype(dtype)
    b = x.shape[0]
    cls = jnp.tile(params["cls_token"].astype(dtype), (b, 1, 1))
    x = jnp.concatenate([cls, x], axis=1)
    pe = params["pos_embed"]
    if pe.shape[1] != x.shape[1]:
        pe = interpolate_pos_embed(pe, x.shape[1] - 1)
    x = x + pe.astype(dtype)
    k_embed, k_layers = (
        jax.random.split(dropout_key) if dropout_key is not None else (None, None)
    )
    x = dropout(k_embed, x, cfg.hidden_dropout_prob, train)
    x = _constrain(ctx, x, ("batch", None, "embed"))

    def body(carry, inp):
        p_l, idx = inp
        k = jax.random.fold_in(k_layers, idx) if k_layers is not None else None
        return _encoder_layer(p_l, carry, cfg, ctx, k, train), None

    body_fn = jax.checkpoint(body) if cfg.use_recompute else body
    x, _ = jax.lax.scan(body_fn, x, (params["layers"], jnp.arange(cfg.num_layers)))

    x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"], eps=cfg.layer_norm_eps)
    feat = x[:, 0] if cfg.pool == "token" else x[:, 1:].mean(axis=1)
    if cfg.representation_size:
        feat = jnp.tanh(
            feat @ params["pre_logits"]["kernel"].astype(dtype)
            + params["pre_logits"]["bias"].astype(dtype)
        )
    if "head" not in params:  # backbone/feature-extractor mode (num_classes 0)
        return feat
    logits = feat @ params["head"]["kernel"].astype(dtype) + params["head"]["bias"].astype(dtype)
    return logits


def cls_loss(
    logits: jax.Array, labels: jax.Array, label_smoothing: float = 0.0
) -> jax.Array:
    """CE with optional smoothing; labels may be int [b] or soft [b, classes]
    (mixup).  Canonical impl: models/vision/loss.py (CELoss parity)."""
    from paddlefleetx_tpu.models.vision.loss import ce_loss

    return ce_loss(logits, labels, label_smoothing or None)


def top_k_accuracy(logits: jax.Array, labels: jax.Array, k: int = 1) -> jax.Array:
    """top-1/top-5 metrics (reference general_classification_module.py:84).
    Canonical impl: models/vision/metrics.py."""
    from paddlefleetx_tpu.models.vision.metrics import topk_acc

    return topk_acc(logits, labels, (k,))[f"top{k}"]
