"""HF DebertaV2 checkpoint -> native param tree (same role as gpt/convert.py).

The disentangled-attention encoder is the subtlest mapping; logits parity
with ``transformers.DebertaV2Model`` (tests/test_hf_convert.py) is the
oracle.  torch ``nn.Linear`` weights are [out, in] — kernels transpose.
"""

from __future__ import annotations

from typing import Dict


from paddlefleetx_tpu.models.debertav2.model import DebertaV2Config


def hf_debertav2_config(hf_cfg, **overrides) -> DebertaV2Config:
    act = getattr(hf_cfg, "hidden_act", "gelu")
    if act != "gelu":
        raise ValueError(f"unsupported hidden_act {act!r}")
    norm_rel = getattr(hf_cfg, "norm_rel_ebd", "none")
    if norm_rel != "layer_norm":
        raise ValueError(f"unsupported norm_rel_ebd {norm_rel!r} (need layer_norm)")
    if getattr(hf_cfg, "position_biased_input", True):
        raise ValueError("position_biased_input=True not supported (v2 uses False)")
    if not getattr(hf_cfg, "share_att_key", False):
        raise ValueError("share_att_key=False not supported")
    emb_size = getattr(hf_cfg, "embedding_size", None) or hf_cfg.hidden_size
    if int(emb_size) != int(hf_cfg.hidden_size):
        raise ValueError(
            f"embedding_size {emb_size} != hidden_size (embed_proj not supported)"
        )
    if int(getattr(hf_cfg, "conv_kernel_size", 0)) > 0:
        if getattr(hf_cfg, "conv_act", "tanh") != "gelu":
            raise ValueError(
                f"conv_act {getattr(hf_cfg, 'conv_act', 'tanh')!r} unsupported "
                "(the native ConvLayer applies gelu)"
            )
        if int(getattr(hf_cfg, "conv_groups", 1)) != 1:
            raise ValueError("grouped conv not supported")
    kw = dict(
        vocab_size=int(hf_cfg.vocab_size),
        hidden_size=int(hf_cfg.hidden_size),
        num_layers=int(hf_cfg.num_hidden_layers),
        num_attention_heads=int(hf_cfg.num_attention_heads),
        intermediate_size=int(hf_cfg.intermediate_size),
        max_position_embeddings=int(hf_cfg.max_position_embeddings),
        layer_norm_eps=float(hf_cfg.layer_norm_eps),
        relative_attention=bool(hf_cfg.relative_attention),
        position_buckets=int(getattr(hf_cfg, "position_buckets", -1)),
        max_relative_positions=int(getattr(hf_cfg, "max_relative_positions", -1)),
        pos_att_type=tuple(hf_cfg.pos_att_type or ()),
        conv_kernel_size=int(getattr(hf_cfg, "conv_kernel_size", 0)),
        pad_token_id=int(getattr(hf_cfg, "pad_token_id", 0)),
    )
    kw.update(overrides)
    return DebertaV2Config(**kw)


def convert_hf_debertav2_state_dict(sd: Dict, cfg: DebertaV2Config) -> Dict:
    """torch/HF ``DebertaV2Model.state_dict()`` -> stacked param tree."""

    from paddlefleetx_tpu.models.convert_common import make_getter, make_stacker

    get = make_getter(sd)

    h, nh, hd = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
    L = cfg.num_layers

    stack = make_stacker(get, L)

    params = {
        "embeddings": {
            "word": get("embeddings.word_embeddings.weight"),
            "ln_scale": get("embeddings.LayerNorm.weight"),
            "ln_bias": get("embeddings.LayerNorm.bias"),
        },
        "layers": {
            "attn": {
                "q_kernel": stack(
                    "encoder.layer.{i}.attention.self.query_proj.weight",
                    (h, nh, hd), transpose=True,
                ),
                "q_bias": stack(
                    "encoder.layer.{i}.attention.self.query_proj.bias", (nh, hd)
                ),
                "k_kernel": stack(
                    "encoder.layer.{i}.attention.self.key_proj.weight",
                    (h, nh, hd), transpose=True,
                ),
                "k_bias": stack(
                    "encoder.layer.{i}.attention.self.key_proj.bias", (nh, hd)
                ),
                "v_kernel": stack(
                    "encoder.layer.{i}.attention.self.value_proj.weight",
                    (h, nh, hd), transpose=True,
                ),
                "v_bias": stack(
                    "encoder.layer.{i}.attention.self.value_proj.bias", (nh, hd)
                ),
                "out_kernel": stack(
                    "encoder.layer.{i}.attention.output.dense.weight",
                    (nh, hd, h), transpose=True,
                ),
                "out_bias": stack("encoder.layer.{i}.attention.output.dense.bias"),
            },
            "ln_attn": {
                "scale": stack("encoder.layer.{i}.attention.output.LayerNorm.weight"),
                "bias": stack("encoder.layer.{i}.attention.output.LayerNorm.bias"),
            },
            "mlp": {
                "fc_in_kernel": stack(
                    "encoder.layer.{i}.intermediate.dense.weight", transpose=True
                ),
                "fc_in_bias": stack("encoder.layer.{i}.intermediate.dense.bias"),
                "fc_out_kernel": stack(
                    "encoder.layer.{i}.output.dense.weight", transpose=True
                ),
                "fc_out_bias": stack("encoder.layer.{i}.output.dense.bias"),
            },
            "ln_mlp": {
                "scale": stack("encoder.layer.{i}.output.LayerNorm.weight"),
                "bias": stack("encoder.layer.{i}.output.LayerNorm.bias"),
            },
        },
        "rel_embeddings": get("encoder.rel_embeddings.weight"),
        "rel_ln": {
            "scale": get("encoder.LayerNorm.weight"),
            "bias": get("encoder.LayerNorm.bias"),
        },
    }
    if cfg.conv_kernel_size > 0:
        # HF Conv1d weight [out, in, ks] -> native WIO [ks, in, out]
        params["conv"] = {
            "kernel": get("encoder.conv.conv.weight").transpose(2, 1, 0),
            "bias": get("encoder.conv.conv.bias"),
            "ln_scale": get("encoder.conv.LayerNorm.weight"),
            "ln_bias": get("encoder.conv.LayerNorm.bias"),
        }
    return params
