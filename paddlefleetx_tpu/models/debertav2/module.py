"""DebertaV2 engine modules (MLM pretrain / sequence classification).

The reference ships DebertaV2 as a model library (used standalone and as an
Imagen text encoder); here it also plugs into the Engine."""

from __future__ import annotations

from paddlefleetx_tpu.core.module import BasicModule, resolve_model_dtype
from paddlefleetx_tpu.models.debertav2 import model as deberta
from paddlefleetx_tpu.models.debertav2.config import DebertaV2Config
from paddlefleetx_tpu.utils.registry import MODULES


def _config_from(cfg) -> DebertaV2Config:
    model_cfg = dict(cfg.Model)
    model_cfg.pop("module", None)
    model_cfg.pop("name", None)
    resolve_model_dtype(cfg, model_cfg)
    return DebertaV2Config.from_config(model_cfg)


@MODULES.register("DebertaV2Module")
class DebertaV2Module(BasicModule):
    """Masked-LM pretraining."""

    head = "mlm"

    def __init__(self, cfg):
        self.config = _config_from(cfg)
        self.tokens_per_sample = self.config.max_position_embeddings
        seq = cfg.get("Data", {}).get("Train", {}).get("dataset", {}).get("max_seq_len")
        if seq:
            self.tokens_per_sample = int(seq)

    def init_params(self, key):
        return deberta.init(self.config, key, head=self.head)

    def logical_axes(self):
        return deberta.debertav2_logical_axes(self.config, head=self.head)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        return deberta.mlm_loss(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )

    def export_spec(self):
        import jax.numpy as jnp

        cfg = self.config

        def fwd(params, input_ids):
            hidden = deberta.encode(params, input_ids, cfg, train=False)
            return deberta.mlm_logits(params, hidden, cfg)

        return fwd, (jnp.zeros((1, self.tokens_per_sample), jnp.int32),)


@MODULES.register("DebertaV2SeqClsModule")
class DebertaV2SeqClsModule(DebertaV2Module):
    """Sequence-classification finetune."""

    head = "cls"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.metric_cfg = dict(cfg.Model.get("metric", {}) or {})

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        logits = deberta.cls_forward(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )
        return deberta.cls_loss(logits, batch["labels"])

    def export_spec(self):
        import jax.numpy as jnp

        cfg = self.config

        def fwd(params, input_ids):
            return deberta.cls_forward(params, {"input_ids": input_ids}, cfg, train=False)

        return fwd, (jnp.zeros((1, self.tokens_per_sample), jnp.int32),)

    def predict_fn(self, params, batch, *, ctx=None):
        return deberta.cls_forward(params, batch, self.config, ctx=ctx, train=False)

    def build_metric(self):
        from paddlefleetx_tpu.models.metrics import Accuracy, build_metric

        if self.metric_cfg.get("eval"):
            return build_metric(self.metric_cfg["eval"])
        return Accuracy()
