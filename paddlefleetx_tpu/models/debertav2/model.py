"""DebertaV2 — disentangled-attention encoder, pure-JAX functional.

TPU-native re-design of the reference DebertaV2 stack
(ppfleetx/models/language_model/debertav2/modeling.py:
DisentangledSelfAttention :688, disentangled_attention_bias :843,
build_relative_position / make_log_bucket_position helpers, ConvLayer :381,
DebertaV2Encoder :428).  Used standalone (MLM / sequence classification)
and as an Imagen text-encoder option.

Disentangled attention: score = c2c + c2p + p2c, all scaled by
1/sqrt(d * scale_factor) with scale_factor = 1 + len(pos_att_type); the
relative-position projections reuse the content q/k kernels when
share_att_key (reference :866-878).  Relative positions are log-bucketed
(position_buckets) so distant offsets share embeddings.

The c2p/p2c "gather at bucket index" (reference paddle.take_along_axis on
[b*h, q, 2*span] scores) is expressed as one-hot matmuls over the bucket
axis — identical math, MXU-friendly, no dynamic gather inside the hot
loop; the one-hot tables are position-only and get CSE'd across layers.

Layers are stacked on a leading ``layers`` axis and run with ``lax.scan``;
the shared rel-position embedding (+ its LayerNorm) lives at top level.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    dropout,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    stack_spec_tree,
    zeros_init,
)
from paddlefleetx_tpu.models.debertav2.config import DebertaV2Config
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, _constrain, layer_norm

NEG_INF = -1e9


# ---------------------------------------------------------------------------
# Relative positions (log buckets)
# ---------------------------------------------------------------------------


def make_log_bucket_position(rel_pos: jax.Array, bucket_size: int, max_position: int) -> jax.Array:
    """Map signed offsets to log-spaced buckets in [-mid, mid]."""
    sign = jnp.sign(rel_pos)
    mid = bucket_size // 2
    abs_pos = jnp.where(
        (rel_pos < mid) & (rel_pos > -mid), mid - 1, jnp.abs(rel_pos)
    ).astype(jnp.float32)
    log_pos = (
        jnp.ceil(
            jnp.log(abs_pos / mid) / jnp.log((max_position - 1) / mid) * (mid - 1)
        )
        + mid
    )
    return jnp.where(jnp.abs(rel_pos) <= mid, rel_pos, (log_pos * sign).astype(rel_pos.dtype))


def build_relative_position(q_len: int, k_len: int, cfg: DebertaV2Config) -> jax.Array:
    """[q, k] signed (possibly bucketed) relative positions q_i - k_j."""
    rel = jnp.arange(q_len)[:, None] - jnp.arange(k_len)[None, :]
    if cfg.position_buckets > 0:
        max_pos = cfg.max_relative_positions if cfg.max_relative_positions > 0 else cfg.max_position_embeddings
        rel = make_log_bucket_position(rel, cfg.position_buckets, max_pos)
    return rel


# ---------------------------------------------------------------------------
# Parameter specs
# ---------------------------------------------------------------------------


def _layer_specs(cfg: DebertaV2Config) -> Dict[str, Any]:
    h, nh, hd, ffn = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim, cfg.intermediate_size
    w = normal_init(cfg.initializer_range)
    specs: Dict[str, Any] = {
        "attn": {
            "q_kernel": ParamSpec((h, nh, hd), ("embed", "heads", "kv"), w),
            "q_bias": ParamSpec((nh, hd), ("heads", "kv"), zeros_init()),
            "k_kernel": ParamSpec((h, nh, hd), ("embed", "heads", "kv"), w),
            "k_bias": ParamSpec((nh, hd), ("heads", "kv"), zeros_init()),
            "v_kernel": ParamSpec((h, nh, hd), ("embed", "heads", "kv"), w),
            "v_bias": ParamSpec((nh, hd), ("heads", "kv"), zeros_init()),
            "out_kernel": ParamSpec((nh, hd, h), ("heads", "kv", "embed"), w),
            "out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "ln_attn": {
            "scale": ParamSpec((h,), ("embed",), ones_init()),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "mlp": {
            "fc_in_kernel": ParamSpec((h, ffn), ("embed", "mlp"), w),
            "fc_in_bias": ParamSpec((ffn,), ("mlp",), zeros_init()),
            "fc_out_kernel": ParamSpec((ffn, h), ("mlp", "embed"), w),
            "fc_out_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "ln_mlp": {
            "scale": ParamSpec((h,), ("embed",), ones_init()),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
    }
    if not cfg.share_att_key and cfg.relative_attention:
        if "c2p" in cfg.pos_att_type:
            specs["attn"]["pos_k_kernel"] = ParamSpec((h, nh, hd), ("embed", "heads", "kv"), w)
            specs["attn"]["pos_k_bias"] = ParamSpec((nh, hd), ("heads", "kv"), zeros_init())
        if "p2c" in cfg.pos_att_type:
            specs["attn"]["pos_q_kernel"] = ParamSpec((h, nh, hd), ("embed", "heads", "kv"), w)
            specs["attn"]["pos_q_bias"] = ParamSpec((nh, hd), ("heads", "kv"), zeros_init())
    return specs


def debertav2_specs(cfg: DebertaV2Config) -> Dict[str, Any]:
    h = cfg.hidden_size
    w = normal_init(cfg.initializer_range)
    specs: Dict[str, Any] = {
        "embeddings": {
            "word": ParamSpec((cfg.vocab_size, h), ("vocab", "embed"), w),
            "ln_scale": ParamSpec((h,), ("embed",), ones_init()),
            "ln_bias": ParamSpec((h,), ("embed",), zeros_init()),
        },
        "layers": stack_spec_tree(_layer_specs(cfg), cfg.num_layers),
    }
    if cfg.position_biased_input:
        specs["embeddings"]["position"] = ParamSpec(
            (cfg.max_position_embeddings, h), ("table", "embed"), w
        )
    if cfg.relative_attention:
        specs["rel_embeddings"] = ParamSpec((cfg.pos_ebd_size * 2, h), ("table", "embed"), w)
        specs["rel_ln"] = {
            "scale": ParamSpec((h,), ("embed",), ones_init()),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
        }
    if cfg.conv_kernel_size > 0:
        specs["conv"] = {
            "kernel": ParamSpec((cfg.conv_kernel_size, h, h), (None, None, "embed"), w),
            "bias": ParamSpec((h,), ("embed",), zeros_init()),
            "ln_scale": ParamSpec((h,), ("embed",), ones_init()),
            "ln_bias": ParamSpec((h,), ("embed",), zeros_init()),
        }
    return specs


def mlm_head_specs(cfg: DebertaV2Config) -> Dict[str, Any]:
    h = cfg.hidden_size
    w = normal_init(cfg.initializer_range)
    return {
        "transform_kernel": ParamSpec((h, h), ("embed", "embed_out"), w),
        "transform_bias": ParamSpec((h,), ("embed",), zeros_init()),
        "ln_scale": ParamSpec((h,), ("embed",), ones_init()),
        "ln_bias": ParamSpec((h,), ("embed",), zeros_init()),
        "decoder_bias": ParamSpec((cfg.vocab_size,), ("vocab",), zeros_init()),
    }


def cls_head_specs(cfg: DebertaV2Config) -> Dict[str, Any]:
    h = cfg.hidden_size
    w = normal_init(cfg.initializer_range)
    return {
        "pooler_kernel": ParamSpec((h, h), ("embed", "embed_out"), w),
        "pooler_bias": ParamSpec((h,), ("embed",), zeros_init()),
        "cls_kernel": ParamSpec((h, cfg.num_classes), ("embed", None), w),
        "cls_bias": ParamSpec((cfg.num_classes,), (None,), zeros_init()),
    }


def init(cfg: DebertaV2Config, key: jax.Array, head: Optional[str] = None) -> Dict[str, Any]:
    specs = debertav2_specs(cfg)
    if head == "mlm":
        specs["mlm_head"] = mlm_head_specs(cfg)
    elif head == "cls":
        specs["cls_head"] = cls_head_specs(cfg)
    return init_params(key, specs)


def debertav2_logical_axes(cfg: DebertaV2Config, head: Optional[str] = None) -> Dict[str, Any]:
    specs = debertav2_specs(cfg)
    if head == "mlm":
        specs["mlm_head"] = mlm_head_specs(cfg)
    elif head == "cls":
        specs["cls_head"] = cls_head_specs(cfg)
    return logical_axes(specs)


# ---------------------------------------------------------------------------
# Disentangled attention
# ---------------------------------------------------------------------------


def _heads(x: jax.Array, kernel: jax.Array, bias: jax.Array) -> jax.Array:
    # params are stored fp32: cast to the activation dtype so a bf16
    # forward is not silently promoted back to fp32
    return jnp.einsum("...d,dhk->...hk", x, kernel.astype(x.dtype)) + bias.astype(x.dtype)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _conv_branch(
    p: Dict[str, jax.Array],
    emb: jax.Array,
    first_out: jax.Array,
    attention_mask: jax.Array,
    cfg,
    key,
    train,
):
    """ConvLayer (:381-427): token conv on the embedding output (zeroed at
    pad positions, reference rmask handling), ACT(dropout(conv)) order,
    summed with the first transformer layer's output, then LN."""
    y = jax.lax.conv_general_dilated(
        emb, p["kernel"],
        window_strides=(1,), padding="SAME",
        dimension_numbers=("NWC", "WIO", "NWC"),
    ) + p["bias"]
    y = y * attention_mask[..., None].astype(y.dtype)
    # exact (erf) gelu: both the reference ConvLayer and HF conv_act="gelu"
    # use the unapproximated form here
    y = jax.nn.gelu(dropout(key, y, cfg.hidden_dropout_prob, train), approximate=False)
    return layer_norm(first_out + y, p["ln_scale"], p["ln_bias"], cfg.layer_norm_eps)


def encode(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: DebertaV2Config,
    *,
    attention_mask: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """Returns final hidden states [b, s, h]."""
    dtype = jnp.dtype(cfg.dtype)
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
    pad_bias = jnp.where(
        attention_mask[:, None, None, :].astype(jnp.bool_), 0.0, NEG_INF
    ).astype(jnp.float32)

    emb = params["embeddings"]
    x = emb["word"][input_ids]
    if cfg.position_biased_input:
        x = x + emb["position"][:s][None]
    x = layer_norm(x.astype(dtype), emb["ln_scale"], emb["ln_bias"], cfg.layer_norm_eps)
    # zero pad rows (reference DebertaV2Embeddings mask multiply): attention
    # masking alone is not enough once the ConvLayer mixes neighboring
    # tokens — a pad row's garbage would leak into valid positions
    x = x * attention_mask[..., None].astype(dtype)
    k_emb = k_stack = k_conv = None
    if dropout_key is not None:
        k_emb, k_stack, k_conv = jax.random.split(dropout_key, 3)
    x = dropout(k_emb, x, cfg.hidden_dropout_prob, train)
    x = _constrain(ctx, x, ("batch", "seq", "embed"))

    # shared rel-position machinery, computed once per forward.  rel_idx
    # [q, k] = bucket(q-k) + span indexes the projected rel-embedding rows
    # for BOTH terms: c2p gathers it directly (q_i . pos_k[bucket(i-j)]),
    # p2c gathers its transpose (k_j . pos_q[bucket(i-j)] consulted at
    # [k, q] — reference disentangled_attention_bias p2c gather+transpose)
    rel_emb = rel_idx = None
    if cfg.relative_attention:
        span = cfg.pos_ebd_size
        rel_emb = layer_norm(
            params["rel_embeddings"].astype(dtype),
            params["rel_ln"]["scale"], params["rel_ln"]["bias"], cfg.layer_norm_eps,
        )
        rel = build_relative_position(s, s, cfg)  # [q, k] in [-span, span)
        rel_idx = jnp.clip(rel + span, 0, 2 * span - 1)

    def block(carry, lp):
        h, idx = carry
        keys = {}
        if dropout_key is not None and train:
            lk = jax.random.fold_in(k_stack, idx)
            names = ("attn", "post_attn", "ffn", "post_ffn")
            keys = dict(zip(names, jax.random.split(lk, len(names))))
        h = _constrain(ctx, h, ("batch", "seq", "embed"))
        lrel_q, lrel_k = None, None
        if cfg.relative_attention:
            if cfg.share_att_key:
                lrel_k = _heads(rel_emb, lp["attn"]["k_kernel"], lp["attn"]["k_bias"])
                lrel_q = _heads(rel_emb, lp["attn"]["q_kernel"], lp["attn"]["q_bias"])
            else:
                if "c2p" in cfg.pos_att_type:
                    lrel_k = _heads(rel_emb, lp["attn"]["pos_k_kernel"], lp["attn"]["pos_k_bias"])
                if "p2c" in cfg.pos_att_type:
                    lrel_q = _heads(rel_emb, lp["attn"]["pos_q_kernel"], lp["attn"]["pos_q_bias"])
        y = _disentangled(
            lp["attn"], h, lrel_q, lrel_k, rel_idx, pad_bias,
            cfg, keys.get("attn"), train,
        )
        y = dropout(keys.get("post_attn"), y, cfg.hidden_dropout_prob, train)
        h = layer_norm(h + y, lp["ln_attn"]["scale"], lp["ln_attn"]["bias"], cfg.layer_norm_eps)
        mp_ = lp["mlp"]
        y = jax.nn.gelu(
            h @ mp_["fc_in_kernel"].astype(h.dtype) + mp_["fc_in_bias"].astype(h.dtype),
            approximate=True,
        )
        y = y @ mp_["fc_out_kernel"].astype(h.dtype) + mp_["fc_out_bias"].astype(h.dtype)
        y = dropout(keys.get("post_ffn"), y, cfg.hidden_dropout_prob, train)
        h = layer_norm(h + y, lp["ln_mlp"]["scale"], lp["ln_mlp"]["bias"], cfg.layer_norm_eps)
        return (h, idx + 1), None

    if cfg.conv_kernel_size > 0:
        # run first layer alone to mix in the conv branch (reference :497-507)
        first = jax.tree.map(lambda a: a[0], params["layers"])
        (x1, _), _ = jax.lax.scan(block, (x, jnp.int32(0)), jax.tree.map(lambda a: a[None], first), length=1)
        x1 = _conv_branch(params["conv"], x, x1, attention_mask, cfg, k_conv, train)
        rest = jax.tree.map(lambda a: a[1:], params["layers"])
        (x, _), _ = jax.lax.scan(block, (x1, jnp.int32(1)), rest, length=cfg.num_layers - 1)
    else:
        (x, _), _ = jax.lax.scan(block, (x, jnp.int32(0)), params["layers"], length=cfg.num_layers)
    return x


def _disentangled(p, h, rel_q, rel_k, rel_idx, pad_bias, cfg, key, train):
    """Core scores (separated from the projection-sharing logic above).

    The reference's take_along_axis gathers are kept as gathers (same
    O(b·h·s·s) cost as the content score) rather than one-hot matmuls,
    which would cost 2·span/head_dim times the content matmul and hold
    [s, s, 2·span] tables live in HBM."""
    b, s, _ = h.shape
    nh, hd = cfg.num_attention_heads, cfg.head_dim
    q = _heads(h, p["q_kernel"], p["q_bias"])
    k = _heads(h, p["k_kernel"], p["k_bias"])
    v = _heads(h, p["v_kernel"], p["v_bias"])

    n_pos = (
        ("c2p" in cfg.pos_att_type) + ("p2c" in cfg.pos_att_type)
        if cfg.relative_attention
        else 0
    )
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd * (1 + n_pos), jnp.float32))

    score = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    if cfg.relative_attention and "c2p" in cfg.pos_att_type and rel_k is not None:
        cp = jnp.einsum("bqhd,phd->bhqp", q, rel_k, preferred_element_type=jnp.float32)
        # score(q,k) += q_q . pos_k[bucket(q-k)]
        score = score + jnp.take_along_axis(cp, rel_idx[None, None, :, :], axis=-1)
    if cfg.relative_attention and "p2c" in cfg.pos_att_type and rel_q is not None:
        pc = jnp.einsum("bkhd,phd->bhkp", k, rel_q, preferred_element_type=jnp.float32)
        # score(q,k) += k_k . pos_q[bucket(q-k)]: gather at [k, q] then swap
        pcg = jnp.take_along_axis(pc, rel_idx.T[None, None, :, :], axis=-1)
        score = score + jnp.swapaxes(pcg, -1, -2)
    score = score * scale
    if pad_bias is not None:
        score = score + pad_bias
    probs = jax.nn.softmax(score, axis=-1)
    if train and cfg.attention_probs_dropout_prob > 0.0 and key is not None:
        keep = 1.0 - cfg.attention_probs_dropout_prob
        probs = probs * jax.random.bernoulli(key, keep, probs.shape) / keep
    probs = probs.astype(h.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return (
        jnp.einsum(
            "bqhd,hdm->bqm", out, p["out_kernel"].reshape(nh, hd, -1).astype(out.dtype)
        )
        + p["out_bias"].astype(out.dtype)
    )


# ---------------------------------------------------------------------------
# Heads / losses
# ---------------------------------------------------------------------------


def mlm_logits(params: Dict[str, Any], hidden: jax.Array, cfg: DebertaV2Config) -> jax.Array:
    hp = params["mlm_head"]
    h = jax.nn.gelu(hidden @ hp["transform_kernel"] + hp["transform_bias"], approximate=True)
    h = layer_norm(h, hp["ln_scale"], hp["ln_bias"], cfg.layer_norm_eps)
    emb = params["embeddings"]["word"].astype(h.dtype)
    return jnp.einsum("bsh,vh->bsv", h, emb) + hp["decoder_bias"]


def mlm_loss(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: DebertaV2Config,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = True,
) -> jax.Array:
    """Masked-token CE (labels == -1 ignored)."""
    hidden = encode(
        params, batch["input_ids"], cfg,
        attention_mask=batch.get("attention_mask"),
        ctx=ctx, dropout_key=dropout_key, train=train,
    )
    logits = mlm_logits(params, hidden, cfg).astype(jnp.float32)
    labels = batch["labels"]
    mask = labels >= 0
    safe = jnp.where(mask, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return jnp.where(mask, nll, 0.0).sum() / jnp.maximum(mask.sum(), 1)


def cls_forward(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: DebertaV2Config,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """ContextPooler (CLS token -> dense+tanh) -> classifier logits."""
    k1 = k2 = None
    if dropout_key is not None:
        k1, k2 = jax.random.split(dropout_key)
    hidden = encode(
        params, batch["input_ids"], cfg,
        attention_mask=batch.get("attention_mask"),
        ctx=ctx, dropout_key=k1, train=train,
    )
    hp = params["cls_head"]
    pooled = jnp.tanh(hidden[:, 0] @ hp["pooler_kernel"] + hp["pooler_bias"])
    pooled = dropout(k2, pooled, cfg.hidden_dropout_prob, train)
    return pooled @ hp["cls_kernel"] + hp["cls_bias"]


def cls_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
