"""DebertaV2 encoder (disentangled attention) family."""

from paddlefleetx_tpu.models.debertav2.config import DebertaV2Config  # noqa: F401
