"""DebertaV2 configuration (reference DebertaV2Encoder /
DisentangledSelfAttention kwargs, ppfleetx/models/language_model/debertav2/
modeling.py:428-508, 688-745)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple


@dataclasses.dataclass(frozen=True)
class DebertaV2Config:
    vocab_size: int = 128100
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-7
    # disentangled attention
    relative_attention: bool = True
    position_buckets: int = 256
    max_relative_positions: int = -1  # -1 -> max_position_embeddings
    pos_att_type: Tuple[str, ...] = ("p2c", "c2p")
    share_att_key: bool = True
    # absolute positions added to the input embedding (off for v2-xxlarge)
    position_biased_input: bool = False
    # optional token conv branch on the first layer output (ConvLayer :381)
    conv_kernel_size: int = 0
    pad_token_id: int = 0
    num_classes: int = 2
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_attention_heads == 0
        return self.hidden_size // self.num_attention_heads

    @property
    def pos_ebd_size(self) -> int:
        if self.position_buckets > 0:
            return self.position_buckets
        m = self.max_relative_positions
        return m if m > 0 else self.max_position_embeddings

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "DebertaV2Config":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        if isinstance(kw.get("pos_att_type"), (list, tuple)):
            kw["pos_att_type"] = tuple(kw["pos_att_type"])
        return cls(**kw)
