"""Model families + BasicModule adapters (reference ppfleetx/models)."""
