"""T5 encoder-decoder LM — pure-JAX functional, sharded by annotation.

TPU-native re-design of the reference T5 family
(ppfleetx/models/language_model/t5/modeling.py: T5LayerNorm :473,
T5DenseActDense :504, T5DenseGatedActDense :520, T5Attention :559,
T5Stack / T5Model / T5ForConditionalGeneration below it): one functional
definition, parallelism via logical-axis annotations (same TP layout as
GPT: heads/ffn/vocab sharded on the ``model`` mesh axis).

Architecture notes (faithful to the reference semantics):
  - RMS LayerNorm without bias or mean-subtraction, fp32 variance
    (T5LayerNorm :473-490).
  - Attention is UNSCALED (1/sqrt(d) folded into initializer — Mesh-TF
    convention the reference inherits); q/k/v/o initialized with the
    factor-scaled normals of T5Config.initializer_factor.
  - Relative position bias: one (num_buckets, num_heads) embedding per
    stack, computed once and shared by every layer (the reference stores
    it in block 0 and passes it down — same sharing, scan-friendly form).
  - FFN: gated-gelu (wi_0 * gelu, T5 v1.1, reference is_gated_act default
    True :451) or plain relu dense.
  - Logits: tied word embedding with d_model**-0.5 rescale when
    tie_word_embeddings (T5ForConditionalGeneration convention).

Layers are stacked on a leading ``layers`` axis and run with ``lax.scan``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    dropout,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    stack_spec_tree,
)
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, _constrain
from paddlefleetx_tpu.models.t5.config import T5Config
from paddlefleetx_tpu.ops.attention import attention

NEG_INF = -1e9


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    """T5LayerNorm: no mean subtraction, no bias, fp32 variance."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    # cast the (fp32-stored) scale to the compute dtype: multiplying after
    # the down-cast would silently promote the whole layer back to fp32
    return (xf * jax.lax.rsqrt(var + eps)).astype(dtype) * scale.astype(dtype)


# ---------------------------------------------------------------------------
# Relative position buckets (T5Attention._relative_position_bucket)
# ---------------------------------------------------------------------------


def relative_position_bucket(
    relative_position: jax.Array,
    *,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """Map signed relative positions to bucket ids (int32).

    Half the buckets are exact small offsets, the other half log-spaced up
    to max_distance; bidirectional splits the space for +/- directions.
    """
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + jnp.where(n < 0, num_buckets, 0)
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def compute_position_bias(
    rel_emb: jax.Array, q_len: int, k_len: int, *, bidirectional: bool, cfg: T5Config
) -> jax.Array:
    """[1, heads, q_len, k_len] additive bias from a (buckets, heads) table."""
    ctx_pos = jnp.arange(q_len)[:, None]
    mem_pos = jnp.arange(k_len)[None, :]
    buckets = relative_position_bucket(
        mem_pos - ctx_pos,
        bidirectional=bidirectional,
        num_buckets=cfg.relative_attention_num_buckets,
        max_distance=cfg.relative_attention_max_distance,
    )
    bias = rel_emb[buckets]  # [q, k, heads]
    return jnp.transpose(bias, (2, 0, 1))[None]


# ---------------------------------------------------------------------------
# Parameter specs
# ---------------------------------------------------------------------------


def _attn_specs(cfg: T5Config) -> Dict[str, ParamSpec]:
    d, nh, dkv = cfg.d_model, cfg.num_heads, cfg.d_kv
    f = cfg.initializer_factor
    return {
        "q_kernel": ParamSpec((d, nh, dkv), ("embed", "heads", "kv"), normal_init(f * (d * dkv) ** -0.5)),
        "k_kernel": ParamSpec((d, nh, dkv), ("embed", "heads", "kv"), normal_init(f * d ** -0.5)),
        "v_kernel": ParamSpec((d, nh, dkv), ("embed", "heads", "kv"), normal_init(f * d ** -0.5)),
        "o_kernel": ParamSpec((nh, dkv, d), ("heads", "kv", "embed"), normal_init(f * (nh * dkv) ** -0.5)),
    }


def _ffn_specs(cfg: T5Config) -> Dict[str, ParamSpec]:
    d, dff, f = cfg.d_model, cfg.d_ff, cfg.initializer_factor
    wi = normal_init(f * d ** -0.5)
    wo = normal_init(f * dff ** -0.5)
    specs = {
        "wi_kernel": ParamSpec((d, dff), ("embed", "mlp"), wi),
        "wo_kernel": ParamSpec((dff, d), ("mlp", "embed"), wo),
    }
    if cfg.is_gated_act:
        specs["wi_gate_kernel"] = ParamSpec((d, dff), ("embed", "mlp"), wi)
    return specs


def _enc_layer_specs(cfg: T5Config) -> Dict[str, Any]:
    ln = lambda: ParamSpec((cfg.d_model,), ("embed",), ones_init())
    return {
        "ln_attn": {"scale": ln()},
        "attn": _attn_specs(cfg),
        "ln_ffn": {"scale": ln()},
        "ffn": _ffn_specs(cfg),
    }


def _dec_layer_specs(cfg: T5Config) -> Dict[str, Any]:
    ln = lambda: ParamSpec((cfg.d_model,), ("embed",), ones_init())
    return {
        "ln_self": {"scale": ln()},
        "self_attn": _attn_specs(cfg),
        "ln_cross": {"scale": ln()},
        "cross_attn": _attn_specs(cfg),
        "ln_ffn": {"scale": ln()},
        "ffn": _ffn_specs(cfg),
    }


def t5_specs(cfg: T5Config) -> Dict[str, Any]:
    d, f = cfg.d_model, cfg.initializer_factor
    rel = lambda: ParamSpec(
        (cfg.relative_attention_num_buckets, cfg.num_heads),
        ("table", "heads"),
        normal_init(f * d ** -0.5),
    )
    specs: Dict[str, Any] = {
        "shared_embedding": ParamSpec((cfg.vocab_size, d), ("vocab", "embed"), normal_init(f * 1.0)),
        "encoder": {
            "rel_bias": rel(),
            "layers": stack_spec_tree(_enc_layer_specs(cfg), cfg.num_layers),
            "final_ln": {"scale": ParamSpec((d,), ("embed",), ones_init())},
        },
        "decoder": {
            "rel_bias": rel(),
            "layers": stack_spec_tree(_dec_layer_specs(cfg), cfg.num_decoder_layers),
            "final_ln": {"scale": ParamSpec((d,), ("embed",), ones_init())},
        },
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = ParamSpec((d, cfg.vocab_size), ("embed", "vocab"), normal_init(f * d ** -0.5))
    return specs


def init(cfg: T5Config, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, t5_specs(cfg))


def t5_logical_axes(cfg: T5Config) -> Dict[str, Any]:
    return logical_axes(t5_specs(cfg))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _proj(x: jax.Array, kernel: jax.Array) -> jax.Array:
    """[b,s,d] @ [d,h,k] -> [b,s,h,k] (fp32-stored kernel cast to the
    activation dtype so bf16 forwards stay bf16)."""
    return jnp.einsum("bsd,dhk->bshk", x, kernel.astype(x.dtype))


def _attn(
    p: Dict[str, jax.Array],
    x_q: jax.Array,
    x_kv: jax.Array,
    bias: Optional[jax.Array],
    cfg: T5Config,
    key: Optional[jax.Array],
    train: bool,
) -> jax.Array:
    q = _proj(x_q, p["q_kernel"])
    k = _proj(x_kv, p["k_kernel"])
    v = _proj(x_kv, p["v_kernel"])
    out = attention(
        q, k, v,
        impl="xla",  # T5 attention always carries a bias -> XLA path only
        causal=False,
        bias=bias,
        dropout_key=key,
        dropout_rate=cfg.dropout_rate,
        train=train,
        scale=1.0,
    )
    return jnp.einsum("bshk,hkd->bsd", out, p["o_kernel"].astype(out.dtype))


def _ffn(p: Dict[str, jax.Array], x: jax.Array, cfg: T5Config, key, train) -> jax.Array:
    dt = x.dtype
    if cfg.is_gated_act:
        h = jax.nn.gelu(x @ p["wi_gate_kernel"].astype(dt), approximate=True) * (
            x @ p["wi_kernel"].astype(dt)
        )
    else:
        h = jax.nn.relu(x @ p["wi_kernel"].astype(dt))
    h = dropout(key, h, cfg.dropout_rate, train)
    return h @ p["wo_kernel"].astype(dt)


def _pad_bias(mask: jax.Array, dtype) -> jax.Array:
    """[b, k] 1/0 keep-mask -> additive [b, 1, 1, k]."""
    return jnp.where(mask[:, None, None, :].astype(jnp.bool_), 0.0, NEG_INF).astype(dtype)


def _run_stack(
    layers_params: Any,
    x: jax.Array,
    cfg: T5Config,
    *,
    self_bias: jax.Array,
    enc_out: Optional[jax.Array],
    cross_bias: Optional[jax.Array],
    dropout_key: Optional[jax.Array],
    train: bool,
    ctx: Optional[ShardingCtx],
    decoder: bool,
) -> jax.Array:
    n_layers = cfg.num_decoder_layers if decoder else cfg.num_layers

    def block(carry, xs):
        h, idx = carry
        lp = xs
        keys = {}
        if dropout_key is not None and train:
            lk = jax.random.fold_in(dropout_key, idx)
            names = ("attn", "res1", "cross", "res_c", "ffn_in", "res2")
            keys = dict(zip(names, jax.random.split(lk, len(names))))
        h = _constrain(ctx, h, ("batch", "seq", "embed"))
        if decoder:
            xn = rms_norm(h, lp["ln_self"]["scale"], cfg.layer_norm_epsilon)
            y = _attn(lp["self_attn"], xn, xn, self_bias, cfg, keys.get("attn"), train)
            h = h + dropout(keys.get("res1"), y, cfg.dropout_rate, train)
            y = _attn(lp["cross_attn"], rms_norm(h, lp["ln_cross"]["scale"], cfg.layer_norm_epsilon),
                      enc_out, cross_bias, cfg, keys.get("cross"), train)
            h = h + dropout(keys.get("res_c"), y, cfg.dropout_rate, train)
        else:
            xn = rms_norm(h, lp["ln_attn"]["scale"], cfg.layer_norm_epsilon)
            y = _attn(lp["attn"], xn, xn, self_bias, cfg, keys.get("attn"), train)
            h = h + dropout(keys.get("res1"), y, cfg.dropout_rate, train)
        y = _ffn(lp["ffn"], rms_norm(h, lp["ln_ffn"]["scale"], cfg.layer_norm_epsilon),
                 cfg, keys.get("ffn_in"), train)
        h = h + dropout(keys.get("res2"), y, cfg.dropout_rate, train)
        return (h, idx + 1), None

    fn = block
    if cfg.use_recompute:
        fn = jax.checkpoint(block, prevent_cse=False)
    (x, _), _ = jax.lax.scan(fn, (x, jnp.int32(0)), layers_params, length=n_layers)
    return x


def encode(
    params: Dict[str, Any],
    input_ids: jax.Array,
    cfg: T5Config,
    *,
    attention_mask: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    dtype = jnp.dtype(cfg.dtype)
    if attention_mask is None:
        attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
    x = params["shared_embedding"][input_ids].astype(dtype)
    k1 = k2 = k3 = None
    if dropout_key is not None:
        k1, k2, k3 = jax.random.split(dropout_key, 3)
    x = dropout(k1, x, cfg.dropout_rate, train)
    s = input_ids.shape[1]
    bias = compute_position_bias(
        params["encoder"]["rel_bias"].astype(jnp.float32), s, s, bidirectional=True, cfg=cfg
    ) + _pad_bias(attention_mask, jnp.float32)
    x = _run_stack(
        params["encoder"]["layers"], x, cfg,
        self_bias=bias, enc_out=None, cross_bias=None,
        dropout_key=k2, train=train, ctx=ctx, decoder=False,
    )
    x = rms_norm(x, params["encoder"]["final_ln"]["scale"], cfg.layer_norm_epsilon)
    return dropout(k3, x, cfg.dropout_rate, train)


def decode(
    params: Dict[str, Any],
    decoder_input_ids: jax.Array,
    enc_out: jax.Array,
    enc_mask: jax.Array,
    cfg: T5Config,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """Returns decoder hidden states [b, s, d]."""
    dtype = jnp.dtype(cfg.dtype)
    x = params["shared_embedding"][decoder_input_ids].astype(dtype)
    k1 = k2 = k3 = None
    if dropout_key is not None:
        k1, k2, k3 = jax.random.split(dropout_key, 3)
    x = dropout(k1, x, cfg.dropout_rate, train)
    s = decoder_input_ids.shape[1]
    causal = jnp.where(
        jnp.tril(jnp.ones((s, s), jnp.bool_))[None, None], 0.0, NEG_INF
    ).astype(jnp.float32)
    self_bias = compute_position_bias(
        params["decoder"]["rel_bias"].astype(jnp.float32), s, s, bidirectional=False, cfg=cfg
    ) + causal
    cross_bias = _pad_bias(enc_mask, jnp.float32)
    x = _run_stack(
        params["decoder"]["layers"], x, cfg,
        self_bias=self_bias, enc_out=enc_out, cross_bias=cross_bias,
        dropout_key=k2, train=train, ctx=ctx, decoder=True,
    )
    x = rms_norm(x, params["decoder"]["final_ln"]["scale"], cfg.layer_norm_epsilon)
    return dropout(k3, x, cfg.dropout_rate, train)


def logits_from_hidden(params: Dict[str, Any], hidden: jax.Array, cfg: T5Config) -> jax.Array:
    if cfg.tie_word_embeddings:
        # Mesh-TF rescale before the tied projection
        hidden = hidden * (cfg.d_model ** -0.5)
        return jnp.einsum("bsd,vd->bsv", hidden, params["shared_embedding"].astype(hidden.dtype))
    return hidden @ params["lm_head"].astype(hidden.dtype)


def shift_right(labels: jax.Array, cfg: T5Config) -> jax.Array:
    """Teacher-forcing decoder inputs: prepend decoder_start, drop last."""
    start = jnp.full((labels.shape[0], 1), cfg.decoder_start_token_id, labels.dtype)
    shifted = jnp.concatenate([start, labels[:, :-1]], axis=1)
    # labels may use -100 as ignore — feed pad instead
    return jnp.where(shifted < 0, cfg.pad_token_id, shifted)


def forward(
    params: Dict[str, Any],
    input_ids: jax.Array,
    decoder_input_ids: jax.Array,
    cfg: T5Config,
    *,
    attention_mask: Optional[jax.Array] = None,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """Full seq2seq forward -> logits [b, s_dec, vocab]."""
    if attention_mask is None:
        attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
    ke = kd = None
    if dropout_key is not None:
        ke, kd = jax.random.split(dropout_key)
    enc = encode(params, input_ids, cfg, attention_mask=attention_mask,
                 ctx=ctx, dropout_key=ke, train=train)
    hid = decode(params, decoder_input_ids, enc, attention_mask, cfg,
                 ctx=ctx, dropout_key=kd, train=train)
    return logits_from_hidden(params, hid, cfg)


def seq2seq_loss(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: T5Config,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = True,
) -> jax.Array:
    """Token CE over labels (ignore -100 / pad positions).

    batch: input_ids [b,s_enc], labels [b,s_dec], optional attention_mask,
    optional decoder_input_ids (defaults to shift_right(labels))."""
    labels = batch["labels"]
    dec_in = batch.get("decoder_input_ids")
    if dec_in is None:
        dec_in = shift_right(labels, cfg)
    mask = jnp.logical_and(labels != cfg.pad_token_id, labels >= 0)
    safe = jnp.where(mask, labels, 0)

    vocab_sharded = False
    if ctx is not None:
        from paddlefleetx_tpu.parallel.mesh import AXIS_MODEL

        vocab_sharded = ctx.mesh.shape.get(AXIS_MODEL, 1) > 1
    if cfg.use_chunked_ce and not vocab_sharded:
        from paddlefleetx_tpu.ops.chunked_ce import chunked_cross_entropy

        attention_mask = batch.get("attention_mask")
        if attention_mask is None:
            attention_mask = (batch["input_ids"] != cfg.pad_token_id).astype(jnp.int32)
        ke = kd = None
        if dropout_key is not None:
            ke, kd = jax.random.split(dropout_key)
        enc = encode(params, batch["input_ids"], cfg, attention_mask=attention_mask,
                     ctx=ctx, dropout_key=ke, train=train)
        hid = decode(params, dec_in, enc, attention_mask, cfg,
                     ctx=ctx, dropout_key=kd, train=train)
        if cfg.tie_word_embeddings:
            hid = hid * (cfg.d_model ** -0.5)
            word = params["shared_embedding"]
        else:
            word = params["lm_head"].T
        return chunked_cross_entropy(
            hid, word, safe, mask.astype(jnp.float32), chunk=cfg.ce_chunk_size
        )

    logits = forward(
        params, batch["input_ids"], dec_in, cfg,
        attention_mask=batch.get("attention_mask"),
        ctx=ctx, dropout_key=dropout_key, train=train,
    ).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1)
    return jnp.where(mask, nll, 0.0).sum() / denom
