from paddlefleetx_tpu.models.t5.config import T5Config  # noqa: F401
