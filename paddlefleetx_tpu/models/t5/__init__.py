"""T5 encoder-decoder family (span-corruption pretrain)."""

from paddlefleetx_tpu.models.t5.config import T5Config  # noqa: F401
