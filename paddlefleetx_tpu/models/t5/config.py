"""T5 model configuration (reference T5Config kwargs,
ppfleetx/models/language_model/t5/modeling.py:434-471)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 768
    d_kv: int = 64  # per-head dim (NOT required to equal d_model/num_heads)
    d_ff: int = 2048
    num_layers: int = 12
    num_decoder_layers: int = 12
    num_heads: int = 12
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_epsilon: float = 1e-6
    initializer_factor: float = 1.0
    # "gated-gelu" (T5 v1.1, reference default is_gated_act=True) or "relu"
    feed_forward_proj: str = "gated-gelu"
    tie_word_embeddings: bool = True
    pad_token_id: int = 0
    eos_token_id: int = 1
    decoder_start_token_id: int = 0
    dtype: str = "bfloat16"
    use_recompute: bool = False
    # chunked softmax-CE (ops/chunked_ce.py): the [b,s_dec,V] fp32 logits
    # buffer never materializes; ignored under vocab (model-axis) sharding
    use_chunked_ce: bool = False
    ce_chunk_size: int = 4096

    @property
    def is_gated_act(self) -> bool:
        return "gated" in self.feed_forward_proj

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "T5Config":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})
