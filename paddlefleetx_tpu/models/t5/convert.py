"""HF T5 checkpoint -> native param tree (same role as gpt/convert.py).

Mapping notes:
- torch ``nn.Linear`` weights are [out, in] — every kernel transposes.
- q/k/v: [nh*d_kv, d] -> T -> [d, nh, d_kv]; o: [d, nh*d_kv] -> T ->
  [nh, d_kv, d].
- relative_attention_bias lives only in block 0 per stack (shared across
  layers), matching the single ``rel_bias`` [num_buckets, nh] here.
- T5 attention is unscaled (folded into init) in both implementations.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from paddlefleetx_tpu.models.t5.model import T5Config


def hf_t5_config(hf_cfg, **overrides) -> T5Config:
    proj = getattr(hf_cfg, "feed_forward_proj", "relu")
    if proj not in ("relu", "gated-gelu"):
        raise ValueError(f"unsupported feed_forward_proj {proj!r}")
    if abs(float(hf_cfg.layer_norm_epsilon) - 1e-6) > 1e-15:
        raise ValueError(
            f"unsupported layer_norm_epsilon {hf_cfg.layer_norm_epsilon} (need 1e-6)"
        )
    kw = dict(
        vocab_size=int(hf_cfg.vocab_size),
        d_model=int(hf_cfg.d_model),
        d_kv=int(hf_cfg.d_kv),
        d_ff=int(hf_cfg.d_ff),
        num_layers=int(hf_cfg.num_layers),
        num_decoder_layers=int(hf_cfg.num_decoder_layers),
        num_heads=int(hf_cfg.num_heads),
        relative_attention_num_buckets=int(hf_cfg.relative_attention_num_buckets),
        relative_attention_max_distance=int(
            getattr(hf_cfg, "relative_attention_max_distance", 128)
        ),
        feed_forward_proj=proj,
        tie_word_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", True)),
        pad_token_id=int(hf_cfg.pad_token_id),
        eos_token_id=int(hf_cfg.eos_token_id),
        decoder_start_token_id=int(getattr(hf_cfg, "decoder_start_token_id", 0)),
    )
    kw.update(overrides)
    return T5Config(**kw)


def convert_hf_t5_state_dict(sd: Dict, cfg: T5Config) -> Dict:
    """torch/HF ``T5ForConditionalGeneration.state_dict()`` -> param tree."""

    from paddlefleetx_tpu.models.convert_common import make_getter

    get = make_getter(sd)

    d, nh, kv = cfg.d_model, cfg.num_heads, cfg.d_kv

    def attn(prefix: str) -> Dict[str, np.ndarray]:
        return {
            "q_kernel": get(prefix + ".q.weight").T.reshape(d, nh, kv),
            "k_kernel": get(prefix + ".k.weight").T.reshape(d, nh, kv),
            "v_kernel": get(prefix + ".v.weight").T.reshape(d, nh, kv),
            "o_kernel": get(prefix + ".o.weight").T.reshape(nh, kv, d),
        }

    def ffn(prefix: str) -> Dict[str, np.ndarray]:
        out = {"wo_kernel": get(prefix + ".wo.weight").T}
        if cfg.is_gated_act:
            out["wi_gate_kernel"] = get(prefix + ".wi_0.weight").T
            out["wi_kernel"] = get(prefix + ".wi_1.weight").T
        else:
            out["wi_kernel"] = get(prefix + ".wi.weight").T
        return out

    enc_layers = []
    for i in range(cfg.num_layers):
        b = f"encoder.block.{i}"
        enc_layers.append(
            {
                "attn": attn(f"{b}.layer.0.SelfAttention"),
                "ln_attn": {"scale": get(f"{b}.layer.0.layer_norm.weight")},
                "ffn": ffn(f"{b}.layer.1.DenseReluDense"),
                "ln_ffn": {"scale": get(f"{b}.layer.1.layer_norm.weight")},
            }
        )
    dec_layers = []
    for i in range(cfg.num_decoder_layers):
        b = f"decoder.block.{i}"
        dec_layers.append(
            {
                "self_attn": attn(f"{b}.layer.0.SelfAttention"),
                "ln_self": {"scale": get(f"{b}.layer.0.layer_norm.weight")},
                "cross_attn": attn(f"{b}.layer.1.EncDecAttention"),
                "ln_cross": {"scale": get(f"{b}.layer.1.layer_norm.weight")},
                "ffn": ffn(f"{b}.layer.2.DenseReluDense"),
                "ln_ffn": {"scale": get(f"{b}.layer.2.layer_norm.weight")},
            }
        )

    def nested_stack(layers):
        out = {}
        for group, val in layers[0].items():
            out[group] = {
                k: np.stack([l[group][k] for l in layers]) for k in val
            }
        return out

    params = {
        "shared_embedding": get("shared.weight"),
        "encoder": {
            "layers": nested_stack(enc_layers),
            "rel_bias": get(
                "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
            ),
            "final_ln": {"scale": get("encoder.final_layer_norm.weight")},
        },
        "decoder": {
            "layers": nested_stack(dec_layers),
            "rel_bias": get(
                "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
            ),
            "final_ln": {"scale": get("decoder.final_layer_norm.weight")},
        },
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight").T
    return params
