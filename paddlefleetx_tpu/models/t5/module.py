"""T5 engine module (seq2seq LM training/finetune).

The reference exposes T5 purely as a model library (modeling.py) consumed
by custom loops; here it plugs into the Engine like every other family."""

from __future__ import annotations

from paddlefleetx_tpu.core.module import BasicModule, resolve_model_dtype
from paddlefleetx_tpu.models.t5 import model as t5
from paddlefleetx_tpu.models.t5.config import T5Config
from paddlefleetx_tpu.utils.registry import MODULES


def _config_from(cfg) -> T5Config:
    model_cfg = dict(cfg.Model)
    model_cfg.pop("module", None)
    model_cfg.pop("name", None)
    resolve_model_dtype(cfg, model_cfg)
    return T5Config.from_config(model_cfg)


@MODULES.register("T5Module")
class T5Module(BasicModule):
    """Seq2seq (span-corruption pretrain or text-to-text finetune)."""

    def __init__(self, cfg):
        self.config = _config_from(cfg)
        data_cfg = cfg.get("Data", {}).get("Train", {}).get("dataset", {})
        self._enc_len = int(data_cfg.get("max_seq_len", 512))
        self._dec_len = int(data_cfg.get("max_target_len", 0)) or 128
        self.tokens_per_sample = self._enc_len + int(data_cfg.get("max_target_len", 0))

    def init_params(self, key):
        return t5.init(self.config, key)

    def logical_axes(self):
        return t5.t5_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        return t5.seq2seq_loss(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )

    def export_spec(self):
        import jax.numpy as jnp

        cfg = self.config

        def fwd(params, input_ids, decoder_input_ids):
            return t5.forward(params, input_ids, decoder_input_ids, cfg, train=False)

        return fwd, (
            jnp.zeros((1, self._enc_len), jnp.int32),
            jnp.zeros((1, self._dec_len), jnp.int32),
        )
