"""Evoformer — MSA/pair trunk of HelixFold, pure-JAX functional.

TPU-native re-design of the reference protein-folding trunk
(ppfleetx/models/protein_folding/: attentions.py Attention :35,
GlobalAttention :167, MSARowAttentionWithPairBias :272,
MSAColumnGlobalAttention :360, MSAColumnAttention :418, TriangleAttention
:473, TriangleMultiplication :555; outer_product_mean.py :70-150;
evoformer.py EvoformerIteration :43 — Jumper et al. 2021 Suppl. Alg. 6).

**DAP (dynamic axial parallelism) the TPU way.**  The reference threads
explicit collectives through every block (dap.scatter/all_gather/
all_to_all, row_to_col/col_to_row — distributed/protein_folding/dap.py:
75-398) to keep the MSA sharded along rows during row attention and along
residues during column attention.  Here the SAME data movement is
expressed as logical sharding constraints over the ``sep`` mesh axis:

    row attention / msa transition:  msa [batch, rows*, residues, c]
    column attention:                msa [batch, rows, residues*, c]
    pair row ops (tri-start):        pair [batch, i*, j, c]
    pair col ops (tri-end):          pair [batch, i, j*, c]

(* = sep-sharded).  Flipping the starred axis between blocks IS the
reference's row_to_col/col_to_row all-to-all; XLA inserts it.  BP (branch
parallel, bp.py:25-152) dissolves under SPMD: the outer-product and
triangle branches are data-independent subgraphs that XLA already
schedules concurrently; their grad allreduce is implied by psum.

AlphaFold conventions kept: gated attention (sigmoid gate, bias init 1),
zero-init output projections (identity residuals at init), fp32 softmax/
layernorm.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    stack_spec_tree,
    zeros_init,
)
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, _constrain, layer_norm


@dataclasses.dataclass(frozen=True)
class EvoformerConfig:
    msa_channel: int = 256
    pair_channel: int = 128
    num_layers: int = 48
    msa_heads: int = 8
    pair_heads: int = 4
    transition_factor: int = 4
    outer_channel: int = 32
    gating: bool = True
    is_extra_msa: bool = False  # extra-MSA stack uses global column attention
    dropout_rate: float = 0.15  # row-wise dropout on msa/pair updates
    dtype: str = "float32"
    use_recompute: bool = False

    @property
    def msa_head_dim(self) -> int:
        return self.msa_channel // self.msa_heads

    @property
    def pair_head_dim(self) -> int:
        return self.pair_channel // self.pair_heads

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "EvoformerConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


# ---------------------------------------------------------------------------
# Specs
# ---------------------------------------------------------------------------

_W = normal_init(0.02)


def _ln(c):
    return {"scale": ParamSpec((c,), ("embed",), ones_init()),
            "bias": ParamSpec((c,), ("embed",), zeros_init())}


def _attn_specs(c_in, c_bias, heads, head_dim, gating):
    """Gated attention (reference Attention attentions.py:35-166)."""
    specs = {
        "q": ParamSpec((c_in, heads, head_dim), ("embed", "heads", "kv"), _W),
        "k": ParamSpec((c_bias, heads, head_dim), ("embed", "heads", "kv"), _W),
        "v": ParamSpec((c_bias, heads, head_dim), ("embed", "heads", "kv"), _W),
        # zero-init output: the residual starts as identity
        "out": ParamSpec((heads, head_dim, c_in), ("heads", "kv", "embed"), zeros_init()),
        "out_b": ParamSpec((c_in,), ("embed",), zeros_init()),
    }
    if gating:
        specs["gate"] = ParamSpec((c_in, heads, head_dim), ("embed", "heads", "kv"), zeros_init())
        specs["gate_b"] = ParamSpec((heads, head_dim), ("heads", "kv"), ones_init())
    return specs


def _transition_specs(c, factor):
    return {
        "ln": _ln(c),
        "fc1": ParamSpec((c, c * factor), ("embed", "mlp"), _W),
        "fc1_b": ParamSpec((c * factor,), ("mlp",), zeros_init()),
        "fc2": ParamSpec((c * factor, c), ("mlp", "embed"), zeros_init()),
        "fc2_b": ParamSpec((c,), ("embed",), zeros_init()),
    }


def _tri_mult_specs(c):
    """(reference TriangleMultiplication attentions.py:555-729)."""
    return {
        "ln_in": _ln(c),
        "left": ParamSpec((c, c), ("embed", "mlp"), _W),
        "left_b": ParamSpec((c,), ("mlp",), zeros_init()),
        "right": ParamSpec((c, c), ("embed", "mlp"), _W),
        "right_b": ParamSpec((c,), ("mlp",), zeros_init()),
        "left_gate": ParamSpec((c, c), ("embed", "mlp"), zeros_init()),
        "left_gate_b": ParamSpec((c,), ("mlp",), ones_init()),
        "right_gate": ParamSpec((c, c), ("embed", "mlp"), zeros_init()),
        "right_gate_b": ParamSpec((c,), ("mlp",), ones_init()),
        "ln_out": _ln(c),
        "out": ParamSpec((c, c), ("mlp", "embed"), zeros_init()),
        "out_b": ParamSpec((c,), ("embed",), zeros_init()),
        "gate": ParamSpec((c, c), ("embed", "mlp"), zeros_init()),
        "gate_b": ParamSpec((c,), ("mlp",), ones_init()),
    }


def _layer_specs(cfg: EvoformerConfig) -> Dict[str, Any]:
    cm, cz = cfg.msa_channel, cfg.pair_channel
    return {
        "msa_row": {
            "ln_msa": _ln(cm),
            "ln_pair": _ln(cz),
            "pair_bias": ParamSpec((cz, cfg.msa_heads), ("embed", "heads"), _W),
            "attn": _attn_specs(cm, cm, cfg.msa_heads, cfg.msa_head_dim, cfg.gating),
        },
        "msa_col": {
            "ln": _ln(cm),
            "attn": _attn_specs(cm, cm, cfg.msa_heads, cfg.msa_head_dim, cfg.gating),
        },
        "msa_transition": _transition_specs(cm, cfg.transition_factor),
        "outer": {
            "ln": _ln(cm),
            "left": ParamSpec((cm, cfg.outer_channel), ("embed", "mlp"), _W),
            "left_b": ParamSpec((cfg.outer_channel,), ("mlp",), zeros_init()),
            "right": ParamSpec((cm, cfg.outer_channel), ("embed", "mlp"), _W),
            "right_b": ParamSpec((cfg.outer_channel,), ("mlp",), zeros_init()),
            "out": ParamSpec(
                (cfg.outer_channel, cfg.outer_channel, cz), (None, "mlp", "embed"), zeros_init()
            ),
            "out_b": ParamSpec((cz,), ("embed",), zeros_init()),
        },
        "tri_mult_out": _tri_mult_specs(cz),
        "tri_mult_in": _tri_mult_specs(cz),
        "tri_attn_start": {
            "ln": _ln(cz),
            "bias": ParamSpec((cz, cfg.pair_heads), ("embed", "heads"), _W),
            "attn": _attn_specs(cz, cz, cfg.pair_heads, cfg.pair_head_dim, cfg.gating),
        },
        "tri_attn_end": {
            "ln": _ln(cz),
            "bias": ParamSpec((cz, cfg.pair_heads), ("embed", "heads"), _W),
            "attn": _attn_specs(cz, cz, cfg.pair_heads, cfg.pair_head_dim, cfg.gating),
        },
        "pair_transition": _transition_specs(cz, cfg.transition_factor),
    }


def evoformer_specs(cfg: EvoformerConfig) -> Dict[str, Any]:
    return {"layers": stack_spec_tree(_layer_specs(cfg), cfg.num_layers)}


def init(cfg: EvoformerConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, evoformer_specs(cfg))


def evoformer_logical_axes(cfg: EvoformerConfig) -> Dict[str, Any]:
    return logical_axes(evoformer_specs(cfg))


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------


def _gated_attention(p, q_in, kv_in, bias, gating):
    """q_in/kv_in: [..., L, c]; bias: [..., heads, L_q, L_k] additive."""
    q = jnp.einsum("...qc,chd->...qhd", q_in, p["q"]) * (p["q"].shape[-1] ** -0.5)
    k = jnp.einsum("...kc,chd->...khd", kv_in, p["k"])
    v = jnp.einsum("...kc,chd->...khd", kv_in, p["v"])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k, preferred_element_type=jnp.float32)
    if bias is not None:
        logits = logits + bias
    probs = jax.nn.softmax(logits, axis=-1).astype(q_in.dtype)
    out = jnp.einsum("...hqk,...khd->...qhd", probs, v)
    if gating:
        gate = jax.nn.sigmoid(
            jnp.einsum("...qc,chd->...qhd", q_in, p["gate"]) + p["gate_b"]
        )
        out = out * gate
    return jnp.einsum("...qhd,hdc->...qc", out, p["out"]) + p["out_b"]


def _global_attention(p, x, mask, gating):
    """Global column attention for extra MSA (attentions.py:167-271):
    one mean-pooled query per column."""
    # x: [b, R, S, c] (residue-major here), mask [b, R, S, 1]
    q_avg = (x * mask).sum(axis=-2) / (mask.sum(axis=-2) + 1e-10)
    q = jnp.einsum("...c,chd->...hd", q_avg, p["q"]) * (p["q"].shape[-1] ** -0.5)
    k = jnp.einsum("...kc,chd->...khd", x, p["k"])
    v = jnp.einsum("...kc,chd->...khd", x, p["v"])
    logits = jnp.einsum("...hd,...khd->...hk", q, k, preferred_element_type=jnp.float32)
    logits = logits + (1.0 - mask[..., 0][..., None, :].astype(jnp.float32)) * -1e9
    probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
    out = jnp.einsum("...hk,...khd->...hd", probs, v)  # [b, R, h, d]
    if gating:
        gate = jax.nn.sigmoid(jnp.einsum("...qc,chd->...qhd", x, p["gate"]) + p["gate_b"])
        out = out[..., None, :, :] * gate  # broadcast per-position
        return jnp.einsum("...qhd,hdc->...qc", out, p["out"]) + p["out_b"]
    out = jnp.broadcast_to(out[..., None, :, :], x.shape[:-1] + p["q"].shape[-2:])
    return jnp.einsum("...qhd,hdc->...qc", out, p["out"]) + p["out_b"]


def _row_dropout(key, x, rate, train, axis):
    """Shared-over-axis dropout (reference dropout axis= semantics)."""
    if not train or rate == 0.0 or key is None:
        return x
    shape = list(x.shape)
    shape[axis] = 1
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def _transition(p, x):
    h = layer_norm(x, p["ln"]["scale"], p["ln"]["bias"])
    h = jax.nn.relu(h @ p["fc1"] + p["fc1_b"])
    return h @ p["fc2"] + p["fc2_b"]


def _outer_product_mean(p, msa, msa_mask):
    """msa [b, S, R, cm] -> pair update [b, R, R, cz]
    (reference outer_product_mean.py:70-150)."""
    act = layer_norm(msa, p["ln"]["scale"], p["ln"]["bias"])
    mask = msa_mask[..., None]  # [b, S, R, 1]
    left = (act @ p["left"] + p["left_b"]) * mask
    right = (act @ p["right"] + p["right_b"]) * mask
    outer = jnp.einsum("bsic,bsjd->bijcd", left, right)
    norm = jnp.einsum("bsi,bsj->bij", msa_mask, msa_mask)[..., None] + 1e-3
    outer = outer / norm[..., None]
    return jnp.einsum("bijcd,cdz->bijz", outer, p["out"]) + p["out_b"]


def _triangle_multiplication(p, pair, pair_mask, outgoing: bool):
    """(reference attentions.py:555-729, Suppl. Alg. 11/12)."""
    act = layer_norm(pair, p["ln_in"]["scale"], p["ln_in"]["bias"])
    mask = pair_mask[..., None]
    left = mask * (act @ p["left"] + p["left_b"])
    right = mask * (act @ p["right"] + p["right_b"])
    left = left * jax.nn.sigmoid(act @ p["left_gate"] + p["left_gate_b"])
    right = right * jax.nn.sigmoid(act @ p["right_gate"] + p["right_gate_b"])
    if outgoing:
        x = jnp.einsum("bikc,bjkc->bijc", left, right)
    else:
        x = jnp.einsum("bkic,bkjc->bijc", left, right)
    x = layer_norm(x, p["ln_out"]["scale"], p["ln_out"]["bias"])
    x = x @ p["out"] + p["out_b"]
    return x * jax.nn.sigmoid(act @ p["gate"] + p["gate_b"])


def _msa_row_attention(p, msa, pair, msa_mask, cfg):
    msa_n = layer_norm(msa, p["ln_msa"]["scale"], p["ln_msa"]["bias"])
    pair_n = layer_norm(pair, p["ln_pair"]["scale"], p["ln_pair"]["bias"])
    bias = jnp.einsum("bijc,ch->bhij", pair_n.astype(jnp.float32), p["pair_bias"].astype(jnp.float32))
    mask_bias = (1.0 - msa_mask[:, :, None, None, :].astype(jnp.float32)) * -1e9
    # per-row attention: rows are batch-like -> bias [b, 1, h, i, j]
    return _gated_attention(p["attn"], msa_n, msa_n, bias[:, None] + mask_bias, cfg.gating)


def _msa_col_attention(p, msa, msa_mask, cfg):
    """Column attention = row attention on the transposed MSA."""
    msa_t = jnp.swapaxes(msa, 1, 2)  # [b, R, S, c]
    mask_t = jnp.swapaxes(msa_mask, 1, 2)
    x = layer_norm(msa_t, p["ln"]["scale"], p["ln"]["bias"])
    if cfg.is_extra_msa:
        out = _global_attention(p["attn"], x, mask_t[..., None], cfg.gating)
    else:
        mask_bias = (1.0 - mask_t[:, :, None, None, :].astype(jnp.float32)) * -1e9
        out = _gated_attention(p["attn"], x, x, mask_bias, cfg.gating)
    return jnp.swapaxes(out, 1, 2)


def _tri_attention(p, pair, pair_mask, cfg, starting: bool):
    x = pair if starting else jnp.swapaxes(pair, 1, 2)
    mask = pair_mask if starting else jnp.swapaxes(pair_mask, 1, 2)
    xn = layer_norm(x, p["ln"]["scale"], p["ln"]["bias"])
    tri_bias = jnp.einsum("bijc,ch->bhij", xn.astype(jnp.float32), p["bias"].astype(jnp.float32))
    mask_bias = (1.0 - mask[:, :, None, None, :].astype(jnp.float32)) * -1e9
    out = _gated_attention(p["attn"], xn, xn, tri_bias[:, None] + mask_bias, cfg.gating)
    return out if starting else jnp.swapaxes(out, 1, 2)


# ---------------------------------------------------------------------------
# Iteration / stack
# ---------------------------------------------------------------------------

# logical layouts: which axis rides the `sep` mesh axis in each phase
_MSA_ROWS_SHARDED = ("batch", "seq", None, "embed")
_MSA_RES_SHARDED = ("batch", None, "seq", "embed")
_PAIR_I_SHARDED = ("batch", "seq", None, "embed")
_PAIR_J_SHARDED = ("batch", None, "seq", "embed")


def evoformer_iteration(
    lp: Dict[str, Any],
    msa: jax.Array,  # [b, S, R, cm]
    pair: jax.Array,  # [b, R, R, cz]
    msa_mask: jax.Array,  # [b, S, R]
    pair_mask: jax.Array,  # [b, R, R]
    cfg: EvoformerConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    keys = {}
    if dropout_key is not None and train:
        names = ("row", "col", "outer", "tri_out", "tri_in", "tri_start", "tri_end")
        keys = dict(zip(names, jax.random.split(dropout_key, len(names))))
    dr = cfg.dropout_rate

    # --- MSA track: rows sharded (DAP "row phase") ---
    msa = _constrain(ctx, msa, _MSA_ROWS_SHARDED)
    msa = msa + _row_dropout(
        keys.get("row"), _msa_row_attention(lp["msa_row"], msa, pair, msa_mask, cfg),
        dr, train, axis=1,
    )
    # DAP flip: residues sharded for column attention (all-to-all in ref)
    msa = _constrain(ctx, msa, _MSA_RES_SHARDED)
    msa = msa + _msa_col_attention(lp["msa_col"], msa, msa_mask, cfg)
    msa = _constrain(ctx, msa, _MSA_ROWS_SHARDED)
    msa = msa + _transition(lp["msa_transition"], msa)

    # --- outer product mean: msa -> pair branch ---
    pair = _constrain(ctx, pair, _PAIR_I_SHARDED)
    pair = pair + _row_dropout(
        keys.get("outer"), _outer_product_mean(lp["outer"], msa, msa_mask),
        dr, train, axis=1,
    )

    # --- pair track ---
    pair = pair + _row_dropout(
        keys.get("tri_out"),
        _triangle_multiplication(lp["tri_mult_out"], pair, pair_mask, outgoing=True),
        dr, train, axis=1,
    )
    pair = pair + _row_dropout(
        keys.get("tri_in"),
        _triangle_multiplication(lp["tri_mult_in"], pair, pair_mask, outgoing=False),
        dr, train, axis=1,
    )
    pair = _constrain(ctx, pair, _PAIR_I_SHARDED)
    pair = pair + _row_dropout(
        keys.get("tri_start"),
        _tri_attention(lp["tri_attn_start"], pair, pair_mask, cfg, starting=True),
        dr, train, axis=1,
    )
    pair = _constrain(ctx, pair, _PAIR_J_SHARDED)
    pair = pair + _row_dropout(
        keys.get("tri_end"),
        _tri_attention(lp["tri_attn_end"], pair, pair_mask, cfg, starting=False),
        dr, train, axis=2,
    )
    pair = _constrain(ctx, pair, _PAIR_I_SHARDED)
    pair = pair + _transition(lp["pair_transition"], pair)
    return msa, pair


def forward(
    params: Dict[str, Any],
    msa: jax.Array,
    pair: jax.Array,
    msa_mask: jax.Array,
    pair_mask: jax.Array,
    cfg: EvoformerConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Run the full Evoformer stack (scan over stacked layer params)."""
    dtype = jnp.dtype(cfg.dtype)
    msa = msa.astype(dtype)
    pair = pair.astype(dtype)

    def block(carry, lp):
        m, z, idx = carry
        key = (
            jax.random.fold_in(dropout_key, idx) if dropout_key is not None else None
        )
        m, z = evoformer_iteration(
            lp, m, z, msa_mask, pair_mask, cfg,
            ctx=ctx, dropout_key=key, train=train,
        )
        return (m, z, idx + 1), None

    fn = jax.checkpoint(block, prevent_cse=False) if cfg.use_recompute else block
    (msa, pair, _), _ = jax.lax.scan(
        fn, (msa, pair, jnp.int32(0)), params["layers"], length=cfg.num_layers
    )
    return msa, pair
