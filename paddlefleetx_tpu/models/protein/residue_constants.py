"""Compact residue constants for the protein stack.

A dependency-free subset of the reference's residue_constants
(ppfleetx/models/protein_folding/residue_constants.py, 961 LoC — itself the
public AlphaFold table set): the 20 restypes, the 37-atom vocabulary,
per-residue chi-angle atom quadruples, chi masks, and pi-periodic flags.
Only the tables the framework consumes (torsion extraction, pseudo-beta,
backbone decoding) are included; the full rigid-group coordinate tables
are deliberately out of scope (backbone-frame decoding uses ideal ALA
geometry, see structure.py).
"""

from __future__ import annotations

import numpy as np

restypes = [
    "A", "R", "N", "D", "C", "Q", "E", "G", "H", "I",
    "L", "K", "M", "F", "P", "S", "T", "W", "Y", "V",
]
restype_order = {r: i for i, r in enumerate(restypes)}
restype_num = len(restypes)  # 20; 'X' (unknown) = 20, gap = 21

restype_1to3 = {
    "A": "ALA", "R": "ARG", "N": "ASN", "D": "ASP", "C": "CYS",
    "Q": "GLN", "E": "GLU", "G": "GLY", "H": "HIS", "I": "ILE",
    "L": "LEU", "K": "LYS", "M": "MET", "F": "PHE", "P": "PRO",
    "S": "SER", "T": "THR", "W": "TRP", "Y": "TYR", "V": "VAL",
}

# the 37 heavy-atom vocabulary (atom37 representation)
atom_types = [
    "N", "CA", "C", "CB", "O", "CG", "CG1", "CG2", "OG", "OG1", "SG", "CD",
    "CD1", "CD2", "ND1", "ND2", "OD1", "OD2", "SD", "CE", "CE1", "CE2",
    "CE3", "NE", "NE1", "NE2", "OE1", "OE2", "CH2", "NH1", "NH2", "OH",
    "CZ", "CZ2", "CZ3", "NZ", "OXT",
]
atom_order = {a: i for i, a in enumerate(atom_types)}
atom_type_num = len(atom_types)  # 37

# chi-angle definitions: per residue, up to 4 quadruples of atom names
chi_angles_atoms = {
    "ALA": [],
    "ARG": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"],
            ["CB", "CG", "CD", "NE"], ["CG", "CD", "NE", "CZ"]],
    "ASN": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "OD1"]],
    "ASP": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "OD1"]],
    "CYS": [["N", "CA", "CB", "SG"]],
    "GLN": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"],
            ["CB", "CG", "CD", "OE1"]],
    "GLU": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"],
            ["CB", "CG", "CD", "OE1"]],
    "GLY": [],
    "HIS": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "ND1"]],
    "ILE": [["N", "CA", "CB", "CG1"], ["CA", "CB", "CG1", "CD1"]],
    "LEU": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD1"]],
    "LYS": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"],
            ["CB", "CG", "CD", "CE"], ["CG", "CD", "CE", "NZ"]],
    "MET": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "SD"],
            ["CB", "CG", "SD", "CE"]],
    "PHE": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD1"]],
    "PRO": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"]],
    "SER": [["N", "CA", "CB", "OG"]],
    "THR": [["N", "CA", "CB", "OG1"]],
    "TRP": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD1"]],
    "TYR": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD1"]],
    "VAL": [["N", "CA", "CB", "CG1"]],
}

# chi angles that are 180-degree symmetric (pi periodic)
chi_pi_periodic = {
    "ASP": [False, True], "GLU": [False, False, True],
    "PHE": [False, True], "TYR": [False, True],
}


def get_chi_atom_indices() -> np.ndarray:
    """[21, 4, 4] atom37 indices for each restype's chi quadruples
    (reference all_atom.py:25-51); unused slots are 0."""
    out = np.zeros((restype_num + 1, 4, 4), dtype=np.int32)
    for i, r in enumerate(restypes):
        for c, quad in enumerate(chi_angles_atoms[restype_1to3[r]]):
            out[i, c] = [atom_order[a] for a in quad]
    return out


def get_chi_angles_mask() -> np.ndarray:
    """[21, 4] which chi angles exist per restype."""
    out = np.zeros((restype_num + 1, 4), dtype=np.float32)
    for i, r in enumerate(restypes):
        out[i, : len(chi_angles_atoms[restype_1to3[r]])] = 1.0
    return out


def get_chi_pi_periodic() -> np.ndarray:
    """[21, 4] chi angles with 180-degree rotational symmetry."""
    out = np.zeros((restype_num + 1, 4), dtype=np.float32)
    for i, r in enumerate(restypes):
        flags = chi_pi_periodic.get(restype_1to3[r], [])
        for c, f in enumerate(flags):
            out[i, c] = float(f)
    return out


# ideal backbone-frame local coordinates (ALA rigid-group geometry,
# angstroms): frame origin at CA, N on one side, C on the x axis
IDEAL_N = np.array([-0.525, 1.363, 0.000], dtype=np.float32)
IDEAL_CA = np.array([0.000, 0.000, 0.000], dtype=np.float32)
IDEAL_C = np.array([1.526, 0.000, 0.000], dtype=np.float32)
IDEAL_CB = np.array([-0.529, -0.774, -1.205], dtype=np.float32)
# O sits in the psi rigid group; with psi=0 its backbone-frame position
IDEAL_O = np.array([2.153, -1.062, 0.000], dtype=np.float32)
