"""Rigid-body / quaternion geometry for the structure module.

TPU-native re-implementation of the reference geometry stack
(ppfleetx/models/protein_folding/quat_affine.py:1-613 QuatAffine,
r3.py:1-518 Rots/Vecs/Rigids) as plain functions over jnp arrays:

  - vectors:   [..., 3] arrays
  - rotations: [..., 3, 3] arrays
  - rigids:    (rot, trans) tuples
  - quats:     [..., 4] arrays, (w, x, y, z), normalized

Everything is differentiable and vmap/scan-friendly; no classes holding
tensors (the reference's QuatAffine object graph does not jit well).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

Rigid = Tuple[jax.Array, jax.Array]  # (rot [...,3,3], trans [...,3])


# ---------------------------------------------------------------------------
# Quaternions
# ---------------------------------------------------------------------------


def quat_normalize(q: jax.Array) -> jax.Array:
    return q / (jnp.linalg.norm(q, axis=-1, keepdims=True) + 1e-12)


def quat_to_rot(q: jax.Array) -> jax.Array:
    """Unit quaternion (w,x,y,z) -> rotation matrix (quat_affine.py
    quat_to_rot semantics)."""
    q = quat_normalize(q)
    w, x, y, z = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    rr = jnp.stack(
        [
            1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y),
            2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x),
            2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y),
        ],
        axis=-1,
    )
    return rr.reshape(q.shape[:-1] + (3, 3))


def rot_to_quat(rot: jax.Array) -> jax.Array:
    """Rotation matrix -> unit quaternion via the symmetric 4x4 eigen trick
    (stable for all rotations, reference rot_to_quat)."""
    xx, xy, xz = rot[..., 0, 0], rot[..., 0, 1], rot[..., 0, 2]
    yx, yy, yz = rot[..., 1, 0], rot[..., 1, 1], rot[..., 1, 2]
    zx, zy, zz = rot[..., 2, 0], rot[..., 2, 1], rot[..., 2, 2]
    k = jnp.stack(
        [
            jnp.stack([xx + yy + zz, zy - yz, xz - zx, yx - xy], axis=-1),
            jnp.stack([zy - yz, xx - yy - zz, xy + yx, xz + zx], axis=-1),
            jnp.stack([xz - zx, xy + yx, yy - xx - zz, yz + zy], axis=-1),
            jnp.stack([yx - xy, xz + zx, yz + zy, zz - xx - yy], axis=-1),
        ],
        axis=-2,
    ) / 3.0
    _, vecs = jnp.linalg.eigh(k)
    q = vecs[..., -1]  # eigenvector of the largest eigenvalue
    # canonical sign: w >= 0
    return q * jnp.sign(q[..., :1] + 1e-12)


def quat_multiply(a: jax.Array, b: jax.Array) -> jax.Array:
    aw, ax, ay, az = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    bw, bx, by, bz = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack(
        [
            aw * bw - ax * bx - ay * by - az * bz,
            aw * bx + ax * bw + ay * bz - az * by,
            aw * by - ax * bz + ay * bw + az * bx,
            aw * bz + ax * by - ay * bx + az * bw,
        ],
        axis=-1,
    )


def quat_precompose_vec(quat: jax.Array, update_vec: jax.Array) -> jax.Array:
    """QuatAffine.pre_compose's quaternion update: compose with the small
    rotation (1, bx, by, bz) then renormalize."""
    b = jnp.concatenate([jnp.ones_like(update_vec[..., :1]), update_vec], axis=-1)
    return quat_normalize(quat_multiply(quat, b))


# ---------------------------------------------------------------------------
# Rotations / rigids (r3 equivalents)
# ---------------------------------------------------------------------------


def rot_identity(shape: Tuple[int, ...] = ()) -> jax.Array:
    return jnp.broadcast_to(jnp.eye(3), shape + (3, 3))


def rigid_identity(shape: Tuple[int, ...] = ()) -> Rigid:
    return rot_identity(shape), jnp.zeros(shape + (3,))


def rot_mul_vec(rot: jax.Array, vec: jax.Array) -> jax.Array:
    return jnp.einsum("...ij,...j->...i", rot, vec)


def rot_mul_rot(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.einsum("...ij,...jk->...ik", a, b)


def rigid_compose(a: Rigid, b: Rigid) -> Rigid:
    """a then b in a's frame: (Ra Rb, Ra tb + ta) (r3.rigids_mul_rigids)."""
    ra, ta = a
    rb, tb = b
    return rot_mul_rot(ra, rb), rot_mul_vec(ra, tb) + ta


def rigid_invert(r: Rigid) -> Rigid:
    rot, t = r
    inv_rot = jnp.swapaxes(rot, -1, -2)
    return inv_rot, -rot_mul_vec(inv_rot, t)


def rigid_apply(r: Rigid, point: jax.Array) -> jax.Array:
    """Map a local point to global coordinates (r3.rigids_mul_vecs)."""
    rot, t = r
    return rot_mul_vec(rot, point) + t


def rigid_invert_apply(r: Rigid, point: jax.Array) -> jax.Array:
    """Map a global point into the rigid's local frame
    (QuatAffine.invert_point)."""
    rot, t = r
    return rot_mul_vec(jnp.swapaxes(rot, -1, -2), point - t)


def rigid_from_quat(quat: jax.Array, trans: jax.Array) -> Rigid:
    return quat_to_rot(quat), trans


def rigids_from_3_points(p_neg_x: jax.Array, origin: jax.Array, p_xy: jax.Array) -> Rigid:
    """Gram-Schmidt frame from three points (r3.rigids_from_3_points,
    AlphaFold Suppl. Alg. 21), backbone convention (N, CA, C):
    p_neg_x (N) lands on the NEGATIVE x axis, p_xy (C) in the xy-plane
    with positive y."""
    e0 = origin - p_neg_x
    e0 = e0 / (jnp.linalg.norm(e0, axis=-1, keepdims=True) + 1e-8)
    v1 = p_xy - origin
    dot = jnp.sum(e0 * v1, axis=-1, keepdims=True)
    e1 = v1 - dot * e0
    e1 = e1 / (jnp.linalg.norm(e1, axis=-1, keepdims=True) + 1e-8)
    e2 = jnp.cross(e0, e1)
    rot = jnp.stack([e0, e1, e2], axis=-1)  # columns are the basis
    return rot, origin


def pre_compose(quat: jax.Array, trans: jax.Array, update: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """QuatAffine.pre_compose (quat_affine.py): 6-vector update
    (rot_vec[3], trans_vec[3]) applied in the CURRENT local frame."""
    rot_upd, trans_upd = update[..., :3], update[..., 3:]
    new_quat = quat_precompose_vec(quat, rot_upd)
    rot = quat_to_rot(quat)
    new_trans = trans + rot_mul_vec(rot, trans_upd)
    return new_quat, new_trans


def frame_aligned_point_error(
    pred_frames: Rigid,
    target_frames: Rigid,
    pred_points: jax.Array,
    target_points: jax.Array,
    length_scale: float = 10.0,
    clamp_distance: float = 10.0,
) -> jax.Array:
    """FAPE loss (AlphaFold Suppl. Alg. 28): every point viewed from every
    frame, clamped L2, averaged.  pred/target_points: [..., P, 3];
    frames: [..., F, 3, 3] / [..., F, 3]."""
    def local(frames, points):
        rot, t = frames
        # [..., F, P, 3]
        return rot_mul_vec(
            jnp.swapaxes(rot, -1, -2)[..., :, None, :, :],
            points[..., None, :, :] - t[..., :, None, :],
        )

    d = jnp.sqrt(jnp.sum((local(pred_frames, pred_points) - local(target_frames, target_points)) ** 2, axis=-1) + 1e-8)
    return jnp.mean(jnp.clip(d, 0.0, clamp_distance)) / length_scale
