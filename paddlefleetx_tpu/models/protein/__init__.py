"""Protein folding (HelixFold/Evoformer) model family."""
