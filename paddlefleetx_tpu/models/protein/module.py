"""ProteinModule: binds the HelixFold model to the engine.

The reference has no engine adapter for protein folding (its
projects/protein_folding/README.md defers training to the upstream
HelixFold app); this module completes the path so ``tools/train.py``
drives folding with DP x DAP layouts like any other family.
"""

from __future__ import annotations

from paddlefleetx_tpu.core.module import BasicModule, resolve_model_dtype
from paddlefleetx_tpu.utils.registry import MODULES


@MODULES.register("ProteinModule")
class ProteinModule(BasicModule):
    def __init__(self, cfg):
        from paddlefleetx_tpu.models.protein.folding import FoldingConfig

        model_cfg = dict(cfg.Model)
        model_cfg.pop("module", None)
        model_cfg.pop("name", None)
        resolve_model_dtype(cfg, model_cfg)
        self.config = FoldingConfig.from_config(model_cfg)
        ds = cfg.get("Data", {}).get("Train", {}).get("dataset", {})
        self.tokens_per_sample = int(ds.get("num_res", 64))  # ips = residues/s

    def init_params(self, key):
        from paddlefleetx_tpu.models.protein import folding

        return folding.init(self.config, key)

    def logical_axes(self):
        from paddlefleetx_tpu.models.protein import folding

        return folding.folding_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        from paddlefleetx_tpu.models.protein import folding

        return folding.loss_fn(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )
