"""Template embedding stack — pure-JAX functional.

TPU-native re-design of the reference template stack
(ppfleetx/models/protein_folding/template.py: TemplatePair :36,
SingleTemplateEmbedding :164, TemplateEmbedding :290 — Jumper et al. 2021
Suppl. Alg. 2 lines 9-13, Alg. 16/17).

Feature construction mirrors SingleTemplateEmbedding.forward (:190-287):
distogram of template pseudo-beta positions (39 bins), pairwise template
mask, tiled aatype one-hots (22) for both residues, inter-residue unit
vectors in each residue's backbone frame (zeroed unless
``use_template_unit_vector``), and the backbone-affine mask — 88 channels
total — projected to the template-pair channel and refined by a small
triangle-op stack, then folded into the query pair representation by
pointwise attention over templates (Alg. 17), one query per (i, j) pair.

DAP: the template-pair activations carry the same ``sep``-axis sharding
constraints as the Evoformer pair track (the reference's dap.scatter/
gather at :276-284 become logical constraints; XLA inserts the moves).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    init_params,
    normal_init,
    stack_spec_tree,
    zeros_init,
)
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, _constrain, layer_norm
from paddlefleetx_tpu.models.protein import rigid
from paddlefleetx_tpu.models.protein.evoformer import (
    _attn_specs,
    _ln,
    _transition,
    _transition_specs,
    _tri_mult_specs,
    _triangle_multiplication,
)

_W = normal_init(0.02)

# atom37 indices for the backbone atoms (residue_constants.atom_order)
ATOM_N, ATOM_CA, ATOM_C, ATOM_CB = 0, 1, 2, 3


@dataclasses.dataclass(frozen=True)
class TemplateConfig:
    pair_channel: int = 64
    num_blocks: int = 2
    num_heads: int = 4
    attn_heads: int = 4  # pointwise attention over templates
    transition_factor: int = 2
    dgram_min_bin: float = 3.25
    dgram_max_bin: float = 50.75
    dgram_num_bins: int = 39
    use_template_unit_vector: bool = False
    dropout_rate: float = 0.25

    @property
    def feat_channels(self) -> int:
        # dgram + mask2d + 2x aatype(22) + unit vec(3) + backbone mask2d
        return self.dgram_num_bins + 1 + 44 + 3 + 1  # = 88


def dgram_from_positions(
    pos: jax.Array, num_bins: int, min_bin: float, max_bin: float
) -> jax.Array:
    """Pairwise distance histogram one-hots (reference template.py
    dgram_from_positions / common.py)."""
    lower = jnp.linspace(min_bin, max_bin, num_bins) ** 2
    upper = jnp.concatenate([lower[1:], jnp.array([1e8])])
    d2 = jnp.sum(
        (pos[..., :, None, :] - pos[..., None, :, :]) ** 2, axis=-1, keepdims=True
    )
    return ((d2 > lower) * (d2 < upper)).astype(jnp.float32)


def pseudo_beta_fn(aatype, all_atom_positions, all_atom_masks=None):
    """CB (CA for glycine) positions (reference evoformer.py:633-668).
    aatype: [..., R] with glycine == 7 (restype_order['G'])."""
    is_gly = aatype == 7
    beta = jnp.where(
        is_gly[..., None],
        all_atom_positions[..., ATOM_CA, :],
        all_atom_positions[..., ATOM_CB, :],
    )
    if all_atom_masks is None:
        return beta
    mask = jnp.where(
        is_gly, all_atom_masks[..., ATOM_CA], all_atom_masks[..., ATOM_CB]
    )
    return beta, mask


# ---------------------------------------------------------------------------
# Specs
# ---------------------------------------------------------------------------


def _pair_block_specs(cfg: TemplateConfig) -> Dict[str, Any]:
    c = cfg.pair_channel
    hd = c // cfg.num_heads
    return {
        "tri_attn_start": {
            "ln": _ln(c),
            "bias": ParamSpec((c, cfg.num_heads), ("embed", "heads"), _W),
            "attn": _attn_specs(c, c, cfg.num_heads, hd, True),
        },
        "tri_attn_end": {
            "ln": _ln(c),
            "bias": ParamSpec((c, cfg.num_heads), ("embed", "heads"), _W),
            "attn": _attn_specs(c, c, cfg.num_heads, hd, True),
        },
        "tri_mult_out": _tri_mult_specs(c),
        "tri_mult_in": _tri_mult_specs(c),
        "pair_transition": _transition_specs(c, cfg.transition_factor),
    }


def template_specs(cfg: TemplateConfig, pair_channel: int) -> Dict[str, Any]:
    c = cfg.pair_channel
    hd = c // cfg.attn_heads
    return {
        "embedding2d": ParamSpec((cfg.feat_channels, c), ("embed", "mlp"), _W),
        "embedding2d_b": ParamSpec((c,), ("mlp",), zeros_init()),
        "blocks": stack_spec_tree(_pair_block_specs(cfg), cfg.num_blocks),
        "out_ln": _ln(c),
        # pointwise attention: queries from the query pair repr, keys/values
        # from per-template embeddings (Alg. 17)
        "pointwise": {
            "q": ParamSpec((pair_channel, cfg.attn_heads, hd), ("embed", "heads", "kv"), _W),
            "k": ParamSpec((c, cfg.attn_heads, hd), ("embed", "heads", "kv"), _W),
            "v": ParamSpec((c, cfg.attn_heads, hd), ("embed", "heads", "kv"), _W),
            "out": ParamSpec(
                (cfg.attn_heads, hd, pair_channel), ("heads", "kv", "embed"), zeros_init()
            ),
            "out_b": ParamSpec((pair_channel,), ("embed",), zeros_init()),
        },
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

_PAIR_I = ("batch", "seq", None, "embed")
_PAIR_J = ("batch", None, "seq", "embed")


def _template_pair_block(lp, act, mask_2d, cfg: TemplateConfig, ctx, key, train):
    """TemplatePair (reference template.py:36-161): triangle attention
    start/end, triangle multiplication out/in, transition — note the
    reference order attn-first (unlike the Evoformer pair track)."""

    class _C:  # minimal cfg shim for the evoformer helpers
        gating = True

    keys = (
        jax.random.split(key, 4)
        if key is not None and train
        else (None, None, None, None)
    )

    def drop(k, x, axis):
        if not train or k is None or cfg.dropout_rate == 0.0:
            return x
        shape = list(x.shape)
        shape[axis] = 1
        keep = 1.0 - cfg.dropout_rate
        m = jax.random.bernoulli(k, keep, shape)
        return jnp.where(m, x / keep, 0.0).astype(x.dtype)

    from paddlefleetx_tpu.models.protein.evoformer import _tri_attention

    act = _constrain(ctx, act, _PAIR_I)
    act = act + drop(keys[0], _tri_attention(lp["tri_attn_start"], act, mask_2d, _C, starting=True), 1)
    act = _constrain(ctx, act, _PAIR_J)
    act = act + drop(keys[1], _tri_attention(lp["tri_attn_end"], act, mask_2d, _C, starting=False), 2)
    act = _constrain(ctx, act, _PAIR_I)
    act = act + drop(keys[2], _triangle_multiplication(lp["tri_mult_out"], act, mask_2d, outgoing=True), 1)
    act = act + drop(keys[3], _triangle_multiplication(lp["tri_mult_in"], act, mask_2d, outgoing=False), 1)
    act = act + _transition(lp["pair_transition"], act)
    return act


def single_template_embedding(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],  # single template: [b, R, ...]
    mask_2d: jax.Array,  # [b, R, R]
    cfg: TemplateConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """Reference SingleTemplateEmbedding.forward (:190-287) -> [b, R, R, ct]."""
    dtype = mask_2d.dtype
    pb = batch["template_pseudo_beta"]
    pb_mask = batch["template_pseudo_beta_mask"]
    mask2d_pb = (pb_mask[..., :, None] * pb_mask[..., None, :]).astype(dtype)

    dgram = dgram_from_positions(
        pb, cfg.dgram_num_bins, cfg.dgram_min_bin, cfg.dgram_max_bin
    ).astype(dtype)

    aatype = jax.nn.one_hot(batch["template_aatype"], 22, dtype=dtype)  # [b,R,22]
    R = aatype.shape[-2]
    feats = [dgram, mask2d_pb[..., None]]
    feats.append(jnp.broadcast_to(aatype[..., None, :, :], aatype.shape[:-2] + (R, R, 22)))
    feats.append(jnp.broadcast_to(aatype[..., :, None, :], aatype.shape[:-2] + (R, R, 22)))

    # backbone frames from N, CA, C; inter-residue unit vectors in the
    # acceptor residue's frame (:229-264)
    pos = batch["template_all_atom_positions"]
    frames = rigid.rigids_from_3_points(
        pos[..., ATOM_N, :], pos[..., ATOM_CA, :], pos[..., ATOM_C, :]
    )
    rot, trans = frames
    vec = rigid.rot_mul_vec(
        jnp.swapaxes(rot, -1, -2)[..., :, None, :, :],
        trans[..., None, :, :] - trans[..., :, None, :],
    )  # [b, R, R, 3]
    inv_d = jax.lax.rsqrt(1e-6 + jnp.sum(vec**2, axis=-1, keepdims=True))
    am = batch["template_all_atom_masks"]
    bb_mask = am[..., ATOM_N] * am[..., ATOM_CA] * am[..., ATOM_C]
    bb_mask_2d = (bb_mask[..., :, None] * bb_mask[..., None, :]).astype(dtype)
    unit_vec = (vec * inv_d * bb_mask_2d[..., None]).astype(dtype)
    if not cfg.use_template_unit_vector:
        unit_vec = jnp.zeros_like(unit_vec)
    feats.append(unit_vec)
    feats.append(bb_mask_2d[..., None])

    act = jnp.concatenate(feats, axis=-1) * bb_mask_2d[..., None]
    act = act @ params["embedding2d"] + params["embedding2d_b"]

    def block(carry, inp):
        a, idx = carry
        lp = inp
        k = (
            jax.random.fold_in(dropout_key, idx) if dropout_key is not None else None
        )
        a = _template_pair_block(lp, a, mask_2d, cfg, ctx, k, train)
        return (a, idx + 1), None

    (act, _), _ = jax.lax.scan(
        block, (act, jnp.int32(0)), params["blocks"], length=cfg.num_blocks
    )
    return layer_norm(act, params["out_ln"]["scale"], params["out_ln"]["bias"])


def template_embedding(
    params: Dict[str, Any],
    query_pair: jax.Array,  # [b, R, R, cz]
    template_batch: Dict[str, jax.Array],  # [b, T, R, ...]
    mask_2d: jax.Array,
    cfg: TemplateConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> jax.Array:
    """Reference TemplateEmbedding.forward (:308-368): embed each template,
    then pointwise attention with one query per (i, j) pair over the T
    template embeddings."""
    T = template_batch["template_mask"].shape[1]
    dtype = query_pair.dtype
    tmask = template_batch["template_mask"].astype(dtype)  # [b, T]

    embs = []
    for t in range(T):  # T is small (4); unrolled like the reference loop
        single = {k: v[:, t] for k, v in template_batch.items()}
        k = jax.random.fold_in(dropout_key, t) if dropout_key is not None else None
        embs.append(
            single_template_embedding(
                params, single, mask_2d, cfg, ctx=ctx, dropout_key=k, train=train
            )
        )
    temp = jnp.stack(embs, axis=1)  # [b, T, R, R, ct]

    p = params["pointwise"]
    q = jnp.einsum("bijc,chd->bijhd", query_pair, p["q"].astype(dtype))
    q = q * (p["q"].shape[-1] ** -0.5)
    k = jnp.einsum("btijc,chd->bijthd", temp, p["k"].astype(dtype))
    v = jnp.einsum("btijc,chd->bijthd", temp, p["v"].astype(dtype))
    logits = jnp.einsum(
        "bijhd,bijthd->bijht", q, k, preferred_element_type=jnp.float32
    )
    logits = logits + (tmask[:, None, None, None, :] - 1.0) * 1e9
    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
    out = jnp.einsum("bijht,bijthd->bijhd", probs, v)
    out = jnp.einsum("bijhd,hdc->bijc", out, p["out"].astype(dtype)) + p["out_b"]
    # no gradients/contribution when no template exists (:367)
    return out * (jnp.sum(tmask) > 0.0).astype(dtype)


def init(cfg: TemplateConfig, pair_channel: int, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, template_specs(cfg, pair_channel))
