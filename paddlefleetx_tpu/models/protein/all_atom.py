"""All-atom geometry: torsion angles from atom37 coordinates.

TPU-native re-implementation of the reference
``atom37_to_torsion_angles`` (ppfleetx/models/protein_folding/all_atom.py:
52-254) as a batched, jit-friendly function: 7 torsions per residue
(pre-omega, phi, psi, chi1-4) extracted by building a rigid frame from the
2nd/3rd atoms of each dihedral quadruple and reading the 4th atom's
(z, y) local coordinates as (sin, cos); alternate torsions mirror the
pi-periodic chis (reference :221-247).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from paddlefleetx_tpu.models.protein import residue_constants as rc
from paddlefleetx_tpu.models.protein import rigid


def atom37_to_torsion_angles(
    aatype: jax.Array,  # [b, R] int
    all_atom_pos: jax.Array,  # [b, R, 37, 3]
    all_atom_mask: jax.Array,  # [b, R, 37]
) -> Dict[str, jax.Array]:
    """Returns torsion_angles_sin_cos [b, R, 7, 2], alt_torsion_angles_sin_cos
    [b, R, 7, 2] and torsion_angles_mask [b, R, 7]."""
    aatype = jnp.minimum(aatype, rc.restype_num)  # map gap/mask -> UNK

    # previous-residue atoms, padded with zeros at position 0 (:74-82)
    pad = jnp.zeros_like(all_atom_pos[:, :1])
    prev_pos = jnp.concatenate([pad, all_atom_pos[:, :-1]], axis=1)
    pad_m = jnp.zeros_like(all_atom_mask[:, :1])
    prev_mask = jnp.concatenate([pad_m, all_atom_mask[:, :-1]], axis=1)

    N, CA, C, O = (rc.atom_order[a] for a in ("N", "CA", "C", "O"))

    # dihedral atom quadruples [b, R, 7, 4, 3]
    pre_omega = jnp.stack(
        [prev_pos[..., CA, :], prev_pos[..., C, :], all_atom_pos[..., N, :],
         all_atom_pos[..., CA, :]], axis=-2)
    phi = jnp.stack(
        [prev_pos[..., C, :], all_atom_pos[..., N, :], all_atom_pos[..., CA, :],
         all_atom_pos[..., C, :]], axis=-2)
    psi = jnp.stack(
        [all_atom_pos[..., N, :], all_atom_pos[..., CA, :], all_atom_pos[..., C, :],
         all_atom_pos[..., O, :]], axis=-2)

    pre_omega_mask = jnp.prod(prev_mask[..., [CA, C]], axis=-1) * jnp.prod(
        all_atom_mask[..., [N, CA]], axis=-1)
    phi_mask = prev_mask[..., C] * jnp.prod(all_atom_mask[..., [N, CA, C]], axis=-1)
    psi_mask = jnp.prod(all_atom_mask[..., [N, CA, C, O]], axis=-1)

    chi_idx = jnp.asarray(rc.get_chi_atom_indices())  # [21, 4, 4]
    chi_mask_table = jnp.asarray(rc.get_chi_angles_mask())  # [21, 4]
    idx = chi_idx[aatype]  # [b, R, 4, 4]
    chi_atoms = jnp.take_along_axis(
        all_atom_pos[..., None, :, :],  # [b, R, 1, 37, 3]
        idx[..., None].repeat(3, axis=-1),  # [b, R, 4, 4, 3]
        axis=-2,
    )  # [b, R, 4, 4, 3]
    chis_mask = chi_mask_table[aatype]  # [b, R, 4]
    chi_atom_m = jnp.take_along_axis(all_atom_mask[..., None, :], idx, axis=-1)
    chis_mask = chis_mask * jnp.prod(chi_atom_m, axis=-1)

    torsion_atoms = jnp.concatenate(
        [jnp.stack([pre_omega, phi, psi], axis=-3), chi_atoms], axis=-3
    )  # [b, R, 7, 4, 3]
    torsion_mask = jnp.concatenate(
        [jnp.stack([pre_omega_mask, phi_mask, psi_mask], axis=-1), chis_mask], axis=-1
    )  # [b, R, 7]

    # torsion frame (reference :189-197): atom1 on the negative x axis,
    # atom2 at the origin, atom0 defining the xy half-plane; the 4th
    # atom's (z, y) in this frame are (sin, cos) of the dihedral
    frames = rigid.rigids_from_3_points(
        torsion_atoms[..., 1, :], torsion_atoms[..., 2, :], torsion_atoms[..., 0, :]
    )
    a4_local = rigid.rigid_invert_apply(frames, torsion_atoms[..., 3, :])
    # torsion = atan2(z, y) in this frame; store (sin, cos)
    denom = jnp.sqrt(
        jnp.sum(a4_local[..., 1:] ** 2, axis=-1, keepdims=True) + 1e-8
    )
    sin_cos = jnp.stack([a4_local[..., 2], a4_local[..., 1]], axis=-1) / denom

    # psi sign flip (reference :218: O is on the opposite side)
    flip = jnp.asarray([1.0, 1.0, -1.0, 1.0, 1.0, 1.0, 1.0])
    sin_cos = sin_cos * flip[..., :, None]

    pi_periodic = jnp.asarray(np.concatenate(
        [np.zeros((rc.restype_num + 1, 3), np.float32), rc.get_chi_pi_periodic()],
        axis=1,
    ))[aatype]  # [b, R, 7]
    mirror = (1.0 - 2.0 * pi_periodic)[..., None]
    alt_sin_cos = sin_cos * mirror

    return {
        "torsion_angles_sin_cos": sin_cos,
        "alt_torsion_angles_sin_cos": alt_sin_cos,
        "torsion_angles_mask": torsion_mask,
    }
