"""HelixFold model: input embeddings + Evoformer + structure module + heads.

TPU-native counterpart of the reference's ``DistEmbeddingsAndEvoformer``
(ppfleetx/models/protein_folding/evoformer.py:532-996: InputEmbedder,
RecyclingEmbedder, relpos, TemplateEmbedding, ExtraMSAStack, main
Evoformer, single projection) COMPLETED with the structure module and
prediction heads the reference defers to the upstream HelixFold app
(projects/protein_folding/README.md): masked-MSA, distogram, pLDDT heads
and the FAPE/torsion losses.

Feature channels follow the AlphaFold conventions the reference uses:
target_feat 22, msa_feat 49, extra-MSA feat 25 (23 one-hot + has_deletion
+ deletion_value, :598), template_pair 88, template_angle 57, relpos
2*32+1.  Recycling inputs (prev_pos/prev_msa_first_row/prev_pair) are
folded in when present in the batch (:715-760).

DAP: the MSA/pair tracks ride the ``sep`` mesh axis via the Evoformer's
logical constraints — the reference's dap.scatter calls (:709-817) are
sharding annotations here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    init_params,
    logical_axes,
    normal_init,
    ones_init,
    zeros_init,
)
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, layer_norm
from paddlefleetx_tpu.models.protein import all_atom
from paddlefleetx_tpu.models.protein import evoformer as evo
from paddlefleetx_tpu.models.protein import rigid
from paddlefleetx_tpu.models.protein import structure as struct
from paddlefleetx_tpu.models.protein import template as tmpl
from paddlefleetx_tpu.models.protein.template import (
    ATOM_C,
    ATOM_CA,
    ATOM_N,
    dgram_from_positions,
    pseudo_beta_fn,
)

_W = normal_init(0.02)

TARGET_FEAT = 22
MSA_FEAT = 49
EXTRA_MSA_FEAT = 25
TEMPLATE_ANGLE_FEAT = 57
MASKED_MSA_CLASSES = 23


@dataclasses.dataclass(frozen=True)
class FoldingConfig:
    msa_channel: int = 256
    pair_channel: int = 128
    seq_channel: int = 384
    extra_msa_channel: int = 64
    evoformer_num_blocks: int = 48
    extra_msa_num_blocks: int = 4
    msa_heads: int = 8
    pair_heads: int = 4
    max_relative_feature: int = 32
    template_enabled: bool = True
    template_embed_torsion_angles: bool = True
    template_pair_channel: int = 64
    template_num_blocks: int = 2
    recycle_pos: bool = True
    recycle_features: bool = True
    prev_pos_num_bins: int = 15
    prev_pos_min_bin: float = 3.25
    prev_pos_max_bin: float = 20.75
    distogram_bins: int = 64
    distogram_first_break: float = 2.3125
    distogram_last_break: float = 21.6875
    plddt_bins: int = 50
    structure: Any = None  # StructureConfig
    dropout_rate: float = 0.15
    dtype: str = "float32"
    use_recompute: bool = False
    # loss weights (AlphaFold defaults)
    masked_msa_weight: float = 2.0
    distogram_weight: float = 0.3
    fape_weight: float = 1.0
    torsion_weight: float = 1.0
    plddt_weight: float = 0.01

    def __post_init__(self):
        if self.structure is None:
            object.__setattr__(
                self,
                "structure",
                struct.StructureConfig(
                    single_channel=self.seq_channel, pair_channel=self.pair_channel
                ),
            )

    @property
    def evoformer_cfg(self) -> evo.EvoformerConfig:
        return evo.EvoformerConfig(
            msa_channel=self.msa_channel,
            pair_channel=self.pair_channel,
            num_layers=self.evoformer_num_blocks,
            msa_heads=self.msa_heads,
            pair_heads=self.pair_heads,
            dropout_rate=self.dropout_rate,
            dtype=self.dtype,
            use_recompute=self.use_recompute,
        )

    @property
    def extra_msa_cfg(self) -> evo.EvoformerConfig:
        return evo.EvoformerConfig(
            msa_channel=self.extra_msa_channel,
            pair_channel=self.pair_channel,
            num_layers=self.extra_msa_num_blocks,
            msa_heads=self.msa_heads,
            pair_heads=self.pair_heads,
            is_extra_msa=True,
            dropout_rate=self.dropout_rate,
            dtype=self.dtype,
            use_recompute=self.use_recompute,
        )

    @property
    def template_cfg(self) -> tmpl.TemplateConfig:
        return tmpl.TemplateConfig(
            pair_channel=self.template_pair_channel,
            num_blocks=self.template_num_blocks,
        )

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "FoldingConfig":
        d = dict(d)
        s = d.pop("structure", None)
        fields = {f.name for f in dataclasses.fields(cls)}
        cfg = {k: v for k, v in d.items() if k in fields}
        if s:
            cfg["structure"] = struct.StructureConfig.from_config(dict(s))
        return cls(**cfg)


# ---------------------------------------------------------------------------
# Specs
# ---------------------------------------------------------------------------


def _linear(cin, cout):
    return {
        "w": ParamSpec((cin, cout), ("embed", "mlp"), _W),
        "b": ParamSpec((cout,), ("mlp",), zeros_init()),
    }


def _ln(c):
    return {"scale": ParamSpec((c,), ("embed",), ones_init()),
            "bias": ParamSpec((c,), ("embed",), zeros_init())}


def folding_specs(cfg: FoldingConfig) -> Dict[str, Any]:
    cm, cz, cs = cfg.msa_channel, cfg.pair_channel, cfg.seq_channel
    specs: Dict[str, Any] = {
        "preprocess_1d": _linear(TARGET_FEAT, cm),
        "preprocess_msa": _linear(MSA_FEAT, cm),
        "left_single": _linear(TARGET_FEAT, cz),
        "right_single": _linear(TARGET_FEAT, cz),
        "relpos": _linear(2 * cfg.max_relative_feature + 1, cz),
        "extra_msa_activations": _linear(EXTRA_MSA_FEAT, cfg.extra_msa_channel),
        "extra_msa_stack": evo.evoformer_specs(cfg.extra_msa_cfg),
        "evoformer": evo.evoformer_specs(cfg.evoformer_cfg),
        "single_activations": _linear(cm, cs),
        "structure": struct.structure_specs(cfg.structure),
        "masked_msa_head": _linear(cm, MASKED_MSA_CLASSES),
        "distogram_head": _linear(cz, cfg.distogram_bins),
        "plddt_head": {
            "ln": _ln(cs),
            "fc1": _linear(cs, cs),
            "fc2": _linear(cs, cfg.plddt_bins),
        },
    }
    if cfg.recycle_pos:
        specs["prev_pos_linear"] = _linear(cfg.prev_pos_num_bins, cz)
    if cfg.recycle_features:
        specs["prev_msa_first_row_norm"] = _ln(cm)
        specs["prev_pair_norm"] = _ln(cz)
    if cfg.template_enabled:
        specs["template"] = tmpl.template_specs(cfg.template_cfg, cz)
        if cfg.template_embed_torsion_angles:
            specs["template_single_embedding"] = _linear(TEMPLATE_ANGLE_FEAT, cm)
            specs["template_projection"] = _linear(cm, cm)
    return specs


def init(cfg: FoldingConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, folding_specs(cfg))


def folding_logical_axes(cfg: FoldingConfig) -> Dict[str, Any]:
    return logical_axes(folding_specs(cfg))


def _lin(p, x):
    return x @ p["w"] + p["b"]


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def forward(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: FoldingConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> Dict[str, jax.Array]:
    """batch (leading batch dim b, residues R, msa rows S):
    aatype [b,R] int, residue_index [b,R], seq_mask [b,R],
    target_feat [b,R,22], msa_feat [b,S,R,49], msa_mask [b,S,R],
    extra_msa [b,Se,R] int, extra_has_deletion/extra_deletion_value
    [b,Se,R], extra_msa_mask [b,Se,R], template_* (optional),
    prev_pos/prev_msa_first_row/prev_pair (optional recycling).

    Returns representations + head outputs."""
    dtype = jnp.dtype(cfg.dtype)
    keys = {}
    if dropout_key is not None:
        names = ("extra", "evo", "template", "structure")
        keys = dict(zip(names, jax.random.split(dropout_key, len(names))))

    target_feat = batch["target_feat"].astype(dtype)
    msa_feat = batch["msa_feat"].astype(dtype)
    seq_mask = batch["seq_mask"].astype(dtype)

    # InputEmbedder (Alg. 3; reference :688-701)
    preprocess_1d = _lin(params["preprocess_1d"], target_feat)
    msa_act = preprocess_1d[:, None] + _lin(params["preprocess_msa"], msa_feat)
    left = _lin(params["left_single"], target_feat)[:, :, None]
    right = _lin(params["right_single"], target_feat)[:, None, :]
    pair_act = left + right

    mask_2d = seq_mask[:, :, None] * seq_mask[:, None, :]

    # RecyclingEmbedder (Alg. 32; reference :715-760)
    if cfg.recycle_pos and "prev_pos" in batch:
        prev_beta = pseudo_beta_fn(batch["aatype"], batch["prev_pos"])
        dgram = dgram_from_positions(
            prev_beta, cfg.prev_pos_num_bins, cfg.prev_pos_min_bin, cfg.prev_pos_max_bin
        ).astype(dtype)
        pair_act = pair_act + _lin(params["prev_pos_linear"], dgram)
    if cfg.recycle_features:
        if "prev_msa_first_row" in batch:
            prev_first = layer_norm(
                batch["prev_msa_first_row"].astype(dtype),
                params["prev_msa_first_row_norm"]["scale"],
                params["prev_msa_first_row_norm"]["bias"],
            )
            msa_act = msa_act.at[:, 0].add(prev_first)
        if "prev_pair" in batch:
            pair_act = pair_act + layer_norm(
                batch["prev_pair"].astype(dtype),
                params["prev_pair_norm"]["scale"],
                params["prev_pair_norm"]["bias"],
            )

    # relpos (Alg. 4/5; reference :765-785)
    pos = batch["residue_index"]
    offset = pos[:, :, None] - pos[:, None, :]
    m = cfg.max_relative_feature
    rel = jax.nn.one_hot(jnp.clip(offset + m, 0, 2 * m), 2 * m + 1, dtype=dtype)
    pair_act = pair_act + _lin(params["relpos"], rel)

    # TemplateEmbedding (Alg. 2 lines 9-13; reference :789-796)
    if cfg.template_enabled and "template_mask" in batch:
        template_batch = {
            k: batch[k] for k in batch if k.startswith("template_")
        }
        pair_act = pair_act + tmpl.template_embedding(
            params["template"],
            pair_act,
            template_batch,
            mask_2d,
            cfg.template_cfg,
            ctx=ctx,
            dropout_key=keys.get("template"),
            train=train,
        )

    # ExtraMSAStack (Alg. 18; reference :798-830)
    extra_1hot = jax.nn.one_hot(batch["extra_msa"], 23, dtype=dtype)
    extra_feat = jnp.concatenate(
        [
            extra_1hot,
            batch["extra_has_deletion"][..., None].astype(dtype),
            batch["extra_deletion_value"][..., None].astype(dtype),
        ],
        axis=-1,
    )
    extra_act = _lin(params["extra_msa_activations"], extra_feat)
    extra_mask = batch["extra_msa_mask"].astype(dtype)
    _, pair_act = evo.forward(
        params["extra_msa_stack"],
        extra_act,
        pair_act,
        extra_mask,
        mask_2d,
        cfg.extra_msa_cfg,
        ctx=ctx,
        dropout_key=keys.get("extra"),
        train=train,
    )

    # template torsion-angle rows appended to the MSA (reference :612-617 +
    # HelixFold template_angle_feat: aatype 22 + 7x(sin,cos) + 7x alt + 7 mask)
    msa_mask = batch["msa_mask"].astype(dtype)
    if (
        cfg.template_enabled
        and cfg.template_embed_torsion_angles
        and "template_mask" in batch
    ):
        ta = all_atom.atom37_to_torsion_angles(
            batch["template_aatype"].reshape(-1, batch["template_aatype"].shape[-1]),
            batch["template_all_atom_positions"].reshape(
                (-1,) + batch["template_all_atom_positions"].shape[-3:]
            ),
            batch["template_all_atom_masks"].reshape(
                (-1,) + batch["template_all_atom_masks"].shape[-2:]
            ),
        )
        b, T, R = batch["template_aatype"].shape
        angle_feat = jnp.concatenate(
            [
                jax.nn.one_hot(batch["template_aatype"], 22, dtype=dtype),
                ta["torsion_angles_sin_cos"].reshape(b, T, R, 14).astype(dtype),
                ta["alt_torsion_angles_sin_cos"].reshape(b, T, R, 14).astype(dtype),
                ta["torsion_angles_mask"].reshape(b, T, R, 7).astype(dtype),
            ],
            axis=-1,
        )
        template_rows = _lin(params["template_single_embedding"], angle_feat)
        template_rows = _lin(
            params["template_projection"], jax.nn.relu(template_rows)
        )
        msa_act = jnp.concatenate([msa_act, template_rows], axis=1)
        template_row_mask = jnp.broadcast_to(
            batch["template_mask"][:, :, None].astype(dtype), (b, T, R)
        )
        msa_mask = jnp.concatenate([msa_mask, template_row_mask], axis=1)

    # main Evoformer (Alg. 2 lines 17-18)
    msa_act, pair_act = evo.forward(
        params["evoformer"],
        msa_act,
        pair_act,
        msa_mask,
        mask_2d,
        cfg.evoformer_cfg,
        ctx=ctx,
        dropout_key=keys.get("evo"),
        train=train,
    )
    single = _lin(params["single_activations"], msa_act[:, 0])

    # structure module + heads
    sm = struct.structure_module(
        params["structure"],
        single,
        pair_act,
        seq_mask,
        cfg.structure,
        ctx=ctx,
        dropout_key=keys.get("structure"),
        train=train,
    )
    plddt_act = layer_norm(
        sm["act"], params["plddt_head"]["ln"]["scale"], params["plddt_head"]["ln"]["bias"]
    )
    plddt_logits = _lin(
        params["plddt_head"]["fc2"],
        jax.nn.relu(_lin(params["plddt_head"]["fc1"], plddt_act)),
    )
    # distogram over the symmetrized pair representation
    disto_logits = _lin(params["distogram_head"], pair_act + jnp.swapaxes(pair_act, 1, 2))

    return {
        "msa": msa_act,
        "pair": pair_act,
        "single": single,
        "masked_msa_logits": _lin(params["masked_msa_head"], msa_act),
        "distogram_logits": disto_logits,
        "plddt_logits": plddt_logits,
        "structure": sm,
    }


# ---------------------------------------------------------------------------
# Targets + loss
# ---------------------------------------------------------------------------


def _softmax_ce(logits, labels_onehot, mask):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ce = -jnp.sum(labels_onehot * logp, axis=-1)
    return jnp.sum(ce * mask) / (jnp.sum(mask) + 1e-8)


def lddt(pred_ca, true_ca, mask, cutoff=15.0):
    """Per-residue lDDT of predicted CA positions (standard 0.5/1/2/4 A
    thresholds), used as the pLDDT head's target."""
    dp = jnp.sqrt(
        jnp.sum((pred_ca[:, :, None] - pred_ca[:, None, :]) ** 2, -1) + 1e-10
    )
    dt = jnp.sqrt(
        jnp.sum((true_ca[:, :, None] - true_ca[:, None, :]) ** 2, -1) + 1e-10
    )
    pair_mask = (
        (dt < cutoff)
        * mask[:, :, None]
        * mask[:, None, :]
        * (1.0 - jnp.eye(mask.shape[-1])[None])
    )
    dl = jnp.abs(dp - dt)
    score = 0.25 * sum((dl < t).astype(jnp.float32) for t in (0.5, 1.0, 2.0, 4.0))
    return jnp.sum(score * pair_mask, axis=-1) / (jnp.sum(pair_mask, axis=-1) + 1e-8)


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: FoldingConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = True,
) -> jax.Array:
    """Weighted multi-task loss: masked-MSA CE + distogram CE + backbone
    FAPE + torsion + pLDDT CE (AlphaFold loss composition)."""
    out = forward(
        params, batch, cfg, ctx=ctx, dropout_key=dropout_key, train=train
    )
    total = jnp.zeros((), jnp.float32)

    # masked MSA (BERT-style over the 23 classes)
    if "true_msa" in batch and "bert_mask" in batch:
        labels = jax.nn.one_hot(batch["true_msa"], MASKED_MSA_CLASSES)
        S = labels.shape[1]
        total = total + cfg.masked_msa_weight * _softmax_ce(
            out["masked_msa_logits"][:, :S], labels, batch["bert_mask"].astype(jnp.float32)
        )

    seq_mask = batch["seq_mask"].astype(jnp.float32)
    pos = batch["all_atom_positions"]
    am = batch["all_atom_mask"]

    # distogram vs true pseudo-beta distances
    beta, beta_mask = pseudo_beta_fn(batch["aatype"], pos, am)
    sq_breaks = jnp.linspace(
        cfg.distogram_first_break, cfg.distogram_last_break, cfg.distogram_bins - 1
    ) ** 2
    d2 = jnp.sum((beta[:, :, None] - beta[:, None, :]) ** 2, axis=-1, keepdims=True)
    true_bins = jnp.sum(d2 > sq_breaks, axis=-1)
    disto_labels = jax.nn.one_hot(true_bins, cfg.distogram_bins)
    pair_mask = beta_mask[:, :, None] * beta_mask[:, None, :]
    total = total + cfg.distogram_weight * _softmax_ce(
        out["distogram_logits"], disto_labels, pair_mask.astype(jnp.float32)
    )

    # backbone FAPE vs frames built from true N/CA/C
    gt_rot, gt_trans = rigid.rigids_from_3_points(
        pos[..., ATOM_N, :], pos[..., ATOM_CA, :], pos[..., ATOM_C, :]
    )
    gt_quat = rigid.rot_to_quat(gt_rot)
    bb_mask = am[..., ATOM_N] * am[..., ATOM_CA] * am[..., ATOM_C] * seq_mask
    sm = out["structure"]
    total = total + cfg.fape_weight * struct.backbone_fape_loss(
        sm["traj_quat"], sm["traj_trans"], gt_quat, gt_trans, bb_mask
    )

    # torsion supervision from the true all-atom coordinates
    ta = all_atom.atom37_to_torsion_angles(batch["aatype"], pos, am)
    total = total + cfg.torsion_weight * struct.torsion_angle_loss(
        sm["torsions"],
        ta["torsion_angles_sin_cos"],
        ta["alt_torsion_angles_sin_cos"],
        ta["torsion_angles_mask"] * seq_mask[..., None],
    )

    # pLDDT head CE against the computed per-residue lDDT
    lddt_target = jax.lax.stop_gradient(
        lddt(sm["final_trans"], pos[..., ATOM_CA, :], bb_mask)
    )
    bins = jnp.clip(
        (lddt_target * cfg.plddt_bins).astype(jnp.int32), 0, cfg.plddt_bins - 1
    )
    total = total + cfg.plddt_weight * _softmax_ce(
        out["plddt_logits"], jax.nn.one_hot(bins, cfg.plddt_bins), bb_mask
    )
    return total
