"""Structure module: invariant point attention + backbone updates + losses.

The reference repo ships only the geometry utilities for this stage
(quat_affine.py, r3.py, all_atom.py) — its README defers the actual
structure module to the upstream HelixFold app.  This module completes the
stack the TPU-native way (AlphaFold2, Jumper et al. 2021, Suppl. Alg.
20-23 "StructureModule" / "InvariantPointAttention", Alg. 27 "torsion
head", Alg. 28 FAPE):

* :func:`invariant_point_attention` — IPA over the single representation
  with pair bias and SE(3)-invariant point terms (queries/keys/values as
  3D points carried through each residue's rigid frame).
* :func:`fold_iteration` — IPA residual + LN + transition + quaternion
  ``pre_compose`` backbone update (rigid.py), torsion-angle resnet head.
* :func:`structure_module` — 8 shared-weight iterations from the
  Evoformer single/pair representations; returns per-iteration backbone
  frames (for intermediate FAPE supervision), final frames, torsions and
  decoded backbone atom37 coordinates (N, CA, C, O, CB from ideal local
  geometry — full sidechain rigid groups documented out of scope).
* :func:`backbone_fape_loss`, :func:`torsion_angle_loss` — training
  losses over rigid.frame_aligned_point_error / predicted torsions.

All functions are batched, jit/scan-friendly, and take the standard
``ShardingCtx`` for mesh execution (the single/pair tracks keep their
Evoformer shardings; IPA is residue-local + attention so GSPMD handles
DAP layouts unchanged).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlefleetx_tpu.models.common import (
    ParamSpec,
    init_params,
    normal_init,
    ones_init,
    zeros_init,
)
from paddlefleetx_tpu.models.gpt.model import ShardingCtx, layer_norm
from paddlefleetx_tpu.models.protein import residue_constants as rc
from paddlefleetx_tpu.models.protein import rigid

_W = normal_init(0.02)


@dataclasses.dataclass(frozen=True)
class StructureConfig:
    single_channel: int = 384
    pair_channel: int = 128
    num_iterations: int = 8
    num_heads: int = 12
    scalar_qk: int = 16
    scalar_v: int = 16
    point_qk: int = 4
    point_v: int = 8
    num_transition_layers: int = 3
    torsion_channel: int = 128
    position_scale: float = 10.0
    dropout_rate: float = 0.1

    @classmethod
    def from_config(cls, d: Dict[str, Any]) -> "StructureConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def _ln(c):
    return {"scale": ParamSpec((c,), ("embed",), ones_init()),
            "bias": ParamSpec((c,), ("embed",), zeros_init())}


def structure_specs(cfg: StructureConfig) -> Dict[str, Any]:
    cs, cz, h = cfg.single_channel, cfg.pair_channel, cfg.num_heads
    ipa = {
        "q_scalar": ParamSpec((cs, h, cfg.scalar_qk), ("embed", "heads", "kv"), _W),
        "kv_scalar": ParamSpec((cs, h, cfg.scalar_qk + cfg.scalar_v), ("embed", "heads", "kv"), _W),
        "q_point": ParamSpec((cs, h, cfg.point_qk, 3), ("embed", "heads", None, None), _W),
        "kv_point": ParamSpec((cs, h, cfg.point_qk + cfg.point_v, 3), ("embed", "heads", None, None), _W),
        "pair_bias": ParamSpec((cz, h), ("embed", "heads"), _W),
        # learned per-head softplus weights for the point term
        "point_weights": ParamSpec((h,), ("heads",), ones_init()),
        "out": ParamSpec(
            (h * (cfg.scalar_v + cfg.point_v * 4 + cz), cs), ("mlp", "embed"), zeros_init()
        ),
        "out_b": ParamSpec((cs,), ("embed",), zeros_init()),
    }
    transition = {
        f"fc{i}": ParamSpec((cs, cs), ("embed", "mlp"), _W if i < cfg.num_transition_layers - 1 else zeros_init())
        for i in range(cfg.num_transition_layers)
    }
    transition.update({
        f"fc{i}_b": ParamSpec((cs,), ("mlp",), zeros_init())
        for i in range(cfg.num_transition_layers)
    })
    ct = cfg.torsion_channel
    return {
        "single_ln": _ln(cs),
        "pair_ln": _ln(cz),
        "initial_proj": ParamSpec((cs, cs), ("embed", "mlp"), _W),
        "ipa": ipa,
        "ipa_ln": _ln(cs),
        "transition": transition,
        "transition_ln": _ln(cs),
        "affine_update": ParamSpec((cs, 6), ("embed", None), zeros_init()),
        "affine_update_b": ParamSpec((6,), (None,), zeros_init()),
        "torsion": {
            "in1": ParamSpec((cs, ct), ("embed", "mlp"), _W),
            "in2": ParamSpec((cs, ct), ("embed", "mlp"), _W),
            "res1": ParamSpec((ct, ct), ("embed", "mlp"), _W),
            "res1_b": ParamSpec((ct,), ("mlp",), zeros_init()),
            "res2": ParamSpec((ct, ct), ("mlp", "embed"), zeros_init()),
            "res2_b": ParamSpec((ct,), ("embed",), zeros_init()),
            "out": ParamSpec((ct, 14), ("embed", None), _W),
            "out_b": ParamSpec((14,), (None,), zeros_init()),
        },
    }


def init(cfg: StructureConfig, key: jax.Array) -> Dict[str, Any]:
    return init_params(key, structure_specs(cfg))


# ---------------------------------------------------------------------------
# IPA
# ---------------------------------------------------------------------------


def invariant_point_attention(
    p: Dict[str, Any],
    single: jax.Array,  # [b, R, cs]
    pair: jax.Array,  # [b, R, R, cz]
    frames: rigid.Rigid,  # rot [b, R, 3, 3], trans [b, R, 3]
    mask: jax.Array,  # [b, R]
    cfg: StructureConfig,
) -> jax.Array:
    """Alg. 22: scalar attention + pair bias + SE(3)-invariant point
    attention; output concatenates scalar values, point values (in the
    local frame, with norms) and attended pair features."""
    dtype = single.dtype
    h, pqk, pv = cfg.num_heads, cfg.point_qk, cfg.point_v

    q_s = jnp.einsum("brc,chd->brhd", single, p["q_scalar"].astype(dtype))
    kv_s = jnp.einsum("brc,chd->brhd", single, p["kv_scalar"].astype(dtype))
    k_s, v_s = kv_s[..., : cfg.scalar_qk], kv_s[..., cfg.scalar_qk:]

    q_p_local = jnp.einsum("brc,chpx->brhpx", single, p["q_point"].astype(dtype))
    kv_p_local = jnp.einsum("brc,chpx->brhpx", single, p["kv_point"].astype(dtype))
    rot, trans = frames
    def to_global(pts):
        return (
            jnp.einsum("brij,brhpj->brhpi", rot.astype(dtype), pts)
            + trans.astype(dtype)[:, :, None, None, :]
        )
    q_p = to_global(q_p_local)
    kv_p = to_global(kv_p_local)
    k_p, v_p = kv_p[..., :pqk, :], kv_p[..., pqk:, :]

    # scalar logits
    wc = jnp.sqrt(2.0 / (9.0 * pqk))
    wl = jnp.sqrt(1.0 / 3.0)
    scalar_logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q_s, k_s, preferred_element_type=jnp.float32
    ) * (cfg.scalar_qk ** -0.5) * wl
    # point logits: -gamma * sum_p |q_i - k_j|^2 / 2
    d2 = jnp.sum(
        (q_p[:, :, None, :, :, :] - k_p[:, None, :, :, :, :]) ** 2, axis=-1
    )  # [b, q, k, h, p]
    gamma = jax.nn.softplus(p["point_weights"]).astype(jnp.float32)
    point_logits = -0.5 * wc * wl * gamma[None, None, None, :] * jnp.sum(
        d2.astype(jnp.float32), axis=-1
    )
    point_logits = jnp.moveaxis(point_logits, -1, 1)  # [b, h, q, k]
    pair_logits = jnp.einsum(
        "bqkc,ch->bhqk", pair.astype(jnp.float32), p["pair_bias"].astype(jnp.float32)
    ) * wl
    mask_bias = (1.0 - mask[:, None, None, :].astype(jnp.float32)) * -1e9
    logits = scalar_logits + point_logits + pair_logits + mask_bias
    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)  # [b, h, q, k]

    out_s = jnp.einsum("bhqk,bkhd->bqhd", probs, v_s)
    out_p_global = jnp.einsum("bhqk,bkhpx->bqhpx", probs, v_p)
    # back into the query's local frame (invariance)
    inv_rot = jnp.swapaxes(rot, -1, -2).astype(dtype)
    out_p = jnp.einsum(
        "brij,brhpj->brhpi", inv_rot,
        out_p_global - trans.astype(dtype)[:, :, None, None, :],
    )
    out_p_norm = jnp.sqrt(jnp.sum(out_p**2, axis=-1, keepdims=True) + 1e-8)
    # attended pair features: sum_k a_qk * z[q, k] (Alg. 22 line 11)
    out_pair = jnp.einsum("bhqk,bqkc->bqhc", probs, pair)

    b, R = single.shape[:2]
    flat = jnp.concatenate(
        [
            out_s.reshape(b, R, -1),
            out_p.reshape(b, R, -1),
            out_p_norm.reshape(b, R, -1),
            out_pair.reshape(b, R, -1),
        ],
        axis=-1,
    )
    return flat @ p["out"].astype(dtype) + p["out_b"].astype(dtype)


# ---------------------------------------------------------------------------
# Fold iteration / structure module
# ---------------------------------------------------------------------------


def _transition(p, x, n_layers):
    for i in range(n_layers):
        x = x @ p[f"fc{i}"] + p[f"fc{i}_b"]
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    return x


def _torsion_head(p, act, initial_act):
    """Alg. 27 resnet: 7 torsions as unnormalized (sin, cos)."""
    x = jax.nn.relu(act) @ p["in1"] + jax.nn.relu(initial_act) @ p["in2"]
    r = jax.nn.relu(x) @ p["res1"] + p["res1_b"]
    x = x + (jax.nn.relu(r) @ p["res2"] + p["res2_b"])
    out = jax.nn.relu(x) @ p["out"] + p["out_b"]
    return out.reshape(out.shape[:-1] + (7, 2))


def fold_iteration(
    params: Dict[str, Any],
    act: jax.Array,
    initial_act: jax.Array,
    pair: jax.Array,
    quat: jax.Array,
    trans: jax.Array,
    mask: jax.Array,
    cfg: StructureConfig,
    key: Optional[jax.Array],
    train: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One Alg. 20 iteration; returns (act, quat, trans, torsions)."""
    frames = (rigid.quat_to_rot(quat), trans)
    act = act + invariant_point_attention(params["ipa"], act, pair, frames, mask, cfg)
    if train and key is not None and cfg.dropout_rate > 0:
        keep = 1.0 - cfg.dropout_rate
        act = jnp.where(
            jax.random.bernoulli(key, keep, act.shape), act / keep, 0.0
        ).astype(act.dtype)
    act = layer_norm(act, params["ipa_ln"]["scale"], params["ipa_ln"]["bias"])
    act = act + _transition(params["transition"], act, cfg.num_transition_layers)
    act = layer_norm(
        act, params["transition_ln"]["scale"], params["transition_ln"]["bias"]
    )
    update = act @ params["affine_update"] + params["affine_update_b"]  # [b,R,6]
    quat, trans = rigid.pre_compose(quat, trans, update)
    torsions = _torsion_head(params["torsion"], act, initial_act)
    return act, quat, trans, torsions


def backbone_atoms(quat: jax.Array, trans: jax.Array) -> jax.Array:
    """Decode N/CA/C/CB/O atom positions from backbone frames using ideal
    local geometry -> [b, R, 5, 3] in atom37 order (N, CA, C, CB, O)."""
    rot = rigid.quat_to_rot(quat)
    local = jnp.stack(
        [
            jnp.asarray(rc.IDEAL_N),
            jnp.asarray(rc.IDEAL_CA),
            jnp.asarray(rc.IDEAL_C),
            jnp.asarray(rc.IDEAL_CB),
            jnp.asarray(rc.IDEAL_O),
        ]
    )  # [5, 3]
    return (
        jnp.einsum("brij,aj->brai", rot, local) + trans[..., None, :]
    )


def structure_module(
    params: Dict[str, Any],
    single: jax.Array,  # [b, R, cs] (evoformer single activations)
    pair: jax.Array,  # [b, R, R, cz]
    seq_mask: jax.Array,  # [b, R]
    cfg: StructureConfig,
    *,
    ctx: Optional[ShardingCtx] = None,
    dropout_key: Optional[jax.Array] = None,
    train: bool = False,
) -> Dict[str, jax.Array]:
    """Alg. 20: 8 shared-weight fold iterations from identity frames.

    Returns dict with 'frames' (per-iteration quats/trans for intermediate
    FAPE), 'final_quat'/'final_trans' (position_scale applied), 'torsions',
    'backbone_atoms', 'act' (for the pLDDT head)."""
    single = layer_norm(single, params["single_ln"]["scale"], params["single_ln"]["bias"])
    pair = layer_norm(pair, params["pair_ln"]["scale"], params["pair_ln"]["bias"])
    initial_act = single
    act = single @ params["initial_proj"]

    b, R = single.shape[:2]
    quat = jnp.broadcast_to(
        jnp.array([1.0, 0.0, 0.0, 0.0], single.dtype), (b, R, 4)
    )
    trans = jnp.zeros((b, R, 3), single.dtype)

    quats, transs, torsions = [], [], None
    for it in range(cfg.num_iterations):  # shared weights (Alg. 20 line 5)
        k = (
            jax.random.fold_in(dropout_key, it)
            if dropout_key is not None
            else None
        )
        act, quat, trans, torsions = fold_iteration(
            params, act, initial_act, pair, quat, trans, seq_mask, cfg, k, train
        )
        quats.append(quat)
        transs.append(trans)
        # stop rotation gradients between iterations (AlphaFold
        # stop_rot_gradient: stabilizes early training)
        quat = jax.lax.stop_gradient(quat)

    scale = cfg.position_scale
    return {
        "traj_quat": jnp.stack(quats, axis=0),  # [iters, b, R, 4]
        "traj_trans": jnp.stack(transs, axis=0) * scale,
        "final_quat": quats[-1],
        "final_trans": transs[-1] * scale,
        "torsions": torsions,
        "backbone_atoms": backbone_atoms(quats[-1], transs[-1] * scale),
        "act": act,
    }


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------


def backbone_fape_loss(
    traj_quat: jax.Array,  # [iters, b, R, 4]
    traj_trans: jax.Array,  # [iters, b, R, 3]
    target_quat: jax.Array,  # [b, R, 4]
    target_trans: jax.Array,  # [b, R, 3]
    mask: jax.Array,  # [b, R]
    length_scale: float = 10.0,
    clamp_distance: float = 10.0,
) -> jax.Array:
    """Averaged-over-iterations backbone FAPE (Alg. 28 applied to CA
    points viewed from every backbone frame), masked."""
    t_rot = rigid.quat_to_rot(target_quat)

    def one(args):
        q, t = args
        rot = rigid.quat_to_rot(q)
        # local views: [b, F, P, 3]
        def local(rot_, tr_):
            return rigid.rot_mul_vec(
                jnp.swapaxes(rot_, -1, -2)[..., :, None, :, :],
                tr_[..., None, :, :] - tr_[..., :, None, :],
            )

        d = jnp.sqrt(
            jnp.sum((local(rot, t) - local(t_rot, target_trans)) ** 2, axis=-1)
            + 1e-8
        )
        m2 = mask[..., :, None] * mask[..., None, :]
        d = jnp.clip(d, 0.0, clamp_distance) * m2
        return jnp.sum(d) / (length_scale * (jnp.sum(m2) + 1e-8))

    losses = jax.lax.map(one, (traj_quat, traj_trans))
    return jnp.mean(losses)


def torsion_angle_loss(
    pred: jax.Array,  # [b, R, 7, 2] unnormalized sin/cos
    target: jax.Array,  # [b, R, 7, 2]
    alt_target: jax.Array,  # [b, R, 7, 2]
    mask: jax.Array,  # [b, R, 7]
) -> jax.Array:
    """Alg. 27 supervised chi loss: min over the pi-periodic alternative,
    plus the unit-norm regularizer."""
    norm = jnp.sqrt(jnp.sum(pred**2, axis=-1, keepdims=True) + 1e-8)
    pred_unit = pred / norm
    sq = jnp.sum((pred_unit - target) ** 2, axis=-1)
    sq_alt = jnp.sum((pred_unit - alt_target) ** 2, axis=-1)
    chi = jnp.minimum(sq, sq_alt) * mask
    l_chi = jnp.sum(chi) / (jnp.sum(mask) + 1e-8)
    l_norm = jnp.sum(jnp.abs(norm[..., 0] - 1.0) * mask) / (jnp.sum(mask) + 1e-8)
    return l_chi + 0.02 * l_norm
