"""LR schedules (reference ppfleetx/optims/lr_scheduler.py:31-192).

Schedules are pure functions ``step -> lr`` (optax convention).  The
reference's ``use_increments`` token-based stepping
(CosineAnnealingWithWarmupDecay steps by global_batch_size each iteration,
eager_engine.py:354-357) maps to passing ``num_tokens`` processed as the
schedule argument; the engine chooses the counter.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from paddlefleetx_tpu.utils.registry import LR_SCHEDULERS

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


@LR_SCHEDULERS.register("CosineAnnealingWithWarmupDecay")
def cosine_annealing_with_warmup_decay(
    max_lr: float,
    min_lr: float,
    warmup_rate: Optional[float] = None,
    decay_steps: int = 0,
    warmup_steps: Optional[int] = None,
    **_unused,
) -> Schedule:
    """Megatron-style: linear warmup to max_lr, cosine decay to min_lr
    (reference lr_scheduler.py:31-74).  ``warmup_rate`` is the fraction of
    decay_steps spent warming up (reference passes warmup_rate*decay_steps)."""
    if warmup_steps is None:
        warmup_steps = int((warmup_rate or 0.0) * decay_steps)

    def schedule(count):
        count = jnp.asarray(count, jnp.float32)
        warm = max_lr * count / jnp.maximum(warmup_steps, 1)
        frac = jnp.clip(
            (count - warmup_steps) / jnp.maximum(decay_steps - warmup_steps, 1), 0.0, 1.0
        )
        cos = min_lr + 0.5 * (max_lr - min_lr) * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(count < warmup_steps, warm, cos)

    return schedule


@LR_SCHEDULERS.register("LinearDecayWithWarmup")
def linear_decay_with_warmup(
    learning_rate: float,
    total_steps: int,
    warmup: float = 0.1,
    **_unused,
) -> Schedule:
    """Linear warmup then linear decay to 0 (reference lr_scheduler.py:77)."""
    warmup_steps = int(warmup * total_steps) if warmup < 1 else int(warmup)

    def schedule(count):
        count = jnp.asarray(count, jnp.float32)
        warm = learning_rate * count / jnp.maximum(warmup_steps, 1)
        decay = learning_rate * jnp.maximum(
            (total_steps - count) / jnp.maximum(total_steps - warmup_steps, 1), 0.0
        )
        return jnp.where(count < warmup_steps, warm, decay)

    return schedule


@LR_SCHEDULERS.register("ViTLRScheduler")
def vit_lr_scheduler(
    learning_rate: float,
    total_steps: int = 0,
    warmup_steps: int = 0,
    decay_type: str = "cosine",
    linear_end: float = 1e-5,
    **_unused,
) -> Schedule:
    """ViT schedule (reference lr_scheduler.py:103): warmup + cosine/linear."""

    def schedule(count):
        count = jnp.asarray(count, jnp.float32)
        warm = learning_rate * count / jnp.maximum(warmup_steps, 1)
        frac = jnp.clip(
            (count - warmup_steps) / jnp.maximum(total_steps - warmup_steps, 1), 0.0, 1.0
        )
        if decay_type == "cosine":
            main = linear_end + 0.5 * (learning_rate - linear_end) * (
                1.0 + jnp.cos(jnp.pi * frac)
            )
        else:
            main = learning_rate + (linear_end - learning_rate) * frac
        return jnp.where(count < warmup_steps, warm, main)

    return schedule


@LR_SCHEDULERS.register("MultiStepDecay")
def multi_step_decay(
    learning_rate: float, milestones=(30, 60, 90), gamma: float = 0.1, **_unused
) -> Schedule:
    """Step decay at milestones (reference lr_scheduler.py:144)."""
    ms = jnp.asarray(sorted(milestones), jnp.float32)

    def schedule(count):
        count = jnp.asarray(count, jnp.float32)
        n = jnp.sum(count >= ms)
        return learning_rate * gamma**n

    return schedule


@LR_SCHEDULERS.register("CosineDecay")
def cosine_decay(learning_rate: float, total_steps: int, **_unused) -> Schedule:
    """Plain cosine to 0 (reference lr_scheduler.py:162)."""

    def schedule(count):
        frac = jnp.clip(jnp.asarray(count, jnp.float32) / max(total_steps, 1), 0.0, 1.0)
        return 0.5 * learning_rate * (1.0 + jnp.cos(jnp.pi * frac))

    return schedule


@LR_SCHEDULERS.register("Constant")
def constant(learning_rate: float, **_unused) -> Schedule:
    return lambda count: jnp.asarray(learning_rate, jnp.float32)


def build_lr_scheduler(cfg) -> Schedule:
    """From YAML ``Optimizer.lr`` block (reference optims/__init__.py:29)."""
    cfg = dict(cfg)
    name = cfg.pop("name")
    return LR_SCHEDULERS.get(name)(**cfg)
