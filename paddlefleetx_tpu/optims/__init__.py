"""Optimizers, LR schedules, grad clipping (reference ppfleetx/optims)."""
