"""Optimizers (reference ppfleetx/optims/optimizer.py + grad_clip.py).

``FusedAdamW`` (reference optimizer.py:31-56) = optax.adamw: XLA already
fuses the elementwise update chain across the flattened param pytree, which
is what the reference's tensor-fusion helper (utils/tensor_fusion_helper.py)
does manually with 256MB buckets.  Weight-decay exemption by name
(LayerNorm/bias, reference ``multi_precision`` decay-param partition) is a
mask over the param tree.

ZeRO optimizer-state sharding (reference group_sharded_parallel) is NOT done
here: optimizer states inherit param shardings under pjit; the `fsdp` axis
rules in parallel.sharding decide the partitioning.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from paddlefleetx_tpu.optims.lr_scheduler import Schedule, build_lr_scheduler
from paddlefleetx_tpu.utils.registry import OPTIMIZERS


def _no_decay_mask(params: Any) -> Any:
    """True where weight decay applies: skip 1-D params (biases, LN scales)
    — same partition the reference computes by name suffix."""
    return jax.tree.map(lambda p: p.ndim > 1, params)


def sqsum_f32(x):
    """Sum of squares of one leaf, accumulated in fp32 — THE shared
    reduction rule under both the global grad norm below and the
    per-layer-group statistics (utils/model_stats.py), so the grouped
    and global norms can never disagree on accumulation dtype."""
    return jnp.sum(jnp.square(x.astype(jnp.float32)))


def global_norm_f32(tree: Any):
    """Global L2 norm with the sum-of-squares accumulated in fp32.

    optax.global_norm reduces each leaf in its own dtype; with bf16 grads
    (``mix_precision.main_grad: False``) an 8-mantissa-bit running sum over
    1e8+ elements is garbage.  The convert sits inside the reduction, so
    XLA fuses it — no fp32 copy of any leaf is materialized."""
    leaves = [x for x in jax.tree.leaves(tree) if x is not None]
    return jnp.sqrt(sum(sqsum_f32(x) for x in leaves))


def clip_by_global_norm_f32(clip_norm: float) -> optax.GradientTransformation:
    """Drop-in for optax.clip_by_global_norm with the norm in fp32 (exact
    for fp32 grads, *correct* for bf16 grads; reference ClipGradByGlobalNorm
    always computed the norm on fp32 main grads so never hit this)."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        g_norm = global_norm_f32(updates)
        scale = jnp.minimum(1.0, clip_norm / jnp.maximum(g_norm, 1e-16))
        updates = jax.tree.map(
            lambda u: (u.astype(jnp.float32) * scale).astype(u.dtype), updates
        )
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


@OPTIMIZERS.register("AdamW")
@OPTIMIZERS.register("FusedAdamW")
def adamw(
    schedule: Schedule,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8,
    weight_decay: float = 0.01,
    grad_clip: Optional[float] = None,
    multi_precision: bool = True,
    moment_dtype: Optional[str] = None,
    **_unused,
) -> optax.GradientTransformation:
    """``moment_dtype: bfloat16`` stores the FIRST moment in bf16 (optax
    mu_dtype), freeing one param-size fp32 buffer of HBM — the lever that
    fits 1.3B-class models on a 16GB chip.  With fp32 masters
    (multi_precision=True, the default) the second moment stays fp32;
    under ``Optimizer.multi_precision: False`` optax inits both moments
    from the bf16 params, so nu is bf16 too — that full-bf16 trade is the
    1.3B single-chip recipe (BENCH_NOTE.md) and is engine-gated to
    bfloat16 compute (fp16 nu would underflow)."""
    txs = []
    if grad_clip:
        txs.append(clip_by_global_norm_f32(grad_clip))
    txs.append(
        optax.adamw(
            learning_rate=schedule,
            b1=beta1,
            b2=beta2,
            eps=epsilon,
            weight_decay=weight_decay,
            mask=_no_decay_mask,
            mu_dtype=moment_dtype or None,
        )
    )
    return optax.chain(*txs)


@OPTIMIZERS.register("Adam")
def adam(
    schedule: Schedule,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8,
    grad_clip: Optional[float] = None,
    **_unused,
) -> optax.GradientTransformation:
    txs = []
    if grad_clip:
        txs.append(clip_by_global_norm_f32(grad_clip))
    txs.append(optax.adam(learning_rate=schedule, b1=beta1, b2=beta2, eps=epsilon))
    return optax.chain(*txs)


@OPTIMIZERS.register("Momentum")
def momentum(
    schedule: Schedule,
    momentum: float = 0.9,
    weight_decay: float = 0.0,
    grad_clip: Optional[float] = None,
    **_unused,
) -> optax.GradientTransformation:
    txs = []
    if grad_clip:
        txs.append(clip_by_global_norm_f32(grad_clip))
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay, mask=_no_decay_mask))
    txs.append(optax.sgd(learning_rate=schedule, momentum=momentum))
    return optax.chain(*txs)


def build_optimizer(cfg, count_scale: int = 1) -> tuple[optax.GradientTransformation, Schedule]:
    """From the YAML ``Optimizer`` block (reference optims/__init__.py:29-74):

    Optimizer:
      name: FusedAdamW
      weight_decay: 0.01
      beta1/beta2/epsilon: ...
      lr: {name: CosineAnnealingWithWarmupDecay, ..., use_increments: True}
      grad_clip: {name: ClipGradByGlobalNorm, clip_norm: 1.0}

    ``use_increments`` (reference lr_scheduler.py:31-74 + eager_engine.py:
    354-357): the schedule counts *samples*, not steps — the caller passes
    ``count_scale=global_batch_size`` and the schedule optax applies is
    ``schedule(step * count_scale)``.
    """
    cfg = dict(cfg)
    name = cfg.pop("name")
    lr_cfg = dict(cfg.pop("lr", {"name": "Constant", "learning_rate": 1e-4}))
    use_increments = bool(lr_cfg.pop("use_increments", False))
    base_schedule = build_lr_scheduler(lr_cfg)
    if use_increments and count_scale != 1:
        schedule: Schedule = lambda count: base_schedule(count * count_scale)
    else:
        schedule = base_schedule
    clip_cfg = cfg.pop("grad_clip", None) or {}
    if isinstance(clip_cfg, (int, float)):
        # shorthand: `grad_clip: 1.0` == global-norm clip at that norm
        clip_cfg = {"name": "ClipGradByGlobalNorm", "clip_norm": float(clip_cfg)}
    clip_norm = clip_cfg.get("clip_norm") if clip_cfg.get("name") != "None" else None
    tx = OPTIMIZERS.get(name)(schedule=schedule, grad_clip=clip_norm, **cfg)
    return tx, schedule
