"""Continuous batching: iteration-level scheduling over a paged KV cache.

The PR 3 coalescer (`core/request_queue.py`) merges requests that happen
to be WAITING together — a request arriving one token after a decode
started waits the entire decode (head-of-line blocking).  Orca's
iteration-level scheduling (Yu et al., OSDI 2022) fixes that by making
the decode STEP the scheduling unit: at every step boundary the running
batch can admit new rows (prefill-on-admit) and retire finished or shed
ones.  vLLM's PagedAttention (Kwon et al., SOSP 2023) supplies the
memory model that makes mid-flight membership cheap: each row owns a
block table into a shared arena (`core/paged_cache.py`), so admission
allocates blocks, eviction frees them, and no row pays another row's
length.

Two layers here:

  - :class:`PagedDecodeEngine` — the device side: owns the arena
    (`PagedPools`), the per-slot row state, and the compiled
    (prefill, step) functions.  ONE fixed-shape step per
    (batch capacity, table-width bucket): batch capacity is static,
    table width buckets to the next power of two of the widest active
    row's allocation (which only changes at admit/evict), so the
    retrace count is bounded by the bucket count and counted in
    ``stats["traces"]`` exactly like `core/serving.py`.
  - :class:`ContinuousScheduler` — the host side: the same admission
    surface as :class:`~paddlefleetx_tpu.core.request_queue.RequestQueue`
    (bounded ``submit`` -> 429/503, deadlines, ``try_remove``, graceful
    ``close``/``join`` drain, ``busy_seconds`` wedge probe) so
    `tools/serve.py` swaps schedulers behind ``--scheduler`` without
    touching the HTTP layer.  Its loop runs one iteration per decode
    step: shed expired waiting entries, EVICT expired active rows
    (mid-decode — their blocks return to the pool immediately), admit
    from the queue head while slots and blocks allow, then step.

Observability (docs/observability.md): the scheduler appends one
structured row per iteration to a bounded **decision log** (admissions,
evictions, sheds, block/width-bucket state, spec proposed/accepted
deltas — replaying an untruncated log reproduces
pfx_prefill_admits_total / pfx_request_evictions_total /
pfx_spec_accepted_total EXACTLY via `utils/tracing.replay_decision_log`;
shed rows cover scheduler-side sheds, while a handler-side
``try_remove`` shed lands between iterations and only in the counter),
stamps sampled
per-request trace contexts (admission → prefill → per-chunk decode),
and publishes a read-only ``debug_state()`` snapshot (queue ages,
per-row positions/budgets, arena occupancy, compile-key families) that
`tools/serve.py` exposes as ``GET /debug/state`` without ever blocking
this thread.

Dispatch-ahead decode (docs/decode_path.md): with
``PFX_DISPATCH_AHEAD=1`` (the scheduler default) the engine leaves each
dispatched step IN FLIGHT and fetches its sampled tokens one call
later, chaining the next dispatch on device-resident row state — the
host's scheduling work (and the ``PFX_SCHED_QUANTUM``-amortized
admission/eviction scans) runs in the device's shadow instead of on the
decode critical path.  Committed tokens can stream to a per-request
sink as they land (``submit(..., stream=...)``).  Decision-log rows
account every event in COMMIT order, so ``replay_decision_log`` folds
to identical totals with overlap on or off; ``PFX_DISPATCH_AHEAD=0`` is
the loud fallback to fully-synchronous stepping.

Greedy outputs are token-identical to the sequential/coalesced path
(same logits-processor chain per row, per-row positions equal to the
contiguous path's real-token positions); sampling rows draw from a
per-step engine subkey — deterministic, but a different stream than the
contiguous path's.  Every PR 2/3 contract holds: admission bounds,
deadline shed (now also MID-decode via eviction), graceful drain, and
drop-donated-state-on-error (a step failure resets the arena rather
than ever reusing donation-invalidated pools).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlefleetx_tpu.core.paged_cache import (
    BlockPoolExhausted,
    NULL_BLOCK,
    PagedCacheManager,
    blocks_for,
    check_handoff_meta,
    kv_block_size,
)
from paddlefleetx_tpu.core.request_queue import (
    DeadlineExceeded,
    QueueClosed,
    QueueFull,
    RequestFuture,
)
from paddlefleetx_tpu.ops.decode_attention import kv_cache_dtype
from paddlefleetx_tpu.ops.speculative import SpecConfig, ngram_propose_host
from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.resilience import maybe_fire
from paddlefleetx_tpu.core.tenancy import (
    DEFAULT_TENANT,
    DeficitRoundRobin,
    TenantConfig,
    TenantLabelCap,
    normalize_tenant,
)
from paddlefleetx_tpu.utils.telemetry import StatsView, _env_int, get_registry
from paddlefleetx_tpu.utils.tracing import (
    attach_request_trace,
    discard_request_trace,
    get_trace_buffer,
)


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class ArenaReset(RuntimeError):
    """A donating dispatch failed and the arena was rebuilt: every row
    that was live died with it.  ``dead_rows`` lets the scheduler fail
    exactly the affected requests; the original failure is chained as
    ``__cause__``."""

    def __init__(self, msg: str, dead_rows: List["_Row"]) -> None:
        super().__init__(msg)
        self.dead_rows = dead_rows


@dataclasses.dataclass(eq=False)
class _Row:
    """One active decode row (slot) in the running batch."""

    seq_id: int
    entry: "_CBEntry"
    row_idx: int  # index into the entry's prompts
    prompt_len: int
    max_new: int
    table: List[int]
    tokens: List[int] = dataclasses.field(default_factory=list)
    # prompt ids kept host-side for the self-drafting n-gram lookup
    # (the speculative drafter reads prompt + tokens between steps)
    prompt_ids: List[int] = dataclasses.field(default_factory=list)
    # sampled deep-dive trace context (utils/tracing.py) or None: the
    # engine stamps prefill + per-chunk decode events onto it
    trace: Any = None
    # prefix reuse / chunked prefill (docs/serving.md): tokens matched
    # against the prefix index (their KV was mapped shared, never
    # recomputed), prompt tokens still to prefill, the per-row chunk
    # width its chunk compiles key on, and whether prefill finished
    # (only then is the row decode-active and its prefix publishable)
    prefix_hit: int = 0
    pending: List[int] = dataclasses.field(default_factory=list)
    prefill_pos: int = 0
    chunk: int = 0
    prefill_done: bool = True


@dataclasses.dataclass(eq=False)
class _CBEntry:
    """One admitted client request (1..n prompts, answered atomically)."""

    prompts: List[List[int]]
    max_new: int
    deadline: Optional[float]
    future: RequestFuture
    enqueued_at: float
    next_row: int = 0  # rows [0, next_row) admitted so far
    done_rows: int = 0
    results: List[Optional[List[int]]] = dataclasses.field(default_factory=list)
    # disaggregated serving: a (meta, arrays) KV-handoff payload instead
    # of a prompt to prefill — the admission loop ADOPTS the exported
    # blocks (engine.adopt) rather than running paged_prefill
    handoff: Optional[tuple] = None
    # token streaming (docs/serving.md): a callable
    # ``stream(row_idx, start, tokens)`` invoked on the SCHEDULER thread
    # as each step's commits land (start = index of tokens[0] in the
    # row's output so far).  Sinks must be fast and never raise into the
    # batch — the engine logs and drops a failing sink's push, the
    # tokens are already committed either way.
    stream: Optional[Any] = None
    # multi-tenant isolation (core/tenancy.py): the fair-share queue the
    # entry waits in and its priority class (higher may preempt lower)
    tenant: str = DEFAULT_TENANT
    priority: int = 0
    # preempt-resume state: tokens a preempted row had already committed
    # (row_idx -> tokens), and the row indices waiting to re-enter the
    # batch as re-prefill continuations.  The continuation's prompt is
    # ``prompts[row_idx] + row_prefill[row_idx]`` with the max_new
    # budget reduced by the committed count, so the resumed greedy
    # decode continues the undisturbed token stream exactly; results and
    # stream offsets are rebased onto the committed prefix below.
    row_prefill: Dict[int, List[int]] = dataclasses.field(default_factory=dict)
    requeue_rows: List[int] = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        self.results = [None] * len(self.prompts)

    def emit_stream(self, row_idx: int, start: int, tokens: List[int]) -> None:
        """Engine-side streaming hook: rebases ``start`` past any tokens
        this row streamed BEFORE a preemption, so SSE clients see one
        monotone token index across a preempt-resume."""
        base = len(self.row_prefill.get(row_idx, ()))
        self.stream(row_idx, base + start, tokens)

    def finished_tokens(self, row_idx: int, tokens: List[int]) -> List[int]:
        """The row's full output: preempt-committed prefix + the tokens
        decoded since the (last) resume."""
        pre = self.row_prefill.get(row_idx)
        return (pre + tokens) if pre else tokens


class PagedDecodeEngine:
    """Device-side continuous-batching engine over a GenerationServer's
    params/mesh/config.  Host code drives it one decode step at a time;
    all compiled shapes are bucketed and counted (``stats["traces"]``).

    The arena pools are DONATED through both compiled entry points
    (prefill writes blocks, the step writes one slot per row): any
    exception after a donating dispatch leaves the pools
    donation-invalidated, so :meth:`reset` rebuilds the arena and the
    caller fails the affected requests — never reuse a maybe-deleted
    buffer (the `core/serving.py` drop-on-error contract).
    """

    def __init__(self, server, *, max_batch: int = 8, block: int = 0,
                 num_blocks: int = 0, spec="auto", kv_dtype: str = "",
                 prefix_cache_blocks: int = 0,
                 prefill_chunk: int = 0,
                 prefix_spill_bytes: int = 0) -> None:
        from paddlefleetx_tpu.models.gpt.generation import init_paged_pools
        from paddlefleetx_tpu.parallel.mesh import data_parallel_world

        self.server = server
        self.mcfg = server.module.config
        self.gen = server.gen
        self.ctx = server.ctx
        self.mesh = server.mesh
        self.bucket = server.bucket
        self.block = kv_block_size(block)
        # speculation + KV quantization: default ("auto"/"") inherits the
        # server's ALREADY-PARSED Generation.speculative settings (ONE
        # parse site — core/serving.py — so both schedulers can never
        # drift apart on the same config); explicit args override (None
        # disables speculation)
        if spec == "auto":
            spec = server.spec
        if spec is not None and not isinstance(spec, SpecConfig):
            raise ValueError(f"spec must be a SpecConfig or None, got {spec!r}")
        self.spec = spec
        self.kv_dtype = (
            kv_cache_dtype(kv_dtype) if kv_dtype else server.kv_dtype
        )
        context = int(self.mcfg.max_position_embeddings)
        self.max_row_blocks = blocks_for(
            context + (self.spec.draft_k if self.spec else 0), self.block
        )
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        dpw = data_parallel_world(self.mesh)
        # fixed batch capacity (dp-world multiple): the step's batch dim
        # NEVER changes shape, so traffic mix cannot key batch retraces
        self.capacity = -(-int(max_batch) // dpw) * dpw
        if num_blocks <= 0:
            num_blocks = self.capacity * self.max_row_blocks + 1
        # shared-prefix KV reuse + chunked prefill (docs/serving.md):
        # prefix_cache_blocks > 0 lets finished rows publish their
        # prompt-prefix blocks into a radix index later admissions map
        # as SHARED (refcounted) table entries; prefill_chunk > 0
        # (block-multiple) streams long prompts in chunk-sized pieces,
        # one per scheduler iteration, interleaved with decode steps
        if prefix_cache_blocks < 0:
            raise ValueError(
                f"prefix_cache_blocks must be >= 0, got {prefix_cache_blocks}"
            )
        if prefill_chunk and (prefill_chunk < self.block
                              or prefill_chunk % self.block):
            raise ValueError(
                f"prefill_chunk {prefill_chunk} must be 0 or a positive "
                f"multiple of the KV block size {self.block}"
            )
        self.prefill_chunk = int(prefill_chunk)
        # host-RAM spill tier (docs/serving.md "KV lifecycle"): evicted
        # prefix blocks demote to a bounded host store and readmit on a
        # later match instead of recomputing.  Spilling without an index
        # to evict FROM is a config error, loudly
        if prefix_spill_bytes and not prefix_cache_blocks:
            raise ValueError(
                "prefix_spill_bytes requires prefix_cache_blocks > 0 "
                "(the spill tier shadows the radix index)"
            )
        self.cache = PagedCacheManager(
            num_blocks, self.block, prefix_blocks=prefix_cache_blocks,
            spill_bytes=prefix_spill_bytes,
        )
        if self.cache.spill.enabled:
            self.cache.prefix.spill_hook = self._spill_block
        self._spill_probes = 0
        self.pools = init_paged_pools(
            self.mcfg, num_blocks, self.block, kv_dtype=self.kv_dtype
        )

        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        self._jax = jax
        vocab = int(self.mcfg.vocab_size)
        B = self.capacity
        self._logits = jnp.zeros((B, vocab), jnp.float32)
        self._counts = jnp.zeros((B, vocab), jnp.int32)
        self._reject = jnp.full((B,), -1, jnp.int32)
        self.positions = np.zeros((B,), np.int32)
        self.gen_steps = np.zeros((B,), np.int32)
        self.max_news = np.zeros((B,), np.int32)
        self.forced_steps = np.zeros((B,), np.int32)
        self.active = np.zeros((B,), bool)
        self.slots: List[Optional[_Row]] = [None] * B
        self._seq_counter = 0
        self._compiled_step: Dict = {}
        self._compiled_prefill: Dict = {}
        self._compiled_adopt: Dict = {}
        self._compiled_chunk: Dict = {}
        self._compiled_copy = None
        # trace-time entries across the compiled families — the bounded-
        # retrace contract's probe, like GenerationServer.stats["traces"]
        # ("exports"/"adopts" count disaggregated KV handoffs served;
        # "prefill_tokens" counts prompt tokens actually COMPUTED — a
        # prefix hit's shared span never enters it, the reuse evidence;
        # "prefill_chunks" counts chunk dispatches)
        # ("host_gap_s"/"gap_steps" measure host time the device sat
        # idle between consuming one step's results and receiving the
        # next dispatch — benchmarks/bench_decode.py's host_gap_ms)
        # (goodput time-ledger accumulators: wall time THIS thread spent
        # in each phase — t_device_decode covers decode dispatches,
        # t_device_prefill every donating dispatch (prefill / chunk /
        # adopt / COW), t_readback the commit fetch barrier,
        # t_stream_flush the SSE sink calls.  The scheduler baseline-
        # diffs them per iteration, so warmup/driver time outside an
        # iteration never enters the ledger.  "ledger_admitted" counts
        # tokens COMMITTED into scheduler-owned rows — the token
        # ledger's admission side, folded by _fold_admitted())
        self.stats: Dict[str, Any] = {
            "traces": 0, "steps": 0, "prefills": 0,
            "spec_proposed": 0, "spec_accepted": 0,
            "exports": 0, "adopts": 0,
            "prefill_tokens": 0, "prefill_chunks": 0,
            "host_gap_s": 0.0, "gap_steps": 0,
            "migrate_adopted": 0,
            "t_device_decode": 0.0, "t_device_prefill": 0.0,
            "t_readback": 0.0, "t_stream_flush": 0.0,
            "ledger_admitted": 0,
        }
        # True only inside warmup(): warmup admits/steps are not traffic
        # and must not bump the traffic-facing registry counters (the
        # decision-log replay must reproduce them EXACTLY)
        self._warmup = False
        self._key = jax.random.fold_in(
            jax.random.key(int(server.cfg.get("Global", {}).get("seed", 0))),
            0x9a6ed,
        )
        # decode_step never reads max_dec_len (budgets are per-row DATA):
        # normalize it out of the compile key
        self._gen_key = dataclasses.replace(self.gen, max_dec_len=0)
        # dispatch-ahead decode (docs/decode_path.md): when True, step()
        # leaves the dispatched step IN FLIGHT and fetches its sampled
        # tokens on the NEXT call (or at flush()), so host scheduling
        # work runs in the device's shadow.  Defaults to synchronous —
        # direct drivers (tests, benches) see tokens after every call;
        # ContinuousScheduler flips it from PFX_DISPATCH_AHEAD.
        self.dispatch_ahead = False
        self._inflight: Optional[Dict[str, Any]] = None
        self._t_results: Optional[float] = None

    # -- capacity queries ----------------------------------------------
    def row_capacity_tokens(self, prompt_len: int, max_new: int) -> int:
        """Cache slots a row reserves: its full decode budget plus the
        prefill bucket width (pad junk lands in the row's own blocks).
        The budget is clamped to the context room like admit() clamps it
        (plan_decode's trim), so reservation == allocation.  With
        speculation on, draft_k slack slots absorb the verify chunk's
        rejected-tail overrun (paged_forward_step also null-routes any
        write past the table — belt and braces)."""
        from paddlefleetx_tpu.models.gpt.generation import bucket_len

        P = bucket_len(prompt_len, self.bucket)
        limit = int(self.mcfg.max_position_embeddings) - P
        slack = self.spec.draft_k if self.spec else 0
        return max(prompt_len + min(max_new, max(1, limit)) + slack, P)

    def kv_block_bytes(self) -> int:
        """K+V payload bytes per arena block (what the decode kernels
        stream from HBM; int8 halves this vs bf16).  The per-(slot,
        head) scale planes are excluded — they are the small constant
        overhead documented in docs/decode_path.md."""
        k = self.pools.k
        layers, _, heads, bs, d = k.shape
        return 2 * layers * heads * bs * d * k.dtype.itemsize

    def _pools_tuple(self):
        return tuple(x for x in self.pools if x is not None)

    def free_slots(self) -> int:
        return sum(1 for r in self.slots if r is None)

    def active_rows(self) -> int:
        return int(self.active.sum())

    def can_admit(self, prompt_len: int, max_new: int) -> bool:
        return self.free_slots() > 0 and self.cache.can_admit(
            self.row_capacity_tokens(prompt_len, max_new)
        )

    def validate_request(self, prompt_len: int, max_new: int) -> None:
        """Reject (loudly, pre-admission) a row that could NEVER fit."""
        need = blocks_for(
            self.row_capacity_tokens(prompt_len, max_new), self.block
        )
        usable = self.cache.allocator.num_blocks - 1
        if need > usable:
            raise ValueError(
                f"request needs {need} KV blocks but the pool has {usable}; "
                f"raise --kv-blocks or lower max_tokens"
            )

    # -- compiled entry points -----------------------------------------
    # the arena rides through both families as ONE donated pytree arg
    # (k, v[, k_scale, v_scale]) so the int8 scale planes donate with
    # their payload
    def _prefill_fn(self, P: int, PB: int):
        key = (self._gen_key, P, PB)
        fn = self._compiled_prefill.get(key)
        if fn is None:
            from paddlefleetx_tpu.models.gpt.generation import (
                PagedPools,
                paged_prefill,
            )

            def traced(p, prompt, plen, pools_t, table_row):
                self.stats["traces"] += 1
                pools, last, counts = paged_prefill(
                    p, prompt, plen, PagedPools(*pools_t), table_row,
                    self.mcfg, ctx=self.ctx,
                )
                out = tuple(x for x in pools if x is not None)
                return out, last, counts

            fn = self._jax.jit(traced, donate_argnums=(3,))
            self._compiled_prefill[key] = fn
            get_registry().counter("pfx_serving_traces_total").inc()
        return fn

    def _step_fn(self, M: int):
        key = (self._gen_key, self.capacity, M)
        fn = self._compiled_step.get(key)
        if fn is None:
            from paddlefleetx_tpu.models.gpt.generation import (
                PagedPools,
                PagedRows,
                decode_step,
                decode_step_spec,
            )

            spec = self.spec

            def traced(p, pools_t, tables, logits, counts, positions,
                       gen_steps, max_news, active, forced_steps, reject,
                       drafts, rng):
                self.stats["traces"] += 1
                if spec is not None:
                    rows = PagedRows(logits, counts, positions, gen_steps,
                                     max_news, active, forced_steps, reject)
                    window, ncommit, pools, rows2 = decode_step_spec(
                        p, PagedPools(*pools_t), tables, rows, drafts,
                        self.mcfg, self._gen_key, key=rng, ctx=self.ctx,
                    )
                    rej2 = rows2.reject
                else:
                    rows = PagedRows(logits, counts, positions, gen_steps,
                                     max_news, active, forced_steps)
                    nxt, pools, rows2 = decode_step(
                        p, PagedPools(*pools_t), tables, rows, self.mcfg,
                        self._gen_key, key=rng, ctx=self.ctx,
                    )
                    window = nxt[:, None]
                    ncommit = active.astype(self._jnp.int32)
                    rej2 = reject
                out = tuple(x for x in pools if x is not None)
                return (window, ncommit, out, rows2.logits, rows2.counts,
                        rows2.positions, rows2.gen_steps, rows2.active, rej2)

            fn = self._jax.jit(traced, donate_argnums=(1,))
            self._compiled_step[key] = fn
            get_registry().counter("pfx_serving_traces_total").inc()
        return fn

    def _chunk_fn(self, t: int, M: int):
        """Compiled chunk-prefill family, keyed (chunk width t, table
        width bucket M) — bounded like the step family and counted the
        same way."""
        key = (self._gen_key, t, M)
        fn = self._compiled_chunk.get(key)
        if fn is None:
            from paddlefleetx_tpu.models.gpt.generation import (
                PagedPools,
                paged_chunk_prefill,
            )

            def traced(p, tokens, pools_t, table, position, n_valid,
                       last_idx):
                self.stats["traces"] += 1
                pools, last = paged_chunk_prefill(
                    p, tokens, PagedPools(*pools_t), table, position,
                    n_valid, last_idx, self.mcfg, ctx=self.ctx,
                )
                return tuple(x for x in pools if x is not None), last

            fn = self._jax.jit(traced, donate_argnums=(2,))
            self._compiled_chunk[key] = fn
            get_registry().counter("pfx_serving_traces_total").inc()
        return fn

    def _copy_fn(self):
        """Compiled single-block arena copy (COW: a row diverging
        mid-block gets a PRIVATE copy of the cached block to overwrite
        from the divergence slot on).  Block ids are runtime data — one
        compile, ever."""
        fn = self._compiled_copy
        if fn is None:
            from paddlefleetx_tpu.models.gpt.generation import PagedPools

            def traced(pools_t, src, dst):
                self.stats["traces"] += 1
                pools = PagedPools(*pools_t)
                out = tuple(
                    x.at[:, dst].set(x[:, src])
                    for x in pools if x is not None
                )
                return out

            fn = self._jax.jit(traced, donate_argnums=(0,))
            self._compiled_copy = fn
            get_registry().counter("pfx_serving_traces_total").inc()
        return fn

    # -- row lifecycle --------------------------------------------------
    @property
    def prefix_enabled(self) -> bool:
        return self.cache.prefix.enabled

    def _publish_prefix(self, tokens, table) -> None:
        """`PrefixIndex.publish` with the budget-eviction accounting
        kept registry-synced (the release/export publish sites share
        this so the decision-log replay cannot drift)."""
        ev0 = self.cache.prefix.stats["evictions"]
        self.cache.prefix.publish(tokens, table)
        evicted = self.cache.prefix.stats["evictions"] - ev0
        if evicted:
            get_registry().counter(
                "pfx_prefix_evictions_total"
            ).inc(evicted)

    def _spill_block(self, path: tuple, block_id: int) -> None:
        """PrefixIndex eviction hook: demote one evicted FULL block's KV
        to the host-RAM spill store before its arena reference drops.
        Runs inside ``_evict_node`` — the gather reads a block whose
        reference is still held, and ``clear()`` (ArenaReset) never
        routes through here, so a dead arena's blocks cannot spill.
        Warmup evictions never spill either (synthetic KV must not
        readmit into traffic).  Any failure degrades to a plain
        eviction behind the discard counter — the graceful-degradation
        contract: spilling is an optimization, never a failure mode."""
        spill = self.cache.spill
        if self._warmup or not spill.enabled:
            return
        from paddlefleetx_tpu.models.gpt.generation import gather_kv_blocks

        sp0 = spill.stats["spills"]
        dc0 = spill.stats["discards"]
        try:
            spill.put(path, gather_kv_blocks(self.pools, [int(block_id)]))
        except Exception as exc:  # noqa: BLE001 — degrade, never block
            logger.warning(                       # the eviction
                f"prefix spill failed ({type(exc).__name__}: {exc}); "
                "block evicted without a host copy"
            )
            spill.stats["discards"] += 1
        reg = get_registry()
        d = spill.stats["spills"] - sp0
        if d:
            reg.counter("pfx_prefix_spills_total").inc(d)
        d = spill.stats["discards"] - dc0
        if d:
            reg.counter("pfx_prefix_spill_discards_total").inc(d)

    def _readmit_spilled(self, prompt_ids: List[int], m: int) -> int:
        """Promote spilled host copies of this prompt's next full blocks
        back into the arena, extending the radix match from ``m`` tokens
        on.  Each hit allocates one block, scatters the host copy in
        (the one-compile-ever ``_adopt_fn(1)`` family), and inserts the
        node — the caller re-runs ``match()`` so the readmitted blocks
        flow through the normal shared-admission and exact-replay hit
        accounting.  Every failure — checksum mismatch, the
        ``spill_corrupt`` drill, pool pressure — degrades to recompute
        behind the discard counter; only :class:`ArenaReset` propagates
        (a donated dispatch died, the engine-wide contract)."""
        spill = self.cache.spill
        limit = len(prompt_ids) - 1  # match's cap: >= 1 token recomputes
        readmitted = 0
        rd0 = spill.stats["readmits"]
        dc0 = spill.stats["discards"]
        jnp = self._jnp
        try:
            while m + self.block <= limit:
                key = tuple(int(t) for t in prompt_ids[:m + self.block])
                self._spill_probes += 1
                # deterministic corruption drill (docs/fault_tolerance.md
                # spill_corrupt): the Kth probe treats the entry as torn —
                # discarded loudly, the request recomputes and succeeds
                if maybe_fire("spill_corrupt", self._spill_probes):
                    spill.discard(key)
                    break
                arrays = spill.get(key)  # checksum-verified; None = miss
                if arrays is None:
                    break
                try:
                    fresh = self.cache.allocator.alloc(1)
                except BlockPoolExhausted:
                    break  # recompute; the entry waits for calmer pressure
                names = ("k", "v", "k_scale", "v_scale")
                blocks_t = tuple(
                    jnp.asarray(arrays[n]) for n in names if n in arrays
                )
                fn = self._adopt_fn(1)
                try:
                    pools_t = self._dispatch_donating(
                        lambda: fn(
                            self._pools_tuple(),
                            jnp.asarray(fresh, jnp.int32),
                            blocks_t,
                        ),
                        "spill readmit",
                    )
                except ArenaReset:
                    # reset() released every row and cleared the index,
                    # but this orphan allocation is ours to return
                    self.cache.allocator.free(fresh)
                    raise
                from paddlefleetx_tpu.models.gpt.generation import PagedPools

                self.pools = PagedPools(*pools_t)
                self.cache.prefix.insert_block(key, fresh[0])
                spill.pop(key)  # back on device; counted as a readmit
                readmitted += 1
                m += self.block
            if readmitted:
                self.cache.prefix.evict_to_budget()
        finally:
            reg = get_registry()
            d = spill.stats["readmits"] - rd0
            if d:
                reg.counter("pfx_prefix_readmits_total").inc(d)
            d = spill.stats["discards"] - dc0
            if d:
                reg.counter("pfx_prefix_spill_discards_total").inc(d)
        return readmitted

    def _prefix_admit(self, prompt_ids: List[int], capacity_tokens: int,
                      label: str = "prefix"
                      ) -> Tuple[int, List[int], List[int],
                                 Optional[Tuple[int, int]], int]:
        """The shared admission prelude of :meth:`admit` and
        :meth:`prefill_export`: radix-prefix lookup, block reservation,
        the landed-admission hit/miss accounting, and the copy-on-write
        block copy for a mid-block divergence.  Returns ``(seq_id,
        table, shared, cow, m)`` with ``self.pools`` already holding the
        COW copy.

        Warmup admissions neither hit nor publish: their synthetic
        prompts must not pollute the index, and the pfx_prefix_*
        counters stay traffic-only.  Index stats and registry counters
        commit together AFTER the reservation landed (a failed
        allocation raises before either moved — stats and counters can
        never desync, the exact-replay contract)."""
        shared: List[int] = []
        cow = None
        m = 0
        if self.prefix_enabled and not self._warmup:
            shared, cow, m = self.cache.prefix.match(prompt_ids)
            # spill-tier readmit: when the on-device trie runs dry at a
            # block boundary (no COW divergence), promote spilled host
            # copies of the NEXT blocks, then re-match so shared/m flow
            # through the one hit-accounting path below
            if (self.cache.spill.enabled and cow is None
                    and len(self.cache.spill)
                    and self._readmit_spilled(prompt_ids, m)):
                shared, cow, m = self.cache.prefix.match(prompt_ids)
        self._seq_counter += 1
        seq_id = self._seq_counter
        table = self._cache_admit(seq_id, capacity_tokens, shared=shared)
        if self.prefix_enabled and not self._warmup:
            self.cache.prefix.record_lookup(m)
            reg = get_registry()
            if m:
                reg.counter("pfx_prefix_hits_total").inc()
                reg.counter("pfx_prefix_hit_tokens_total").inc(m)
            else:
                reg.counter("pfx_prefix_misses_total").inc()
        if cow is not None:
            # copy-on-write: the diverging cached block is copied into
            # the row's first PRIVATE block; the suffix prefill
            # overwrites it from the divergence slot on, so the cached
            # original (and every row sharing it) is never touched
            src, _keep = cow
            dst = table[len(shared)]
            fn = self._copy_fn()
            jnp = self._jnp
            pools_t = self._dispatch_donating(
                lambda: fn(
                    self._pools_tuple(), jnp.int32(src), jnp.int32(dst)
                ),
                f"{label} COW copy", release_seq=seq_id,
            )
            from paddlefleetx_tpu.models.gpt.generation import PagedPools

            self.pools = PagedPools(*pools_t)
        return seq_id, table, shared, cow, m

    def _cache_admit(self, seq_id: int, tokens: int,
                     shared: Optional[List[int]] = None) -> List[int]:
        """`PagedCacheManager.admit` with the eviction accounting kept
        registry-synced: any cached prefixes the admission displaced
        under pool pressure bump pfx_prefix_evictions_total together
        with the index stats — EVERY admission spelling (admit / adopt /
        prefill_export) must route through here or the decision-log
        replay and /metrics drift apart."""
        ev0 = self.cache.prefix.stats["evictions"]
        try:
            return self.cache.admit(seq_id, tokens, shared=shared)
        finally:
            evicted = self.cache.prefix.stats["evictions"] - ev0
            if evicted and not self._warmup:
                get_registry().counter(
                    "pfx_prefix_evictions_total"
                ).inc(evicted)

    def _dispatch_donating(self, thunk, what: str,
                           release_seq: Optional[int] = None):
        """Run one donating dispatch under the arena error contract: any
        failure means the pools may be donation-invalidated — release a
        not-yet-slotted row's allocation first (``release_seq``; a row
        already in ``slots`` is released by :meth:`reset` itself),
        rebuild the arena, and raise :class:`ArenaReset` carrying the
        dead rows.  ONE spelling for the COW-copy / monolithic-prefill /
        chunk dispatches so the recovery contract cannot drift between
        them."""
        t0 = time.monotonic()
        try:
            with self.mesh:
                return thunk()
        except BaseException as exc:
            if release_seq is not None:
                self.cache.release(release_seq)
            dead = self.reset()
            raise ArenaReset(
                f"{what} failed ({type(exc).__name__}: {exc}); arena reset",
                dead,
            ) from exc
        finally:
            # time-ledger: every donating dispatch is prefill-side
            # device work (decode steps go through _dispatch instead)
            self.stats["t_device_prefill"] += time.monotonic() - t0

    def admit(self, prompt_ids: Sequence[int], max_new: int,
              entry: Optional[_CBEntry] = None, row_idx: int = 0) -> int:
        """Allocate blocks + a batch slot and prefill the prompt into the
        arena.  Raises :class:`BlockPoolExhausted` / RuntimeError("no
        free slot") when full — callers check :meth:`can_admit` first.

        With the prefix cache on, the radix index is consulted first:
        the matched span's cached blocks map into the new row's table as
        SHARED entries (their KV is never recomputed — only the suffix
        runs through the model), a mid-block divergence gets a private
        copy-on-write block, and the suffix rides the chunk family.
        With ``prefill_chunk`` set, a long prompt is admitted
        mid-prefill: one chunk runs now, the rest stream one per
        scheduler iteration interleaved with decode steps."""
        from paddlefleetx_tpu.models.gpt.generation import bucket_len

        # an admission legitimately sits between a commit and the next
        # decode dispatch (prefill is device work) — drop the gap timer
        # so host_gap_s measures only decode-loop scheduling gaps
        self._t_results = None
        jnp = self._jnp
        prompt_ids = [int(t) for t in prompt_ids]
        plen = len(prompt_ids)
        if plen < 1:
            raise ValueError("prompt must be non-empty")
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        P = bucket_len(plen, self.bucket)
        context = int(self.mcfg.max_position_embeddings)
        limit = context - P
        if limit < 1:
            raise ValueError(
                f"prompt bucket {P} leaves no decode room in context "
                f"{context}"
            )
        # the COALESCE path trims an over-budget request to the context
        # room (core/serving.plan_decode); deliver the identical count —
        # the HTTP layer pre-clamps, this covers direct library callers
        max_new = min(max_new, limit)
        slot = next((i for i, r in enumerate(self.slots) if r is None), None)
        if slot is None:
            raise RuntimeError("no free slot in the running batch")
        seq_id, table, shared, cow, m = self._prefix_admit(
            prompt_ids, self.row_capacity_tokens(plen, max_new)
        )
        trace = entry.future.trace if entry is not None else None

        if m == 0 and self.prefill_chunk == 0:
            # no reuse, no chunking: the original monolithic prefill
            # (contiguous forward + block repack), kept bit-identical
            PB = blocks_for(P, self.block)
            # prefill scatters PB blocks (bucket width incl. pad junk,
            # which lands in the row's own blocks — row_capacity_tokens
            # reserves at least the bucket width, so the table covers PB)
            prefill_table = table[:PB]
            prompt = np.full((1, P), self.gen.pad_token_id, np.int32)
            prompt[0, :plen] = prompt_ids  # RIGHT-pad (paged rows are unpadded)
            fn = self._prefill_fn(P, PB)
            t_prefill = time.monotonic()
            pools_t, last, counts = self._dispatch_donating(
                lambda: fn(
                    self.server.params,
                    jnp.asarray(prompt),
                    jnp.int32(plen),
                    self._pools_tuple(),
                    jnp.asarray(prefill_table, jnp.int32),
                ),
                "prefill", release_seq=seq_id,
            )
            from paddlefleetx_tpu.models.gpt.generation import PagedPools

            self.pools = PagedPools(*pools_t)
            self._logits = self._logits.at[slot].set(last)
            self._counts = self._counts.at[slot].set(counts)
            self._reject = self._reject.at[slot].set(-1)
            self.positions[slot] = plen
            self.gen_steps[slot] = 0
            self.max_news[slot] = max_new
            # forced-EOS fires where the COALESCE path fires it: the
            # bucketed run end of core/serving.plan_decode (min(ceil32(
            # budget), context room)) — NOT the raw budget, whose step
            # the contiguous path's trimmed output usually never shows
            self.forced_steps[slot] = min(-(-max_new // 32) * 32, limit) - 1
            self.active[slot] = True
            if trace is not None:
                trace.span(
                    "prefill", t0=t_prefill, t1=time.monotonic(),
                    prompt_len=plen, bucket=P, blocks=len(table), slot=slot,
                )
            self.slots[slot] = _Row(
                seq_id=seq_id, entry=entry, row_idx=row_idx, prompt_len=plen,
                max_new=max_new, table=table, prompt_ids=prompt_ids,
                trace=trace,
            )
            self.stats["prefills"] += 1
            self.stats["prefill_tokens"] += plen
            return slot

        # prefix-hit / chunked path: only the unmatched suffix
        # [m, plen) ever runs through the model, in chunk-sized pieces
        # riding the compiled chunk family.  The row sits decode-INACTIVE
        # until its last chunk lands (a fixed-shape decode step ignores
        # it), so decode latency stays flat while the prompt streams in.
        chunk = self.prefill_chunk or bucket_len(plen - m, self.bucket)
        self.positions[slot] = m
        self.gen_steps[slot] = 0
        self.max_news[slot] = max_new
        self.forced_steps[slot] = min(-(-max_new // 32) * 32, limit) - 1
        self.active[slot] = False
        self.slots[slot] = _Row(
            seq_id=seq_id, entry=entry, row_idx=row_idx, prompt_len=plen,
            max_new=max_new, table=table, prompt_ids=prompt_ids,
            trace=trace, prefix_hit=m, pending=prompt_ids[m:],
            prefill_pos=m, chunk=chunk, prefill_done=False,
        )
        self.stats["prefills"] += 1
        if trace is not None and m:
            trace.event(
                "prefix_hit", slot=slot, hit_tokens=m,
                shared_blocks=len(shared), cow=cow is not None,
            )
        # first chunk runs NOW (admission = work started); the rest ride
        # step(), one per scheduler iteration, interleaved with decode
        self._tick_prefill(slot)
        return slot

    def _padded_chunk_table(self, table: List[int]) -> np.ndarray:
        """Pad a row's block table to the power-of-two width the chunk
        family is compiled for."""
        M = min(
            _pow2_at_least(len(table)),
            _pow2_at_least(self.max_row_blocks),
        )
        tbl = np.full((M,), NULL_BLOCK, np.int32)
        tbl[: len(table)] = table
        return tbl

    def _run_prefill_chunk(self, chunk: int, tbl: np.ndarray, pos: int,
                           pending, *, label: str,
                           release_seq: Optional[int] = None):
        """Dispatch ONE compiled prefill chunk — the shared body of the
        scheduler's :meth:`_tick_prefill` and the export path's suffix
        loop, so the chunk-call contract and its stats/counter
        accounting live in exactly one place.  Returns ``(last_logits,
        take)``."""
        jnp = self._jnp
        take = min(chunk, len(pending))
        toks = np.full((1, chunk), self.gen.pad_token_id, np.int32)
        toks[0, :take] = pending[:take]
        fn = self._chunk_fn(chunk, len(tbl))
        pools_t, last = self._dispatch_donating(
            lambda: fn(
                self.server.params,
                jnp.asarray(toks),
                self._pools_tuple(),
                jnp.asarray(tbl),
                jnp.int32(pos),
                jnp.int32(take),
                jnp.int32(max(take - 1, 0)),
            ),
            label, release_seq=release_seq,
        )
        from paddlefleetx_tpu.models.gpt.generation import PagedPools

        self.pools = PagedPools(*pools_t)
        self.stats["prefill_chunks"] += 1
        self.stats["prefill_tokens"] += take
        if not self._warmup:
            get_registry().counter("pfx_prefill_chunks_total").inc()
        return last, take

    def _tick_prefill(self, slot: int) -> None:
        """Run ONE chunk of a mid-prefill row's prompt suffix.  The
        final chunk seeds the row's pending logits (last REAL prompt
        token) + repetition counts and flips it decode-active."""
        jnp = self._jnp
        self._t_results = None  # prefill chunk between commit and dispatch
        row = self.slots[slot]
        final = min(row.chunk, len(row.pending)) == len(row.pending)
        t0 = time.monotonic()
        # no release_seq: this row already sits in slots, so reset()
        # releases it with the other dead rows
        last, take = self._run_prefill_chunk(
            row.chunk, self._padded_chunk_table(row.table),
            row.prefill_pos, row.pending, label="chunk prefill",
        )
        from paddlefleetx_tpu.models.gpt.generation import (
            prefix_token_counts,
        )

        row.pending = row.pending[take:]
        row.prefill_pos += take
        self.positions[slot] = row.prefill_pos
        if row.trace is not None:
            row.trace.span(
                "prefill_chunk", t0=t0, t1=time.monotonic(), slot=slot,
                tokens=take, position=row.prefill_pos, final=final,
            )
        if final:
            counts = prefix_token_counts(
                row.prompt_ids, int(self.mcfg.vocab_size)
            )
            self._logits = self._logits.at[slot].set(last)
            self._counts = self._counts.at[slot].set(jnp.asarray(counts))
            self._reject = self._reject.at[slot].set(-1)
            self.positions[slot] = row.prompt_len
            self.active[slot] = True
            row.prefill_done = True

    # -- disaggregated prefill/decode (KV handoff) ----------------------
    def _pool_sig(self) -> List[int]:
        """[layers, heads, block, head_dim] — the arena compatibility
        signature a handoff payload must match (num_blocks excluded: the
        two replicas' pools may legitimately differ in size)."""
        layers, _, heads, bs, d = self.pools.k.shape
        return [int(layers), int(heads), int(bs), int(d)]

    def _clamp_budget(self, prompt_len: int, max_new: int):
        """(P, PB, limit, clamped max_new) — THE admit-side budget clamp,
        shared by admit/export/adopt so a payload clamped on the prefill
        replica re-clamps to the identical value on the decode replica."""
        from paddlefleetx_tpu.models.gpt.generation import bucket_len

        if prompt_len < 1:
            raise ValueError("prompt must be non-empty")
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        P = bucket_len(prompt_len, self.bucket)
        context = int(self.mcfg.max_position_embeddings)
        limit = context - P
        if limit < 1:
            raise ValueError(
                f"prompt bucket {P} leaves no decode room in context "
                f"{context}"
            )
        return P, blocks_for(P, self.block), limit, min(max_new, limit)

    def prefill_export(self, prompt_ids: Sequence[int], max_new: int,
                       trace: Any = None):
        """Prefill-replica half of the disaggregated handoff: run ONE
        row's prompt through `paged_prefill` into this arena, then copy
        the prefilled blocks + row state out as ``(meta, arrays)`` for
        `core/paged_cache.pack_handoff` and free the blocks.  Only the
        prompt bucket's blocks are held (and only for the duration of
        the export), so a prefill pool stays small regardless of decode
        budgets.  ``meta["max_new"]`` carries the ALREADY-clamped budget;
        the adopting engine re-clamps with the same formula, so the two
        agree whenever the replicas share a Model config (and
        `check_handoff_meta` has already insisted they do).

        With the prefix cache on (``--prefix-cache-blocks`` on a
        ``--role prefill`` replica), the radix index is consulted
        exactly like :meth:`admit`: the matched span's cached blocks map
        SHARED into the export table (a fleet-shared system prefix is
        computed once per prefill replica, not once per request), a
        mid-block divergence gets a private copy-on-write block, and
        ONLY the unmatched suffix runs through the chunk family.  The
        exported bytes are identical either way — `gather_kv_blocks`
        copies shared and private blocks alike, and `pack_handoff`'s
        pool signature already guards cross-replica compatibility."""
        prompt_ids = [int(t) for t in prompt_ids]
        plen = len(prompt_ids)
        P, PB, _, max_new = self._clamp_budget(plen, int(max_new))
        jnp = self._jnp
        t0 = time.monotonic()
        # reserve ONLY the prompt bucket: the decode budget is the
        # decode replica's to hold
        seq_id, table, _shared, _cow, m = self._prefix_admit(
            prompt_ids, P, label="export prefix"
        )
        if m == 0:
            prompt = np.full((1, P), self.gen.pad_token_id, np.int32)
            prompt[0, :plen] = prompt_ids
            fn = self._prefill_fn(P, PB)
            pools_t, last, counts = self._dispatch_donating(
                lambda: fn(
                    self.server.params,
                    jnp.asarray(prompt),
                    jnp.int32(plen),
                    self._pools_tuple(),
                    jnp.asarray(table, jnp.int32),
                ),
                "prefill export", release_seq=seq_id,
            )
            from paddlefleetx_tpu.models.gpt.generation import PagedPools

            self.pools = PagedPools(*pools_t)
            self.stats["prefill_tokens"] += plen
            counts = np.asarray(counts, np.int32)
        else:
            from paddlefleetx_tpu.models.gpt.generation import (
                prefix_token_counts,
            )

            last = self._export_suffix_chunks(prompt_ids, m, table, seq_id)
            counts = np.asarray(
                prefix_token_counts(prompt_ids, int(self.mcfg.vocab_size)),
                np.int32,
            )
        from paddlefleetx_tpu.models.gpt.generation import gather_kv_blocks

        arrays = gather_kv_blocks(self.pools, table)
        arrays["logits"] = np.asarray(last, np.float32)
        arrays["counts"] = counts
        if self.prefix_enabled and not self._warmup:
            # publish BEFORE release: the index takes its own refs while
            # the row's table still pins the blocks
            self._publish_prefix(prompt_ids, table)
        self.cache.release(seq_id)  # contents copied out; blocks free
        meta = {
            "prompt_ids": prompt_ids,
            "prompt_len": plen,
            "max_new": int(max_new),
            "block": self.block,
            "kv_dtype": self.kv_dtype,
            "pool_sig": self._pool_sig(),
        }
        self.stats["prefills"] += 1
        self.stats["exports"] += 1
        if not self._warmup:
            get_registry().counter("pfx_handoff_exports_total").inc()
        if trace is not None:
            trace.span("prefill_export", t0=t0, t1=time.monotonic(),
                       prompt_len=plen, bucket=P, blocks=PB,
                       prefix_hit=m)
        return meta, arrays

    def _export_suffix_chunks(self, prompt_ids: List[int], m: int,
                              table: List[int], seq_id: int):
        """Run a prefix-hit export's unmatched suffix ``[m, plen)``
        through the compiled chunk family, synchronously (an export must
        return a complete payload — there is no decode loop to
        interleave with on a prefill replica).  Returns the last REAL
        prompt token's logits."""
        from paddlefleetx_tpu.models.gpt.generation import bucket_len

        chunk = self.prefill_chunk or bucket_len(
            len(prompt_ids) - m, self.bucket
        )
        tbl = self._padded_chunk_table(table)
        pending = prompt_ids[m:]
        pos = m
        last = None
        while pending:
            last, take = self._run_prefill_chunk(
                chunk, tbl, pos, pending,
                label="export suffix chunk", release_seq=seq_id,
            )
            pending = pending[take:]
            pos += take
        return last

    def _adopt_fn(self, PB: int):
        key = (PB,)
        fn = self._compiled_adopt.get(key)
        if fn is None:
            from paddlefleetx_tpu.models.gpt.generation import (
                PagedPools,
                scatter_kv_blocks,
            )

            names = ("k", "v", "k_scale", "v_scale")

            def traced(pools_t, idx, blocks_t):
                self.stats["traces"] += 1
                pools = scatter_kv_blocks(
                    PagedPools(*pools_t), idx, dict(zip(names, blocks_t))
                )
                return tuple(x for x in pools if x is not None)

            fn = self._jax.jit(traced, donate_argnums=(0,))
            self._compiled_adopt[key] = fn
            get_registry().counter("pfx_serving_traces_total").inc()
        return fn

    def adopt(self, meta: Dict[str, Any], arrays: Dict[str, Any],
              entry: Optional[_CBEntry] = None, row_idx: int = 0) -> int:
        """Decode-replica half of the handoff: validate the payload
        against this arena (LOUD on dtype/block-size/shape mismatch),
        allocate the row's FULL capacity (prompt + decode budget, like
        `admit`), scatter the exported blocks into its first PB blocks
        (donated dispatch — a failure resets the arena, the `admit`
        contract), and seed the row state so the continuous scheduler
        continues exactly where the prefill replica's math stopped —
        greedy output token-identical to a single-process `admit`."""
        check_handoff_meta(
            meta, block=self.block, kv_dtype=self.kv_dtype,
            pool_sig=self._pool_sig(),
        )
        prompt_ids = [int(t) for t in meta["prompt_ids"]]
        plen = int(meta["prompt_len"])
        if plen != len(prompt_ids):
            raise ValueError(
                f"handoff prompt_len {plen} != {len(prompt_ids)} prompt ids"
            )
        P, PB, limit, max_new = self._clamp_budget(plen, int(meta["max_new"]))
        jnp = self._jnp
        self._t_results = None  # adoption is an admission for gap accounting
        vocab = int(self.mcfg.vocab_size)
        for name, want in (("logits", (vocab,)), ("counts", (vocab,))):
            got = tuple(np.shape(arrays.get(name)))
            if got != want:
                raise ValueError(
                    f"handoff {name} shape {got} != {want} (vocab {vocab})"
                )
        # the block-array SET is validated BEFORE the donated dispatch: a
        # payload missing k/v must fail this request alone, not trip the
        # in-trace check and reset the arena under every live row
        names = ("k", "v", "k_scale", "v_scale")
        need = set(names[: 4 if self.kv_dtype == "int8" else 2])
        if not need <= set(arrays):
            raise ValueError(
                f"handoff payload missing arrays "
                f"{sorted(need - set(arrays))} (has {sorted(arrays)})"
            )
        slot = next((i for i, r in enumerate(self.slots) if r is None), None)
        if slot is None:
            raise RuntimeError("no free slot in the running batch")
        self._seq_counter += 1
        seq_id = self._seq_counter
        table = self._cache_admit(
            seq_id, self.row_capacity_tokens(plen, max_new)
        )
        # NAMES order (k, v, scales) — _adopt_fn zips the same order
        blocks_t = tuple(jnp.asarray(arrays[n]) for n in names if n in need)
        trace = entry.future.trace if entry is not None else None
        t0 = time.monotonic()
        fn = self._adopt_fn(PB)
        pools_t = self._dispatch_donating(
            lambda: fn(
                self._pools_tuple(),
                jnp.asarray(table[:PB], jnp.int32),
                blocks_t,
            ),
            "handoff adopt", release_seq=seq_id,
        )
        from paddlefleetx_tpu.models.gpt.generation import PagedPools

        self.pools = PagedPools(*pools_t)
        self._logits = self._logits.at[slot].set(
            jnp.asarray(arrays["logits"], jnp.float32)
        )
        self._counts = self._counts.at[slot].set(
            jnp.asarray(arrays["counts"], jnp.int32)
        )
        self._reject = self._reject.at[slot].set(-1)
        self.positions[slot] = plen
        self.gen_steps[slot] = 0
        self.max_news[slot] = max_new
        # same forced-EOS step as admit(): the coalesce path's bucketed
        # run end, so disaggregated output stays token-identical
        self.forced_steps[slot] = min(-(-max_new // 32) * 32, limit) - 1
        self.active[slot] = True
        if trace is not None:
            trace.span(
                "adopt", t0=t0, t1=time.monotonic(),
                prompt_len=plen, bucket=P, blocks=len(table), slot=slot,
            )
        self.slots[slot] = _Row(
            seq_id=seq_id, entry=entry, row_idx=row_idx, prompt_len=plen,
            max_new=max_new, table=table, prompt_ids=prompt_ids,
            trace=trace,
        )
        self.stats["adopts"] += 1
        if not self._warmup:
            get_registry().counter("pfx_handoff_adopts_total").inc()
        # deterministic decode-death drill (docs/fault_tolerance.md
        # adopt_crash): the Kth adoption hard-exits AFTER the row landed
        # in the arena — the transport sees the connection die
        # mid-exchange, driving the router's bounded re-prefill failover
        maybe_fire("adopt_crash", self.stats["adopts"])
        return slot

    # -- peer-to-peer prefix migration (drain/scale-down survival) -----
    def export_hot_prefixes(self, max_blocks: int = 0
                            ) -> Optional[Tuple[Dict[str, Any],
                                                Dict[str, np.ndarray]]]:
        """Snapshot the hottest published prefix blocks as ONE handoff
        payload ``(meta, arrays)`` for peer adoption on drain.  The
        top-``max_blocks`` most-recently-used FULL blocks are taken
        together with their ancestor chains (a child's KV is unmatchable
        without its parents), shortest path first, so the receiver can
        adopt in order and stop cleanly at any boundary.  Returns None
        when nothing is cached.  Called on the drain path AFTER the
        scheduler thread exited — the index walk is single-threaded."""
        if not self.prefix_enabled:
            return None
        pfx = self.cache.prefix
        nodes = [
            n for n in list(pfx._nodes) if len(n.tokens) == self.block
        ]
        if not nodes:
            return None
        nodes.sort(key=lambda n: n.last_used, reverse=True)
        picked = nodes[:max_blocks] if max_blocks > 0 else nodes
        chosen: set = set()
        for n in picked:
            while n is not None and n not in chosen:
                if len(n.tokens) == self.block:
                    chosen.add(n)
                n = n.parent
        order = sorted(chosen, key=lambda n: len(pfx.node_path(n)))
        from paddlefleetx_tpu.models.gpt.generation import gather_kv_blocks

        arrays = gather_kv_blocks(self.pools, [n.block_id for n in order])
        meta = {
            "kind": "prefixes",
            "prefixes": [list(pfx.node_path(n)) for n in order],
            "block": self.block,
            "kv_dtype": self.kv_dtype,
            "pool_sig": self._pool_sig(),
        }
        return meta, arrays

    def validate_prefix_payload(self, meta: Dict[str, Any],
                                arrays: Dict[str, Any]) -> int:
        """LOUD structural validation of a migration payload — run in
        full BEFORE anything touches the arena (the adopt rule: a torn
        or incompatible transfer is rejected whole, never half-adopted).
        Returns the block count."""
        check_handoff_meta(
            meta, block=self.block, kv_dtype=self.kv_dtype,
            pool_sig=self._pool_sig(),
        )
        prefixes = meta.get("prefixes")
        if not isinstance(prefixes, list) or not prefixes:
            raise ValueError("prefix payload carries no prefixes")
        for p in prefixes:
            if not isinstance(p, (list, tuple)) or not p \
                    or len(p) % self.block:
                raise ValueError(
                    "prefix path is not a token list of positive "
                    f"block-{self.block}-multiple length: {p!r:.60}"
                )
        names = ("k", "v", "k_scale", "v_scale")
        need = set(names[: 4 if self.kv_dtype == "int8" else 2])
        if not need <= set(arrays):
            raise ValueError(
                f"prefix payload missing arrays "
                f"{sorted(need - set(arrays))} (has {sorted(arrays)})"
            )
        nb = len(prefixes)
        for name in sorted(need):
            got = tuple(np.shape(arrays[name]))
            if len(got) != 5 or got[1] != nb:
                raise ValueError(
                    f"prefix payload array {name!r} shape {got} does "
                    f"not carry {nb} blocks"
                )
        return nb

    def adopt_prefixes(self, meta: Dict[str, Any],
                       arrays: Dict[str, Any]) -> int:
        """Migration-receiver half: adopt a draining peer's exported
        prefix blocks into this arena's radix index.  Entries land
        shortest-path-first so ancestor chains always precede children;
        pool pressure stops the adoption cleanly at a block boundary
        (what landed is a valid prefix, the rest is dropped — never
        half-adopted), and an already-cached path is skipped (an
        idempotent re-send only bumps LRU).  Returns adopted count."""
        nb = self.validate_prefix_payload(meta, arrays)
        if not self.prefix_enabled:
            return 0
        prefixes = meta["prefixes"]
        names = ("k", "v", "k_scale", "v_scale")
        need = set(names[: 4 if self.kv_dtype == "int8" else 2])
        order = sorted(range(nb), key=lambda i: len(prefixes[i]))
        jnp = self._jnp
        adopted = 0
        for i in order:
            path = [int(t) for t in prefixes[i]]
            if self.cache.prefix.has_path(path):
                continue
            if len(path) > self.block and not self.cache.prefix.has_path(
                    path[:-self.block]):
                continue  # its parent never landed (pressure): skip child
            try:
                fresh = self.cache.allocator.alloc(1)
            except BlockPoolExhausted:
                break  # prefix-closed stop: everything adopted so far holds
            blocks_t = tuple(
                jnp.asarray(np.ascontiguousarray(arrays[n][:, i:i + 1]))
                for n in names if n in need
            )
            fn = self._adopt_fn(1)
            try:
                pools_t = self._dispatch_donating(
                    lambda: fn(
                        self._pools_tuple(),
                        jnp.asarray(fresh, jnp.int32),
                        blocks_t,
                    ),
                    "prefix adopt",
                )
            except ArenaReset:
                self.cache.allocator.free(fresh)  # orphan: ours to return
                raise
            from paddlefleetx_tpu.models.gpt.generation import PagedPools

            self.pools = PagedPools(*pools_t)
            self.cache.prefix.insert_block(path, fresh[0])
            adopted += 1
        if adopted:
            self.cache.prefix.evict_to_budget()
            self.stats["migrate_adopted"] += adopted
            if not self._warmup:
                get_registry().counter(
                    "pfx_migrate_adopted_total"
                ).inc(adopted)
        return adopted

    def table_width_bucket(self) -> int:
        widest = max(
            (len(r.table) for r in self.slots if r is not None), default=1
        )
        return min(_pow2_at_least(widest), _pow2_at_least(self.max_row_blocks))

    def _host_drafts(self) -> np.ndarray:
        """Self-draft every active row from its host-side prompt+output
        history: the n-gram lookup proposes k+1 tokens continuing the
        trailing n-gram's last earlier occurrence; proposal[0] predicts
        the not-yet-sampled pending token, proposals[1:] are the drafts
        the verify chunk carries.  Pure runtime data — never a compile
        key."""
        from paddlefleetx_tpu.ops.speculative import NGRAM_WINDOW

        k = self.spec.draft_k
        # the lookup never scans past NGRAM_WINDOW, so hand it only the
        # tail (+ needle/draft slack) — a 100k-token history must not
        # pay an O(history) copy per row per step on the decode hot path
        need = NGRAM_WINDOW + self.spec.ngram + k + 2
        out = np.zeros((self.capacity, k), np.int32)
        for i, r in enumerate(self.slots):
            if r is not None and self.active[i]:
                if len(r.tokens) >= need:
                    seq = r.tokens[-need:]
                else:
                    seq = r.prompt_ids[-(need - len(r.tokens)):] + r.tokens
                out[i] = ngram_propose_host(seq, k + 1, n=self.spec.ngram)[1:]
        return out

    def step(self) -> List[int]:
        """Run ONE decode step (speculative: one draft-verify iteration,
        committing 1..draft_k+1 tokens per row) for every active row;
        returns the slots that finished (their tokens are complete —
        release them with :meth:`release`).

        Synchronous mode (default): dispatch and commit in one call.
        Dispatch-ahead mode (``dispatch_ahead=True``): the NEXT step is
        dispatched before the in-flight step's sampled tokens are
        fetched — when possible it chains directly on the in-flight
        step's device-resident row state, so the readback barrier
        overlaps the chained step's compute and the host scheduling
        work between calls runs in the device's shadow.  The finished
        slots returned are those of the COMMITTED (previous) step.
        Callers that mutate row membership or host row state
        (admit/adopt/release/evict) between steps must :meth:`flush`
        first."""
        jnp = self._jnp
        pending = [
            i for i, r in enumerate(self.slots)
            if r is not None and not r.prefill_done
        ]
        # dispatch-ahead fast path: chain the next step on the in-flight
        # step's device-side outputs (positions/gen_steps/active are
        # async futures with the same avals as the host mirrors — and
        # NOT donated, so the commit below can still read them).  The
        # chained dispatch reaches the device queue before the host
        # fetches a single token.  Speculation needs the committed
        # tokens to draft from and a pending chunked prefill needs the
        # host tick, so both take the commit-first ordering below
        # instead (the readback then only waits for whatever compute
        # the prior dispatch has not finished yet).
        if (self.dispatch_ahead and self._inflight is not None
                and self.spec is None and not pending and self.active.any()):
            prev, self._inflight = self._inflight, None
            nxt = self._dispatch(
                prev["positions"], prev["gen_steps"], prev["active"],
                overlapped=True,
            )
            # stash BEFORE the commit barrier: a commit failure resets
            # the arena, and reset() must drop the chained dispatch too
            # (its pools chain on the poisoned step)
            self._inflight = nxt
            finished = self._commit(prev)
            # the chained step's dispatch-time active view IS the
            # committed step's output actives (merged on the host now);
            # rows the commit finished are excluded, so a later commit
            # of the chained step can never re-finish a released slot
            nxt["was_active"] = self.active.copy()
            return finished
        finished = self.flush()
        # chunked-prefill interleave: at most ONE pending chunk per
        # iteration, oldest admission first — a long prompt streams in
        # across iterations while the decode batch below keeps stepping,
        # so no prefill ever head-of-line-blocks active rows
        if pending:
            self._tick_prefill(
                min(pending, key=lambda i: self.slots[i].seq_id)
            )
        if not self.active.any():
            return finished
        fl = self._dispatch(
            jnp.asarray(self.positions), jnp.asarray(self.gen_steps),
            jnp.asarray(self.active), overlapped=False,
        )
        fl["was_active"] = self.active.copy()
        self._inflight = fl
        if self.dispatch_ahead:
            return finished
        return finished + self.flush()

    @property
    def has_inflight(self) -> bool:
        """True while a dispatched step's results are not yet fetched
        (dispatch-ahead mode only; always False when synchronous)."""
        return self._inflight is not None

    def _dispatch(self, positions, gen_steps, active, *,
                  overlapped: bool) -> Dict[str, Any]:
        """Dispatch one decode step and ADOPT its device-side outputs
        immediately: pools/logits/counts/reject are async futures, so a
        later dispatch (prefill chunk, COW copy, the next step) queues
        behind this one on device instead of ever touching the
        donation-invalidated inputs.  Returns the in-flight record
        whose window/ncommit/row-state handles :meth:`_commit` fetches;
        the caller fills ``was_active`` with its dispatch-time view."""
        jnp = self._jnp
        M = self.table_width_bucket()
        tables = np.full((self.capacity, M), NULL_BLOCK, np.int32)
        for i, r in enumerate(self.slots):
            if r is not None:
                tables[i, : len(r.table)] = r.table
        self._key, sub = self._jax.random.split(self._key)
        k = self.spec.draft_k if self.spec else 0
        drafts = (
            self._host_drafts() if self.spec
            else np.zeros((self.capacity, 1), np.int32)
        )
        fn = self._step_fn(M)
        # host-gap accounting (bench_decode's host_gap_ms): host time
        # between consuming one step's results and handing the device
        # its next dispatch.  A chained dispatch lands while the
        # previous step is still in flight — the device never waits on
        # the host, so its gap is zero by construction.
        if self._t_results is not None and not overlapped:
            self.stats["host_gap_s"] += max(
                0.0, time.monotonic() - self._t_results
            )
            self.stats["gap_steps"] += 1
        t_disp = time.monotonic()
        try:
            with self.mesh:
                (window, ncommit, pools_t, logits, counts, positions_t,
                 gen_steps_t, active_t, reject) = fn(
                    self.server.params, self._pools_tuple(),
                    jnp.asarray(tables), self._logits, self._counts,
                    positions, gen_steps,
                    jnp.asarray(self.max_news), active,
                    jnp.asarray(self.forced_steps), self._reject,
                    jnp.asarray(drafts), sub,
                )
        except BaseException as exc:
            dead = self.reset()
            raise ArenaReset(
                f"decode step failed ({type(exc).__name__}: {exc}); "
                "arena reset",
                dead,
            ) from exc
        finally:
            self.stats["t_device_decode"] += time.monotonic() - t_disp
        from paddlefleetx_tpu.models.gpt.generation import PagedPools

        self.pools = PagedPools(*pools_t)
        self._logits, self._counts = logits, counts
        self._reject = reject
        return {
            "window": window, "ncommit": ncommit,
            "positions": positions_t, "gen_steps": gen_steps_t,
            "active": active_t, "rows": list(self.slots), "k": k,
            "was_active": None,
        }

    def flush(self) -> List[int]:
        """Commit the in-flight dispatched step, if any (no-op when
        synchronous or nothing is in flight); returns the slots it
        finished.  This is the flush the dispatch-ahead contract
        requires before any row-membership or host-row-state mutation:
        the commit merge only protects rows that join or leave AFTER
        the dispatch it is committing."""
        if self._inflight is None:
            return []
        prev, self._inflight = self._inflight, None
        return self._commit(prev)

    def _commit(self, fl: Dict[str, Any]) -> List[int]:
        """Fetch one dispatched step's sampled window and fold it into
        host state — the ONLY host-device barrier on the decode path.
        The dispatched computation's errors materialize here: any
        failure resets the arena exactly like a synchronous step
        failure, and the ArenaReset carries every live row — INCLUDING
        rows admitted while the step was in flight, whose pools chained
        onto the poisoned dispatch."""
        t_rb = time.monotonic()
        try:
            maybe_fire("cb_commit_crash", int(self.stats["steps"]) + 1)
            window = np.array(fl["window"])
            ncommit = np.array(fl["ncommit"])
            new_active = np.array(fl["active"])
            positions = np.array(fl["positions"])
            gen_steps = np.array(fl["gen_steps"])
        except BaseException as exc:
            # stamp the failed fetch before the reset: reset/requeue cost
            # belongs to host_sched (the iterate residual), not readback
            self.stats["t_readback"] += time.monotonic() - t_rb
            dead = self.reset()
            raise ArenaReset(
                f"decode step failed ({type(exc).__name__}: {exc}); "
                "arena reset",
                dead,
            ) from exc
        self.stats["t_readback"] += time.monotonic() - t_rb
        self._t_results = time.monotonic()
        was_active = fl["was_active"]
        # merge, never overwrite: slots that joined (admit/adopt) or
        # left (release/evict) after the dispatch were not part of it —
        # the step carried their stale state through, and their fresh
        # host values must win over its outputs
        self.positions[was_active] = positions[was_active]
        self.gen_steps[was_active] = gen_steps[was_active]
        self.active[was_active] = new_active[was_active]
        self.stats["steps"] += 1
        finished: List[int] = []
        n_act = int(was_active.sum())
        t_chunk = time.monotonic()
        for i, r in enumerate(fl["rows"]):
            if r is None or not was_active[i]:
                continue
            committed = int(ncommit[i])
            start = len(r.tokens)
            for tok in window[i, :committed].tolist():
                if tok != self.gen.eos_token_id:
                    r.tokens.append(int(tok))
            if r.entry is not None:
                # token ledger: commits into scheduler-owned rows are
                # ADMITTED tokens — every one must later reach exactly
                # one terminal disposition (delivered / evicted_lost /
                # preempt_refunded / shed_after_admit).  EOS never
                # appends, so it never enters the books.
                self.stats["ledger_admitted"] += len(r.tokens) - start
            if (len(r.tokens) > start and not self._warmup
                    and r.entry is not None and r.entry.stream is not None):
                # token streaming: push this step's commits as they
                # land.  A broken sink must never kill the batch — the
                # tokens are committed either way.
                t_sf = time.monotonic()
                try:
                    r.entry.emit_stream(r.row_idx, start, r.tokens[start:])
                except Exception as sink_exc:
                    logger.warning(
                        f"stream sink failed for seq {r.seq_id}: "
                        f"{type(sink_exc).__name__}: {sink_exc}"
                    )
                finally:
                    self.stats["t_stream_flush"] += time.monotonic() - t_sf
            if r.trace is not None:
                # per-chunk decode timeline: one event per iteration the
                # row decoded in, carrying its commit + spec-accept
                # counts (counts only — never token values)
                r.trace.event(
                    "decode_chunk", t=t_chunk, slot=i,
                    committed=committed,
                    accepted=(committed - 1 if self.spec else 0),
                    position=int(self.positions[i]),
                )
            if not new_active[i]:
                finished.append(i)
        if self.spec and n_act and not self._warmup:
            proposed = fl["k"] * n_act
            accepted = int(ncommit[was_active].sum()) - n_act
            self.stats["spec_proposed"] += proposed
            self.stats["spec_accepted"] += accepted
            reg = get_registry()
            reg.counter("pfx_spec_proposed_total").inc(proposed)
            reg.counter("pfx_spec_accepted_total").inc(accepted)
        return finished

    def release(self, slot: int) -> None:
        """Return a finished/evicted row's blocks to the pool and clear
        its batch slot (loud on an empty slot — a double release means
        the caller's bookkeeping aliased two rows).  With the prefix
        cache on, the row's PROMPT-prefix blocks are published to the
        radix index first (the index takes its own references, so the
        blocks outlive the row under the LRU budget); a row still
        mid-chunked-prefill never publishes — its blocks are only
        partially written."""
        row = self.slots[slot]
        if row is None:
            raise ValueError(f"slot {slot} is already empty")
        if self.prefix_enabled and not self._warmup and row.prefill_done:
            self._publish_prefix(row.prompt_ids, row.table)
        self.cache.release(row.seq_id)
        self.slots[slot] = None
        self.active[slot] = False
        self.positions[slot] = 0
        self.gen_steps[slot] = 0
        self.max_news[slot] = 0
        self.forced_steps[slot] = 0

    def preempt_row(self, slot: int) -> List[int]:
        """Evict an ACTIVE row mid-decode for a priority preemption and
        return the tokens it had committed — the scheduler requeues the
        row as a re-prefill continuation, so the request is paused, not
        killed.  The KV-valid prefix (prompt plus the committed tokens
        whose KV has been written: ``positions - prompt_len`` of them;
        the LAST sampled token's KV does not exist yet) is published to
        the radix index first, so the continuation's prefill is a prefix
        hit riding the drilled token-identical reuse path — with the
        spill tier as the backstop when the live blocks get evicted
        before the resume lands.  Caller must ``flush()`` first
        (row-membership mutation, the dispatch-ahead contract)."""
        row = self.slots[slot]
        if row is None:
            raise ValueError(f"slot {slot} is empty")
        if not row.prefill_done:
            raise ValueError(
                f"slot {slot} is mid-chunked-prefill; only decode-active "
                "rows are preemptible"
            )
        committed = list(row.tokens)
        if self.prefix_enabled and not self._warmup:
            kv_valid = max(0, int(self.positions[slot]) - row.prompt_len)
            self._publish_prefix(
                row.prompt_ids + committed[:kv_valid], row.table
            )
        self.cache.release(row.seq_id)
        self.slots[slot] = None
        self.active[slot] = False
        self.positions[slot] = 0
        self.gen_steps[slot] = 0
        self.max_news[slot] = 0
        self.forced_steps[slot] = 0
        return committed

    def reset(self) -> List["_Row"]:
        """Rebuild the arena after a failed donating dispatch: the old
        pools may be donation-invalidated and must never be reused.
        Returns the rows that were live (the caller fails their
        requests)."""
        from paddlefleetx_tpu.models.gpt.generation import init_paged_pools

        dead = [r for r in self.slots if r is not None]
        # any in-flight dispatched step chains on the poisoned pools:
        # drop its handles, its results must never be committed
        self._inflight = None
        self._t_results = None
        for r in dead:
            self.cache.release(r.seq_id)
        # the rebuilt pools hold NONE of the old blocks' KV: every cached
        # prefix is donation-invalidated and must never resurface as a
        # hit — drop the whole index (its block references with it) AND
        # the spill store in the same breath: a host copy of a dead
        # arena's block must never readmit (the ArenaReset atomicity
        # half of the spill contract; clear() frees directly, never
        # through _evict_node, so nothing re-spills here either)
        self.cache.prefix.clear()
        self.cache.spill.clear()
        self.slots = [None] * self.capacity
        self.active[:] = False
        self.positions[:] = 0
        self.gen_steps[:] = 0
        self.max_news[:] = 0
        self.forced_steps[:] = 0
        self.pools = init_paged_pools(
            self.mcfg, self.cache.allocator.num_blocks, self.block,
            kv_dtype=self.kv_dtype,
        )
        jnp = self._jnp
        self._logits = jnp.zeros_like(self._logits)
        self._counts = jnp.zeros_like(self._counts)
        self._reject = jnp.full_like(self._reject, -1)
        return dead

    def warmup_prefill(self, prompt_lens: Sequence[int]) -> Dict[str, float]:
        """Prefill-replica warmup: compile the prefill family per prompt
        bucket by running one export end-to-end (the blocks are freed on
        export, so nothing stays allocated).  Warmup exports are not
        traffic — the handoff counters stay clean.  With the prefix
        cache on, the COW-copy and EXPORT-width chunk families a traffic
        hit routes through compile here too (warmup exports skip the
        index, so they never exercise — or pollute — the hit path)."""
        from paddlefleetx_tpu.models.gpt.generation import bucket_len

        per: Dict[str, float] = {}
        self._warmup = True
        try:
            if self.prefix_enabled:
                self._warm_copy_family()
            for n in prompt_lens:
                t0 = time.time()
                try:
                    if self.prefix_enabled:
                        self._warm_chunk_family(
                            int(n),
                            capacity_tokens=bucket_len(int(n), self.bucket),
                        )
                    self.prefill_export([1] * int(n), self.gen.max_dec_len)
                except Exception as exc:
                    raise RuntimeError(
                        f"prefill warmup failed at bucket {n} (warmed so "
                        f"far: {sorted(per) or 'none'}): "
                        f"{type(exc).__name__}: {exc}"
                    ) from exc
                per[str(int(n))] = round(time.time() - t0, 2)
                logger.info(
                    f"prefill warmup: prompt bucket {n} compiled in "
                    f"{per[str(int(n))]:.1f}s"
                )
        finally:
            self._warmup = False
        return per

    def _warm_copy_family(self) -> None:
        """Compile the COW arena copy (one compile ever): a null-block
        self-copy is a safe no-op dispatch.  Without it, the first
        mid-block-divergence hit after boot would pay this compile
        inside a scheduler iteration."""
        fn = self._copy_fn()
        pools_t = self._dispatch_donating(
            lambda: fn(
                self._pools_tuple(),
                self._jnp.int32(NULL_BLOCK), self._jnp.int32(NULL_BLOCK),
            ),
            "COW copy warmup",
        )
        from paddlefleetx_tpu.models.gpt.generation import PagedPools

        self.pools = PagedPools(*pools_t)

    def _warm_chunk_family(self, n: int,
                           capacity_tokens: Optional[int] = None) -> None:
        """Compile the chunk fns a traffic prefix hit at bucket ``n``
        routes its suffix through (only needed when ``prefill_chunk`` is
        off — a chunked config's normal warmup admission already rides
        the chunk path): the SHORT-suffix chunk (one bucket quantum —
        the hot case, a long cached prefix plus a short new suffix) and
        the full-bucket chunk, both at the table-width bucket a
        bucket-``n`` row allocates.  A null-table dispatch with
        ``n_valid=0`` compiles each without touching the arena.
        Suffix buckets between those two still compile on first use,
        and the width bucket follows the DEFAULT decode budget exactly
        like the warmed step family does (a request with a much smaller
        max_tokens keys a narrower width and compiles then) — the same
        partial-coverage contract as the prompt buckets.

        ``capacity_tokens`` overrides the row capacity the table width
        is derived from: EXPORT tables cover only the prompt bucket
        (the decode budget is the decode replica's to hold), so a
        prefill replica warms a narrower width than a decode-capacity
        row would."""
        from paddlefleetx_tpu.models.gpt.generation import (
            PagedPools,
            bucket_len,
        )

        jnp = self._jnp
        blocks = blocks_for(
            capacity_tokens if capacity_tokens is not None
            else self.row_capacity_tokens(int(n), self.gen.max_dec_len),
            self.block,
        )
        M = min(_pow2_at_least(blocks), _pow2_at_least(self.max_row_blocks))
        chunks = ({self.prefill_chunk} if self.prefill_chunk
                  else {self.bucket, bucket_len(int(n), self.bucket)})
        for t in sorted(chunks):
            fn = self._chunk_fn(t, M)
            toks = np.full((1, t), self.gen.pad_token_id, np.int32)
            tbl = np.full((M,), NULL_BLOCK, np.int32)
            pools_t, _ = self._dispatch_donating(
                lambda: fn(
                    self.server.params, jnp.asarray(toks),
                    self._pools_tuple(), jnp.asarray(tbl),
                    jnp.int32(0), jnp.int32(0), jnp.int32(0),
                ),
                "chunk warmup",
            )
            self.pools = PagedPools(*pools_t)

    def warmup(self, prompt_lens: Sequence[int]) -> Dict[str, float]:
        """Compile (prefill, step) for each prompt bucket at the default
        decode budget — the continuous counterpart of
        `GenerationServer.warmup`; fails loudly naming the bucket.  With
        the prefix cache on, also compiles the chunk + COW-copy families
        a traffic hit will route through (suffix buckets smaller than
        the warmed list still compile on first use — the same
        partial-coverage contract as the prompt buckets themselves)."""
        per: Dict[str, float] = {}
        self._warmup = True  # warmup admits/steps are not traffic
        # warmup drives step()/release() with synchronous expectations
        # (step, then inspect/release the slot): force the synchronous
        # path for its duration regardless of the dispatch-ahead knob
        ahead, self.dispatch_ahead = self.dispatch_ahead, False
        try:
            if self.prefix_enabled:
                self._warm_copy_family()
            for n in prompt_lens:
                t0 = time.time()
                try:
                    if self.prefix_enabled and self.prefill_chunk == 0:
                        self._warm_chunk_family(int(n))
                    slot = self.admit(
                        [1] * int(n), max_new=self.gen.max_dec_len
                    )
                    # with chunked prefill on, admission returns
                    # mid-prefill: drive the remaining chunks so the
                    # whole chunk family compiles before traffic
                    guard = 0
                    while (self.slots[slot] is not None
                           and not self.slots[slot].prefill_done):
                        self.step()
                        guard += 1
                        if guard > 4096:
                            raise RuntimeError(
                                "warmup prefill never completed"
                            )
                    self.step()
                    if self.slots[slot] is not None:
                        self.release(slot)
                except Exception as exc:
                    raise RuntimeError(
                        f"continuous warmup failed at bucket {n} (warmed so "
                        f"far: {sorted(per) or 'none'}): "
                        f"{type(exc).__name__}: {exc}"
                    ) from exc
                per[str(int(n))] = round(time.time() - t0, 2)
                logger.info(
                    f"continuous warmup: prompt bucket {n} compiled in "
                    f"{per[str(int(n))]:.1f}s"
                )
        finally:
            self._warmup = False
            self.dispatch_ahead = ahead
        return per


class ContinuousScheduler:
    """Iteration-level scheduler with the RequestQueue admission surface.

    ``submit`` -> bounded waiting queue (QueueFull/QueueClosed exactly
    like RequestQueue); the scheduler thread loops one decode step per
    iteration: shed expired waiting entries, evict expired ACTIVE rows
    mid-decode (blocks freed immediately), admit from the queue head
    while slots + blocks allow (prefill-on-admit), then step the batch.
    """

    def __init__(self, engine: PagedDecodeEngine, *, max_depth: int = 64,
                 name: str = "serve-cb",
                 dispatch_ahead: Optional[bool] = None,
                 quantum: Optional[int] = None,
                 tenant_config: Optional[TenantConfig] = None,
                 preempt_min_tokens: int = 8) -> None:
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        if preempt_min_tokens < 1:
            raise ValueError(
                f"preempt_min_tokens must be >= 1, got {preempt_min_tokens}"
            )
        self.engine = engine
        self.max_depth = int(max_depth)
        self.name = name
        # multi-tenant isolation (docs/serving.md "Multi-tenant
        # isolation"): the admission pull is a deficit round-robin
        # across tenant queues (weights from the config; FCFS within a
        # tenant — one tenant degenerates to exactly the old FCFS), and
        # a high-priority arrival that cannot fit may preempt the
        # lowest-priority active row once it has committed at least
        # preempt_min_tokens since its (last) admission — the
        # minimum-progress floor that makes preemption thrash-free.
        self.tenant_config = tenant_config or TenantConfig()
        self._fair = DeficitRoundRobin(self.tenant_config.weight)
        self._tenant_labels = TenantLabelCap(
            seed=self.tenant_config.known_tenants()
        )
        self.preempt_min_tokens = int(preempt_min_tokens)
        # cumulative per-tenant-label row counts (scheduler thread only;
        # the decision log diffs them per iteration and the same sites
        # bump the labeled registry counters, so replaying an
        # untruncated log reproduces pfx_tenant_admitted_total /
        # pfx_tenant_preemptions_total exactly)
        self._tenant_admitted: Dict[str, int] = {}
        self._tenant_preempted: Dict[str, int] = {}
        # dispatch-ahead decode + k-step scheduling quantum
        # (docs/decode_path.md).  PFX_DISPATCH_AHEAD=0 is the loud
        # fallback to fully-synchronous stepping; the scheduler (not
        # the engine ctor) owns the knob because direct engine drivers
        # need the synchronous default.  PFX_SCHED_QUANTUM=k runs the
        # admission/eviction/shed scans every k-th iteration only,
        # amortizing the host bookkeeping across k decode steps.
        if dispatch_ahead is None:
            dispatch_ahead = _env_int("PFX_DISPATCH_AHEAD", 1) != 0
        self.dispatch_ahead = bool(dispatch_ahead)
        engine.dispatch_ahead = self.dispatch_ahead
        if not self.dispatch_ahead:
            logger.warning(
                f"{name}: PFX_DISPATCH_AHEAD=0 — synchronous decode "
                "stepping; host scheduling no longer overlaps device "
                "compute"
            )
        self.quantum = (
            _env_int("PFX_SCHED_QUANTUM", 1)
            if quantum is None else int(quantum)
        )
        if self.quantum < 1:
            raise ValueError(
                f"PFX_SCHED_QUANTUM must be >= 1, got {self.quantum}"
            )
        self._entries: List[_CBEntry] = []
        # peer prefix adoptions (POST /admin/adopt_prefixes) queued for
        # the scheduler thread: (meta, arrays, future) triples, drained
        # at iteration boundaries so donated dispatches stay
        # single-threaded with every other arena touch
        self._admin_tasks: List[tuple] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._busy_since: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._req_counter = 0
        self._step_counter = 0
        # per-iteration decision log (docs/observability.md): one
        # structured row per scheduler iteration — admitted/evicted/shed
        # counts, block + width-bucket state, spec proposed/accepted
        # deltas.  Bounded (PFX_DECISION_LOG_CAP, default 4096) and
        # gated on tracing being enabled (PFX_TRACE_SAMPLE>0): replaying
        # an untruncated log reproduces pfx_prefill_admits_total /
        # pfx_request_evictions_total / pfx_spec_accepted_total exactly
        # (utils/tracing.replay_decision_log; agreement-tested).
        self.decision_log: deque = deque(
            maxlen=_env_int("PFX_DECISION_LOG_CAP", 4096)
        )
        self._iter_counter = 0
        # goodput ledgers (docs/observability.md "Goodput ledger").
        # Time: every scheduler-thread wall-second lands in exactly one
        # bucket — idle is stamped in _run's wait loop, the device/
        # readback/stream buckets are baseline-diffed off the engine's
        # per-phase accumulators inside _iterate, and host_sched is the
        # iterate residual, so the bucket sum closes against
        # _sched_wall_s BY CONSTRUCTION (drilled to <=1%).
        self._time_ledger: Dict[str, float] = {
            "device_decode": 0.0, "device_prefill": 0.0,
            "host_sched": 0.0, "readback": 0.0,
            "stream_flush": 0.0, "idle": 0.0,
        }
        self._sched_wall_s = 0.0
        # Tokens: bank accounting over ADMITTED (committed) tokens.
        # admitted == delivered + evicted_lost + preempt_refunded +
        # shed_after_admit + (tokens still on live rows) holds EXACTLY
        # at every iteration boundary; preempt refunds the on-book
        # amount and a resume re-admits its carried prefix, so the
        # equation survives any preempt/resume interleaving.  Scheduler
        # thread writes only; _ledger_admit_base folds the engine's
        # commit-site counter per call site.
        self._tok_ledger: Dict[str, int] = {
            "admitted": 0, "delivered": 0, "evicted_lost": 0,
            "preempt_refunded": 0, "shed_after_admit": 0,
        }
        self._ledger_admit_base = 0
        # per-tenant-label occupancy integrals (billing-grade cost
        # attribution): decode-slot seconds and KV-block seconds,
        # accrued over each iteration's duration for every live row.
        # The scheduler never parks with live rows (_run's wait
        # predicate), so iterate durations cover all occupancy.
        self._tenant_occ: Dict[str, Dict[str, float]] = {}
        # engine-side debug view published by the scheduler thread at
        # the end of every iteration (read by debug_state() without
        # taking any lock the scheduler holds during decode).  With
        # tracing disabled AND no /debug client ever seen, the per-
        # iteration rebuild is skipped — the zero-observability-work
        # configuration pays nothing; the first debug_state() call
        # latches interest and views are fresh from the next iteration
        self._debug_requested = False
        self._debug_engine: Dict[str, Any] = self._engine_debug_view()
        # same pfx_queue_* registry names as RequestQueue (one scheduler
        # runs per process; /healthz's queue block works unchanged) plus
        # the continuous-only counters
        self.stats = StatsView(
            {
                "submitted": "pfx_queue_submitted_total",
                "completed": "pfx_queue_completed_total",
                "batches": "pfx_queue_batches_total",
                "coalesced_batches": "pfx_queue_coalesced_batches_total",
                "coalesced_requests": "pfx_queue_coalesced_requests_total",
                "shed_deadline": "pfx_queue_shed_deadline_total",
                "rejected_full": "pfx_queue_rejected_full_total",
                "rejected_closed": "pfx_queue_rejected_closed_total",
                "gen_errors": "pfx_queue_gen_errors_total",
                "evictions": "pfx_request_evictions_total",
                "prefill_admits": "pfx_prefill_admits_total",
                # instance-local: the per-tenant labeled counter
                # (pfx_tenant_preemptions_total) is the exported form
                "preemptions": None,
            }
        )
        get_registry().register_collector(self)

    def collect(self):
        eng = self.engine
        occ = eng.active_rows() / max(1, eng.capacity)
        cstats = eng.cache.stats()
        out = [
            ("pfx_queue_depth", {}, float(self.depth())),
            ("pfx_queue_busy_seconds", {}, self.busy_seconds()),
            ("pfx_batch_occupancy", {}, occ),
            ("pfx_kv_blocks_used", {}, float(cstats["kv_blocks_used"])),
            ("pfx_kv_blocks_free", {}, float(cstats["kv_blocks_free"])),
            # free + reclaimable cached-prefix blocks: what an admission
            # can actually obtain — /healthz surfaces it and the decode
            # pool controller + router scoring read it (a nearly-full
            # arena must stop attracting adoptions it will bounce)
            ("pfx_kv_blocks_available", {},
             float(eng.cache.available_blocks())),
            # live arena payload bytes: used blocks x K+V bytes/block —
            # int8 halves the per-block bytes, the acceptance evidence.
            # kv_blocks_used counts PHYSICAL blocks (refcount-deduped),
            # so neither gauge can exceed the arena under any sharing
            ("pfx_kv_bytes", {},
             float(cstats["kv_blocks_used"]) * eng.kv_block_bytes()),
            ("pfx_prefix_cached_blocks", {},
             float(cstats["prefix_cached_blocks"])),
            # host-RAM spill tier occupancy (0 when --prefix-spill-bytes
            # is off; the spills/readmits/discards counters live in the
            # engine's readmit/spill sites)
            ("pfx_prefix_spill_bytes", {},
             float(cstats["prefix_spill_bytes"])),
            ("pfx_prefix_spill_entries", {},
             float(cstats["prefix_spill_entries"])),
        ]
        if eng.spec is not None:
            prop = float(eng.stats["spec_proposed"])
            out.append((
                "pfx_spec_accept_rate", {},
                float(eng.stats["spec_accepted"]) / prop if prop else 0.0,
            ))
        # goodput ledgers (docs/observability.md "Goodput ledger"):
        # the per-bucket time counters close against the wall counter
        # (<=1% drift) and the token dispositions close against
        # admitted exactly once in_flight drains to zero
        for b, v in sorted(self._time_ledger.items()):
            out.append((
                "pfx_sched_time_seconds_total", {"bucket": b}, round(v, 6),
            ))
        out.append((
            "pfx_sched_wall_seconds_total", {}, round(self._sched_wall_s, 6),
        ))
        # device-starved host seconds (host_gap_s): overlaps the
        # host_sched/readback buckets rather than joining the exhaustive
        # bucket family — it is the goodput_frac subtrahend
        # (goodput = 1 - host_gap / non-idle wall)
        out.append((
            "pfx_sched_host_gap_seconds_total", {},
            round(float(eng.stats["host_gap_s"]), 6),
        ))
        for d, v in sorted(self._tok_ledger.items()):
            out.append((
                "pfx_token_ledger_total", {"disposition": d}, float(v),
            ))
        out.append((
            "pfx_token_ledger_in_flight", {}, float(self._ledger_in_flight()),
        ))
        for lab, occ in sorted(self._tenant_occ.items()):
            out.append((
                "pfx_tenant_slot_seconds_total", {"tenant": lab},
                round(occ["slot_s"], 6),
            ))
            out.append((
                "pfx_tenant_kv_block_seconds_total", {"tenant": lab},
                round(occ["kv_block_s"], 6),
            ))
        per_tenant: Dict[str, int] = {}
        with self._lock:
            for e in self._entries:
                lab = self._tenant_labels.label(e.tenant)
                per_tenant[lab] = per_tenant.get(lab, 0) + 1
        for lab, n in sorted(per_tenant.items()):
            out.append(("pfx_tenant_queue_depth", {"tenant": lab}, float(n)))
        return out

    # -- admission (RequestQueue-compatible surface) --------------------
    def submit(self, prompts: Sequence[Any], max_new_tokens: int, *,
               coalesce_key=None, deadline_s: Optional[float] = None,
               stream=None, tenant: Optional[str] = None,
               priority: int = 0) -> RequestFuture:
        """``stream`` (optional): a ``stream(row_idx, start, tokens)``
        callable invoked on the scheduler thread as tokens commit —
        the token-streaming hook tools/serve.py's SSE path plugs in
        (see :class:`_CBEntry`).  ``tenant``/``priority`` place the
        entry in its weighted-fair tenant queue and priority class."""
        if not prompts:
            raise ValueError("prompts must be non-empty")
        for p in prompts:
            self.engine.validate_request(len(p), int(max_new_tokens))
        entry = _CBEntry(
            prompts=[list(p) for p in prompts],
            max_new=int(max_new_tokens),
            deadline=(time.monotonic() + float(deadline_s))
            if deadline_s is not None else None,
            future=RequestFuture(),
            enqueued_at=time.monotonic(),
            stream=stream,
            tenant=normalize_tenant(tenant),
            priority=int(priority),
        )
        entry.future.times["enqueued"] = entry.enqueued_at
        # deep-dive tracing (sampled; no-op at PFX_TRACE_SAMPLE=0):
        # attached BEFORE the entry becomes visible to the scheduler
        # thread, or a fast pickup could miss the prefill span
        attach_request_trace(
            entry.future, t0=entry.enqueued_at, scheduler=self.name,
            prompts=len(entry.prompts), max_new=entry.max_new,
        )
        try:
            with self._wake:
                if self._closed:
                    self.stats["rejected_closed"] += 1
                    raise QueueClosed(f"{self.name} queue is draining")
                if len(self._entries) >= self.max_depth:
                    self.stats["rejected_full"] += 1
                    raise QueueFull(
                        f"{self.name} queue full ({self.max_depth} waiting)"
                    )
                self._entries.append(entry)
                self.stats["submitted"] += 1
                self._wake.notify_all()
        except (QueueClosed, QueueFull):
            discard_request_trace(entry.future)  # never admitted
            raise
        return entry.future

    def submit_handoff(self, meta: Dict[str, Any], arrays: Dict[str, Any],
                       *, deadline_s: Optional[float] = None,
                       tenant: Optional[str] = None, priority: int = 0
                       ) -> RequestFuture:
        """Admit a disaggregated KV-handoff payload (one prefilled row
        from a prefill replica): same bounded-queue/deadline surface as
        :meth:`submit`, but the admission loop ADOPTS the exported blocks
        instead of prefilling.  Pre-admission validation is loud: an
        incompatible payload (dtype/block-size/pool-shape) or a
        could-never-fit budget raises ``ValueError`` before a queue slot
        is spent (HTTP 400 in tools/serve.py)."""
        check_handoff_meta(
            meta, block=self.engine.block, kv_dtype=self.engine.kv_dtype,
            pool_sig=self.engine._pool_sig(),
        )
        prompt = [int(t) for t in meta.get("prompt_ids", [])]
        max_new = int(meta.get("max_new", 0))
        self.engine.validate_request(len(prompt), max_new)
        entry = _CBEntry(
            prompts=[prompt],
            max_new=max_new,
            deadline=(time.monotonic() + float(deadline_s))
            if deadline_s is not None else None,
            future=RequestFuture(),
            enqueued_at=time.monotonic(),
            handoff=(meta, arrays),
            tenant=normalize_tenant(tenant),
            priority=int(priority),
        )
        entry.future.times["enqueued"] = entry.enqueued_at
        attach_request_trace(
            entry.future, t0=entry.enqueued_at, scheduler=self.name,
            prompts=1, max_new=entry.max_new,
        )
        try:
            with self._wake:
                if self._closed:
                    self.stats["rejected_closed"] += 1
                    raise QueueClosed(f"{self.name} queue is draining")
                if len(self._entries) >= self.max_depth:
                    self.stats["rejected_full"] += 1
                    raise QueueFull(
                        f"{self.name} queue full ({self.max_depth} waiting)"
                    )
                self._entries.append(entry)
                self.stats["submitted"] += 1
                self._wake.notify_all()
        except (QueueClosed, QueueFull):
            discard_request_trace(entry.future)  # never admitted
            raise
        return entry.future

    def submit_prefix_adoption(self, meta: Dict[str, Any],
                               arrays: Dict[str, Any]) -> RequestFuture:
        """Queue a draining peer's exported prefix payload for adoption
        on the scheduler thread (POST /admin/adopt_prefixes).  The FULL
        structural validation runs here, pre-queue — a torn or
        incompatible payload raises ``ValueError`` now (HTTP 400) and
        never reaches a donated dispatch (the adopt rule).  The future
        resolves with the adopted-block count once the scheduler folds
        the payload in at an iteration boundary."""
        self.engine.validate_prefix_payload(meta, arrays)
        fut = RequestFuture()
        with self._wake:
            if self._closed:
                raise QueueClosed(f"{self.name} queue is draining")
            self._admin_tasks.append((meta, arrays, fut))
            self._wake.notify_all()
        return fut

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def busy_seconds(self) -> float:
        with self._lock:
            if self._busy_since is None:
                return 0.0
            return time.monotonic() - self._busy_since

    # -- goodput ledgers ------------------------------------------------
    def _fold_admitted(self) -> None:
        """Fold the engine's commit-site admitted-token counter into the
        scheduler ledger.  Called right after any step/flush that can
        commit tokens and BEFORE the rows are resolved or failed, so
        delivered/lost never outruns admitted within an iteration."""
        cur = int(self.engine.stats["ledger_admitted"])
        if cur != self._ledger_admit_base:
            self._tok_ledger["admitted"] += cur - self._ledger_admit_base
            self._ledger_admit_base = cur

    def _row_on_books(self, row: "_Row") -> int:
        """Tokens currently on the books for one live slot row: commits
        since its (last) admission plus the resume prefix it re-admitted
        (row_prefill carries it for rows seated via a resume)."""
        if row.entry is None:
            return 0
        return len(row.tokens) + len(
            row.entry.row_prefill.get(row.row_idx, ())
        )

    def _ledger_in_flight(self) -> int:
        """Admitted tokens without a terminal disposition yet: the sum
        over live scheduler-owned rows of their on-book tokens."""
        return sum(
            self._row_on_books(r)
            for r in self.engine.slots if r is not None
        )

    def time_ledger(self) -> Dict[str, Any]:
        """Snapshot of the scheduler-thread time ledger (bench/report
        accessor): per-bucket seconds plus the wall total they close
        against."""
        return {
            "buckets": dict(self._time_ledger),
            "wall_s": self._sched_wall_s,
        }

    def token_ledger(self) -> Dict[str, int]:
        """Snapshot of the token ledger plus the live in-flight count —
        ``admitted == delivered + evicted_lost + preempt_refunded +
        shed_after_admit + in_flight`` holds exactly at iteration
        boundaries (and with ``in_flight == 0`` at quiescence)."""
        out = dict(self._tok_ledger)
        out["in_flight"] = self._ledger_in_flight()
        return out

    def try_remove(self, future: RequestFuture) -> bool:
        """Shed a WAITING entry (no row admitted yet).  An entry already
        in the running batch resolves via mid-decode eviction at its
        deadline instead."""
        with self._wake:
            for e in self._entries:
                if e.future is future and e.next_row == 0:
                    self._entries.remove(e)
                    self.stats["shed_deadline"] += 1
                    if e.future.trace is not None:
                        e.future.trace.event("shed", reason="handler_timeout")
                    e.future.set_exception(
                        DeadlineExceeded("deadline exceeded while queued")
                    )
                    return True
        return False

    # -- live introspection (GET /debug/state) --------------------------
    def _engine_debug_view(self) -> Dict[str, Any]:
        """The engine-side half of debug_state(), built ONLY on the
        scheduler thread (or before it starts): per-row positions and
        budgets, arena occupancy/fragmentation, width bucket, compile-
        key family counts.  Carries lengths/counts, never token ids."""
        eng = self.engine
        rows = []
        for i, r in enumerate(eng.slots):
            if r is None:
                continue
            rows.append({
                "slot": i,
                "seq_id": r.seq_id,
                "prompt_len": r.prompt_len,
                "max_new": r.max_new,
                "position": int(eng.positions[i]),
                "gen_step": int(eng.gen_steps[i]),
                "tokens_out": len(r.tokens),
                "blocks": len(r.table),
                "active": bool(eng.active[i]),
                "prefix_hit_tokens": r.prefix_hit,
                "prefill_pending": len(r.pending),
            })
        view: Dict[str, Any] = {
            # which scheduler iteration this view reflects: staleness is
            # visible to the reader, never silent
            "as_of_iter": self._iter_counter,
            "batch": {
                "capacity": eng.capacity,
                "active_rows": eng.active_rows(),
                "occupancy": round(
                    eng.active_rows() / max(1, eng.capacity), 4
                ),
                "width_bucket": eng.table_width_bucket(),
                "rows": rows,
            },
            "arena": eng.cache.stats(),
            "overlap": {
                "dispatch_ahead": bool(eng.dispatch_ahead),
                "quantum": self.quantum,
                "inflight": eng.has_inflight,
                "host_gap_s": round(float(eng.stats["host_gap_s"]), 6),
                "gap_steps": int(eng.stats["gap_steps"]),
            },
            "compiled": {
                "prefill_families": len(eng._compiled_prefill),
                "step_families": len(eng._compiled_step),
                "chunk_families": len(eng._compiled_chunk),
                "traces": int(eng.stats["traces"]),
            },
            # goodput ledgers, snapshotted in the SAME build as the row
            # list above: tokens.admitted == delivered + evicted_lost +
            # preempt_refunded + shed_after_admit + tokens_in_flight
            # holds EXACTLY within this view
            "goodput": {
                "time_s": {
                    k: round(v, 6) for k, v in self._time_ledger.items()
                },
                "wall_s": round(self._sched_wall_s, 6),
                "tokens": dict(self._tok_ledger),
                "tokens_in_flight": self._ledger_in_flight(),
                "tenant_occupancy": {
                    lab: {
                        "slot_s": round(occ["slot_s"], 6),
                        "kv_block_s": round(occ["kv_block_s"], 6),
                    }
                    for lab, occ in sorted(self._tenant_occ.items())
                },
            },
        }
        if eng.prefix_enabled or eng.prefill_chunk:
            pfx = eng.cache.prefix
            view["prefix_cache"] = {
                "enabled": eng.prefix_enabled,
                "budget_blocks": pfx.budget,
                "cached_blocks": pfx.cached_blocks(),
                "hits": int(pfx.stats["hits"]),
                "misses": int(pfx.stats["misses"]),
                "hit_tokens": int(pfx.stats["hit_tokens"]),
                "evictions": int(pfx.stats["evictions"]),
                "prefill_chunk": eng.prefill_chunk,
                "prefill_chunks": int(eng.stats["prefill_chunks"]),
                "prefill_tokens": int(eng.stats["prefill_tokens"]),
                "spill_budget_bytes": eng.cache.spill.budget,
                "spill_bytes": eng.cache.spill.bytes_used(),
                "spill_entries": len(eng.cache.spill),
                "spills": int(eng.cache.spill.stats["spills"]),
                "readmits": int(eng.cache.spill.stats["readmits"]),
                "spill_discards": int(eng.cache.spill.stats["discards"]),
                "migrate_adopted": int(eng.stats["migrate_adopted"]),
            }
        if eng.spec is not None:
            prop = int(eng.stats["spec_proposed"])
            acc = int(eng.stats["spec_accepted"])
            view["spec"] = {
                "draft_k": eng.spec.draft_k,
                "proposed": prop,
                "accepted": acc,
                "accept_rate": round(acc / prop, 4) if prop else 0.0,
            }
        return view

    def _publish_debug(self) -> None:
        # one atomic reference assignment: readers get either the old
        # or the new fully-built view, never a torn one
        self._debug_engine = self._engine_debug_view()

    def debug_state(self) -> Dict[str, Any]:
        """Read-only snapshot for ``GET /debug/state``: the waiting
        queue (under this scheduler's lock, briefly) plus the engine
        view.  While the scheduler is mid-iteration the view is the one
        PUBLISHED at the last iteration end (the HTTP thread never
        touches live engine state, so a decode step is never blocked or
        torn); while the scheduler is provably parked (``_busy_since``
        is None under this lock, and it cannot enter ``_iterate``
        without re-acquiring it) the view is rebuilt LIVE here — an
        idle, quiesced server always reports current arena/row state
        even with tracing disabled.  ``as_of_iter`` marks which
        iteration the view reflects."""
        self._debug_requested = True
        now = time.monotonic()
        with self._lock:
            waiting = [
                {
                    "age_s": round(now - e.enqueued_at, 4),
                    "prompts": len(e.prompts),
                    "admitted_rows": e.next_row,
                    "max_new": e.max_new,
                    "deadline_in_s": (
                        round(e.deadline - now, 4)
                        if e.deadline is not None else None
                    ),
                    "tenant": e.tenant,
                    "priority": e.priority,
                    "requeued_rows": len(e.requeue_rows),
                }
                for e in self._entries
            ]
            tenant_admitted = dict(self._tenant_admitted)
            tenant_preempted = dict(self._tenant_preempted)
            closed = self._closed
            busy = (
                now - self._busy_since if self._busy_since is not None else 0.0
            )
            decisions = list(self.decision_log)  # appended under this lock
            if self._busy_since is None:
                # scheduler parked: engine state is stable, refresh the
                # view (O(capacity) dict build — microseconds; the next
                # iteration can't start until we release this lock)
                self._publish_debug()
        # aggregate per LABEL (the top-k fold) so the keys line up with
        # the admitted/preempted counters; raw names stay on the
        # per-entry waiting rows above
        tenants: Dict[str, Dict[str, Any]] = {}
        for w in waiting:
            lab = self._tenant_labels.label(w["tenant"])
            t = tenants.setdefault(lab, {"waiting": 0, "admitted_rows": 0})
            t["waiting"] += 1
        for lab, n in tenant_admitted.items():
            tenants.setdefault(lab, {"waiting": 0})["admitted_rows"] = n
        for lab, n in tenant_preempted.items():
            tenants.setdefault(lab, {"waiting": 0})["preempted_rows"] = n
        return {
            "scheduler": "continuous",
            "depth": len(waiting),
            "waiting": waiting,
            "tenants": tenants,
            "preempt_min_tokens": self.preempt_min_tokens,
            "busy_s": round(busy, 4),
            "closed": closed,
            "iterations": self._iter_counter,
            "decisions": decisions,
            **self._debug_engine,
        }

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "ContinuousScheduler":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name=f"{self.name}-scheduler", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        with self._wake:
            self._closed = True
            self._wake.notify_all()

    def join(self, timeout: Optional[float] = None) -> bool:
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> bool:
        self.close()
        if not drain:
            with self._wake:
                while self._entries:
                    e = self._entries.pop(0)
                    e.future.set_exception(
                        QueueClosed(f"{self.name} queue shut down")
                    )
                self._wake.notify_all()
        return self.join(timeout)

    def warmup(self, prompt_lens: Sequence[int]) -> Dict[str, float]:
        per = self.engine.warmup(prompt_lens)
        self._publish_debug()  # /debug/state sees the warmed compile keys
        return per

    # -- scheduler loop -------------------------------------------------
    def _has_live_rows(self) -> bool:
        return any(r is not None for r in self.engine.slots)

    def _run(self) -> None:
        while True:
            t_wait0 = time.monotonic()
            with self._wake:
                while (not self._entries and not self._admin_tasks
                       and not self._has_live_rows()):
                    if self._closed:
                        return  # drained
                    self._wake.wait()
                t_busy0 = time.monotonic()
                self._busy_since = t_busy0
                # time-ledger idle: the parked wait between iterations.
                # _iterate accounts its own duration, so idle + the
                # iterate folds cover this thread's whole wall clock.
                self._time_ledger["idle"] += t_busy0 - t_wait0
                self._sched_wall_s += t_busy0 - t_wait0
            try:
                self._iterate()
            finally:
                with self._lock:
                    self._busy_since = None

    def _shed_locked(self, entry: _CBEntry) -> None:
        self.stats["shed_deadline"] += 1
        waited = time.monotonic() - entry.enqueued_at
        logger.warning(
            f"{self.name}: shed expired request after {waited:.2f}s queued"
        )
        if entry.future.trace is not None:
            entry.future.trace.event("shed", reason="expired_in_queue")
        entry.future.set_exception(
            DeadlineExceeded(f"deadline exceeded after {waited:.2f}s queued")
        )

    def _evict_entry(self, entry: _CBEntry, reason: str) -> None:
        """Mid-decode eviction: free every admitted row of the entry and
        resolve its future.  Blocks return to the pool IMMEDIATELY — the
        next admission can use them this same iteration.  Token ledger:
        the rows' on-book tokens get their terminal disposition here —
        ``shed_after_admit`` when the entry expired only PARTIALLY
        admitted (reason ``expired_partial``), ``evicted_lost`` for a
        fully-admitted entry evicted mid-decode."""
        eng = self.engine
        disposition = (
            "shed_after_admit" if reason == "expired_partial"
            else "evicted_lost"
        )
        n = 0
        for i, r in enumerate(eng.slots):
            if r is not None and r.entry is entry:
                self._tok_ledger[disposition] += self._row_on_books(r)
                eng.release(i)
                n += 1
        self.stats["evictions"] += n
        self.stats["shed_deadline"] += 1
        waited = time.monotonic() - entry.enqueued_at
        if entry.future.trace is not None:
            entry.future.trace.event("evicted", rows=n, reason=reason)
        logger.warning(
            f"{self.name}: evicted {n} mid-decode row(s) of an expired "
            f"request after {waited:.2f}s ({reason})"
        )
        if not entry.future.done():
            entry.future.set_exception(
                DeadlineExceeded(
                    f"deadline exceeded after {waited:.2f}s ({reason})"
                )
            )

    def _fail_rows(self, rows, exc: BaseException) -> None:
        # token ledger: the rows died with their on-book tokens (the
        # arena reset already released them) — every one is evicted_lost.
        # Fold first: commits that landed before the crash must be
        # admitted before they can be lost.
        self._fold_admitted()
        for r in rows:
            if r.entry is not None:
                self._tok_ledger["evicted_lost"] += (
                    len(r.tokens)
                    + len(r.entry.row_prefill.get(r.row_idx, ()))
                )
        failed = {r.entry for r in rows if r.entry is not None}
        for e in failed:
            if not e.future.done():
                e.future.set_exception(exc)

    def _iterate(self) -> None:
        # per-iteration decision accounting (the decision log's row):
        # pre-iteration counter baselines diffed at the end, so every
        # SCHEDULER-side admit/evict/shed — including helper-raised
        # ones — lands in exactly one row.  (A handler-thread
        # try_remove shed can land between iterations: shed rows are
        # scheduler-side only, and shed is deliberately NOT part of the
        # exact-replay trio.)
        eng = self.engine
        admit0 = int(self.stats["prefill_admits"])
        shed0 = int(self.stats["shed_deadline"])
        evict0 = int(self.stats["evictions"])
        spec_p0 = int(eng.stats["spec_proposed"])
        spec_a0 = int(eng.stats["spec_accepted"])
        pfx = eng.cache.prefix.stats
        pfx_h0 = int(pfx["hits"])
        pfx_t0 = int(pfx["hit_tokens"])
        pfx_e0 = int(pfx["evictions"])
        chunks0 = int(eng.stats["prefill_chunks"])
        spill = eng.cache.spill.stats
        spill_s0 = int(spill["spills"])
        spill_r0 = int(spill["readmits"])
        spill_d0 = int(spill["discards"])
        mig_a0 = int(eng.stats["migrate_adopted"])
        blocks_free0 = eng.cache.allocator.free_count()
        tadmit0 = dict(self._tenant_admitted)
        tpre0 = dict(self._tenant_preempted)
        # goodput-ledger baselines: the iterate's wall duration is fully
        # attributed — engine per-phase deltas plus a host_sched
        # residual — and the token columns are per-iteration deltas of
        # the same dicts the registry and /debug/state export
        t_iter0 = time.monotonic()
        tdd0 = float(eng.stats["t_device_decode"])
        tdp0 = float(eng.stats["t_device_prefill"])
        trb0 = float(eng.stats["t_readback"])
        tsf0 = float(eng.stats["t_stream_flush"])
        tok0 = dict(self._tok_ledger)
        n_finished = 0
        try:
            n_finished = self._iterate_inner()
        finally:
            self._fold_admitted()
            dur = time.monotonic() - t_iter0
            dd = float(eng.stats["t_device_decode"]) - tdd0
            dp = float(eng.stats["t_device_prefill"]) - tdp0
            rb = float(eng.stats["t_readback"]) - trb0
            sf = float(eng.stats["t_stream_flush"]) - tsf0
            led = self._time_ledger
            led["device_decode"] += dd
            led["device_prefill"] += dp
            led["readback"] += rb
            led["stream_flush"] += sf
            led["host_sched"] += max(0.0, dur - (dd + dp + rb + sf))
            self._sched_wall_s += dur
            # per-tenant occupancy integrals: every live row held its
            # decode slot and KV blocks for this whole iteration
            for r in eng.slots:
                if r is not None and r.entry is not None:
                    lab = self._tenant_labels.label(r.entry.tenant)
                    occ = self._tenant_occ.setdefault(
                        lab, {"slot_s": 0.0, "kv_block_s": 0.0}
                    )
                    occ["slot_s"] += dur
                    occ["kv_block_s"] += len(r.table) * dur
            self._iter_counter += 1
            if get_trace_buffer().enabled:
                row = {
                    "iter": self._iter_counter,
                    "t": round(time.monotonic(), 6),
                    # baseline-diffed (like evicted/shed), NOT the inner
                    # return value: an exception escaping after some
                    # admits succeeded must still land them in this row
                    # or the replay contract breaks with no event lost
                    "admitted": int(self.stats["prefill_admits"]) - admit0,
                    "evicted": int(self.stats["evictions"]) - evict0,
                    "shed": int(self.stats["shed_deadline"]) - shed0,
                    # informational only (not a replayed counter): 0 when
                    # the step raised before resolving finishes
                    "finished": n_finished,
                    "active": eng.active_rows(),
                    "width_bucket": eng.table_width_bucket(),
                    "blocks_free": eng.cache.allocator.free_count(),
                    "blocks_delta":
                        eng.cache.allocator.free_count() - blocks_free0,
                    "spec_proposed":
                        int(eng.stats["spec_proposed"]) - spec_p0,
                    "spec_accepted":
                        int(eng.stats["spec_accepted"]) - spec_a0,
                    # prefix-reuse + chunked-prefill accounting: hits
                    # join the exact-replay contract (replay reproduces
                    # pfx_prefix_hits_total like the admit/evict trio)
                    "prefix_hits": int(pfx["hits"]) - pfx_h0,
                    "prefix_hit_tokens": int(pfx["hit_tokens"]) - pfx_t0,
                    "prefix_evictions": int(pfx["evictions"]) - pfx_e0,
                    "chunks": int(eng.stats["prefill_chunks"]) - chunks0,
                    # spill-tier + migration deltas: every site moves
                    # the store stats and registry counters together,
                    # so the replay fold reproduces pfx_prefix_spills/
                    # readmits/spill_discards and pfx_migrate_adopted
                    # exactly (the PR 8/12 contract extended)
                    "spills": int(spill["spills"]) - spill_s0,
                    "readmits": int(spill["readmits"]) - spill_r0,
                    "spill_discards": int(spill["discards"]) - spill_d0,
                    "migrate_adopted":
                        int(eng.stats["migrate_adopted"]) - mig_a0,
                    # token-ledger columns (baseline-diffed like the
                    # trio): folding an untruncated log reproduces the
                    # pfx_token_ledger_total dispositions exactly
                    "tok_admitted":
                        self._tok_ledger["admitted"] - tok0["admitted"],
                    "tok_delivered":
                        self._tok_ledger["delivered"] - tok0["delivered"],
                    "tok_evicted_lost":
                        self._tok_ledger["evicted_lost"]
                        - tok0["evicted_lost"],
                    "tok_preempt_refunded":
                        self._tok_ledger["preempt_refunded"]
                        - tok0["preempt_refunded"],
                    "tok_shed_after_admit":
                        self._tok_ledger["shed_after_admit"]
                        - tok0["shed_after_admit"],
                }
                # multi-tenant columns (same baseline-diff discipline):
                # per-tenant-label admitted/preempted row counts — the
                # replay fold reproduces pfx_tenant_admitted_total and
                # pfx_tenant_preemptions_total exactly from these
                tenants_row = {
                    lab: n - tadmit0.get(lab, 0)
                    for lab, n in self._tenant_admitted.items()
                    if n - tadmit0.get(lab, 0)
                }
                preempted_row = {
                    lab: n - tpre0.get(lab, 0)
                    for lab, n in self._tenant_preempted.items()
                    if n - tpre0.get(lab, 0)
                }
                row["preempted"] = sum(preempted_row.values())
                if tenants_row:
                    row["tenants"] = tenants_row
                if preempted_row:
                    row["preempted_tenants"] = preempted_row
                with self._lock:
                    self.decision_log.append(row)
            if get_trace_buffer().enabled or self._debug_requested:
                self._publish_debug()

    def _iterate_inner(self):
        eng = self.engine
        now = time.monotonic()
        n_finished = 0

        # k-step scheduling quantum (PFX_SCHED_QUANTUM, default 1 =
        # every iteration): the shed/evict/admission scans below run on
        # quantum boundaries only, amortizing the host bookkeeping over
        # k decode steps.  An iteration with no live rows always takes
        # the boundary path — waiting entries must admit NOW, never
        # after k empty spins.
        boundary = (
            self.quantum <= 1
            or self._iter_counter % self.quantum == 0
            or not self._has_live_rows()
        )
        if not boundary:
            return self._step_batch()

        # peer prefix adoptions (drain-migration receiver): folded in at
        # a boundary, BEFORE this iteration's admissions, so a migrated
        # prefix is hittable by the very next admit.  Each payload was
        # fully validated at submit time; adoption failures fail only
        # their own future — except an ArenaReset, which fails every
        # live row exactly like a prefill dispatch death
        with self._wake:
            tasks, self._admin_tasks = self._admin_tasks, []
        for meta, arrays, fut in tasks:
            try:
                fut.set_result(eng.adopt_prefixes(meta, arrays))
            except ArenaReset as exc:
                self.stats["gen_errors"] += 1
                self._fail_rows(exc.dead_rows, exc)
                if not fut.done():
                    fut.set_exception(exc)
                logger.warning(f"{self.name}: {exc}")
            except Exception as exc:  # noqa: BLE001 — fail this payload
                if not fut.done():    # alone, keep serving
                    fut.set_exception(exc)
                logger.warning(
                    f"{self.name}: prefix adoption failed: "
                    f"{type(exc).__name__}: {exc}"
                )

        admitted: List[tuple] = []
        expired_partial: List[_CBEntry] = []
        with self._wake:
            # shed expired WAITING entries before spending anything; an
            # expired PARTIALLY-admitted entry leaves the queue too (its
            # remaining rows must never start) and is evicted below
            keep: List[_CBEntry] = []
            for e in self._entries:
                if e.deadline is not None and now > e.deadline:
                    if e.next_row == 0:
                        self._shed_locked(e)
                    else:
                        expired_partial.append(e)
                else:
                    keep.append(e)
            self._entries = keep

        # evict expired ACTIVE rows BEFORE picking admissions (mid-decode
        # shed): their slots and blocks return to the pool for this same
        # iteration's admissions
        expired = set(expired_partial)
        for r in eng.slots:
            if r is not None and r.entry is not None:
                e = r.entry
                if e.deadline is not None and now > e.deadline:
                    expired.add(e)
        if expired:
            # row membership is about to change: commit the in-flight
            # dispatched step first (dispatch-ahead), so evicted rows'
            # final state is folded in before their blocks return
            n_finished += self._flush_engine()
        partial = set(expired_partial)
        for e in expired:
            if e.future.done():
                continue  # the in-flight step completed it first
            # reason doubles as the ledger disposition: a PARTIALLY
            # admitted entry's on-book tokens are shed_after_admit, a
            # fully-admitted one's are evicted_lost
            self._evict_entry(
                e, "expired_partial" if e in partial else "mid-decode"
            )

        with self._wake:
            waiting = bool(self._entries)
        if waiting:
            # admission capacity (free slots/blocks) must reflect rows
            # the in-flight step just finished — the synchronous path
            # admits with exactly this view
            n_finished += self._flush_engine()

        reserved_blocks = 0
        blocked: Optional[tuple] = None
        with self._wake:
            # weighted-fair admission: a deficit round-robin across
            # tenant queues replaces the old global-FCFS head pull.
            # Each pick serves the chosen tenant's OLDEST admissible
            # unit (a preempted row waiting to resume before any fresh
            # row) and charges one deficit — FCFS within a tenant, one
            # tenant degenerates to exactly the old FCFS order.
            # Nothing is allocated until the prefill loop below, so the
            # pull accounts for its OWN picks — a burst larger than free
            # capacity stays queued instead of hard-failing at admit()
            free_slots = eng.free_slots()
            free_blocks = eng.cache.allocator.free_count()
            # cached-prefix blocks only the index references evict on
            # demand inside admit — add them to the budget LAZILY (the
            # reclaimable scan is O(cached nodes); an iteration whose
            # free pool already covers its admissions never pays it)
            reclaim_counted = False
            # already-failed entries (e.g. an earlier row died in an
            # ArenaReset) must neither reserve capacity nor spend a
            # tenant's turn
            self._entries = [e for e in self._entries if not e.future.done()]
            while self._entries:
                backlog: Dict[str, int] = {}
                for e in self._entries:
                    backlog[e.tenant] = backlog.get(e.tenant, 0) + 1
                pick = self._fair.pick(backlog)
                head = next(e for e in self._entries if e.tenant == pick)
                row_idx, p, mx, resumed = self._next_unit(head)
                need = blocks_for(
                    eng.row_capacity_tokens(len(p), mx), eng.block
                )
                if need > free_blocks and not reclaim_counted:
                    free_blocks += eng.cache.prefix.reclaimable_blocks()
                    reclaim_counted = True
                if free_slots < 1 or need > free_blocks:
                    # head-of-line blocked (same backpressure as the old
                    # FCFS pull) — remembered as the priority-preemption
                    # candidate below
                    blocked = (head, row_idx, p, mx, resumed, need)
                    break
                free_slots -= 1
                free_blocks -= need
                reserved_blocks += need
                self._fair.charge(head.tenant)
                t_pick = time.monotonic()
                head.future.times.setdefault("picked", t_pick)
                if (head.future.trace is not None and head.next_row == 0
                        and not resumed):
                    head.future.trace.span(
                        "queue_wait", t0=head.enqueued_at, t1=t_pick,
                    )
                admitted.append((head, row_idx, p, mx, resumed))
                if resumed:
                    head.requeue_rows.pop(0)
                else:
                    head.next_row += 1
                if (head.next_row >= len(head.prompts)
                        and not head.requeue_rows):
                    self._entries.remove(head)

        # priority preemption (outside the lock: engine work).  A
        # blocked arrival with strictly higher priority than the
        # lowest-priority active row may seat itself by preempting that
        # row; preempt_storm:K forces one preemption per fire with no
        # arrival needed (the deterministic drill hook).  Victims must
        # be past the protected minimum-progress floor
        # (preempt_min_tokens committed since their last admission), so
        # a priority storm cannot livelock the batch — and a preempted
        # row is requeued as a re-prefill continuation, never killed.
        storm = maybe_fire("preempt_storm", self._iter_counter + 1)
        want = None
        if blocked is not None and not blocked[0].future.done():
            want = blocked
        if want is not None or storm:
            flushed = False
            fits = False
            for _ in range(eng.capacity + 1):
                if want is not None:
                    head, row_idx, p, mx, resumed, need = want
                    free_s = eng.free_slots() - len(admitted)
                    free_b = (eng.cache.allocator.free_count()
                              - reserved_blocks)
                    if need > free_b:
                        free_b += eng.cache.prefix.reclaimable_blocks()
                    if free_s >= 1 and need <= free_b:
                        fits = True
                        break
                victim = self._pick_victim(
                    below_priority=(
                        want[0].priority if want is not None else None
                    ),
                    # before the flush a row's committed count lags the
                    # in-flight step by one token — give the pre-flush
                    # probe that slack, so the flush (which costs the
                    # dispatch-ahead overlap) only runs when a victim
                    # is at least plausibly eligible
                    progress_slack=0 if flushed else 1,
                )
                if victim is None:
                    break
                if not flushed:
                    # row membership is about to change: commit the
                    # in-flight dispatched step first (the engine's
                    # dispatch-ahead flush contract)
                    n_finished += self._flush_engine()
                    # the flush may have finished the victim — re-pick
                    # against the committed state, strictly
                    flushed = True
                    continue
                self._preempt_slot(victim)
                if want is None:
                    break  # storm fire: exactly one forced preemption
            if want is not None and fits:
                # seat the preemptor NOW: it earned the freed capacity
                # (no second fair-pick — priority cuts across fairness
                # by design, and its tenant's deficit is still charged)
                head, row_idx, p, mx, resumed, need = want
                with self._wake:
                    if not head.future.done():
                        reserved_blocks += need
                        self._fair.charge(head.tenant)
                        t_pick = time.monotonic()
                        head.future.times.setdefault("picked", t_pick)
                        if (head.future.trace is not None
                                and head.next_row == 0 and not resumed):
                            head.future.trace.span(
                                "queue_wait", t0=head.enqueued_at,
                                t1=t_pick,
                            )
                        admitted.append((head, row_idx, p, mx, resumed))
                        if resumed:
                            head.requeue_rows.pop(0)
                        else:
                            head.next_row += 1
                        if (head.next_row >= len(head.prompts)
                                and not head.requeue_rows
                                and head in self._entries):
                            self._entries.remove(head)

        # prefill-on-admit (outside the lock: device work)
        for entry, row_idx, prompt, mx, resumed in admitted:
            if entry.future.done():
                continue  # an earlier row of this entry already failed
            self._req_counter += 1
            try:
                maybe_fire("gen_crash", self._req_counter)
                if entry.handoff is not None and not resumed:
                    # disaggregated: adopt the prefill replica's exported
                    # blocks instead of running paged_prefill.  Counted in
                    # prefill_admits too — it IS a row admission, and the
                    # decision-log replay contract stays exact.  (A
                    # RESUMED handoff row re-prefills below instead: its
                    # arrays were consumed at the original adoption.)
                    meta, arrays = entry.handoff
                    eng.adopt(meta, arrays, entry=entry, row_idx=row_idx)
                else:
                    eng.admit(prompt, mx, entry=entry, row_idx=row_idx)
                if resumed:
                    # token ledger: a resume re-admits the prefix its
                    # preemption refunded — the tokens are back on the
                    # books, and finished_tokens will deliver them
                    self._tok_ledger["admitted"] += len(
                        entry.row_prefill.get(row_idx, ())
                    )
                self.stats["prefill_admits"] += 1
                lab = self._tenant_labels.label(entry.tenant)
                self._tenant_admitted[lab] = (
                    self._tenant_admitted.get(lab, 0) + 1
                )
                get_registry().counter(
                    "pfx_tenant_admitted_total", tenant=lab
                ).inc()
            except ArenaReset as exc:
                # the donating prefill dispatch failed: every live row
                # died with the arena — fail them all, keep serving on
                # the fresh pools
                self.stats["gen_errors"] += 1
                self._fail_rows(exc.dead_rows, exc)
                if not entry.future.done():
                    entry.future.set_exception(exc)
                logger.warning(f"{self.name}: {exc}")
            except (BlockPoolExhausted, RuntimeError, ValueError) as exc:
                # host-side failure BEFORE any dispatch (capacity raced
                # between the locked check and here, or an injected
                # crash): arena intact, fail only this entry
                self.stats["gen_errors"] += 1
                for i, r in enumerate(eng.slots):
                    if r is not None and r.entry is entry:
                        # sibling rows admitted earlier die with their
                        # on-book tokens: evicted_lost
                        self._tok_ledger["evicted_lost"] += (
                            self._row_on_books(r)
                        )
                        eng.release(i)
                if not entry.future.done():
                    entry.future.set_exception(exc)
                logger.warning(
                    f"{self.name}: admission failed: "
                    f"{type(exc).__name__}: {exc}"
                )

        if not self._has_live_rows():
            return n_finished
        return n_finished + self._step_batch()

    def _step_batch(self) -> int:
        """One iteration-level decode step: dispatch (and, synchronous
        or commit-first, fetch) via engine.step(), then resolve the rows
        it finished.  Under dispatch-ahead the finished rows are the
        PREVIOUS step's — commit order, which is exactly the order the
        decision log accounts them in."""
        if not self._has_live_rows():
            return 0
        self._step_counter += 1
        maybe_fire("cb_step_hang", self._step_counter)
        try:
            finished = self.engine.step()
        except ArenaReset as exc:
            self.stats["gen_errors"] += 1
            self._fail_rows(exc.dead_rows, exc)
            logger.warning(f"{self.name}: {exc}")
            return 0
        self._fold_admitted()  # before _finish_rows can deliver them
        self.stats["batches"] += 1
        return self._finish_rows(finished)

    def _flush_engine(self) -> int:
        """Commit the engine's in-flight dispatched step (no-op when
        synchronous or idle) and resolve the rows it finished.  Must
        run before anything that mutates row membership — eviction and
        admission — per the engine's dispatch-ahead flush contract."""
        if not self.engine.has_inflight:
            return 0
        try:
            finished = self.engine.flush()
        except ArenaReset as exc:
            self.stats["gen_errors"] += 1
            self._fail_rows(exc.dead_rows, exc)
            logger.warning(f"{self.name}: {exc}")
            return 0
        self._fold_admitted()  # before _finish_rows can deliver them
        return self._finish_rows(finished)

    def _next_unit(self, head: "_CBEntry") -> tuple:
        """The entry's next admissible unit.  A preempted row waiting to
        resume goes before any fresh row: its prompt is the original
        prompt plus every committed token (the last sampled token needs
        no KV yet, so the whole committed prefix re-prefills — mostly as
        a radix-index prefix hit), and its budget is what remains.
        Returns ``(row_idx, prompt, max_new, resumed)``."""
        if head.requeue_rows:
            row_idx = head.requeue_rows[0]
            committed = head.row_prefill.get(row_idx, [])
            return (
                row_idx,
                head.prompts[row_idx] + committed,
                head.max_new - len(committed),
                True,
            )
        return head.next_row, head.prompts[head.next_row], head.max_new, False

    def _pick_victim(self, below_priority: Optional[int],
                     progress_slack: int = 0) -> Optional[int]:
        """The slot of the lowest-priority active row eligible for
        preemption, or None.  Eligible means: decode-active with prefill
        done, its entry still live, past the protected minimum-progress
        floor (``preempt_min_tokens`` committed since its last
        admission — the anti-livelock guard: a resumed victim must
        re-earn eligibility before it can be preempted again), and —
        unless ``below_priority`` is None (the preempt_storm drill) —
        strictly below the preemptor's priority.  Deterministic
        tie-break: lowest slot index."""
        eng = self.engine
        best: Optional[int] = None
        for i, r in enumerate(eng.slots):
            if r is None or r.entry is None or r.entry.future.done():
                continue
            if not r.prefill_done or not bool(eng.active[i]):
                continue
            if len(r.tokens) + progress_slack < self.preempt_min_tokens:
                continue
            if below_priority is not None and r.entry.priority >= below_priority:
                continue
            if best is None or (
                (r.entry.priority, i)
                < (eng.slots[best].entry.priority, best)
            ):
                best = i
        return best

    def _preempt_slot(self, slot: int) -> None:
        """Evict one active row mid-decode and requeue it as a
        re-prefill continuation: the engine publishes its KV-valid
        prefix to the radix index and frees the slot, the committed
        tokens are folded into the entry's resume state, and the entry
        re-enters the queue at the FRONT (it already waited its turn).
        The caller must have flushed the engine first."""
        eng = self.engine
        row = eng.slots[slot]
        entry = row.entry
        committed = eng.preempt_row(slot)
        prev = entry.row_prefill.get(row.row_idx)
        entry.row_prefill[row.row_idx] = (
            prev + committed if prev else committed
        )
        entry.requeue_rows.append(row.row_idx)
        # token ledger: the row's WHOLE on-book amount (any earlier
        # resume prefix + this stint's commits) leaves the books as a
        # refund; the resume re-admits it, so books stay closed across
        # any preempt/resume chain
        self._tok_ledger["preempt_refunded"] += len(
            entry.row_prefill[row.row_idx]
        )
        self.stats["preemptions"] += 1
        lab = self._tenant_labels.label(entry.tenant)
        self._tenant_preempted[lab] = self._tenant_preempted.get(lab, 0) + 1
        get_registry().counter(
            "pfx_tenant_preemptions_total", tenant=lab
        ).inc()
        if row.trace is not None:
            row.trace.event(
                "preempted",
                slot=slot,
                committed=len(committed),
                total_committed=len(entry.row_prefill[row.row_idx]),
            )
        logger.info(
            f"{self.name}: preempted slot {slot} (tenant {entry.tenant}, "
            f"priority {entry.priority}) after {len(committed)} committed "
            "token(s); requeued as a re-prefill continuation"
        )
        with self._wake:
            if entry not in self._entries:
                self._entries.insert(0, entry)
            self._wake.notify_all()

    def _finish_rows(self, finished: List[int]) -> int:
        eng = self.engine
        reg = get_registry()
        for slot in finished:
            row = eng.slots[slot]
            entry = row.entry
            eng.release(slot)
            if entry is None:
                continue
            entry.results[row.row_idx] = entry.finished_tokens(
                row.row_idx, row.tokens
            )
            # token ledger: the full output (resume prefix + this
            # stint's commits) reached the results array — delivered
            self._tok_ledger["delivered"] += len(
                entry.results[row.row_idx]
            )
            entry.done_rows += 1
            if entry.done_rows == len(entry.prompts):
                entry.future.set_result(list(entry.results))
                self.stats["completed"] += 1
                reg.counter("pfx_serving_requests_total").inc()
                reg.counter("pfx_serving_tokens_out_total").inc(
                    sum(len(t) for t in entry.results)
                )
        return len(finished)
