"""Engine layer: Engine/InferenceEngine/serving + BasicModule protocol."""
