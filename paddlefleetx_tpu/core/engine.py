"""Engine: sharded train/eval loops, checkpointing, metrics.

TPU-native re-design of the reference ``EagerEngine``
(ppfleetx/core/engine/eager_engine.py:53-926).  What the reference does with
fleet wrapping + manual micro-batching + AMP scaler + pipeline scheduling is
here ONE jitted train step:

  - grad accumulation  = ``lax.scan`` over a leading microbatch dim
    (reference ``_model_forward_backward`` :522-531)
  - DP grad allreduce  = psum implied by the batch sharding (:483-506)
  - TP/SP collectives  = param/activation shardings (hybrid_model.py)
  - ZeRO               = `fsdp` axis in param/opt-state shardings (:281-307)
  - AMP O2 main-grad   = params+opt fp32, compute bf16 casts inside the
    model; grads land fp32 because params are fp32 (apis/amp.py:30-234 —
    loss scaling unneeded in bf16, kept for the fp16 parity path)
  - found_inf skip     = jnp.isfinite check on grad norm; step skipped
    lockstep on all ranks (amp.py:219-225 semantics for free under SPMD)

Checkpoint layout follows the reference contract (eager_engine.py:717-825):
orbax sharded params/opt-state + meta{step, consumed_samples} with resume.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import math
import os
import time
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlefleetx_tpu.core.module import BasicModule
from paddlefleetx_tpu.models.gpt.model import ShardingCtx
from paddlefleetx_tpu.optims.optimizer import build_optimizer, global_norm_f32
from paddlefleetx_tpu.parallel.sharding import (
    drop_small_fsdp,
    logical_to_spec,
    make_rules,
    tree_logical_to_sharding,
)
from paddlefleetx_tpu.parallel.seed import get_seed_tracker
from paddlefleetx_tpu.utils.log import logger


@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    # non-gradient mutable state (BN running stats, MoCo queue/momentum
    # params — the reference carries these as buffers/stop-gradient params,
    # e.g. moco.py:130-159); None for stateless modules
    extra: Any = None
    # fp16 DynamicLossScaler state {scale, good_steps} (reference
    # apis/amp.py:193-234); None on the bf16/fp32 paths
    scaler: Any = None

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state, self.extra, self.scaler), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten
)


def opt_state_shardings(
    opt_state_shapes, params, moment_shardings, mesh: Mesh, memory_kind=None
):
    """Sharding tree for an optax state: subtrees structurally identical to
    the param tree (mu/nu/...) get ``moment_shardings``; everything else
    (step counts, empty states) is replicated.

    This is the ZeRO move (reference group_sharded_parallel 'os_g',
    eager_engine.py:281-307): the moments shard over `fsdp` from stage 1
    on, independently of whether the params do (stage 3).  With
    ``memory_kind='pinned_host'`` the moments live in host memory — the
    reference's ``offload=True`` option."""
    params_def = jax.tree.structure(params)
    replicated = NamedSharding(mesh, P())
    if memory_kind is not None:
        moment_shardings = jax.tree.map(
            lambda s: s.with_memory_kind(memory_kind), moment_shardings
        )

    def rec(node):
        if jax.tree.structure(node) == params_def:
            return moment_shardings
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # namedtuple
            return type(node)(*[rec(c) for c in node])
        if isinstance(node, (list, tuple)):
            return type(node)(rec(c) for c in node)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return jax.tree.map(lambda _: replicated, node)

    return rec(opt_state_shapes)


def _cast_fp32_leaves(tree: Any, dtype) -> Any:
    """Cast fp32 leaves to `dtype`, passing every other dtype through —
    the one rule behind both low-precision param storage
    (multi_precision=False) and low-precision grads (main_grad=False);
    keep the two paths on this single definition so they cannot diverge."""
    return jax.tree.map(
        lambda x: x.astype(dtype) if x.dtype == jnp.float32 else x, tree
    )


def _host_offload_supported(mesh: Mesh) -> bool:
    """Probe whether this backend can COMPILE pinned_host placements over
    the mesh (having the memory space is not enough: XLA CPU's SPMD
    partitioner rejects the placement custom-calls that TPU accepts)."""
    try:
        host = NamedSharding(mesh, P(), memory_kind="pinned_host")
        jax.jit(lambda x: x + 1.0, out_shardings=host)(jnp.zeros(()))
        return True
    except Exception:
        return False


class Engine:
    """Train/eval engine over one mesh (reference EagerEngine + AutoEngine
    collapse into this: pjit IS the auto-parallel path)."""

    def __init__(self, cfg, module: BasicModule, mesh: Mesh, mode: str = "train",
                 abstract_init: bool = False):
        """abstract_init=True builds the engine WITHOUT materializing any
        state: params/opt-state become ShapeDtypeStructs carrying their
        shardings, and only ``memory_report`` (AOT compile + per-device
        memory analysis) is usable.  This is the fit-check path for
        layouts larger than the local machine — e.g. validating the
        reference's 6.7B recipe (projects/gpt/docs/hybrid_parallel.md:
        47-54) against a per-chip HBM budget on a virtual mesh."""
        self.abstract_init = abstract_init
        self.cfg = cfg
        self.module = module
        self.mesh = mesh
        eng = cfg.Engine
        self.max_steps = int(eng.max_steps)
        self.eval_freq = int(eng.get("eval_freq", 0) or 0)
        self.eval_iters = int(eng.get("eval_iters", 10))
        self.logging_freq = int(eng.get("logging_freq", 10))
        self.accumulate_steps = int(eng.get("accumulate_steps", 1))
        # cross-host replica verification cadence (reference `check` fused
        # comm group, comm_groups.py:64; parallel/check.py) — 0 disables
        self.consistency_check_freq = int(eng.get("consistency_check_freq", 0) or 0)
        self.save_steps = int(eng.get("save_load", {}).get("save_steps", 0) or 0)
        self.output_dir = eng.get("save_load", {}).get("output_dir", "./output")
        # async_save: array write proceeds in background (orbax async) and
        # meta.json — the completeness marker — lands only once the write
        # is durable, so resume never sees a half-written checkpoint
        self.async_save = bool(eng.get("save_load", {}).get("async_save", False))
        self._async_ckptr = None
        self._save_thread = None
        self._save_error = None
        self._atexit_registered = False
        # retention GC: keep only the newest N complete checkpoints
        # (0 = keep everything); the last verified-good one — the anomaly
        # rollback target — is never deleted regardless of age
        self.keep_last_n = int(eng.get("save_load", {}).get("keep_last_n", 0) or 0)
        self._last_good_ckpt: Optional[str] = None
        # preemption contract (utils/resilience.py): SIGTERM/SIGINT during
        # fit finishes the in-flight step, saves with a `preempted` marker,
        # and fit returns with this flag set so the launcher exits 0
        self.preempted = False
        # exit_after_save (tools/train.py --exit-after-save): stop cleanly
        # right after the next periodic checkpoint completes — bounds a
        # preemptible-slice run to checkpoint-aligned work units
        self.exit_after_save = bool(eng.get("exit_after_save", False))
        # anomaly guard budgets (Engine.resilience block): past them the
        # engine rolls back to the last checkpoint instead of skipping or
        # diverging forever; see utils/resilience.AnomalyGuard
        res = eng.get("resilience", {}) or {}
        self.res_enable = bool(res.get("enable", True))
        self.res_max_skip_streak = int(res.get("max_skip_streak", 10))
        self.res_spike_zscore = float(res.get("loss_spike_zscore", 0.0))
        self.res_spike_streak = int(res.get("loss_spike_streak", 5))
        self.res_loss_window = int(res.get("loss_window", 64))
        self.res_max_rollbacks = int(res.get("max_rollbacks", 2))
        # QAT (reference Compress.Quantization, compression_helper.py:19-79):
        # fake-quantized weights in the forward, fp32 masters updated
        from paddlefleetx_tpu.utils.compression import build_qat_transform

        self.qat_transform = build_qat_transform(cfg.get("Compress"))
        if self.qat_transform is not None:
            logger.info("QAT enabled: int8 fake-quant weights in fwd/eval")
        self.global_batch_size = int(cfg.Global.global_batch_size)
        # machine-readable metrics stream: one JSON line per logging step
        # (the TIPC-style harness and dashboards parse this instead of
        # regexing the console log; "" disables)
        self.metrics_file = eng.get("metrics_file", "")
        # training observatory (utils/model_stats.py): per-layer-group
        # grad/param/update statistics computed IN-GRAPH every
        # ``model_stats_every`` steps (Engine.logging.model_stats_every,
        # default = logging cadence) behind a lax.cond, riding the
        # existing step-record device fetch — no new per-step host syncs.
        # 0 disables: the train step graph is then identical to the
        # stats-less one (tests/test_model_stats.py asserts the dispatch
        # and host-sync counts match the pre-observatory loop exactly).
        log_cfg = eng.get("logging", {}) or {}
        raw_every = log_cfg.get(
            "model_stats_every", eng.get("model_stats_every")
        )
        self.model_stats_every = (
            int(raw_every) if raw_every is not None else self.logging_freq
        )
        self._group_spec = None
        self._pending_stats = None  # (step, device refs) until next log
        self._fit_peak_bytes = None  # memory watermark peak, per fit
        self._headroom_warned = False
        # unified telemetry (utils/telemetry.py): every record written to
        # the metrics stream ALSO lands in the crash flight recorder (so a
        # postmortem never depends on metrics_file being set) and the
        # logged throughput feeds the process-wide registry.  MFU: the
        # analytic GPT-family estimator (6·N per token) against the
        # per-device-kind peak (PFX_PEAK_FLOPS override); None for
        # non-GPT modules — no MFU column rather than a wrong one.
        from paddlefleetx_tpu.utils.telemetry import (
            get_flight_recorder,
            get_registry,
            model_flops_per_token,
            peak_flops,
        )

        self._registry = get_registry()
        self._recorder = get_flight_recorder()
        # retrace attribution (utils/model_stats.py): structured compile
        # events (fn, aval diff vs the previous key, elapsed) into the
        # flight ring + pfx_compile_* — installed before the first jit so
        # the train step's own compile is attributed too.  Process-wide
        # and idempotent; PFX_COMPILE_LOG=0 disables.
        from paddlefleetx_tpu.utils.model_stats import install_compile_watcher

        install_compile_watcher()
        self._flops_per_token = model_flops_per_token(
            getattr(module, "config", None)
        )
        self._peak_flops = peak_flops() if self._flops_per_token else None
        # first-dispatch trace+compile seconds (emitted once as compile_s
        # in the step record and EXCLUDED from the ips/mfu window — the
        # old first window understated early throughput wildly)
        self._compile_s: Optional[float] = None
        self._compile_emitted = False

        # fp16 parity path: dynamic loss scaling (reference DynamicLossScaler
        # apis/amp.py:193-234).  bf16 (the TPU default) needs no scaler —
        # same exponent range as fp32.
        mix = eng.get("mix_precision", {})
        # enable defaults True to match resolve_model_dtype (core/module.py),
        # and a pinned Model.dtype=float16 counts too: fp16 compute must get
        # the scaler in every spelling, never one without the other
        model_dtype = str(getattr(getattr(module, "config", None), "dtype", ""))
        self.use_loss_scaling = (
            bool(mix.get("enable", True))
            and str(mix.get("dtype", "bfloat16")) in ("float16", "fp16")
        ) or model_dtype in ("float16", "fp16")
        scale_loss = mix.get("scale_loss", 32768.0)
        scale_cfg = scale_loss if isinstance(scale_loss, dict) else {"init": scale_loss}
        self.init_loss_scaling = float(scale_cfg.get("init", 32768.0))
        self.scale_incr_every = int(scale_cfg.get("incr_every_n_steps", 1000))
        self.scale_incr_ratio = float(scale_cfg.get("incr_ratio", 2.0))
        self.scale_decr_ratio = float(scale_cfg.get("decr_ratio", 0.5))
        # main_grad=False (reference AMP O2 without main-grad, apis/amp.py):
        # differentiate w.r.t. the compute-dtype cast of the params, so the
        # gradient tree — and its per-microbatch accumulators — lives in
        # bf16/fp16 instead of fp32.  Halves grad HBM (the lever that fits
        # GPT-1.3B + AdamW on one 16G chip); costs grad-accumulation
        # precision, so it defaults to True (fp32 main grads) like the
        # reference.  The optimizer update still runs on fp32 masters; the
        # global-norm clip upcasts inside its reduction (optims/optimizer.py
        # global_norm_f32) so clipping stays exact.
        self.main_grad = bool(mix.get("main_grad", True))
        if not bool(mix.get("enable", True)):
            if not self.main_grad and "main_grad" in mix:
                # contradictory: main_grad=False is an AMP knob (it casts
                # fwd params/grads to the compute dtype); with AMP off it
                # would silently bf16-cast a nominally-fp32 run
                raise ValueError(
                    "mix_precision.main_grad=False requires "
                    "mix_precision.enable=True (main_grad only controls "
                    "the AMP gradient dtype)"
                )
            self.main_grad = True
        if (
            bool(mix.get("enable", True))
            and "dtype" in mix
            and model_dtype
            and model_dtype != str(mix["dtype"])
        ):
            # a pinned Model.dtype silently overrides the AMP dtype
            # (compute_dtype = model_dtype first), which turns an
            # explicitly-requested mix_precision.dtype into a mislabeled
            # run — r4's ZeRO-3 dryrun logged "main_grad=False: float32
            # gradients" for exactly this; fail loudly in every spelling
            raise ValueError(
                f"Model.dtype={model_dtype} contradicts "
                f"mix_precision.dtype={mix['dtype']}: pin one or make them "
                "agree (the model dtype wins, so the AMP request would be "
                "silently ignored)"
            )
        self.compute_dtype = model_dtype or str(mix.get("dtype", "bfloat16"))
        if not self.main_grad:
            logger.info(
                "AMP main_grad=False: %s gradients", self.compute_dtype
            )
        # Optimizer.multi_precision=False (reference FusedAdamW
        # multi_precision flag, optims/optimizer.py:31-56): NO fp32 master
        # weights — params live in the compute dtype and the Adam moments
        # follow it.  Frees 3 param-size fp32 buffers (masters + nu), the
        # difference between GPT-1.3B fitting one 16G chip and not; costs
        # update precision (bf16 weight updates round away ~1e-3-relative
        # deltas), so it defaults to True like the reference.
        self.multi_precision = bool(
            cfg.get("Optimizer", {}).get("multi_precision", True)
        )
        self._param_cast = None
        if not self.multi_precision and self.compute_dtype not in ("", "float32"):
            if self.compute_dtype in ("float16", "fp16"):
                # fp16 moments are unusable: typical g^2 ~1e-8 sits below
                # fp16's subnormal floor (6e-8), so nu flushes to zero and
                # the update explodes.  bf16 has the fp32 exponent range
                # and is the measured-safe pairing.
                raise ValueError(
                    "Optimizer.multi_precision=False requires bfloat16 "
                    "compute (fp16 Adam moments underflow); use "
                    "mix_precision.dtype=bfloat16 or multi_precision=True"
                )
            self._param_cast = jnp.dtype(self.compute_dtype)
            logger.info(
                "multi_precision=False: %s params, no fp32 masters",
                self.compute_dtype,
            )

        dist = cfg.get("Distributed", {})
        sharding_cfg = dist.get("sharding", {})
        sharding_degree = int(sharding_cfg.get("sharding_degree", 1))
        # default stage when a degree is configured but no stage: ZeRO-1
        # (process_dist_config normalizes this for config-file paths; the
        # fallback here covers hand-built cfg dicts)
        self.sharding_stage = int(
            sharding_cfg.get("sharding_stage", 1 if sharding_degree > 1 else 0)
        )
        self.sharding_offload = bool(
            sharding_cfg.get("sharding_offload", sharding_cfg.get("offload", False))
        )
        # params below this many elements stay whole on the fsdp axis
        # (see drop_small_fsdp) — configurable for tiny-model tests
        self.min_shard_size = int(sharding_cfg.get("min_shard_size", 1 << 16))
        num_experts = int(
            getattr(getattr(module, "config", None), "num_experts", 0) or 0
        )
        # ZeRO stage semantics (reference group_sharded_parallel
        # eager_engine.py:281-307): stage 1 = optimizer state sharded,
        # stage 2 = +gradients (reduce-scatter constraint in the train
        # step), stage 3 = +parameters.  Param rules use `fsdp` only at
        # stage 3; the moment rules use it from stage 1 on.
        self.rules = make_rules(
            fsdp_enabled=self.sharding_stage >= 3,
            sequence_parallel=bool(dist.get("sequence_parallel", False)),
            mesh=mesh,
            num_experts=num_experts,
        )
        self.moment_rules = make_rules(
            fsdp_enabled=self.sharding_stage >= 1,
            sequence_parallel=bool(dist.get("sequence_parallel", False)),
            mesh=mesh,
            num_experts=num_experts,
        )
        # Activation constraints NEVER use the fsdp mapping: ZeRO-3 shards
        # params' `embed` dim over fsdp (gathered at use), but the residual
        # stream stays batch-sharded — constraining activations' hidden dim
        # to fsdp would fight the (data,fsdp)-sharded batch inputs and trips
        # XLA's "involuntary full rematerialization" resharding path.
        self.act_rules = make_rules(
            fsdp_enabled=False,
            sequence_parallel=bool(dist.get("sequence_parallel", False)),
            mesh=mesh,
            num_experts=num_experts,
        )
        # balanced causal context parallelism: feed sequences in the zigzag
        # block order (parallel/ring_attention.zigzag_permutation) so ring
        # attention's causal masking wastes the same work on every device
        self.sep_zigzag = bool(dist.get("sep_zigzag", False)) and (
            mesh.shape.get("sep", 1) > 1
        )
        self._zigzag_perm = None
        self._zigzag_inv = None
        self._zigzag_seq = None
        pp_degree = int(dist.get("pp_degree", 1))
        if self.sep_zigzag:
            # only ring attention masks by explicit positions; any other
            # attention would silently attend across the permuted order
            attn_impl = str(getattr(getattr(module, "config", None), "attn_impl", ""))
            if attn_impl != "ring":
                raise ValueError(
                    f"sep_zigzag requires Model.attn_impl=ring, got {attn_impl!r}"
                )
            # pp composes: ctx.attn_positions rides into the 1F1B chunk
            # fns as a stage-replicated constant, and ring attention's
            # inner shard_map nests against the ambient abstract mesh
            # (parallel/ring_attention.py) — parity-tested pp2 x sep2 in
            # tests/test_long_context.py
        pipeline = None
        if pp_degree > 1:
            from paddlefleetx_tpu.parallel.pipeline import PipelineConfig

            # pipeline microbatches default to the stage count (reference
            # accumulate_steps >= pp semantics); batch must divide
            pipeline = PipelineConfig(
                num_stages=pp_degree,
                num_microbatches=int(
                    dist.get("pipeline", {}).get("micro_batches", pp_degree)
                ),
                # reference num_virtual_pipeline_stages (hybrid_model.py:1206)
                num_virtual_stages=int(
                    dist.get("pipeline", {}).get("virtual_pp_degree", 1)
                ),
            )
        self.ctx = ShardingCtx(mesh, self.act_rules, pipeline=pipeline)

        # token/sample-counted schedules (use_increments) are scaled inside
        # build_optimizer so optax's per-step count yields the right lr
        self.tx, self.schedule = build_optimizer(
            cfg.Optimizer, count_scale=self.global_batch_size
        )

        # ---- sharded state construction -------------------------------
        logical = module.logical_axes()
        self.param_shardings = tree_logical_to_sharding(logical, mesh, self.rules)
        self.batch_spec = NamedSharding(mesh, logical_to_spec(("batch",), self.rules))
        self.replicated = NamedSharding(mesh, P())

        self._consumed_samples = 0
        self._step = 0  # host mirror of state.step (avoids device sync in fit)
        self._train_loader = None  # held during fit: ckpt meta + rollback rewind
        self._loader_state = None  # loader state from a restored ckpt meta
        self.state = self._init_state()
        if self.model_stats_every > 0:
            # deterministic path -> layer-group mapping (embed / block_<i>
            # / head), total over every model in the zoo; built from the
            # state tree so abstract_init fit-checks get it too
            from paddlefleetx_tpu.utils.model_stats import build_group_spec

            self._group_spec = build_group_spec(self.state.params)
        # install zigzag positions EAGERLY for the configured sequence
        # length: a caller that resolves the step attribute before placing
        # the first batch must not run a positions-less (wrong-mask) graph
        zig_seq = int(
            getattr(getattr(module, "config", None), "max_position_embeddings", 0) or 0
        )
        # the config seq can be zigzag-incompatible (not divisible by
        # 2*sep) while the loader's actual batches are padded to a length
        # that is — fall back to the lazy per-batch install for those
        if self.sep_zigzag and zig_seq > 0 and zig_seq % (
            2 * self.mesh.shape["sep"]
        ) == 0:
            self._install_zigzag(zig_seq)  # builds the steps itself
        else:
            self._train_step = self._build_train_step()
            self._eval_step = self._build_eval_step()

    # ------------------------------------------------------------------
    def train_step(self, state, dev_batch):
        """Run one jitted train step on an already-placed batch.

        Always dispatches to the CURRENT compiled step: `_put_batch` may
        rebuild the jitted steps (first-seen zigzag sequence length), so
        callers must not hold `_train_step` across a `_put_batch` call —
        this indirection makes that mistake impossible.
        """
        return self._train_step(state, dev_batch)

    def eval_step(self, state, dev_batch, it):
        """Dispatcher for the current jitted eval step (see train_step)."""
        return self._eval_step(state, dev_batch, it)

    # ------------------------------------------------------------------
    def memory_report(self, batch_shapes: Dict[str, Any]) -> Dict[str, int]:
        """AOT-compile the train step and return PER-DEVICE memory stats.

        ``batch_shapes`` maps batch names to (shape, dtype) pairs (or any
        objects with .shape/.dtype).  Works with ``abstract_init=True`` to
        fit-check layouts bigger than this machine: XLA's SPMD program is
        identical on every device, so the compiled executable's memory
        analysis IS the per-device HBM budget (reference counterpart: the
        published 6.7B recipe sizing, projects/gpt/docs/
        hybrid_parallel.md:47-54, which is validated only by running it)."""
        import numpy as _np

        def _abs(v):
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                shape, dtype = v.shape, v.dtype
            else:
                shape, dtype = v
            return jax.ShapeDtypeStruct(
                tuple(shape), jnp.dtype(dtype), sharding=self.batch_spec
            )

        batch_abs = {k: _abs(v) for k, v in batch_shapes.items()}
        compiled = self._train_step.lower(self.state, batch_abs).compile()
        ma = compiled.memory_analysis()
        required = ("argument_size_in_bytes", "output_size_in_bytes",
                    "temp_size_in_bytes", "alias_size_in_bytes")
        if ma is None or not all(hasattr(ma, n) for n in required):
            # memory_analysis() is backend-dependent and may return None:
            # a silent 0-byte peak would report every layout as fitting
            # every budget — the exact wrong answer for this tool
            raise RuntimeError(
                "compiled.memory_analysis() unavailable on this backend; "
                "memory_report cannot produce a trustworthy byte budget"
            )
        stats = {n: int(getattr(ma, n)) for n in required}
        if hasattr(ma, "generated_code_size_in_bytes"):
            stats["generated_code_size_in_bytes"] = int(
                ma.generated_code_size_in_bytes
            )

        def shard_bytes(tree):
            total = 0
            for leaf in jax.tree.leaves(tree):
                shape = leaf.sharding.shard_shape(leaf.shape)
                total += int(_np.prod(shape, dtype=_np.int64)) * leaf.dtype.itemsize
            return total

        stats["params_bytes_per_device"] = shard_bytes(self.state.params)
        stats["opt_state_bytes_per_device"] = shard_bytes(self.state.opt_state)
        # donated state aliases its output; peak live ~= args + out - alias
        # + temps (XLA's own accounting, conservative for CPU/TPU alike)
        stats["peak_bytes_per_device_est"] = (
            stats.get("argument_size_in_bytes", 0)
            + stats.get("output_size_in_bytes", 0)
            - stats.get("alias_size_in_bytes", 0)
            + stats.get("temp_size_in_bytes", 0)
        )
        return stats

    # ------------------------------------------------------------------
    def _init_state(self) -> TrainState:
        key = get_seed_tracker().params_key()

        params_shapes = jax.eval_shape(self.module.init_params, key)
        opt_shapes = jax.eval_shape(self.tx.init, params_shapes)
        moment_shardings = tree_logical_to_sharding(
            self.module.logical_axes(), self.mesh, self.moment_rules
        )
        if self.sharding_stage >= 1:
            self.param_shardings = drop_small_fsdp(
                self.param_shardings, params_shapes, self.min_shard_size
            )
            moment_shardings = drop_small_fsdp(
                moment_shardings, params_shapes, self.min_shard_size
            )
        self.offload_active = self.sharding_offload and _host_offload_supported(
            self.mesh
        )
        if self.sharding_offload and not self.offload_active:
            logger.warning(
                "sharding.offload requested but this backend cannot compile "
                "pinned_host placements; optimizer state stays on device"
            )
        # device-memory shardings drive compute; the host variants are where
        # the state LIVES between steps when offload is active
        self._opt_shardings_device = opt_state_shardings(
            opt_shapes, params_shapes, moment_shardings, self.mesh, None
        )
        self.opt_shardings = (
            opt_state_shardings(
                opt_shapes, params_shapes, moment_shardings, self.mesh, "pinned_host"
            )
            if self.offload_active
            else self._opt_shardings_device
        )
        self._grad_shardings = moment_shardings if self.sharding_stage >= 2 else None

        has_extra = getattr(self.module, "has_extra_state", False)
        if has_extra:
            extra_logical = self.module.extra_logical_axes()
            self.extra_shardings = tree_logical_to_sharding(
                extra_logical, self.mesh, self.rules
            )
            if self.sharding_stage >= 1:
                # same small-param exemption as params/moments: extra state
                # (momentum encoders, queues, running stats) holds LN-sized
                # vectors with the same pathological-reshard backward
                extra_shapes = jax.eval_shape(
                    self.module.init_extra, key, params_shapes
                )
                self.extra_shardings = drop_small_fsdp(
                    self.extra_shardings, extra_shapes, self.min_shard_size
                )
        else:
            self.extra_shardings = None

        # ONE sharding tree for the whole TrainState, shared by make_state
        # and the train step's out_shardings: with the step's output left
        # to sharding propagation (out_shardings=None), XLA under a
        # model-parallel mesh may pick a DIFFERENT output sharding than
        # the input state carries — the donated buffers then cannot alias
        # ("Some donated buffers were not usable" on every step, and a
        # silent reshard of the whole state).  Pinning output == input
        # sharding makes donation always usable.
        self.state_shardings = TrainState(
            step=self.replicated,
            params=self.param_shardings,
            # host-placed directly when offload is active: materializing
            # on device first would OOM exactly the models offload serves
            opt_state=self.opt_shardings,
            extra=self.extra_shardings,
            scaler={"scale": self.replicated, "good_steps": self.replicated}
            if self.use_loss_scaling
            else None,
        )

        @functools.partial(jax.jit, out_shardings=self.state_shardings)
        def make_state(key):
            params = self.module.init_params(key)
            if self._param_cast is not None:
                # multi_precision=False: params (and the optax moments
                # init'd from them) live in the compute dtype
                params = _cast_fp32_leaves(params, self._param_cast)
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=self.tx.init(params),
                extra=self.module.init_extra(key, params) if has_extra else None,
                scaler={
                    "scale": jnp.float32(self.init_loss_scaling),
                    "good_steps": jnp.int32(0),
                }
                if self.use_loss_scaling
                else None,
            )

        if self.abstract_init:
            # fit-check path: the state is its shapes + shardings, nothing
            # is allocated (make_state.eval_shape reuses the jit's
            # out_shardings, so the abstract tree matches the real one
            # leaf-for-leaf, pinned-host placements included)
            shapes = make_state.eval_shape(key)
            n_params = sum(
                x.size for x in jax.tree.leaves(shapes.params)
            )
            logger.info(
                f"abstract init: {n_params/1e6:.1f}M params (no allocation) "
                f"over {self.mesh.size} devices"
            )
            return shapes

        t0 = time.time()
        state = make_state(key)
        pretrained = self.cfg.Engine.get("save_load", {}).get("pretrained_params")
        if pretrained and self.cfg.Engine.get("save_load", {}).get("ckpt_dir"):
            # every entry point follows Engine() with engine.load(ckpt_dir),
            # which replaces params wholesale — skip the redundant (possibly
            # multi-GB) warm-start restore.  auto_resume resolution happens
            # in tools/train.py, which nulls pretrained_params itself.
            logger.info("pretrained_params skipped: ckpt_dir load takes over")
            pretrained = None
        if pretrained:
            # params-only warm start (e.g. tools/convert_hf_gpt2.py output):
            # optimizer state stays fresh, unlike ckpt_dir full-state resume
            from paddlefleetx_tpu.utils.checkpoint import restore_params

            loaded = restore_params(pretrained)
            ref, got = jax.tree.structure(state.params), jax.tree.structure(loaded)
            if ref != got:
                raise ValueError(
                    f"pretrained_params tree mismatch: model {ref} vs ckpt {got}"
                )
            mismatched = [
                f"{jax.tree_util.keystr(kp)}: model {t.shape} vs ckpt {np.shape(n)}"
                for (kp, t), n in zip(
                    jax.tree_util.tree_leaves_with_path(state.params),
                    jax.tree.leaves(loaded),
                )
                if tuple(t.shape) != tuple(np.shape(n))
            ]
            if mismatched:
                raise ValueError(
                    "pretrained_params shape mismatch (hint: --pad-vocab-to "
                    "in tools/convert_hf_gpt2.py must match Model.vocab_size):\n  "
                    + "\n  ".join(mismatched)
                )
            # .copy(): device_put of a host numpy array can be zero-copy on
            # CPU; these params are later DONATED by the train step, so they
            # must live in XLA-owned buffers (same hazard as load(), below)
            loaded = jax.tree.map(
                lambda t, n: jax.device_put(np.asarray(n, t.dtype), t.sharding).copy(),
                state.params,
                loaded,
            )
            state = dataclasses.replace(state, params=loaded)
            logger.info(f"pretrained params loaded from {pretrained}")
        if hasattr(self.module, "post_init_state"):
            # module hook for installing pretrained weights into fresh state
            # (e.g. MOCOClsModule's frozen backbone, moco_module.py:160-180)
            state = self.module.post_init_state(self, state)
        n_params = sum(x.size for x in jax.tree.leaves(state.params))
        logger.info(
            f"init: {n_params/1e6:.1f}M params sharded over {self.mesh.size} devices "
            f"({time.time()-t0:.1f}s)"
        )
        return state

    # ------------------------------------------------------------------
    def _build_train_step(self):
        module, ctx, tx = self.module, self.ctx, self.tx
        accum = self.accumulate_steps
        has_extra = getattr(module, "has_extra_state", False)
        grad_shardings = self._grad_shardings
        offload = self.offload_active
        opt_dev_shardings = self._opt_shardings_device
        opt_host_shardings = self.opt_shardings
        use_scaling = self.use_loss_scaling
        incr_every = self.scale_incr_every
        incr_ratio = self.scale_incr_ratio
        decr_ratio = self.scale_decr_ratio
        qat = self.qat_transform
        grad_dtype = None if self.main_grad else jnp.dtype(self.compute_dtype)
        group_spec = self._group_spec
        stats_every = self.model_stats_every

        @functools.partial(
            jax.jit,
            donate_argnums=(0,),
            in_shardings=(None, self.batch_spec),
            # the state output is PINNED to the input state's sharding tree
            # (built at init): letting propagation choose (None) can pick a
            # different sharding for the new params/moments under a
            # model-parallel mesh, which both breaks donation ("donated
            # buffers were not usable" every step) and resharding-copies
            # the whole state each step
            out_shardings=(self.state_shardings, self.replicated),
        )
        def train_step(state: TrainState, batch: Dict[str, jax.Array]):
            # per-step dropout stream: 'global' stream folded with the step
            # counter (reference RNG-tracker semantics, env.py:34-98)
            base_key = get_seed_tracker().key("global")
            step_key = jax.random.fold_in(base_key, state.step)

            # fp16 dynamic loss scaling: multiply the loss by the current
            # scale before differentiation, unscale the grads after
            # (reference DynamicLossScaler apis/amp.py:193-234)
            loss_scale = (
                state.scaler["scale"] if use_scaling else jnp.float32(1.0)
            )

            def run_loss(p, mb, extra):
                if has_extra:
                    loss, new_extra = module.loss_fn(
                        p, mb, ctx=ctx, extra=extra, dropout_key=step_key, train=True
                    )
                else:
                    loss = module.loss_fn(
                        p, mb, ctx=ctx, dropout_key=step_key, train=True
                    )
                    new_extra = None
                return loss * loss_scale, (loss, new_extra)

            def micro_batches(b):
                return jax.tree.map(
                    lambda x: x.reshape((accum, x.shape[0] // accum) + x.shape[1:]), b
                )

            # QAT: quantize ONCE per step, outside the microbatch scan —
            # fake_quant's straight-through VJP makes d/d(quantized) equal
            # d/d(master), so differentiating from the quantized tree gives
            # the master-weight grads without re-quantizing per microbatch
            fwd_params = qat(state.params) if qat is not None else state.params
            if grad_dtype is not None:
                # main_grad=False: differentiate w.r.t. the compute-dtype
                # cast, so grads (and the scan accumulator below) are bf16.
                # The model's per-use .astype(dtype) then no-ops; non-fp32
                # leaves (int tables, already-low-precision) pass through.
                fwd_params = _cast_fp32_leaves(fwd_params, grad_dtype)

            def micro(carry, mb):
                gacc, lacc, extra = carry
                (_, (loss, new_extra)), grads = jax.value_and_grad(
                    run_loss, has_aux=True
                )(fwd_params, mb, extra)
                return (jax.tree.map(jnp.add, gacc, grads), lacc + loss, new_extra), None

            zeros = jax.tree.map(jnp.zeros_like, fwd_params)
            if accum > 1:
                (gsum, lsum, new_extra), _ = jax.lax.scan(
                    micro,
                    (zeros, jnp.zeros((), jnp.float32), state.extra),
                    micro_batches(batch),
                )
                grads = jax.tree.map(lambda g: g / accum, gsum)
                loss = lsum / accum
            else:
                (_, (loss, new_extra)), grads = jax.value_and_grad(
                    run_loss, has_aux=True
                )(fwd_params, batch, state.extra)

            if use_scaling:
                # unscale in fp32 and STAY fp32: casting back to fp16 would
                # flush exactly the small gradients loss scaling exists to
                # keep representable (they were only representable scaled).
                # main_grad=False still bought fp16 accumulators inside the
                # microbatch scan, where grads are scaled; from the unscale
                # boundary on, the clip/Adam path is fp32 anyway.
                grads = jax.tree.map(
                    lambda g: g.astype(jnp.float32) / loss_scale, grads
                )

            if grad_shardings is not None:
                # ZeRO-2: the dp grad-sum lands fsdp-sharded (XLA lowers
                # the psum + constraint to a reduce-scatter); the sharded
                # optimizer update then all-gathers only the param updates
                grads = jax.lax.with_sharding_constraint(grads, grad_shardings)

            if group_spec is not None:
                # per-layer-group sum of squares feeds BOTH the global
                # grad norm (sum of the group sums — same fp32 rule as
                # global_norm_f32, one pass over the gradients) and the
                # per-group finiteness vector the non-finite-provenance
                # contract needs on every step
                from paddlefleetx_tpu.utils import model_stats as _ms

                grad_gsq = _ms.group_sqsum(group_spec, grads)
                gnorm = jnp.sqrt(jnp.sum(grad_gsq))
            else:
                gnorm = global_norm_f32(grads)
            finite = jnp.isfinite(gnorm)
            safe = jax.tree.map(lambda g: jnp.where(finite, g, 0.0), grads)
            # host offload: stage the moments onto device for the update,
            # park the new state back in pinned host memory afterwards
            opt_in = (
                jax.device_put(state.opt_state, opt_dev_shardings)
                if offload
                else state.opt_state
            )
            updates, new_opt = tx.update(safe, opt_in, state.params)
            new_params = optax.apply_updates(state.params, updates)
            # skip non-finite steps in lockstep (reference found_inf contract)
            new_params = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_params, state.params
            )
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_opt, opt_in
            )
            if offload:
                new_opt = jax.device_put(new_opt, opt_host_shardings)
            # extra (queue/BN/EMA) must revert too: a NaN forward would
            # otherwise poison enqueued keys / running stats permanently
            new_extra = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_extra, state.extra
            )
            new_scaler = state.scaler
            if use_scaling:
                # grow after incr_every consecutive finite steps, shrink on
                # overflow (reference update :219-234); never below 1.0
                good = jnp.where(finite, state.scaler["good_steps"] + 1, 0)
                grow = good >= incr_every
                scale = jnp.where(
                    finite,
                    jnp.where(grow, state.scaler["scale"] * incr_ratio,
                              state.scaler["scale"]),
                    jnp.maximum(state.scaler["scale"] * decr_ratio, 1.0),
                )
                new_scaler = {
                    "scale": scale,
                    "good_steps": jnp.where(grow, 0, good),
                }
            new_state = TrainState(
                state.step + 1, new_params, new_opt, new_extra, new_scaler
            )
            metrics = {
                "loss": loss,
                "grad_norm": gnorm,
                "lr": self.schedule(state.step),
                "found_inf": (~finite).astype(jnp.float32),
            }
            if use_scaling:
                metrics["loss_scale"] = new_scaler["scale"]
            if group_spec is not None:
                from paddlefleetx_tpu.utils import model_stats as _ms

                # every step (free: isfinite of the sums the norm needed):
                # which groups went non-finite — rides the anomaly guard's
                # existing prev-metrics fetch, so rollback postmortems can
                # name the first offending group with no extra sync
                metrics["group_nonfinite"] = (
                    ~jnp.isfinite(grad_gsq)
                ).astype(jnp.int32)

                # cadence steps only (lax.cond: the untaken branch costs
                # nothing off-cadence): the full per-group statistic set.
                # (state.step + 1) is the 1-based step number this
                # dispatch computes — the same numbering the host loop and
                # step records use.
                def _stats_on(args):
                    g_sq, p, u, g = args
                    return _ms.group_stats(
                        group_spec, grad_sqsum=g_sq, params=p, updates=u,
                        grads=g,
                    )

                def _stats_off(args):
                    zeros = jnp.zeros(
                        (group_spec.num_groups,), jnp.float32
                    )
                    return {
                        k: zeros
                        for k in ("grad_norm", "param_norm", "update_norm",
                                  "update_ratio", "nonfinite_frac")
                    }

                metrics["model_stats"] = jax.lax.cond(
                    (state.step + 1) % stats_every == 0,
                    _stats_on,
                    _stats_off,
                    (grad_gsq, state.params, updates, grads),
                )
            return new_state, metrics

        return train_step

    def _get_predict_step(self):
        """Jitted module.predict_fn, built once (recompiling per evaluate()
        call would retrace every eval round)."""
        if getattr(self, "_predict_step", None) is None:
            module, ctx = self.module, self.ctx
            qat = self.qat_transform

            def predict(state, batch):
                # metrics must measure the same quantized weights the eval
                # loss and the exported model use
                p = qat(state.params) if qat is not None else state.params
                return module.predict_fn(p, batch, ctx=ctx)

            self._predict_step = jax.jit(
                predict,
                in_shardings=(None, self.batch_spec),
                out_shardings=self.replicated,
            )
        return self._predict_step

    def _build_eval_step(self):
        module, ctx = self.module, self.ctx

        has_extra = getattr(module, "has_extra_state", False)
        qat = self.qat_transform

        @functools.partial(
            jax.jit,
            in_shardings=(None, self.batch_spec, None),
            out_shardings=self.replicated,
        )
        def eval_step(state: TrainState, batch, eval_it):
            # per-eval-batch key (folded with step AND batch index): modules
            # that sample stochastic quantities at eval time — e.g. Imagen's
            # diffusion timesteps — must not see a constant key, or eval
            # loss becomes a low-variance biased estimate
            ekey = jax.random.fold_in(
                jax.random.fold_in(get_seed_tracker().key("global"), state.step), eval_it
            )
            # eval sees the same quantized weights training optimizes for
            p = qat(state.params) if qat is not None else state.params
            if has_extra:
                loss, _ = module.loss_fn(
                    p,
                    batch,
                    ctx=ctx,
                    extra=state.extra,
                    dropout_key=ekey,
                    train=False,
                )
                return loss
            return module.loss_fn(
                p, batch, ctx=ctx, dropout_key=ekey, train=False
            )

        return eval_step

    # ------------------------------------------------------------------
    # sequence-dim keys reordered under the zigzag context-parallel layout
    _SEQ_KEYS = ("tokens", "labels", "loss_mask", "position_ids", "input_ids")

    def _install_zigzag(self, seq: int) -> None:
        """Install the zigzag permutation + attn_positions for sequence
        length `seq` and rebuild the jitted steps against it.

        The positions ride the sharding ctx as a CONSTANT: ring attention
        masks by TRUE token order.  Called eagerly at init (config seq) and
        again from _put_batch only if a different seq shows up.
        """
        import dataclasses as _dc

        from paddlefleetx_tpu.parallel.ring_attention import zigzag_permutation

        self._zigzag_perm = np.asarray(
            zigzag_permutation(seq, self.mesh.shape["sep"])
        )
        self._zigzag_inv = np.argsort(self._zigzag_perm)
        self._zigzag_seq = seq
        self.ctx = _dc.replace(
            self.ctx, attn_positions=jnp.asarray(self._zigzag_perm, jnp.int32)
        )
        self._train_step = self._build_train_step()
        self._eval_step = self._build_eval_step()
        self._predict_step = None

    def _put_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        if self.sep_zigzag:
            seq = next(
                (v.shape[1] for k, v in batch.items()
                 if k in self._SEQ_KEYS and getattr(v, "ndim", 0) >= 2),
                None,
            )
            if seq is not None:
                if self._zigzag_seq != seq:
                    self._install_zigzag(seq)
                perm = self._zigzag_perm
                inv = self._zigzag_inv
                batch = {
                    k: (v[:, perm] if k in self._SEQ_KEYS and getattr(v, "ndim", 0) >= 2 else v)
                    for k, v in batch.items()
                }
                # per-sample indices INTO the sequence must follow the
                # token they point at (e.g. finetune cls_position)
                for key in ("cls_position",):
                    if batch.get(key) is not None:
                        batch[key] = inv[np.asarray(batch[key])]
                if batch.get("position_ids") is None:
                    # loaders that omit position_ids would otherwise embed
                    # (and mask) in permuted index order
                    b = next(
                        v.shape[0] for k, v in batch.items()
                        if k in self._SEQ_KEYS and getattr(v, "ndim", 0) >= 2
                    )
                    batch["position_ids"] = np.tile(perm, (b, 1))
        return jax.tree.map(lambda x: jax.device_put(x, self.batch_spec), batch)

    def _write_metrics(self, record: Dict) -> None:
        # EVERY record (step, data_skip, rollback, preempt_save) also
        # enters the flight recorder ring — before the metrics_file gates,
        # so a crash postmortem exists even when no stream is configured
        rec = dict(record)
        rec.setdefault("event", "step")
        self._recorder.record(rec)
        if not self.metrics_file:
            return
        if jax.process_index() != 0:
            # multi-host: one writer, or a shared-storage file double-counts
            return
        # fresh runs truncate (a retry would otherwise interleave two step
        # sequences); checkpoint-resumed runs append to the prior stream
        mode = getattr(self, "_metrics_mode", None)
        if mode is None:
            mode = "a" if getattr(self, "_resumed", False) else "w"
        try:
            os.makedirs(os.path.dirname(os.path.abspath(self.metrics_file)), exist_ok=True)
            with open(self.metrics_file, mode) as f:
                f.write(json.dumps(record) + "\n")
            self._metrics_mode = "a"
        except OSError as e:
            logger.warning(f"metrics_file write failed (disabling): {e}")
            self.metrics_file = ""

    def _dump_flight(self, reason: str) -> None:
        """Training-side flight-recorder dump: lands next to the
        checkpoints (output_dir) so the postmortem travels with the run's
        artifacts; PFX_FLIGHT_RECORDER still overrides inside dump()."""
        if jax.process_index() != 0:
            # multi-host: one writer to the shared output_dir, same
            # convention as _write_metrics (rollback/preempt fire on
            # every process; host 0's ring is the canonical postmortem)
            return
        self._recorder.dump(
            path=os.path.join(self.output_dir, "flight_recorder.jsonl"),
            reason=reason,
        )

    def _update_registry(self, record: Dict, ips: float) -> None:
        """Mirror the logged step record onto the process-wide telemetry
        registry (scraped by any /metrics surface this process hosts).
        Cumulative values are ``set`` from the engine's own counters —
        exporter style — so a resumed run reports monotonic totals."""
        reg = self._registry
        reg.counter("pfx_train_steps_total").set(self._step)
        reg.counter("pfx_train_tokens_total").set(
            self._consumed_samples * (self.module.tokens_per_sample or 1)
        )
        reg.gauge("pfx_train_loss").set(record["loss"])
        reg.gauge("pfx_train_tokens_per_second").set(round(ips, 1))
        reg.counter("pfx_train_data_wait_seconds_total").set(
            record.get("data_wait_s", 0.0)
        )
        reg.counter("pfx_train_host_seconds_total").set(
            record.get("host_s", 0.0)
        )
        if self._compile_s is not None:
            reg.gauge("pfx_train_compile_seconds").set(round(self._compile_s, 3))
        if "model_flops" in record:
            reg.gauge("pfx_train_model_flops_per_second").set(
                record["model_flops"]
            )
        if "mfu" in record:
            reg.gauge("pfx_train_mfu").set(record["mfu"])

    def _format_model_stats(self, stats_step: int, vals: Dict) -> Dict:
        """Shape one fetched per-group statistic set for the step record
        (and mirror it onto the pfx_train_group_* gauges): group names in
        canonical order plus parallel value lists — compact enough for
        JSONL, self-describing enough for tools/report.py."""
        names = list(self._group_spec.names)
        out: Dict[str, Any] = {"step": int(stats_step), "groups": names}
        reg = self._registry
        gauge_of = {
            "grad_norm": "pfx_train_group_grad_norm",
            "param_norm": "pfx_train_group_param_norm",
            "update_ratio": "pfx_train_group_update_ratio",
            "nonfinite_frac": "pfx_train_group_nonfinite_frac",
        }
        for key in ("grad_norm", "param_norm", "update_norm",
                    "update_ratio", "nonfinite_frac"):
            row = [round(float(v), 6) for v in np.asarray(vals[key])]
            out[key] = row
            metric = gauge_of.get(key)
            if metric:
                for name, v in zip(names, row):
                    if math.isfinite(v):
                        reg.gauge(metric, group=name).set(v)
        return out

    def _sample_memory(self, record: Dict) -> None:
        """Attach a memory-watermark block to a step record and mirror it
        onto the pfx_mem_* gauges.  ``fit_peak_bytes`` is the highest
        SAMPLED in-use watermark THIS fit (worst device bytes_in_use
        where the backend reports it, host RSS otherwise, sampled at
        logging cadence) — the allocator's own ``device_peak_bytes`` is
        reported alongside but is process-lifetime (the backend never
        resets it, so it cannot be per-fit).  A loud warning fires once
        per fit when device headroom drops under
        PFX_MEM_WARN_HEADROOM."""
        from paddlefleetx_tpu.utils import model_stats as _ms

        wm = _ms.memory_watermarks()
        mem = {
            k: wm[k]
            for k in ("host_rss_bytes", "device_in_use_bytes",
                      "device_peak_bytes", "headroom_frac")
            if wm.get(k) is not None
        }
        watermark = wm.get("device_in_use_bytes") or wm.get("host_rss_bytes")
        if watermark:
            self._fit_peak_bytes = max(self._fit_peak_bytes or 0, watermark)
        if self._fit_peak_bytes:
            mem["fit_peak_bytes"] = self._fit_peak_bytes
        if mem:
            record["mem"] = mem
        _ms.export_memory_gauges(self._registry, wm)
        if not self._headroom_warned and _ms.warn_headroom(wm):
            self._headroom_warned = True

    def _drain_skip_events(self, loader) -> None:
        """Move the loader's structured ``data_skip`` events (appended by
        the skip budget, data/batch_sampler.py) into the metrics stream,
        stamped with the upcoming step."""
        events = getattr(loader, "skip_events", None)
        if not events:
            return
        while events:
            ev = dict(events.pop(0))
            ev.setdefault("step", self._step + 1)
            self._write_metrics(ev)

    def _require_concrete(self, op: str) -> None:
        if self.abstract_init:
            raise RuntimeError(
                f"Engine was built with abstract_init=True (fit-check "
                f"mode): state holds shapes, not arrays, so {op} is "
                "unavailable — only memory_report() works; rebuild the "
                "Engine without abstract_init to train"
            )

    def fit(self, train_loader: Iterable, eval_loader: Optional[Iterable] = None):
        """Training loop (reference fit/_fit_impl eager_engine.py:422-520).

        Preemption-aware: SIGTERM/SIGINT finishes the in-flight step, joins
        any async save, writes a final checkpoint with a ``preempted``
        marker, and returns with ``self.preempted`` set — the launcher
        (tools/train.py) then exits 0 so a relaunch auto-resumes.

        Data-pipeline contract (docs/data_pipeline.md): the engine holds
        the train loader for checkpoint meta (stream position + skip
        budget), rewinds it on anomaly rollback when it supports
        ``rewind``, drains its structured ``data_skip`` events into the
        metrics stream, and CLOSES both loaders on the way out so
        prefetch threads / worker pools never outlive the loop."""
        self._require_concrete("fit")
        t_last = time.time()
        window_tokens = 0
        eval_iter = iter(eval_loader) if eval_loader is not None else None
        tokens_per_sample = self.module.tokens_per_sample or 1
        self._train_loader = train_loader
        # resumed checkpoints carry loader state (skip budget spent so a
        # rotten shard cannot earn a fresh budget every crash-loop lap);
        # the stream position itself was already applied when the loader
        # was built from this engine's _consumed_samples
        # consumed unconditionally: a stale entry must never leak into a
        # later fit() with a different loader
        loader_state, self._loader_state = self._loader_state, None
        if loader_state and hasattr(train_loader, "load_state"):
            train_loader.load_state(loader_state)

        # config-gated trace window (reference Profiler block,
        # eager_engine.py:250-272 + profiler.step :419)
        from paddlefleetx_tpu.utils.profiler import ProfilerHook
        from paddlefleetx_tpu.utils.resilience import PreemptionGuard

        profiler = ProfilerHook(self.cfg.get("Profiler"))
        self.preempted = False
        # per-fit observatory state: stats stashed for the next logging
        # fetch, the memory watermark peak, and the once-per-fit headroom
        # warning latch
        self._pending_stats = None
        self._fit_peak_bytes = None
        self._headroom_warned = False
        preempt = PreemptionGuard().install()
        try:
            return self._fit_loop(
                train_loader, eval_iter, tokens_per_sample, profiler, t_last,
                window_tokens, preempt
            )
        finally:
            preempt.uninstall()
            # flush an in-flight trace even when a step raises
            profiler.close()
            # reclaim loader machinery (prefetch thread, worker pool)
            # before returning: an abandoned daemon thread blocked on a
            # fetch is a leak the interpreter drags to shutdown
            for ldr in (train_loader, eval_loader):
                close = getattr(ldr, "close", None)
                if callable(close):
                    try:
                        close()
                    except Exception as e:  # noqa: BLE001 — best-effort
                        logger.warning(f"loader close failed: {e}")
            # a checkpoint still writing in background must become durable
            # before fit returns (callers may exit the process right after)
            self.wait_for_save()

    def _build_anomaly_guard(self):
        from paddlefleetx_tpu.utils.resilience import AnomalyGuard

        if not self.res_enable or (
            self.res_max_skip_streak <= 0 and self.res_spike_zscore <= 0
        ):
            return None
        return AnomalyGuard(
            max_skip_streak=self.res_max_skip_streak,
            spike_zscore=self.res_spike_zscore,
            spike_streak=self.res_spike_streak,
            window=self.res_loss_window,
        )

    def _rollback(self, step: int, reason: str, rollbacks: int,
                  nonfinite_groups: Optional[list] = None) -> bool:
        """Anomaly response: restore params+opt-state from the last good
        checkpoint and let the loop re-enter from there.  Bounded: past
        ``resilience.max_rollbacks`` (or with no checkpoint to return to)
        the run fails loudly instead of thrashing.

        ``nonfinite_groups`` is the non-finite-provenance list (canonical
        group order, first entry = first offending layer group) observed
        on the step that tripped the guard; it rides the ``rollback``
        event and the flight postmortem so the postmortem names a
        culprit layer, not just "found_inf fired".

        Returns True when the data stream was REWOUND to the checkpoint
        position (loader supports ``rewind``): the caller must re-iter()
        the loader, and the replayed loss stream is then a token-for-token
        repeat of what an uninterrupted run would have produced.  False =
        legacy behavior (stream keeps its live position, same contract as
        a process restart mid-epoch without loader state)."""
        # an async save may be seconds from durable: join it first so its
        # checkpoint counts as the rollback target (the finisher thread is
        # what records _last_good_ckpt)
        self.wait_for_save()
        if self._last_good_ckpt is None:
            raise RuntimeError(
                f"anomaly budget exceeded at step {step} ({reason}) and no "
                "checkpoint exists to roll back to — enable periodic saves "
                "(Engine.save_load.save_steps) or disable the guard "
                "(Engine.resilience.enable=False)"
            )
        if rollbacks >= self.res_max_rollbacks:
            raise RuntimeError(
                f"anomaly budget exceeded at step {step} ({reason}) after "
                f"{rollbacks} rollback(s) — max_rollbacks="
                f"{self.res_max_rollbacks} exhausted; the run is not "
                "recovering, stopping instead of thrashing"
            )
        loader = self._train_loader
        rewindable = loader is not None and hasattr(loader, "rewind")
        culprit = (
            f" (first non-finite group(s): {', '.join(nonfinite_groups[:3])})"
            if nonfinite_groups else ""
        )
        logger.error(
            f"ANOMALY at step {step}: {reason}{culprit}; rolling back to "
            f"{self._last_good_ckpt} (rollback {rollbacks + 1}/"
            f"{self.res_max_rollbacks})"
        )
        event = {
            "event": "rollback",
            "step": step,
            "reason": reason,
            "ckpt": self._last_good_ckpt,
            "rollback_index": rollbacks + 1,
            "rewound": bool(rewindable),
        }
        if nonfinite_groups:
            event["nonfinite_groups"] = list(nonfinite_groups)
        self._write_metrics(event)
        # postmortem dump: the ring (recent step records, the rollback
        # event, any data_skips) hits disk NOW — if the post-rollback
        # replay diverges again and max_rollbacks kills the run, the
        # window that tripped the guard is already preserved
        self._registry.counter("pfx_train_rollbacks_total").inc()
        self._dump_flight(f"anomaly_rollback: {reason}")
        # the LIVE data-stream position: every step served so far plus the
        # just-dispatched (discarded) batch — needed only on the legacy
        # (non-rewindable) path, where load() resets the counter to the
        # checkpoint's value but the stream cannot rewind; leaving the
        # stale count would make the next save record a consumed_samples
        # behind the true stream, and a later crash+auto_resume would then
        # re-serve batches, breaking the resume-parity contract.
        live_consumed = self._consumed_samples + self.global_batch_size
        self.load(self._last_good_ckpt)
        # load() parked the ckpt's loader state for the NEXT fit(); this
        # fit applies it here (or discards it on the legacy path — it must
        # not leak into a later fit() against a different loader)
        loader_state, self._loader_state = self._loader_state, None
        if rewindable:
            # rewindable loader: put the stream back at the checkpoint
            # position so the post-rollback run REPLAYS the failed window
            # token-for-token (the replay is what proves the rollback
            # recovered — a diverging replay re-trips the guard).  The
            # ckpt's full loader state also restores the skip budget to
            # its checkpoint value: the replayed window re-hits any
            # corrupt sample, and keeping the live count would charge
            # max_skips twice for the same record
            if loader_state and hasattr(loader, "load_state"):
                loader.load_state(loader_state)
            else:
                loader.rewind(self._consumed_samples)
            logger.warning(
                f"data stream rewound to consumed_samples="
                f"{self._consumed_samples} for a token-for-token replay"
            )
            return True
        self._consumed_samples = live_consumed
        return False

    def _preempt_save(self, step: int, cause: str) -> None:
        """Final checkpoint on the clean-exit path (signal or
        exit_after_save): join any in-flight async write first so the two
        saves can't interleave, then save with the ``preempted`` marker.

        When the periodic save already wrote this exact step (signal
        landing on a save boundary), only the meta marker is re-stamped —
        re-writing multi-GB arrays inside the preemption grace window for
        a flag would be the worst possible use of that window."""
        logger.warning(
            f"{cause} at step {step}: writing final checkpoint, then "
            "exiting cleanly for auto-resume"
        )
        self.wait_for_save()
        expected = os.path.abspath(os.path.join(self.output_dir, f"step_{step}"))
        if self._last_good_ckpt == expected:
            try:
                with open(os.path.join(expected, "meta.json")) as f:
                    meta = json.load(f)
            except (OSError, json.JSONDecodeError):
                meta = {"step": step, "consumed_samples": self._consumed_samples}
            meta["preempted"] = True
            self._write_meta(expected, meta)
            path = expected
            logger.info(f"preempt marker stamped on existing {expected}")
        else:
            path = self.save(preempted=True)
            self.wait_for_save()
        self._write_metrics(
            {"event": "preempt_save", "step": step, "cause": cause, "ckpt": path}
        )
        # the process is about to exit (or be killed if the grace window
        # runs out): leave the flight ring on disk alongside the ckpt
        self._registry.counter("pfx_train_preempt_saves_total").inc()
        self._dump_flight(f"preempt_save: {cause}")
        self.preempted = True

    def _fit_loop(self, train_loader, eval_iter, tokens_per_sample, profiler,
                  t_last, window_tokens, preempt=None):
        from paddlefleetx_tpu.utils import resilience

        from paddlefleetx_tpu.utils.tracing import get_trace_buffer

        guard = self._build_anomaly_guard()
        # deep-dive tracing (sampled, docs/observability.md): one trace
        # per fit; each logged window appends a span mirroring the step
        # record's phase fields, and the record carries the trace_id so
        # a JSONL row links to its timeline.  None at PFX_TRACE_SAMPLE=0
        # — the loop then does zero tracing work.
        fit_trace = get_trace_buffer().maybe_start("train")
        window_t0 = time.monotonic()
        # goodput time ledger (docs/observability.md "Goodput ledger"):
        # everything since loop_t0 is attributed to one of
        # compile/data_wait/host/eval, and the unattributed remainder is
        # device_step — dispatched device compute the async-dispatch loop
        # never blocks on.  Buckets are exhaustive by construction.
        loop_t0 = time.monotonic()
        eval_total = 0.0
        # metrics of the previous step, observed AFTER the next step has
        # been dispatched: step N-1 necessarily finished before step N
        # runs on device, so the fetch resolves while step N computes and
        # the guard never idles the device (async dispatch stays ahead)
        prev_metrics = None
        rollbacks = 0
        # per-phase accounting (docs/observability.md): cumulative seconds
        # this fit spent blocked on data (consumer-side next()) and on the
        # host path (batch placement + step dispatch).  Pure monotonic
        # host clocks — no device sync is added to the hot path.
        data_wait_total = 0.0
        host_total = 0.0
        steps_in_window = 0
        data_iter = iter(train_loader)
        while True:
            try:
                t_fetch = time.monotonic()
                batch = next(data_iter)
                data_wait_total += time.monotonic() - t_fetch
            except StopIteration:
                break
            if self._step >= self.max_steps:
                break
            self._drain_skip_events(train_loader)
            if resilience.maybe_fire("nan_grads", self._step + 1):
                batch = resilience.poison_batch(batch)
            t_host = time.monotonic()
            dev_batch = self._put_batch(batch)
            self.state, metrics = self._train_step(self.state, dev_batch)
            host_dt = time.monotonic() - t_host
            if (
                self._group_spec is not None
                and (self._step + 1) % self.model_stats_every == 0
            ):
                # device REFS only (no sync): the stats branch just ran
                # in-graph; the arrays are fetched with the next logging
                # fetch and attached to that record
                self._pending_stats = (self._step + 1, metrics["model_stats"])
            if self._compile_s is None:
                # the first dispatch traces + compiles synchronously inside
                # the jit call: time it separately (compile_s) and restart
                # the throughput window so ips/mfu never average the
                # compile into the first window
                self._compile_s = host_dt
                t_last = time.time()
            else:
                host_total += host_dt
            if guard is not None and prev_metrics is not None:
                pm = jax.device_get(prev_metrics)
                reason = guard.observe(
                    float(pm["loss"]), float(pm["found_inf"]) > 0
                )
                if reason is not None:
                    # the step just dispatched is discarded along with the
                    # anomalous state: load() replaces self.state and
                    # restores the step/consumed counters from the meta.
                    # A rewindable loader is rewound to the checkpoint
                    # position (token-for-token replay); otherwise the
                    # stream keeps its live position — same contract as a
                    # process restart mid-epoch.
                    culprits = None
                    if self._group_spec is not None and "group_nonfinite" in pm:
                        from paddlefleetx_tpu.utils.model_stats import (
                            nonfinite_group_names,
                        )

                        culprits = nonfinite_group_names(
                            self._group_spec, pm["group_nonfinite"]
                        ) or None
                    rewound = self._rollback(
                        self._step, reason, rollbacks,
                        nonfinite_groups=culprits,
                    )
                    rollbacks += 1
                    guard.reset()
                    prev_metrics = None
                    # stats stashed from the discarded window must not
                    # label a post-rollback record
                    self._pending_stats = None
                    if rewound:
                        # position is read at iter() time: restart the
                        # iteration so the replay starts AT the checkpoint
                        data_iter = iter(train_loader)
                    continue
            if guard is not None:
                prev_metrics = {
                    "loss": metrics["loss"], "found_inf": metrics["found_inf"]
                }
                if self._group_spec is not None:
                    # provenance rides the guard's existing step-behind
                    # fetch: [G] int32, no extra sync
                    prev_metrics["group_nonfinite"] = metrics["group_nonfinite"]
            self._consumed_samples += self.global_batch_size
            window_tokens += self.global_batch_size * tokens_per_sample
            steps_in_window += 1
            self._step += 1
            step = self._step
            profiler.step(step)

            if step % self.logging_freq == 0:
                # ONE host fetch: the step metrics plus any pending
                # model-stats arrays stashed at the last cadence step —
                # the observatory's "stats ride the existing step-record
                # device fetch" contract
                pending_stats, self._pending_stats = self._pending_stats, None
                if pending_stats is not None:
                    metrics, stats_vals = jax.device_get(
                        (metrics, pending_stats[1])
                    )
                else:
                    metrics = jax.device_get(metrics)
                dt = time.time() - t_last
                ips = window_tokens / dt
                logger.info(
                    f"step {step}/{self.max_steps} loss: {float(metrics['loss']):.5f} "
                    f"lr: {float(metrics['lr']):.3e} grad_norm: {float(metrics['grad_norm']):.3f} "
                    f"ips: {ips:,.0f} tokens/s ({ips/self.mesh.size:,.0f}/device)"
                )
                record = {
                    "step": step,
                    "loss": float(metrics["loss"]),
                    "lr": float(metrics["lr"]),
                    "grad_norm": float(metrics["grad_norm"]),
                    "ips": round(ips, 1),
                    "consumed_samples": self._consumed_samples,
                    # phase breakdown: cumulative consumer-side data wait
                    # and host-side (placement+dispatch) seconds, plus the
                    # average wall seconds per step over this window —
                    # wall minus data/host is dispatched-device time
                    "tokens_per_sec": round(ips, 1),
                    "data_wait_s": round(data_wait_total, 3),
                    "host_s": round(host_total, 3),
                    "step_s": round(dt / max(1, steps_in_window), 4),
                }
                if self._compile_s is not None and not self._compile_emitted:
                    # first logged window: trace+compile seconds, timed at
                    # the first dispatch and excluded from the ips window
                    record["compile_s"] = round(self._compile_s, 3)
                    self._compile_emitted = True
                if self._flops_per_token:
                    model_fps = ips * self._flops_per_token
                    record["model_flops"] = round(model_fps, 1)
                    if self._peak_flops:
                        record["mfu"] = round(
                            model_fps / (self._peak_flops * self.mesh.size), 6
                        )
                # data-pipeline health (prefetch depth, cumulative seconds
                # the loop sat starved, skip budget spent) rides the same
                # stream so dashboards see starvation next to throughput.
                # The loader's own data_wait_s (producer-side, sees stalls
                # the prefetch buffer hides from the loop) overrides the
                # engine's consumer-side measurement when available.
                stats_fn = getattr(train_loader, "stats", None)
                if callable(stats_fn):
                    record.update(
                        (k, v) for k, v in stats_fn().items()
                        if k in ("data_wait_s", "prefetch_depth",
                                 "stall_warnings", "skips")
                    )
                if pending_stats is not None:
                    record["model_stats"] = self._format_model_stats(
                        pending_stats[0], stats_vals
                    )
                if (
                    self._group_spec is not None
                    and float(metrics.get("found_inf", 0.0)) > 0
                ):
                    # non-finite provenance: this logged step was skipped;
                    # name the offending group(s) right on the record
                    from paddlefleetx_tpu.utils.model_stats import (
                        nonfinite_group_names,
                    )

                    record["found_inf"] = 1
                    record["nonfinite_groups"] = nonfinite_group_names(
                        self._group_spec, metrics["group_nonfinite"]
                    )
                # memory watermarks: host-side accounting only (device
                # memory_stats where the backend has it, host RSS always)
                self._sample_memory(record)
                if fit_trace is not None:
                    # mirror the record's phase fields as a trace span:
                    # the step-record JSONL and the Perfetto timeline
                    # describe the SAME window, linked by trace_id
                    now_mono = time.monotonic()
                    fit_trace.span(
                        "step_window", t0=window_t0, t1=now_mono,
                        step=step, loss=record["loss"],
                        tokens_per_sec=record["tokens_per_sec"],
                        data_wait_s=record["data_wait_s"],
                        host_s=record["host_s"],
                        step_s=record["step_s"],
                    )
                    window_t0 = now_mono
                    record["trace_id"] = fit_trace.trace_id
                self._update_registry(record, ips)
                # time ledger: attribute the whole fit's wall clock from
                # the loop's OWN accumulators (not the record — a loader
                # stats() override swaps in producer-side data_wait_s,
                # which would break closure against this thread's wall).
                # Exporter-style .set(): totals stay monotonic per fit.
                buckets = {
                    "compile": self._compile_s or 0.0,
                    "data_wait": data_wait_total,
                    "host": host_total,
                    "eval": eval_total,
                }
                buckets["device_step"] = max(
                    0.0,
                    (time.monotonic() - loop_t0) - sum(buckets.values()),
                )
                reg = self._registry
                for bname, bval in sorted(buckets.items()):
                    reg.counter(
                        "pfx_train_time_seconds_total", bucket=bname
                    ).set(round(bval, 4))
                # the record carries the same ledger so tools/report.py
                # renders the stacked breakdown from artifacts alone
                record["time_ledger"] = {
                    k: round(v, 3) for k, v in buckets.items()
                }
                self._write_metrics(record)
                t_last = time.time()
                window_tokens = 0
                steps_in_window = 0

            if self.consistency_check_freq and step % self.consistency_check_freq == 0:
                from paddlefleetx_tpu.parallel.check import check_replica_consistency

                fp = check_replica_consistency(self.state.params)
                logger.info(f"consistency check OK @ step {step}: params fp {fp:#010x}")
                t_last = time.time()
                window_tokens = 0
                steps_in_window = 0

            if self.eval_freq and eval_iter is not None and step % self.eval_freq == 0:
                # on_empty="event": a finite eval stream exhausting mid-fit
                # logs loudly + emits a structured event instead of either
                # nan-poisoning silently or killing the training run
                t_eval = time.monotonic()
                self.evaluate(eval_iter, iters=self.eval_iters, on_empty="event")
                eval_total += time.monotonic() - t_eval
                t_last = time.time()
                window_tokens = 0
                steps_in_window = 0

            if self.save_steps and step % self.save_steps == 0:
                self.save()
                # a save landing while the guard sees a healthy stream is
                # proof of recovery: the budget guards against rollback
                # THRASH, not against independent anomalies days apart in
                # a long run.  The streak check matters — saves fire on
                # skipped steps too, and resetting mid-streak would let a
                # persistent anomaly roll back forever.
                if guard is None or (
                    guard.skip_streak == 0 and guard.spike_streak == 0
                ):
                    rollbacks = 0
                t_last = time.time()
                window_tokens = 0
                steps_in_window = 0
                if self.exit_after_save:
                    # checkpoint-aligned clean exit: the save above is
                    # durable once wait_for_save joins (fit's finally);
                    # reuse the preempted flag so the launcher exits 0
                    logger.info(
                        f"exit_after_save: checkpoint at step {step} "
                        "complete, exiting cleanly"
                    )
                    self.wait_for_save()
                    self.preempted = True
                    break

            # fault injection: deliver a real SIGTERM to this process so
            # the handler path itself is what the test exercises
            sig_fired = resilience.maybe_fire("sigterm", step)
            if (preempt is not None and preempt.requested) or sig_fired:
                self._preempt_save(step, "preemption signal")
                break

        if fit_trace is not None:
            # finished cleanly; a crashed fit deliberately stays
            # done=false in the buffer — that IS the postmortem signal
            fit_trace.finish()
        return self.state

    def evaluate(self, loader: Iterable, iters: Optional[int] = None,
                 on_empty: str = "raise") -> float:
        """Average eval loss over up to ``iters`` batches.

        An empty/exhausted loader used to return ``float("nan")``
        silently, poisoning every downstream consumer of the value.  Now
        ``on_empty`` decides: ``"raise"`` (default — a CLI eval against
        no data is a config error and must be loud) or ``"event"``
        (ERROR log + structured ``eval_empty`` metrics/flight event +
        nan return — the in-fit periodic path uses this, where a finite
        eval stream legitimately exhausts mid-run and must not kill the
        training loop)."""
        self._require_concrete("evaluate")
        if on_empty not in ("raise", "event"):
            raise ValueError(
                f"on_empty={on_empty!r}: use 'raise' or 'event'"
            )
        # loaders iterate forever (epoch-looping sampler): always bound
        iters = iters if iters is not None else self.eval_iters
        losses = []
        # modules exposing predict_fn + build_metric (finetune) stream
        # predictions into a host-side metric accumulator (reference
        # GPTFinetuneModule validation_step, language_module.py:370-420)
        metric = None
        if hasattr(self.module, "build_metric") and hasattr(self.module, "predict_fn"):
            metric = self.module.build_metric()
        it = iter(loader)
        try:
            for i, batch in enumerate(it):
                if i >= iters:
                    break
                dev_batch = self._put_batch(batch)
                losses.append(float(self._eval_step(self.state, dev_batch, jnp.int32(i))))
                if metric is not None:
                    # fetched per-iteration: _put_batch may retrace the steps
                    # (zigzag positions install) and a stale closure would
                    # predict with the wrong causal mask
                    predict = self._get_predict_step()
                    preds = np.asarray(jax.device_get(predict(self.state, dev_batch)))
                    metric.update(preds, np.asarray(batch["labels"]))
        finally:
            # a fresh stream created from a loader (it is not loader) is
            # OURS to reclaim: abandoning a live prefetch iterator leaves
            # its producer thread spinning forever.  When the CALLER owns
            # the stream (fit passes its long-lived eval_iter, which
            # iter() returns unchanged), it stays live.
            if it is not loader:
                close = getattr(loader, "close", None)
                if callable(close):
                    close()
        if not losses:
            msg = (
                f"evaluate saw ZERO batches (iters={iters}): the eval "
                "loader is empty or exhausted — the old behavior returned "
                "nan and silently poisoned downstream records"
            )
            if on_empty == "raise":
                raise RuntimeError(msg)
            logger.error(msg)
            self._write_metrics(
                {"event": "eval_empty", "step": self._step, "iters": iters}
            )
            return float("nan")
        avg = float(np.mean(losses))
        if metric is not None:
            from paddlefleetx_tpu.models.metrics import format_metric

            vals = " ".join(f"{k}: {v:.4f}" for k, v in format_metric(metric).items())
            logger.info(f"eval loss: {avg:.5f} {vals}")
        else:
            logger.info(f"eval loss: {avg:.5f} (ppl {np.exp(min(avg, 20.0)):.2f})")
        return avg

    # ------------------------------------------------------------------
    # Checkpoint (reference save/load eager_engine.py:717-825 + apis/io.py)
    def _write_meta(self, path: str, meta: Dict[str, Any]) -> None:
        # meta.json is the checkpoint's completeness marker (written last,
        # checked by latest_checkpoint): write atomically so a crash can
        # never leave a truncated marker that wedges the restart loop.
        # Multi-host: one writer — concurrent os.replace from N processes
        # on shared storage is a needless race (reference: only dp_rank0
        # saves, apis/io.py:28-151)
        if jax.process_index() != 0:
            return
        tmp = os.path.join(path, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(path, "meta.json"))

    def wait_for_save(self) -> None:
        """Join an in-flight async save (no-op when none is pending).
        Re-raises any error the background write hit — a swallowed storage
        failure would let training run for hours believing checkpoints
        exist."""
        t = self._save_thread
        if t is not None:
            t.join()
            self._save_thread = None
            err = self._save_error
            self._save_error = None
            if err is not None:
                raise err

    def _finish_save(self, path: str, step: int) -> None:
        """Post-save bookkeeping shared by the sync and async paths: record
        the rollback target, run the fault-injection bit-rot hook, then the
        retention GC (which never deletes the recorded last-good dir).

        Known limit: "good" here means "saved and durable", not "loss
        verified healthy" — a save landing within the spike detector's
        observation window of a finite divergence can record diverging
        state as the rollback target; max_rollbacks then stops the thrash
        and older checkpoints stay on disk for a manual resume
        (docs/fault_tolerance.md)."""
        from paddlefleetx_tpu.utils import resilience

        self._last_good_ckpt = path
        resilience.maybe_fire("ckpt_truncate", step, path=path)
        if self.keep_last_n and jax.process_index() == 0:
            from paddlefleetx_tpu.utils.checkpoint import gc_checkpoints

            try:
                gc_checkpoints(
                    self.output_dir, self.keep_last_n, protect=self._last_good_ckpt
                )
            except OSError as e:
                # GC is best-effort housekeeping: a failed delete must not
                # take down the save (the checkpoint itself is durable)
                logger.warning(f"checkpoint retention GC failed: {e}")

    def _atexit_join(self) -> None:
        """Interpreter-exit safety net (registered once, first async save):
        a SIGTERM-driven sys.exit while ``_save_thread`` is in flight must
        not strand a meta-less directory — join the write so it either
        completes (meta.json lands) or its error is logged.  Errors are
        logged, not raised: atexit swallows exceptions anyway."""
        try:
            self.wait_for_save()
        except BaseException as e:  # noqa: BLE001 — last-chance reporting
            logger.error(f"async checkpoint write failed during exit: {e}")

    def save(self, path: Optional[str] = None, preempted: bool = False):
        """Checkpoint the full train state.  ``preempted=True`` stamps the
        meta (written by the preemption path) so operators and tooling can
        distinguish a scheduled save from a SIGTERM final save."""
        self._require_concrete("save")
        import orbax.checkpoint as ocp

        from paddlefleetx_tpu.utils import resilience

        step = int(self.state.step)
        path = os.path.abspath(path or os.path.join(self.output_dir, f"step_{step}"))
        payload = {"params": self.state.params, "opt_state": self.state.opt_state}
        if self.state.extra is not None:
            payload["extra"] = self.state.extra
        meta = {"step": step, "consumed_samples": self._consumed_samples}
        loader = self._train_loader
        if loader is not None and hasattr(loader, "state_dict"):
            # loader state rides the meta (docs/data_pipeline.md).  The
            # position is overwritten with the ENGINE's counter: the
            # sampler's own count runs ahead by the prefetch lookahead
            # (batches buffered but not yet trained on), and resuming
            # from it would silently drop those batches.
            loader_state = dict(loader.state_dict())
            loader_state["consumed_samples"] = self._consumed_samples
            # same lookahead correction for the skip budget: the live
            # count includes prefetched-but-untrained batches, and the
            # resumed replay of those batches re-spends it
            skips_at = getattr(loader, "skips_at", None)
            if callable(skips_at):
                skips = skips_at(self._consumed_samples)
                if skips is not None:
                    loader_state["skips"] = skips
            meta["loader"] = loader_state
        if preempted:
            meta["preempted"] = True
        if self.state.scaler is not None:
            meta["loss_scale"] = float(self.state.scaler["scale"])
            meta["scaler_good_steps"] = int(self.state.scaler["good_steps"])

        if self.async_save:
            # one in-flight save at a time: a second save against the same
            # checkpointer must wait for the first write to finish anyway
            self.wait_for_save()
            if self._async_ckptr is None:
                self._async_ckptr = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler()
                )
            if not self._atexit_registered:
                # interpreter exit (sys.exit, end of main) must join the
                # background write: without this a clean exit right after
                # save() could leave a forever-incomplete directory when
                # the finisher thread loses the shutdown race.  Registered
                # over a weakref so atexit does not pin the Engine (and
                # its params/opt-state trees) for the process lifetime in
                # multi-Engine processes (test suites, notebooks).
                import atexit
                import weakref

                ref = weakref.ref(self)

                def _join_at_exit(ref=ref):
                    eng = ref()
                    if eng is not None:
                        eng._atexit_join()

                atexit.register(_join_at_exit)
                self._atexit_registered = True
            # returns once arrays are snapshotted to host — the training
            # loop may donate the live buffers immediately after; the
            # directory write continues in background
            self._async_ckptr.save(
                os.path.join(path, "state"),
                args=ocp.args.StandardSave(payload),
                force=True,
            )

            def finish(ckptr=self._async_ckptr, path=path, meta=meta, step=step):
                try:
                    ckptr.wait_until_finished()
                    resilience.maybe_fire("save_crash", step)
                    self._write_meta(path, meta)
                    logger.info(f"saved checkpoint (async): {path}")
                    self._finish_save(path, step)
                except BaseException as e:  # noqa: BLE001 — surfaced by
                    # wait_for_save; meta.json is never written, so resume
                    # correctly skips the incomplete directory
                    self._save_error = e

            import threading

            # non-daemon: a final save() right before process exit must not
            # be killed mid-write (interpreter joins non-daemon threads)
            self._save_thread = threading.Thread(target=finish, daemon=False)
            self._save_thread.start()
            return path

        from paddlefleetx_tpu.utils.resilience import retry

        ckptr = ocp.StandardCheckpointer()

        def write():
            ckptr.save(os.path.join(path, "state"), payload, force=True)
            ckptr.wait_until_finished()

        retry(write, desc=f"checkpoint save {path}")
        resilience.maybe_fire("save_crash", step)
        self._write_meta(path, meta)
        logger.info(f"saved checkpoint: {path}")
        self._finish_save(path, step)
        return path

    def load(self, path: str):
        self._require_concrete("load")
        import orbax.checkpoint as ocp

        from paddlefleetx_tpu.utils.resilience import retry

        self.wait_for_save()  # never restore over a half-written save
        path = os.path.abspath(path)
        ckptr = ocp.StandardCheckpointer()
        target = {
            "params": jax.tree.map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
                self.state.params,
                self.param_shardings,
            ),
            "opt_state": jax.tree.map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
                self.state.opt_state,
                self.opt_shardings,
            ),
        }
        if self.state.extra is not None:
            target["extra"] = jax.tree.map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
                self.state.extra,
                self.extra_shardings,
            )
        # transient-storage retry only: corruption raises ValueError from
        # the tensorstore layer and propagates immediately so the caller
        # (checkpoint.resume_with_fallback) can quarantine + fall back
        restored = retry(
            lambda: ckptr.restore(os.path.join(path, "state"), target),
            desc=f"checkpoint restore {path}",
        )
        # Deep-copy into XLA-owned buffers.  Orbax/tensorstore-born arrays
        # can be zero-copy views of host memory the restore pipeline still
        # owns; the train step DONATES its state (donate_argnums=0), and
        # donating such a view corrupts the first post-resume update
        # (non-finite params, occasionally a shutdown segfault) once the
        # persistent compile cache makes the executable available before
        # the restore buffers settle.  Found by the crash-resume parity
        # tests (tests/test_fault_injection.py); the copy is one-time load
        # cost and makes every restored leaf donation-safe.
        restored = jax.tree.map(lambda x: x.copy(), restored)
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self._consumed_samples = int(meta.get("consumed_samples", 0))
        self._step = int(meta["step"])
        # loader state (skip budget spent, …) applied to the train loader
        # at the next fit(); the position itself flows through
        # _consumed_samples -> build_dataloader
        self._loader_state = meta.get("loader")
        self._resumed = True  # metrics stream appends instead of truncating
        scaler = None
        if self.use_loss_scaling:
            scaler = {
                "scale": jnp.float32(meta.get("loss_scale", self.init_loss_scaling)),
                "good_steps": jnp.int32(meta.get("scaler_good_steps", 0)),
            }
        self.state = TrainState(
            step=jnp.asarray(meta["step"], jnp.int32),
            params=restored["params"],
            opt_state=restored["opt_state"],
            extra=restored.get("extra"),
            scaler=scaler,
        )
        # a checkpoint that restored IS verified-good: it becomes the
        # anomaly-rollback target until the next successful save
        self._last_good_ckpt = path
        logger.info(f"loaded checkpoint: {path} (step {meta['step']})")
