"""Admission-controlled serving request queue with deadlines, load
shedding, coalescing, and graceful drain.

`tools/serve.py` used to serialize every request behind one
``threading.Lock``: under a burst each queued request redundantly ran its
own decode, expired clients were still served after they had gone away,
and SIGTERM killed in-flight generations mid-decode.  This module is the
serving-side counterpart of the PR 2 training preemption contract
(`utils/resilience.py`), in the spirit of Clipper's deadline-aware
admission control (Crankshaw et al., NSDI 2017) and Orca's batched
iteration scheduling (Yu et al., OSDI 2022), adapted to the
bucketed-compile serving model of `core/serving.py`:

  - **bounded admission**: ``submit`` rejects when the queue is full
    (`QueueFull` -> HTTP 429 + Retry-After) or draining (`QueueClosed`
    -> HTTP 503), so backpressure reaches clients instead of piling up
    threads behind a lock.
  - **deadlines**: each request may carry an absolute deadline; the
    scheduler sheds expired entries (`DeadlineExceeded` -> HTTP 503)
    *before* spending a decode on them, and a waiter that times out can
    `try_remove` its entry so an abandoned request never wastes work.
  - **coalescing**: one scheduler thread drains the queue and merges
    compatible waiting requests (equal ``coalesce_key``) into a single
    batched runner call.  The key is computed by the caller from the
    same prompt-length/decode-length bucketing that `core/serving.py`
    uses for its jit memo, so a coalesced batch lands on an
    already-compiled artifact (power-of-two batch buckets) instead of
    keying a fresh trace — and greedy outputs stay token-identical to
    serving the requests sequentially (rows are independent across the
    batch dim).
  - **graceful drain**: ``close`` stops admission while the scheduler
    finishes every already-admitted request; ``join`` waits for the
    drain so a SIGTERM handler can answer all admitted work and exit 0.

The queue is transport-agnostic: entries carry opaque prompt payloads
and a ``runner(prompts, max_new_tokens) -> rows`` callable does the
actual generation.  All coordination is plain ``threading`` — one
scheduler thread, condition-variable wakeups, no polling while idle.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Hashable, List, Optional, Sequence

from paddlefleetx_tpu.core.tenancy import (
    DEFAULT_TENANT,
    DeficitRoundRobin,
    TenantConfig,
    TenantLabelCap,
    normalize_tenant,
)
from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.telemetry import StatsView, get_registry
from paddlefleetx_tpu.utils.tracing import (
    attach_request_trace,
    discard_request_trace,
)


class QueueFull(RuntimeError):
    """Admission rejected: the bounded queue is at capacity (HTTP 429)."""


class QueueClosed(RuntimeError):
    """Admission rejected: the queue is draining/shut down (HTTP 503)."""


class DeadlineExceeded(RuntimeError):
    """The request expired before a decode was spent on it (HTTP 503)."""


class RequestFuture:
    """Minimal one-shot future: the handler thread blocks on ``result``
    while the scheduler thread resolves it exactly once.

    ``times`` carries the request's lifecycle span stamps (monotonic):
    ``enqueued`` at admission, ``picked`` when the scheduler takes the
    entry, ``resolved`` when the result/exception lands — the transport
    layer turns these into queue-wait/decode span phases and TTFT
    histograms without the queue knowing about telemetry.

    ``trace`` is the request's sampled deep-dive trace context
    (`utils/tracing.py`) or None: both schedulers stamp their phases
    onto it (admission/queue_wait/decode; the continuous scheduler adds
    prefill + per-chunk decode events), and `/debug/trace?id=` replays
    the full timeline offline."""

    __slots__ = ("_event", "_value", "_exc", "times", "trace")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._value: Any = None
        self._exc: Optional[BaseException] = None
        self.times: Dict[str, float] = {}
        self.trace = None

    def set_result(self, value: Any) -> None:
        self._value = value
        self.times.setdefault("resolved", time.monotonic())
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self.times.setdefault("resolved", time.monotonic())
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        """Wait for resolution; raises ``TimeoutError`` if the future is
        still pending after ``timeout`` (the entry may still be queued —
        pair with ``RequestQueue.try_remove`` to shed it)."""
        if not self._event.wait(timeout):
            raise TimeoutError("request still pending")
        if self._exc is not None:
            raise self._exc
        return self._value


@dataclass
class _Entry:
    """One admitted client request (possibly carrying several prompts —
    a client-side batch stays atomic through coalescing)."""

    prompts: List[Any]
    max_new_tokens: int
    coalesce_key: Optional[Hashable]
    deadline: Optional[float]  # absolute time.monotonic(), None = no deadline
    future: RequestFuture
    enqueued_at: float
    tenant: str = DEFAULT_TENANT
    priority: int = 0


class RequestQueue:
    """Bounded admission queue + single scheduler thread.

    ``runner(prompts, max_new_tokens)`` must return one output row per
    prompt (row order matches prompt order); the scheduler splits rows
    back per entry and trims each row to that entry's own
    ``max_new_tokens`` (a coalesced batch runs at the batch max).

    Coalescing pulls *later* same-key entries forward to join the oldest
    entry's batch; entries with different keys keep their relative FIFO
    order.  ``coalesce_key=None`` opts an entry out entirely.

    With a ``tenant_config``, the head pick is a deficit round-robin
    across tenant queues (weights from the config) instead of global
    FCFS — FCFS order is preserved WITHIN a tenant, and coalescing only
    merges same-tenant entries so one tenant's batch never grows on
    another's flood.  Without a config (or when every request is the
    default tenant) the pick degenerates to exactly the old FCFS.
    """

    def __init__(
        self,
        runner: Callable[[List[Any], int], Sequence[Any]],
        *,
        max_depth: int = 64,
        max_coalesce: int = 8,
        name: str = "serve",
        tenant_config: Optional[TenantConfig] = None,
    ) -> None:
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        if max_coalesce < 1:
            raise ValueError(f"max_coalesce must be >= 1, got {max_coalesce}")
        self._runner = runner
        self.max_depth = int(max_depth)
        self.max_coalesce = int(max_coalesce)
        self.name = name
        self.tenant_config = tenant_config or TenantConfig()
        self._fair = DeficitRoundRobin(self.tenant_config.weight)
        self._tenant_labels = TenantLabelCap(
            seed=self.tenant_config.known_tenants()
        )
        # per-prompt trace contexts of the batch CURRENTLY inside the
        # runner (row order matches the runner's prompts; None when
        # untraced).  Set by the scheduler thread right before the
        # runner call and cleared after — a runner that stamps its own
        # fine-grained spans (the prefill replica's prefill_export)
        # reads it to land them on the right request timeline.  Only
        # meaningful DURING a runner call, on the scheduler thread.
        self.batch_traces: List[Any] = []
        self._entries: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._busy_since: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        # per-instance counts with the old dict interface, exported onto
        # the process-wide telemetry registry (StatsView) so /metrics and
        # /healthz read the same locked snapshot; depth/busy ride along as
        # live gauges via a weakly-held collector
        self.stats = StatsView(
            {
                "submitted": "pfx_queue_submitted_total",
                "completed": "pfx_queue_completed_total",
                "batches": "pfx_queue_batches_total",
                "coalesced_batches": "pfx_queue_coalesced_batches_total",
                "coalesced_requests": "pfx_queue_coalesced_requests_total",
                "shed_deadline": "pfx_queue_shed_deadline_total",
                "rejected_full": "pfx_queue_rejected_full_total",
                "rejected_closed": "pfx_queue_rejected_closed_total",
                "gen_errors": "pfx_queue_gen_errors_total",
            }
        )
        get_registry().register_collector(self)

    def collect(self):
        """Telemetry collector: live queue depth + runner busy seconds
        (the watchdog's wedge probe) in every registry snapshot, plus
        per-tenant waiting depth (labels folded by the top-k cap)."""
        per_tenant: Dict[str, int] = {}
        with self._lock:
            for e in self._entries:
                lab = self._tenant_labels.label(e.tenant)
                per_tenant[lab] = per_tenant.get(lab, 0) + 1
        rows = [
            ("pfx_queue_depth", {}, float(self.depth())),
            ("pfx_queue_busy_seconds", {}, self.busy_seconds()),
        ]
        for lab, n in sorted(per_tenant.items()):
            rows.append(("pfx_tenant_queue_depth", {"tenant": lab}, float(n)))
        return rows

    # -- admission ------------------------------------------------------
    def submit(
        self,
        prompts: Sequence[Any],
        max_new_tokens: int,
        *,
        coalesce_key: Optional[Hashable] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: int = 0,
    ) -> RequestFuture:
        """Admit a request; returns its future.  Raises ``QueueClosed``
        when draining and ``QueueFull`` at capacity — admission control
        happens HERE, synchronously, so the transport layer can turn the
        rejection into 503/429 without tying up a worker."""
        if not prompts:
            raise ValueError("prompts must be non-empty")
        entry = _Entry(
            prompts=list(prompts),
            max_new_tokens=int(max_new_tokens),
            coalesce_key=coalesce_key,
            deadline=(time.monotonic() + float(deadline_s))
            if deadline_s is not None else None,
            future=RequestFuture(),
            enqueued_at=time.monotonic(),
            tenant=normalize_tenant(tenant),
            priority=int(priority),
        )
        entry.future.times["enqueued"] = entry.enqueued_at
        # deep-dive tracing (sampled; no-op at PFX_TRACE_SAMPLE=0):
        # attached BEFORE the entry becomes visible to the scheduler
        # thread, or a fast pickup could miss the phase stamps
        attach_request_trace(
            entry.future, t0=entry.enqueued_at, scheduler=self.name,
            prompts=len(entry.prompts), max_new=entry.max_new_tokens,
        )
        try:
            with self._wake:
                if self._closed:
                    self.stats["rejected_closed"] += 1
                    raise QueueClosed(f"{self.name} queue is draining")
                if len(self._entries) >= self.max_depth:
                    self.stats["rejected_full"] += 1
                    raise QueueFull(
                        f"{self.name} queue full ({self.max_depth} waiting)"
                    )
                self._entries.append(entry)
                self.stats["submitted"] += 1
                self._wake.notify_all()
        except (QueueClosed, QueueFull):
            discard_request_trace(entry.future)  # never admitted
            raise
        return entry.future

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def busy_seconds(self) -> float:
        """How long the current runner call has been executing (0 when
        idle) — the serve-layer watchdog's wedged-generation probe."""
        with self._lock:
            if self._busy_since is None:
                return 0.0
            return time.monotonic() - self._busy_since

    def try_remove(self, future: RequestFuture) -> bool:
        """Shed a still-queued entry (handler-side deadline timeout): if
        the entry has not been picked up yet, remove it, resolve its
        future with ``DeadlineExceeded``, count the shed, and return
        True.  Returns False when the entry is already running/resolved
        (the scheduler will resolve it normally)."""
        with self._wake:
            for e in self._entries:
                if e.future is future:
                    self._entries.remove(e)
                    self.stats["shed_deadline"] += 1
                    if e.future.trace is not None:
                        e.future.trace.event("shed", reason="handler_timeout")
                    e.future.set_exception(
                        DeadlineExceeded("deadline exceeded while queued")
                    )
                    return True
        return False

    def debug_state(self) -> Dict[str, Any]:
        """Read-only, lock-consistent live-introspection snapshot for
        ``GET /debug/state``: waiting-entry ages and sizes (NO prompt
        contents — redaction contract), depth, drain flag.  Takes only
        this queue's lock, briefly — never blocks a running decode."""
        now = time.monotonic()
        with self._lock:
            waiting = [
                {
                    "age_s": round(now - e.enqueued_at, 4),
                    "prompts": len(e.prompts),
                    "max_new": e.max_new_tokens,
                    "deadline_in_s": (
                        round(e.deadline - now, 4)
                        if e.deadline is not None else None
                    ),
                    "tenant": e.tenant,
                    "priority": e.priority,
                }
                for e in self._entries
            ]
            closed = self._closed
            busy = (
                now - self._busy_since if self._busy_since is not None else 0.0
            )
        tenants: Dict[str, int] = {}
        for w in waiting:
            tenants[w["tenant"]] = tenants.get(w["tenant"], 0) + 1
        return {
            "scheduler": "coalesce",
            "depth": len(waiting),
            "waiting": waiting,
            "tenants": tenants,
            "busy_s": round(busy, 4),
            "closed": closed,
        }

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "RequestQueue":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name=f"{self.name}-scheduler", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop admitting; already-admitted entries still run (drain)."""
        with self._wake:
            self._closed = True
            self._wake.notify_all()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the drain to finish (queue empty, runner idle,
        scheduler exited).  Returns False on timeout — e.g. a wedged
        generation; the caller escalates (force-quit)."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None) -> bool:
        """Close + (optionally) flush waiting entries with QueueClosed
        + join.  ``drain=False`` answers queued-but-unstarted requests
        with an error instead of running them."""
        self.close()
        if not drain:
            with self._wake:
                while self._entries:
                    e = self._entries.popleft()
                    e.future.set_exception(
                        QueueClosed(f"{self.name} queue shut down")
                    )
                self._wake.notify_all()
        return self.join(timeout)

    # -- scheduler ------------------------------------------------------
    def _shed_locked(self, entry: _Entry) -> None:
        self.stats["shed_deadline"] += 1
        waited = time.monotonic() - entry.enqueued_at
        logger.warning(
            f"{self.name}: shed expired request after {waited:.2f}s queued "
            f"({len(entry.prompts)} prompt(s))"
        )
        if entry.future.trace is not None:
            entry.future.trace.event("shed", reason="expired_in_queue")
        entry.future.set_exception(
            DeadlineExceeded(f"deadline exceeded after {waited:.2f}s queued")
        )

    def _take_batch_locked(self) -> Optional[List[_Entry]]:
        """Pop the next entry by weighted-fair tenant pick (oldest entry
        of the deficit-round-robin-chosen tenant — plain FCFS when only
        one tenant waits) plus every compatible waiting entry of the
        SAME tenant (same coalesce_key, combined prompt count <=
        max_coalesce).  Expired entries found along the way are shed.
        Returns None when the queue is empty."""
        now = time.monotonic()
        while self._entries:
            # shed expired entries first so the fair pick never spends a
            # tenant's turn on a request nobody is waiting for
            live: List[_Entry] = []
            for e in self._entries:
                if e.deadline is not None and now > e.deadline:
                    self._shed_locked(e)
                else:
                    live.append(e)
            self._entries = deque(live)
            if not self._entries:
                return None
            backlog: Dict[str, int] = {}
            for e in self._entries:
                backlog[e.tenant] = backlog.get(e.tenant, 0) + 1
            pick = self._fair.pick(backlog)
            head = next(e for e in self._entries if e.tenant == pick)
            self._entries.remove(head)
            self._fair.charge(pick)
            batch = [head]
            n = len(head.prompts)
            if head.coalesce_key is not None and self.max_coalesce > n:
                keep: List[_Entry] = []
                for e in self._entries:
                    if (
                        e.tenant == head.tenant
                        and e.coalesce_key == head.coalesce_key
                        and n + len(e.prompts) <= self.max_coalesce
                    ):
                        batch.append(e)
                        n += len(e.prompts)
                    else:
                        keep.append(e)
                self._entries = deque(keep)
            return batch
        return None

    def _run(self) -> None:
        while True:
            with self._wake:
                batch = self._take_batch_locked()
                while batch is None:
                    if self._closed:
                        return  # drained: admission closed + queue empty
                    self._wake.wait()
                    batch = self._take_batch_locked()
                self._busy_since = time.monotonic()
                for e in batch:
                    # span stamp: queue-wait ends here, decode begins
                    e.future.times.setdefault("picked", self._busy_since)
                    if e.future.trace is not None:
                        e.future.trace.span(
                            "queue_wait", t0=e.enqueued_at,
                            t1=self._busy_since,
                        )
            try:
                self._run_batch(batch)
            finally:
                with self._lock:
                    self._busy_since = None

    def _run_batch(self, batch: List[_Entry]) -> None:
        prompts = [p for e in batch for p in e.prompts]
        max_new = max(e.max_new_tokens for e in batch)
        self.stats["batches"] += 1
        if len(batch) > 1:
            self.stats["coalesced_batches"] += 1
            self.stats["coalesced_requests"] += len(batch)
            logger.info(
                f"{self.name}: coalesced {len(batch)} requests "
                f"({len(prompts)} prompts) into one batch"
            )
        t_decode = time.monotonic()
        self.batch_traces = [
            e.future.trace for e in batch for _ in e.prompts
        ]
        try:
            rows = self._runner(prompts, max_new)
        except BaseException as exc:  # noqa: BLE001 — fan the error out
            # every coalesced client gets the error; the scheduler
            # itself survives and keeps draining the queue
            self.stats["gen_errors"] += 1
            for e in batch:
                if e.future.trace is not None:
                    e.future.trace.event("error", type=type(exc).__name__)
                e.future.set_exception(exc)
            logger.warning(
                f"{self.name}: generation failed for a batch of "
                f"{len(batch)} request(s): {type(exc).__name__}: {exc}"
            )
            return
        finally:
            self.batch_traces = []
        rows = list(rows)
        if len(rows) != len(prompts):
            exc = RuntimeError(
                f"runner returned {len(rows)} rows for {len(prompts)} prompts"
            )
            self.stats["gen_errors"] += 1
            for e in batch:
                e.future.set_exception(exc)
            return
        t_done = time.monotonic()
        i = 0
        for e in batch:
            out = rows[i:i + len(e.prompts)]
            i += len(e.prompts)
            # a coalesced batch decodes to the batch max; honor each
            # request's own cap (greedy prefixes are step-identical)
            out = [
                r[: e.max_new_tokens] if len(r) > e.max_new_tokens else r
                for r in out
            ]
            if e.future.trace is not None:
                e.future.trace.span(
                    "decode", t0=t_decode, t1=t_done,
                    batch=len(batch), prompts=len(prompts),
                    tokens=sum(len(r) for r in out),
                )
            e.future.set_result(out)
            self.stats["completed"] += 1
