"""Multi-tenant isolation substrate: labels, quotas, fair-share state.

Every queue in the serving fabric was single-class FCFS, so one heavy
tenant's flood moved every other user's p99.  This module is the one
place the tenant/priority vocabulary lives; the router edge, the
coalescing RequestQueue, and the ContinuousScheduler all import from
here so the config file, the header names, and the fairness math cannot
drift apart:

  - **Labels** — requests carry ``X-Tenant`` / ``X-Priority`` headers
    end-to-end (router -> retries -> direct handoff -> replica).
    :func:`normalize_tenant` maps raw header bytes onto a bounded,
    metrics-safe alphabet (unknown/empty -> ``anon``);
    :func:`parse_priority` clamps priorities to [-100, 100] with 0 as
    the neutral default.
  - **TenantConfig** — one JSON file (``--tenants path``) feeds BOTH the
    router's edge quotas and the schedulers' fair-share weights::

        {"default": {"weight": 1.0},
         "tenants": {"gold": {"weight": 4, "rps": 50, "burst": 100,
                              "max_inflight": 32}}}

    Absent fields mean "no limit" (the default config admits everything
    — single-tenant deployments pay nothing).  Parse errors are LOUD.
  - **TokenBucket / TenantAdmission** — the router front door's
    per-tenant request-rate + in-flight caps.  A rate rejection returns
    the bucket's ACTUAL time-to-next-token so the 429's Retry-After is
    honest, never a made-up constant.
  - **DeficitRoundRobin** — the weighted-fair pick used by both
    scheduler admission loops: each replenish round grants every
    backlogged tenant ``quantum * weight`` deficit, a pick costs 1, and
    an idle tenant's deficit resets (classic DRR, Shreedhar & Varghese
    1996).  Starvation-free by construction: every replenish strictly
    grows every backlogged tenant's deficit, so any waiting tenant is
    picked within a bounded number of rounds regardless of the flood
    next door.  FCFS order is preserved WITHIN a tenant by the caller.
  - **TenantLabelCap** — tenants are unbounded but metric label
    cardinality must not be (the PR 15 federation-cap discipline): the
    first ``PFX_TENANT_LABEL_TOPK`` distinct tenants (config-declared
    tenants seeded first) keep their own label, everyone later folds
    into the ``__other__`` overflow bucket.  A tenant never changes
    buckets once assigned, so per-label counters stay monotonic.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from paddlefleetx_tpu.utils.log import logger

# Header names carried verbatim across every hop (router dispatch
# retries, re-prefill failover, direct prefill->decode handoff).
TENANT_HEADER = "X-Tenant"
PRIORITY_HEADER = "X-Priority"

# The label every unlabeled request lands on.  A deployment that never
# sends X-Tenant runs exactly as before: one tenant, default weight, no
# quotas.
DEFAULT_TENANT = "anon"

# The fold-bucket for tenants past the top-k label cap.
OVERFLOW_TENANT = "__other__"

_TENANT_SAFE_RE = re.compile(r"[^A-Za-z0-9_.:-]")
_TENANT_MAX_LEN = 64

PRIORITY_MIN = -100
PRIORITY_MAX = 100


def normalize_tenant(raw: Optional[str]) -> str:
    """Map a raw ``X-Tenant`` header value onto the bounded, metrics-safe
    tenant alphabet.  Empty/missing -> :data:`DEFAULT_TENANT`."""
    if raw is None:
        return DEFAULT_TENANT
    cleaned = _TENANT_SAFE_RE.sub("_", raw.strip())[:_TENANT_MAX_LEN]
    return cleaned or DEFAULT_TENANT


def parse_priority(raw: Optional[str]) -> int:
    """Parse an ``X-Priority`` header: int, clamped to [-100, 100];
    missing/garbage -> 0 (never a 500 off a malformed header)."""
    if raw is None:
        return 0
    try:
        val = int(str(raw).strip())
    except ValueError:
        return 0
    return max(PRIORITY_MIN, min(PRIORITY_MAX, val))


@dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant knobs.  ``None`` means no limit for that axis."""

    weight: float = 1.0
    rps: Optional[float] = None
    burst: Optional[float] = None
    max_inflight: Optional[int] = None

    def __post_init__(self) -> None:
        if not (self.weight > 0.0):
            raise ValueError(f"tenant weight must be > 0, got {self.weight}")
        if self.rps is not None and not (self.rps > 0.0):
            raise ValueError(
                f"tenant rps must be > 0 when set, got {self.rps} "
                f"(omit it for 'no rate limit')"
            )
        if self.burst is not None and not (self.burst >= 1.0):
            raise ValueError(f"tenant burst must be >= 1, got {self.burst}")
        if self.max_inflight is not None and self.max_inflight < 1:
            raise ValueError(
                f"tenant max_inflight must be >= 1, got {self.max_inflight}"
            )


_POLICY_FIELDS = ("weight", "rps", "burst", "max_inflight")


def _policy_from_obj(obj: Dict, where: str) -> TenantPolicy:
    if not isinstance(obj, dict):
        raise ValueError(f"{where}: expected an object, got {type(obj).__name__}")
    unknown = sorted(set(obj) - set(_POLICY_FIELDS))
    if unknown:
        raise ValueError(f"{where}: unknown keys {unknown} (valid: {_POLICY_FIELDS})")
    kwargs = {}
    for key in _POLICY_FIELDS:
        if key in obj and obj[key] is not None:
            kwargs[key] = obj[key]
    try:
        return TenantPolicy(**kwargs)
    except (TypeError, ValueError) as exc:
        raise ValueError(f"{where}: {exc}") from None


class TenantConfig:
    """The one tenant policy table: default policy + per-tenant overrides.

    Parsed from the JSON shape documented in the module docstring; the
    same object feeds router quotas, scheduler weights, and the label
    cap's seed set.
    """

    def __init__(self, default: Optional[TenantPolicy] = None,
                 tenants: Optional[Dict[str, TenantPolicy]] = None) -> None:
        self.default = default or TenantPolicy()
        self.tenants: Dict[str, TenantPolicy] = dict(tenants or {})

    def policy(self, tenant: str) -> TenantPolicy:
        return self.tenants.get(tenant, self.default)

    def weight(self, tenant: str) -> float:
        return self.policy(tenant).weight

    def known_tenants(self) -> List[str]:
        """Config-declared tenants in declaration order (label-cap seed)."""
        return list(self.tenants)

    @classmethod
    def from_obj(cls, obj: Dict, where: str = "tenants config") -> "TenantConfig":
        if not isinstance(obj, dict):
            raise ValueError(f"{where}: expected a JSON object at the top level")
        unknown = sorted(set(obj) - {"default", "tenants"})
        if unknown:
            raise ValueError(
                f"{where}: unknown top-level keys {unknown} "
                f"(valid: 'default', 'tenants')"
            )
        default = _policy_from_obj(obj.get("default", {}), f"{where}.default")
        tenants: Dict[str, TenantPolicy] = {}
        for name, spec in (obj.get("tenants") or {}).items():
            key = normalize_tenant(name)
            if key != name:
                raise ValueError(
                    f"{where}.tenants[{name!r}]: tenant names must already be "
                    f"label-safe (normalized form: {key!r})"
                )
            tenants[key] = _policy_from_obj(spec, f"{where}.tenants[{name!r}]")
        return cls(default=default, tenants=tenants)

    @classmethod
    def from_file(cls, path: str) -> "TenantConfig":
        try:
            with open(path, "r", encoding="utf-8") as fh:
                obj = json.load(fh)
        except OSError as exc:
            raise ValueError(f"tenants config {path!r}: {exc}") from None
        except json.JSONDecodeError as exc:
            raise ValueError(f"tenants config {path!r}: invalid JSON: {exc}") from None
        cfg = cls.from_obj(obj, where=path)
        logger.info(
            f"tenants config {path}: {len(cfg.tenants)} tenant(s) declared, "
            f"default weight {cfg.default.weight}"
        )
        return cfg


class TokenBucket:
    """Monotonic-clock token bucket.  NOT thread-safe on its own — the
    owning :class:`TenantAdmission` serializes access."""

    def __init__(self, rate: float, burst: Optional[float] = None) -> None:
        if not (rate > 0.0):
            raise ValueError(f"token bucket rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(1.0, self.rate)
        self.tokens = self.burst
        self._t_last: Optional[float] = None

    def refill(self, now: float) -> None:
        """Advance the refill clock to ``now`` without consuming — the
        snapshot path uses it so journaled token counts are current as
        of the snapshot instant, not as of the tenant's last request."""
        if self._t_last is None:
            self._t_last = now
        elapsed = max(0.0, now - self._t_last)
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        self._t_last = now

    def try_acquire(self, now: float) -> Tuple[bool, float]:
        """Take one token.  Returns ``(ok, retry_after_s)`` where
        ``retry_after_s`` is the ACTUAL time until the next whole token
        refills (0.0 on success) — the honest Retry-After."""
        self.refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        return False, (1.0 - self.tokens) / self.rate


class TenantAdmission:
    """The router front door's per-tenant quota gate: request-rate token
    buckets plus in-flight caps, all from one :class:`TenantConfig`.

    ``admit`` / ``release`` bracket a request exactly like the router's
    global acquire/release; an unlimited tenant (the default policy)
    takes one dict lookup and returns.
    """

    def __init__(self, config: Optional[TenantConfig] = None,
                 clock=time.monotonic) -> None:
        self.config = config or TenantConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._inflight: Dict[str, int] = {}

    def admit(self, tenant: str) -> Tuple[bool, str, float]:
        """Returns ``(ok, reason, retry_after_s)``.  Reasons: ``rate``
        (bucket empty; retry_after is the real refill time) or
        ``inflight`` (cap reached; retry_after estimates one token
        interval, or 1.0 for rate-unlimited tenants).  On ``ok`` the
        tenant's in-flight count is already incremented — callers MUST
        pair with :meth:`release`."""
        pol = self.config.policy(tenant)
        with self._lock:
            if (pol.max_inflight is not None
                    and self._inflight.get(tenant, 0) >= pol.max_inflight):
                retry = 1.0 if pol.rps is None else 1.0 / pol.rps
                return False, "inflight", retry
            if pol.rps is not None:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = TokenBucket(pol.rps, pol.burst)
                    self._buckets[tenant] = bucket
                ok, retry = bucket.try_acquire(self._clock())
                if not ok:
                    return False, "rate", retry
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        return True, "", 0.0

    def release(self, tenant: str) -> None:
        with self._lock:
            n = self._inflight.get(tenant, 0) - 1
            if n > 0:
                self._inflight[tenant] = n
            else:
                self._inflight.pop(tenant, None)

    def inflight_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._inflight)

    # -- control-plane journal surface (core/router.py FleetJournal) ----
    def bucket_snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant token-bucket state for the control-plane journal
        (docs/serving.md "Control-plane recovery"): tokens are refilled
        to NOW first, so the snapshot is current at the instant it is
        taken and restorers only need the wall-clock age of the record
        — the monotonic refill clock never leaves this process."""
        with self._lock:
            now = self._clock()
            out: Dict[str, Dict[str, float]] = {}
            for tn, b in self._buckets.items():
                b.refill(now)
                out[tn] = {
                    "tokens": round(b.tokens, 6),
                    "rate": b.rate,
                    "burst": b.burst,
                }
            return out

    def restore_buckets(self, buckets: Dict[str, Dict[str, float]],
                        age_s: float = 0.0) -> int:
        """Fold a journaled :meth:`bucket_snapshot` back in (router
        restart): each tenant's bucket resumes from its recorded token
        count plus ``age_s`` seconds of refill at the CURRENTLY
        configured rate — the router's death window earns exactly the
        refill it would have earned, never a fresh burst allowance
        (that free window is the 429-storm hole this closes).  Tenants
        whose current config no longer rate-limits are skipped (the
        operator's new config wins); rate/burst come from the current
        policy, not the journal, for the same reason.  Returns the
        number of buckets restored."""
        restored = 0
        with self._lock:
            now = self._clock()
            for tn, snap in (buckets or {}).items():
                pol = self.config.policy(str(tn))
                if pol.rps is None:
                    continue
                try:
                    tokens = float(snap.get("tokens", 0.0))
                except (TypeError, ValueError, AttributeError):
                    continue
                b = TokenBucket(pol.rps, pol.burst)
                b.tokens = min(
                    b.burst,
                    max(0.0, tokens) + max(0.0, float(age_s)) * b.rate,
                )
                b._t_last = now
                self._buckets[str(tn)] = b
                restored += 1
        return restored


class DeficitRoundRobin:
    """Weighted-fair tenant pick for an admission loop.

    Usage: ``pick(backlog)`` with ``{tenant: waiting_count}`` returns
    the tenant to serve next (or ``None`` if nothing waits); the caller
    admits that tenant's OLDEST entry (FCFS within tenant) and calls
    ``charge(tenant)``.  Deficit state for tenants with no backlog is
    dropped (classic DRR reset), so a returning tenant starts fresh
    rather than cashing in idle time.
    """

    def __init__(self, weight_fn=None, quantum: float = 1.0) -> None:
        self._weight_fn = weight_fn or (lambda tenant: 1.0)
        self.quantum = max(1e-6, float(quantum))
        # insertion-ordered: first-seen order breaks deficit ties, so
        # the pick is deterministic for the decision-log replay
        self._deficit: Dict[str, float] = {}

    def pick(self, backlog: Dict[str, int]) -> Optional[str]:
        active = [t for t, n in backlog.items() if n > 0]
        if not active:
            return None
        active_set = set(active)
        for t in list(self._deficit):
            if t not in active_set:
                del self._deficit[t]
        for t in active:
            self._deficit.setdefault(t, 0.0)
        # every replenish adds quantum*weight (> 0) to every backlogged
        # tenant, so the worst case to cross cost=1 is bounded by the
        # smallest weight; the cap below is generous headroom over that
        max_rounds = int(2 + 1.0 / (self.quantum * min(
            max(1e-6, float(self._weight_fn(t))) for t in active
        )))
        for _ in range(max_rounds):
            best = None
            for t in self._deficit:  # insertion order breaks ties
                if t in active_set and (best is None
                                        or self._deficit[t] > self._deficit[best]):
                    best = t
            if best is not None and self._deficit[best] >= 1.0:
                return best
            for t in active:
                self._deficit[t] += self.quantum * max(
                    1e-6, float(self._weight_fn(t))
                )
        return best  # unreachable in practice; never None (active nonempty)

    def charge(self, tenant: str, cost: float = 1.0) -> None:
        if tenant in self._deficit:
            self._deficit[tenant] -= cost


class TenantLabelCap:
    """First-K-distinct tenant -> metric label fold (PR 15 cardinality
    discipline).  Config-declared tenants are seeded first so the
    tenants an operator actually configured never fold into
    ``__other__`` (as long as they fit in K)."""

    def __init__(self, topk: Optional[int] = None,
                 seed: Sequence[str] = ()) -> None:
        if topk is None:
            raw = os.environ.get("PFX_TENANT_LABEL_TOPK") or ""
            if raw.strip():
                try:
                    topk = int(raw)
                except ValueError:
                    raise ValueError(
                        f"PFX_TENANT_LABEL_TOPK={raw!r} is not an int "
                        f"(loud-parse: unset it or pass a valid value)"
                    ) from None
                if topk < 1:
                    raise ValueError(
                        f"PFX_TENANT_LABEL_TOPK={topk} must be >= 1"
                    )
            else:
                topk = 8
        self.topk = topk
        self._lock = threading.Lock()
        self._known: Dict[str, None] = {}
        for t in seed:
            if len(self._known) >= self.topk:
                break
            self._known.setdefault(normalize_tenant(t), None)

    def label(self, tenant: str) -> str:
        """The metric label for ``tenant``: itself while distinct-tenant
        count stays within top-k, else the overflow bucket.  Stable per
        tenant for the life of the process (monotonic counters)."""
        with self._lock:
            if tenant in self._known:
                return tenant
            if len(self._known) < self.topk:
                self._known[tenant] = None
                return tenant
        return OVERFLOW_TENANT

    def labels(self) -> List[str]:
        with self._lock:
            return list(self._known)
