"""Generation serving: persistent model + bucketed compiled decode.

The reference deploys generation through its static-graph predictor
(core/engine/inference_engine.py:104 `InferenceEngine.predict` :252, one
process per mp rank over NCCL).  TPU-native serving is simpler: ONE process
per host, params sharded over the serving mesh by the same logical rules as
training, and a jitted decode per (prompt-bucket, max_dec_len) pair — the
bucket padding (`pad_prompts`) keeps the number of compiled artifacts small
and stable under real traffic.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from paddlefleetx_tpu.models.gpt.generation import (
    GenerationConfig,
    bucket_len,
    generate,
    init_cache,
    pad_prompts,
)
from paddlefleetx_tpu.ops.decode_attention import kv_cache_dtype
from paddlefleetx_tpu.ops.speculative import spec_config_from
from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.resilience import maybe_fire
from paddlefleetx_tpu.utils.telemetry import StatsView, get_registry


def plan_decode(padded_len: int, max_toks: int, *, context: int):
    """THE decode-length clamp for an explicit client ``max_tokens``:
    (trim, run) where ``trim`` is the per-request output cap (context
    room respected, floored at 1) and ``run`` is the 32-bucketed decode
    length that keys the compile.  Single-sourced on purpose —
    ``generate_ids`` clamps with it and the serve-layer coalesce key
    (tools/serve.py ``plan_request``) predicts with it, so "equal keys
    pad identically whether served together or apart" can never drift.
    Raises ValueError when the padded prompt leaves no decode room."""
    limit = int(context) - int(padded_len)
    if limit < 1:
        raise ValueError(
            f"prompt bucket {padded_len} leaves no decode room in "
            f"context {context}"
        )
    trim = max(1, min(int(max_toks), limit))
    run = min(-(-trim // 32) * 32, limit)
    return trim, run


class GenerationServer:
    """Holds params on the mesh and serves tokenized generation requests.

    ``generate_ids`` is the transport-independent core; ``generate_text``
    adds tokenizer round-tripping when one is configured.
    """

    def __init__(self, cfg, mesh, module, params=None, tokenizer=None):
        from paddlefleetx_tpu.models.gpt.model import ShardingCtx
        from paddlefleetx_tpu.parallel.seed import get_seed_tracker
        from paddlefleetx_tpu.parallel.sharding import (
            make_rules,
            tree_logical_to_sharding,
        )

        self.cfg = cfg
        self.mesh = mesh
        self.module = module
        self.tokenizer = tokenizer

        gen_cfg = cfg.get("Generation", {})
        self.bucket = int(gen_cfg.get("pad_to_multiple", 64))
        self.gen = GenerationConfig(
            max_dec_len=int(gen_cfg.get("max_dec_len", 64)),
            min_dec_len=int(gen_cfg.get("min_dec_len", 1)),
            decode_strategy=gen_cfg.get("decode_strategy", "sampling"),
            temperature=float(gen_cfg.get("temperature", 1.0)),
            top_k=int(gen_cfg.get("top_k", 0)),
            top_p=float(gen_cfg.get("top_p", 1.0)),
            repetition_penalty=float(gen_cfg.get("repetition_penalty", 1.0)),
            eos_token_id=int(gen_cfg.get("eos_token_id", 50256)),
            pad_token_id=int(gen_cfg.get("pad_token_id", 0)),
            forced_bos_token_id=int(gen_cfg.get("forced_bos_token_id", -1)),
            forced_eos_token_id=int(gen_cfg.get("forced_eos_token_id", -1)),
        )
        # Generation.speculative: {draft_k, drafter, ngram, kv_dtype} —
        # draft_k > 0 routes the contiguous decode through the
        # speculative while-loop (greedy stays token-identical); kv_dtype
        # int8 quantizes the donated cache pool (PFX_KV_DTYPE is the env
        # spelling for benches; an explicit config value wins)
        spec_section = dict(gen_cfg.get("speculative", {}) or {})
        self.spec = spec_config_from(spec_section)
        self.kv_dtype = kv_cache_dtype(
            str(spec_section.get("kv_dtype", "") or "")
        )

        rules = make_rules(mesh=mesh)
        self.ctx = ShardingCtx(mesh, rules) if mesh.size > 1 else None
        if params is None:
            params = module.init_params(get_seed_tracker().params_key())
        if self.ctx is not None:
            shardings = tree_logical_to_sharding(module.logical_axes(), mesh, rules)
            params = jax.device_put(params, shardings)
        self.params = params
        self._key = jax.random.key(int(cfg.get("Global", {}).get("seed", 0)))
        # one jitted decode per (bucket_b, bucket_len, GenerationConfig):
        # mixed-traffic serving hits a small, log-bounded set of compiled
        # artifacts (pad_prompts length buckets x power-of-two batch
        # buckets) and NEVER retraces a key it has seen — stats["traces"]
        # counts trace-time entries so a retrace regression is testable
        self._compiled: Dict = {}
        # live cache pairs recycled between same-bucket requests via
        # donation (see generate_ids).  LRU-BOUNDED: unlike the compiled-fn
        # memo (host-side artifacts), each pooled entry pins a full
        # [layers,b,heads,max_len,dim] k/v pair in device memory, and the
        # key space multiplies across batch x prompt x dec-len buckets —
        # unbounded mixed traffic on a real model would exhaust HBM.  An
        # evicted bucket just re-allocates a zeros pair on its next hit.
        from collections import OrderedDict

        self._cache_pool: "OrderedDict" = OrderedDict()
        self._cache_pool_size = int(gen_cfg.get("cache_pool_size", 4))
        # last_latency_s: wall-clock of the most recent generate_ids call —
        # /healthz surfaces it so operators see a slow/regressed decode
        # without scraping logs (tools/serve.py)
        # gen_errors / last_error: structured generation-failure stats —
        # /healthz spreads server.stats, so an operator sees a failing
        # decode (and its class) without scraping logs
        # StatsView: same dict interface as before, but the numeric keys
        # are exported onto the process-wide telemetry registry so
        # /metrics and /healthz render one locked snapshot (non-exported
        # keys — last_error, warmup_s — stay instance-local)
        self.stats = StatsView(
            {
                "requests": "pfx_serving_requests_total",
                "tokens_out": "pfx_serving_tokens_out_total",
                "time_s": "pfx_serving_gen_seconds_total",
                "traces": "pfx_serving_traces_total",
                "gen_errors": "pfx_serving_gen_errors_total",
                "last_latency_s": "pfx_serving_last_latency_seconds",
                "spec_proposed": "pfx_spec_proposed_total",
                "spec_accepted": "pfx_spec_accepted_total",
            },
            init={"time_s": 0.0, "last_latency_s": 0.0, "last_error": ""},
        )

    def _decode_fn(self, gen: GenerationConfig, batch: int, bucket_len: int):
        key = (gen, batch, bucket_len)
        fn = self._compiled.get(key)
        if fn is None:
            beam = gen.decode_strategy == "beam_search"
            spec = None if beam else self.spec

            def traced(p, x, lens, k, cache):
                # trace-time side effect: runs once per compile, never at
                # execution — the retrace-count contract's probe
                self.stats["traces"] += 1
                # (tokens, final cache[, (proposed, accepted)]) on the
                # sampling/greedy path; bare tokens for beam (no
                # donation there)
                return generate(
                    p, x, self.module.config, gen, key=k, ctx=self.ctx,
                    prompt_lens=lens, cache=cache, return_cache=not beam,
                    spec=spec, return_spec_stats=spec is not None,
                )

            # the KV cache is DONATED and RETURNED: donation aliases the
            # input pair to the returned final cache, so the per-step
            # dynamic_update_slice writes the [layers,b,heads,max_len,dim]
            # buffers in place; generate_ids feeds the returned cache of
            # one request straight back into the next same-bucket request
            # (stale tail slots are never visited by the blocked kernel)
            fn = jax.jit(traced, donate_argnums=(4,))
            self._compiled[key] = fn
        return fn

    # ------------------------------------------------------------------
    def generate_ids(
        self, prompts: Sequence[Sequence[int]], max_dec_len: Optional[int] = None
    ) -> List[List[int]]:
        """Generate continuations for a batch of token-id prompts."""
        import dataclasses

        if not prompts or any(len(p) == 0 for p in prompts):
            raise ValueError("prompts must be a non-empty list of non-empty id lists")
        from paddlefleetx_tpu.parallel.mesh import data_parallel_world

        gen = self.gen
        # the batch dim is sharded over (data, fsdp): pad the request batch
        # to a dp-world multiple (replicas of the last prompt) so any mesh
        # serves any request size; batched traffic rides the data axis
        n_req = len(prompts)
        dpw = data_parallel_world(self.mesh)
        # bucket the batch dim like the decode length: pad to the next power
        # of two (then up to a dp-world multiple) so varied client batch
        # sizes reuse a small log-bounded set of compiled artifacts instead
        # of keying a fresh multi-second XLA compile per distinct size
        target = 1
        while target < n_req:
            target *= 2
        target = -(-target // dpw) * dpw
        batch = list(prompts)
        while len(batch) < target:
            batch.append(batch[-1])
        prompt, prompt_lens = pad_prompts(batch, gen.pad_token_id, multiple=self.bucket)

        # clamp + bucket the decode length: an uncapped client value would
        # key an unbounded number of jit compiles (and a huge one would try
        # to allocate a decode buffer that long); the cap is whatever room
        # the model context leaves after the padded prompt bucket
        limit = int(self.module.config.max_position_embeddings) - prompt.shape[1]
        if limit < 1:
            raise ValueError(
                f"prompt bucket {prompt.shape[1]} leaves no decode room in "
                f"context {self.module.config.max_position_embeddings}"
            )
        if max_dec_len is None:
            # configured default: honor it exactly (one compile), just clamp
            trim = min(gen.max_dec_len, limit)
            run_len = trim
        else:
            # shared clamp: the serve-layer coalesce key predicts this
            trim, run_len = plan_decode(
                int(prompt.shape[1]), max_dec_len,
                context=int(self.module.config.max_position_embeddings),
            )
        if run_len != gen.max_dec_len:
            gen = dataclasses.replace(gen, max_dec_len=run_len)
        self._key, k = jax.random.split(self._key)
        t0 = time.time()
        beam = gen.decode_strategy == "beam_search"
        bucket_key = (gen, int(prompt.shape[0]), int(prompt.shape[1]))
        req_idx = int(self.stats["requests"]) + 1
        with self.mesh:
            # donated cache per request: first hit of a bucket allocates a
            # zeros pair, every later request re-donates the FINAL cache
            # the previous same-bucket request returned (the jit aliases
            # input to output, so steady-state serving does zero cache
            # copies and zero cache allocations; stale tail slots are
            # never visited by the blocked decode kernel).  Beam search
            # reorders the cache by parent each step and allocates
            # internally instead.
            cache = None
            if not beam:
                cache = self._cache_pool.pop(bucket_key, None)
                if cache is None:
                    # speculation needs draft_k slack slots for the
                    # verify chunk's rejected tail; kv_dtype int8
                    # allocates the quantized pair + scale planes
                    slack = self.spec.draft_k if self.spec else 0
                    cache = init_cache(
                        self.module.config, prompt.shape[0],
                        prompt.shape[1] + gen.max_dec_len + slack,
                        kv_dtype=self.kv_dtype,
                    )
            try:
                # serving fault sites (tests/test_serve_drills.py): both
                # fire after the cache pop so an injected failure lands on
                # the same path as a real mid-decode one
                maybe_fire("gen_crash", req_idx)
                maybe_fire("gen_hang", req_idx)
                out = self._decode_fn(gen, prompt.shape[0], prompt.shape[1])(
                    self.params,
                    jax.numpy.asarray(prompt),
                    jax.numpy.asarray(prompt_lens),
                    k,
                    cache,
                )
            except BaseException as exc:
                # the popped pair was already fed to a donating jit call
                # (or is about to be abandoned): it may be
                # donation-invalidated, so DROP it — never return a
                # possibly-deleted buffer to the pool, and never leave
                # the bucket pointing at one.  The next same-bucket
                # request re-allocates a fresh zeros pair.
                self.stats["gen_errors"] += 1
                self.stats["last_error"] = f"{type(exc).__name__}: {exc}"
                raise
            spec_stats = None
            if not beam:
                if self.spec is not None:
                    out, final_cache, spec_stats = out
                else:
                    out, final_cache = out
                self._cache_pool[bucket_key] = final_cache
                self._cache_pool.move_to_end(bucket_key)
                while len(self._cache_pool) > self._cache_pool_size:
                    self._cache_pool.popitem(last=False)  # evict LRU pair
        out = np.asarray(out)[:n_req]
        dt = time.time() - t0
        outs: List[List[int]] = []
        for row in out:
            ids = row.tolist()[:trim]
            if gen.eos_token_id in ids:
                ids = ids[: ids.index(gen.eos_token_id)]
            outs.append(ids)
        self.stats["requests"] += 1
        self.stats["tokens_out"] += sum(len(o) for o in outs)
        self.stats["time_s"] += dt
        self.stats["last_latency_s"] = round(dt, 4)
        if spec_stats is not None:
            self.stats["spec_proposed"] += int(spec_stats[0])
            self.stats["spec_accepted"] += int(spec_stats[1])
            prop = float(self.stats["spec_proposed"])
            get_registry().gauge("pfx_spec_accept_rate").set(
                float(self.stats["spec_accepted"]) / prop if prop else 0.0
            )
        return outs

    def generate_text(self, prompts: Sequence[str], max_dec_len: Optional[int] = None):
        if self.tokenizer is None:
            raise ValueError("no tokenizer configured (Generation.tokenizer_dir)")
        ids = [self.tokenizer.encode(p) for p in prompts]
        outs = self.generate_ids(ids, max_dec_len=max_dec_len)
        return [self.tokenizer.decode(o) for o in outs]

    def warmup(
        self,
        prompt_lens: "Sequence[int] | int" = (8,),
        batch_sizes: Sequence[int] = (1,),
    ) -> Dict[str, float]:
        """Compile the decode for a list of prompt-length buckets
        (`--warmup-buckets` in tools/serve.py), optionally crossed with
        batch-size buckets (`--warmup-batches` — the coalescing scheduler
        makes power-of-two batch buckets a hot compile key too); returns
        and records per-bucket compile seconds in ``stats["warmup_s"]``.

        Fails LOUDLY: every bucket is validated up front (positive,
        leaves decode room in the context) and a failing bucket raises
        naming what did and did not warm — a silently half-warmed server
        would pay a surprise multi-second compile on its first live
        request.
        """
        if isinstance(prompt_lens, int):  # old warmup(prompt_len=8) shape
            prompt_lens = (prompt_lens,)
        lens = [int(n) for n in prompt_lens]
        batches = [int(b) for b in batch_sizes]
        if not lens or not batches:
            raise ValueError("warmup needs >= 1 prompt-length and batch bucket")
        ctx = int(self.module.config.max_position_embeddings)
        for n in lens:
            padded = bucket_len(n, self.bucket)
            if n < 1 or padded >= ctx:
                raise ValueError(
                    f"warmup bucket {n} invalid: padded prompt {padded} "
                    f"leaves no decode room in context {ctx}"
                )
        for b in batches:
            if b < 1:
                raise ValueError(f"warmup batch size {b} must be >= 1")
        per: Dict[str, float] = {}
        for n in lens:
            for b in batches:
                key = f"{n}" if b == 1 else f"{n}x{b}"
                t0 = time.time()
                try:
                    # int max_dec_len: land on the 32-bucketed compile key
                    # live traffic hits (a client always sends/clamps to
                    # an explicit max_tokens in tools/serve.py)
                    self.generate_ids(
                        [[1] * n] * b, max_dec_len=self.gen.max_dec_len
                    )
                except Exception as exc:
                    raise RuntimeError(
                        f"warmup failed at bucket {key} (warmed so far: "
                        f"{sorted(per) or 'none'}): "
                        f"{type(exc).__name__}: {exc}"
                    ) from exc
                per[key] = round(time.time() - t0, 2)
                logger.info(
                    f"serving warmup: prompt bucket {n} batch {b} "
                    f"(pad multiple {self.bucket}) compiled in {per[key]:.1f}s"
                )
        self.stats["warmup_s"] = dict(per)
        get_registry().counter("pfx_serving_warmup_seconds_total").inc(
            sum(per.values())
        )
        return per
