"""SLO-driven elastic control plane: replica supervision + autoscaling
over the multi-host router (docs/serving.md "Elastic control plane").

PR 10's router and PR 8's SLO burn-rate gauges were the two halves of an
autoscaler nobody had connected: the router already polls every replica's
``/healthz`` (queue depth, busy seconds, the ``slo`` breach verdict, and
— new — continuous-batch ``occupancy``), and the rolling-drain primitive
already takes a replica out without dropping admitted work.  This module
closes the loop with two cooperating pieces, both pure host-side Python
(no jax import — the control plane boots instantly and survives anything
the accelerator does):

  - :class:`ReplicaSupervisor` — spawns replicas as MANAGED subprocesses
    from one command template, restarts crashes with exponential backoff,
    and applies a **flap budget**: a replica that crash-loops more than
    ``flap_budget`` times inside ``flap_window_s`` is QUARANTINED loudly
    (ERROR log + ``pfx_replica_quarantines_total``) instead of being
    restarted forever — a broken image must page a human, not burn a
    port.  **Warm boot**: spawned replicas get ``--compile-cache-dir``
    appended (``tools/serve.py`` seeds jax's persistent compile cache
    from it), so scale-up is seconds of process boot, not a cold trace.
  - :class:`ElasticController` — one control loop consuming the router's
    replica snapshots and emitting scale decisions: **breach-driven fast
    scale-up** (any serving replica reporting an SLO burn-rate breach,
    or average queue depth / paged-arena occupancy past the high
    watermarks) bounded by ``up_cooldown_s`` per spawn; **idle
    scale-down** only after the fleet has been idle ``idle_s`` AND
    ``down_cooldown_s`` has passed since the last scale action
    (hysteresis — the two watermarks plus the dwell keep the fleet from
    oscillating), executed through the authenticated remote-drain
    primitive so no admitted request is ever dropped; hard
    ``min_replicas``/``max_replicas`` bounds.

Every control tick appends ONE row to a bounded decision log (the PR 8
decision-log contract, controller edition): an untruncated log replays
to EXACT agreement with the ``pfx_controller_*`` counters via
:func:`replay_controller_log` — a scale action the log does not explain
shows up as a mismatch.  ``tools/router.py --supervise`` wires all of
this behind ``GET /debug/controller`` (auth-gated) and the drills in
``tests/test_elastic_drills.py`` exercise it through the real CLIs:
SIGKILL-under-flood -> restart + rejoin, wedged-decode breach ->
scale-up -> recovery, crash-loop -> loud quarantine.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shlex
import signal
import subprocess
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from paddlefleetx_tpu.utils.log import logger
from paddlefleetx_tpu.utils.telemetry import (
    Registry,
    _env_int,
    get_registry,
)

CONTROLLER_LOG_CAP_ENV = "PFX_CONTROLLER_LOG_CAP"


def _cmd_hash(cmd: List[str]) -> str:
    """Short stable hash of a spawn command — the fleet journal records
    it per slot so re-adoption can recognize OUR replica build in
    /proc/<pid>/cmdline (corpse reaping) without journaling the full
    command line."""
    return hashlib.sha256(" ".join(cmd).encode()).hexdigest()[:12]


def _pid_alive(pid: int) -> bool:
    """Signal-0 liveness probe (PermissionError means alive but not
    ours — treated alive: we must never respawn onto its port)."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def _proc_cmd_hash(pid: int) -> Optional[str]:
    """The live process's spawn-command hash via /proc (None when the
    process is gone or the platform has no /proc) — the only safe way
    to recognize a journaled pid after the parent died: pid alone may
    have been recycled by an unrelated process."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            raw = f.read()
    except OSError:
        return None
    parts = [p.decode("utf-8", "replace") for p in raw.split(b"\0") if p]
    return _cmd_hash(parts) if parts else None


@dataclasses.dataclass
class ScalePolicy:
    """The autoscaling knobs, validated loudly (a policy whose
    watermarks invert would oscillate by construction).

    ``high_depth``/``low_depth`` are AVERAGE waiting-queue depth per
    serving replica (router in-flight included); occupancy watermarks
    are the max continuous-batch rows/capacity across the fleet.  Scale
    UP when any breach/high-watermark signal fires (at most once per
    ``up_cooldown_s`` — a spawned replica needs time to reach serving
    before it can relieve anything); scale DOWN only after ``idle_s`` of
    sustained idleness and ``down_cooldown_s`` since the last scale
    action.

    Disaggregated pools watch DIFFERENT signals (docs/serving.md
    "Disaggregated operations"): a prefill pool scales on queue depth /
    TTFT burn (``use_occupancy=False`` — prefill replicas hold no
    decode arena), a decode pool on arena occupancy and
    ``available_blocks`` (``use_depth=False``, ``low_blocks`` > 0: any
    serving replica's admissible-block count at or below it is
    pressure).  The SLO-breach signal is always live.

    ``count_in_flight=False`` builds the depth signal from replica-
    reported queue depth ONLY: under the direct handoff transport a
    prefill replica's router-side in-flight spans the whole
    prefill->decode relay, so counting it would scale the prefill pool
    on DECODE duration (tools/router.py sets this for the prefill pool
    when ``--handoff direct``)."""

    min_replicas: int = 1
    max_replicas: int = 4
    high_depth: float = 4.0
    low_depth: float = 0.5
    high_occupancy: float = 0.9
    low_occupancy: float = 0.25
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 60.0
    idle_s: float = 30.0
    interval_s: float = 1.0
    use_depth: bool = True
    use_occupancy: bool = True
    low_blocks: int = 0
    count_in_flight: bool = True

    def validate(self) -> "ScalePolicy":
        if self.low_blocks < 0:
            raise ValueError(
                f"low_blocks must be >= 0, got {self.low_blocks}"
            )
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}"
            )
        if self.low_depth >= self.high_depth:
            raise ValueError(
                f"low_depth {self.low_depth} must be < high_depth "
                f"{self.high_depth} (hysteresis band)"
            )
        if self.low_occupancy >= self.high_occupancy:
            raise ValueError(
                f"low_occupancy {self.low_occupancy} must be < "
                f"high_occupancy {self.high_occupancy} (hysteresis band)"
            )
        for name in ("up_cooldown_s", "down_cooldown_s", "idle_s",
                     "interval_s"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be > 0")
        if (not self.use_depth and not self.use_occupancy
                and self.low_blocks <= 0):
            # with every load signal off, pressure is breach-only and
            # "idle" degenerates to "no breach": a slammed pool would
            # read as idle and be drained to min_replicas mid-load
            raise ValueError(
                "ScalePolicy needs at least one load signal: enable "
                "use_depth or use_occupancy, or set low_blocks > 0"
            )
        return self

    def view(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ManagedReplica:
    """One supervised replica slot (fixed port; the process comes and
    goes — crash-restart and drain-respawn reuse the slot, so the router
    sees the same url walk gone -> warm -> serving)."""

    slot: int
    port: int
    url: str
    cmd: List[str]
    rid: str = ""        # replica_id: <slot_prefix><slot> (m0, p0, d1...)
    log_path: str = ""
    key: Optional[str] = None            # router registry key
    proc: Optional[subprocess.Popen] = None
    desired: bool = False                # False = expected to exit (drain)
    quarantined: bool = False
    restarts: int = 0
    restart_times: List[float] = dataclasses.field(default_factory=list)
    next_restart_t: float = 0.0          # 0 = no restart pending
    flap_exempt: bool = False            # pending respawn spends no flap
    last_exit_rc: Optional[int] = None
    started_t: float = 0.0
    # re-adoption (docs/serving.md "Control-plane recovery"): a replica
    # spawned by a PREVIOUS router incarnation and re-adopted at boot is
    # not our child — no Popen handle, so liveness is signal-0 on the
    # pid and identity is the /healthz boot_id captured at adoption
    adopted_pid: Optional[int] = None
    adopted_boot_id: Optional[str] = None

    def pid(self) -> Optional[int]:
        if self.proc is not None:
            return self.proc.pid
        return self.adopted_pid

    def view(self) -> Dict[str, Any]:
        return {
            "slot": self.slot,
            "replica_id": self.rid,
            "port": self.port,
            "url": self.url,
            "key": self.key,
            "pid": self.pid(),
            "cmd_hash": _cmd_hash(self.cmd),
            "adopted": self.adopted_pid is not None,
            "desired": self.desired,
            "quarantined": self.quarantined,
            "restarts": self.restarts,
            "restart_pending": self.next_restart_t > 0,
            "last_exit_rc": self.last_exit_rc,
            "log_path": self.log_path,
        }


class ReplicaSupervisor:
    """Managed-subprocess replica supervision: spawn from a command
    template, crash-restart with exponential backoff, quarantine
    crash-loopers LOUDLY within the flap budget, warm-boot via the
    persistent compile cache.

    ``cmd_template`` is a shell-style string with ``{port}`` and
    ``{replica_id}`` placeholders, e.g.::

        python tools/serve.py -c cfg.yaml --port {port} --replica-id {replica_id}

    Slot ``i`` listens on ``base_port + i`` with replica_id ``m<i>``.
    When ``compile_cache_dir`` is set, ``--compile-cache-dir <dir>`` is
    appended so every spawn (first boot, crash-restart, scale-up) seeds
    jax's persistent compile cache — scale-up cost is process boot, not
    a cold trace.  ``spawn_fn`` is injectable for tests; the default
    Popen routes stdout+stderr to ``<log_dir>/<replica_id>.log`` so a
    crash-looping replica leaves evidence instead of a blocked pipe."""

    def __init__(self, cmd_template: str, *, base_port: int,
                 max_replicas: int, role: str = "monolith",
                 slot_prefix: str = "m",
                 compile_cache_dir: str = "", log_dir: str = "",
                 backoff_base_s: float = 0.5, backoff_max_s: float = 30.0,
                 flap_budget: int = 5, flap_window_s: float = 60.0,
                 env: Optional[Dict[str, str]] = None,
                 spawn_fn: Optional[Callable[..., Any]] = None,
                 registry: Optional[Registry] = None) -> None:
        if "{port}" not in cmd_template:
            raise ValueError(
                "replica command template must contain a {port} "
                f"placeholder, got {cmd_template!r}"
            )
        if flap_budget < 1:
            raise ValueError(f"flap_budget must be >= 1, got {flap_budget}")
        # slot_prefix keeps two pools' replica ids distinct (the
        # disaggregated control plane runs one supervisor per pool:
        # prefill p<i>, decode d<i>; the monolith fleet keeps m<i>)
        self.slot_prefix = slot_prefix
        self.cmd_template = cmd_template
        self.base_port = int(base_port)
        self.max_replicas = int(max_replicas)
        self.role = role
        self.compile_cache_dir = compile_cache_dir
        self.log_dir = log_dir
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.flap_budget = int(flap_budget)
        self.flap_window_s = float(flap_window_s)
        self.env = dict(env) if env is not None else None
        self._spawn_fn = spawn_fn
        self._registry = registry or get_registry()
        # optional control-plane journal (core.router.FleetJournal —
        # tools/router.py wires one): slot facts land in it BEFORE the
        # child process exists, so there is no window where a spawned
        # replica is untracked and unadoptable
        self.journal: Optional[Any] = None
        self.slots: Dict[int, ManagedReplica] = {}
        # guards the slots DICT (inserted by the control thread, read
        # by HTTP handler threads via views()/counts — an unguarded
        # sorted(items()) during a scale-up insert is a RuntimeError);
        # ManagedReplica field reads stay lock-free (ints/bools, racy
        # reads are benign)
        self._lock = threading.Lock()

    # -- slot construction ----------------------------------------------
    def _slot(self, i: int) -> ManagedReplica:
        m = self.slots.get(i)
        if m is None:
            port = self.base_port + i
            replica_id = f"{self.slot_prefix}{i}"
            cmd = shlex.split(
                self.cmd_template.format(port=port, replica_id=replica_id)
            )
            if self.compile_cache_dir:
                cmd += ["--compile-cache-dir", self.compile_cache_dir]
            log_path = (os.path.join(self.log_dir, f"{replica_id}.log")
                        if self.log_dir else "")
            m = ManagedReplica(
                slot=i, port=port, url=f"http://127.0.0.1:{port}",
                cmd=cmd, rid=replica_id, log_path=log_path,
            )
            with self._lock:
                self.slots[i] = m
        return m

    def _snapshot(self) -> List[ManagedReplica]:
        with self._lock:
            return [m for _, m in sorted(self.slots.items())]

    def _journal_slot(self, m: ManagedReplica, phase: str,
                      pid: Optional[int], boot_id: Optional[str] = None
                      ) -> None:
        j = self.journal
        if j is not None:
            j.record("slot", pool=self.role, slot=m.slot, port=m.port,
                     url=m.url, rid=m.rid, cmd_hash=_cmd_hash(m.cmd),
                     phase=phase, pid=pid, boot_id=boot_id)

    def _spawn(self, m: ManagedReplica, now: float) -> None:
        # the "spawning" record lands BEFORE the child exists: if the
        # router dies between this append and the Popen returning, the
        # next boot still knows the slot/port/cmd_hash and can adopt or
        # reap whatever the half-spawn left behind (satellite: no
        # untracked-child window)
        m.adopted_pid = None
        m.adopted_boot_id = None
        self._journal_slot(m, "spawning", None)
        if self._spawn_fn is not None:
            m.proc = self._spawn_fn(m)
        else:
            if m.log_path:
                os.makedirs(os.path.dirname(m.log_path), exist_ok=True)
                # append: one log tells the whole crash-loop story
                out = open(m.log_path, "ab", buffering=0)
            else:
                out = subprocess.DEVNULL
            m.proc = subprocess.Popen(
                m.cmd, stdout=out, stderr=subprocess.STDOUT,
                env=self.env,
            )
            if out is not subprocess.DEVNULL:
                out.close()  # the child holds its own fd now
        m.started_t = now
        m.next_restart_t = 0.0
        self._journal_slot(m, "spawned", m.proc.pid)
        logger.info(
            f"supervisor: spawned replica {m.rid} "
            f"(pid {m.proc.pid}, port {m.port})"
        )

    # -- desired-state management ---------------------------------------
    def ensure(self, target: int, now: Optional[float] = None
               ) -> List[ManagedReplica]:
        """Desire ``target`` running replicas among non-quarantined
        slots (lowest slots first), spawning the missing ones NOW.
        Returns the newly DESIRED slots — spawned immediately, or
        respawn-pending behind a still-draining predecessor (the
        controller registers their urls with the router and commits a
        scale-up only when this list is non-empty)."""
        now = time.monotonic() if now is None else now
        started: List[ManagedReplica] = []
        desired = 0
        for i in range(self.max_replicas):
            if desired >= target:
                break
            m = self._slot(i)
            if m.quarantined:
                continue
            if not m.desired:
                m.desired = True
                started.append(m)
                if m.proc is None:
                    self._spawn(m, now)
                else:
                    # the slot's previous process is still draining out:
                    # spawning now would double-bind the port — respawn
                    # right after poll() reaps its exit
                    m.next_restart_t = now
            desired += 1
        return started

    # -- fleet re-adoption (docs/serving.md "Control-plane recovery") ----
    def _probe_identity(self, url: str, timeout: float
                        ) -> Optional[Dict[str, Any]]:
        """GET /healthz on a slot's port -> its identity block, or None
        when nothing answers (import is deferred: core.router is jax-free
        but the supervisor must stay importable standalone)."""
        from paddlefleetx_tpu.core.router import _http_request
        try:
            status, body, _, _ = _http_request(
                url, "GET", "/healthz", timeout=timeout)
            if status != 200:
                return None
            h = json.loads(body)
            return h.get("identity") or {}
        except Exception:  # noqa: BLE001 — any failure means no replica
            return None

    def adopt(self, slot_facts: Dict[str, Any], *,
              probe_timeout_s: float = 2.0) -> List[ManagedReplica]:
        """Reconcile journaled slot facts against what is actually
        running (the Borg/Pathways reconcile step, PR 19): probe each
        recorded slot's port, and a live replica whose /healthz identity
        matches the journal (replica_id + pid + boot_id — never bare
        pid) is RE-ADOPTED into its slot with zero restarts and no flap
        budget spent.  A port answering with the WRONG identity is a
        squatter — the slot is quarantined loudly rather than spawned
        into a bind collision.  A journaled pid that is alive but not
        answering is reaped ONLY when /proc/<pid>/cmdline hashes to the
        slot's recorded spawn command (a recycled pid never gets our
        SIGKILL).  Slots left empty respawn through the normal
        ``ensure`` path.  With an EMPTY fact for a slot (journal lost),
        a live replica answering with the slot's own replica_id is
        still adopted — the probe on OUR port reporting OUR replica_id
        is the identity match.  Returns the newly adopted slots (the
        controller registers their urls like freshly spawned ones)."""
        adopted: List[ManagedReplica] = []
        now = time.monotonic()
        for slot_key, fact in sorted(
                (slot_facts or {}).items(), key=lambda kv: str(kv[0])):
            try:
                i = int(slot_key)
            except (TypeError, ValueError):
                continue
            if not (0 <= i < self.max_replicas):
                continue
            fact = fact if isinstance(fact, dict) else {}
            m = self._slot(i)
            if m.proc is not None or m.adopted_pid is not None:
                continue
            ident = self._probe_identity(m.url, probe_timeout_s)
            if ident is None:
                # nothing answering: if the journaled pid is still alive
                # AND provably ours (cmdline hash), it is a wedged corpse
                # from the dead router — reap it so ensure() can respawn
                # onto the port
                pid = fact.get("pid")
                if (isinstance(pid, int) and pid > 0 and _pid_alive(pid)
                        and fact.get("cmd_hash")
                        and _proc_cmd_hash(pid) == fact.get("cmd_hash")):
                    logger.warning(
                        f"supervisor: reaping stale replica corpse "
                        f"{m.rid} (pid {pid} alive but /healthz silent; "
                        "cmdline matches the journaled spawn command)")
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except OSError:
                        pass
                continue
            live_pid = ident.get("pid")
            live_boot = ident.get("boot_id")
            rid_ok = ident.get("replica_id") == m.rid
            if fact.get("pid") is not None or fact.get("boot_id"):
                # journal has identity facts: the FULL triple must match
                match = (rid_ok and live_pid == fact.get("pid")
                         and (not fact.get("boot_id")
                              or live_boot == fact.get("boot_id")))
            else:
                # journal lost/stale (self-registration rebuild path):
                # the process answering on our slot's port with our
                # replica_id IS the identity match
                match = rid_ok
            if not match:
                m.quarantined = True
                logger.error(
                    f"QUARANTINE: slot {i} (port {m.port}) is held by a "
                    f"process whose identity does not match "
                    f"(journal pid={fact.get('pid')} "
                    f"boot_id={fact.get('boot_id')}; live "
                    f"pid={live_pid} boot_id={live_boot} "
                    f"replica_id={ident.get('replica_id')!r}); NOT "
                    "spawning into a bind collision — free the port and "
                    "restart the control plane")
                continue
            m.desired = True
            m.adopted_pid = int(live_pid) if live_pid is not None else None
            m.adopted_boot_id = live_boot
            m.started_t = now
            m.next_restart_t = 0.0
            m.last_exit_rc = None
            adopted.append(m)
            self._registry.counter(
                "pfx_router_adopted_replicas_total", replica=m.rid
            ).inc()
            self._journal_slot(m, "adopted", m.adopted_pid,
                               m.adopted_boot_id)
            logger.info(
                f"supervisor: re-adopted replica {m.rid} "
                f"(pid {m.adopted_pid}, port {m.port}, "
                f"boot_id {m.adopted_boot_id}) — zero restarts, no flap "
                "budget spent")
        return adopted

    def drain_slot(self, slot: int) -> ManagedReplica:
        """Mark a slot's exit EXPECTED (scale-down): the supervisor will
        not restart it.  The actual drain goes through the router's
        authenticated remote-drain so admitted work finishes."""
        m = self.slots[slot]
        m.desired = False
        m.next_restart_t = 0.0
        return m

    def pick_drain_slot(self) -> Optional[ManagedReplica]:
        """Highest desired, non-quarantined slot — scale-down retires
        the newest replica first so the stable low slots keep their
        warm caches and router history."""
        live = [m for m in self._snapshot()
                if m.desired and not m.quarantined]
        return max(live, key=lambda m: m.slot) if live else None

    # -- supervision ----------------------------------------------------
    def poll(self, now: Optional[float] = None) -> None:
        """One supervision sweep: reap exits, schedule/execute
        backoff restarts, quarantine crash-loopers loudly."""
        now = time.monotonic() if now is None else now
        for m in self._snapshot():
            if m.proc is None and m.adopted_pid is not None:
                # adopted replicas are not our children: liveness is
                # signal-0, and an exit's rc is unobservable — treat it
                # like a clean out-of-band drain (flap budget untouched)
                # and respawn if still desired
                if _pid_alive(m.adopted_pid):
                    continue
                pid = m.adopted_pid
                m.adopted_pid = None
                m.adopted_boot_id = None
                m.last_exit_rc = None
                if not m.desired or m.quarantined:
                    logger.info(
                        f"supervisor: adopted replica {m.rid} "
                        f"(pid {pid}) exited (expected: drained)")
                    continue
                m.flap_exempt = True
                m.next_restart_t = now + self.backoff_base_s
                logger.info(
                    f"supervisor: adopted replica {m.rid} (pid {pid}) "
                    f"exited (rc unobservable — not our child); "
                    f"respawning in {self.backoff_base_s:.2f}s "
                    "(flap budget not spent)")
                continue
            if m.proc is not None:
                rc = m.proc.poll()
                if rc is None:
                    continue
                m.last_exit_rc = rc
                m.proc = None
                if not m.desired:
                    logger.info(
                        f"supervisor: replica {m.rid} exited rc={rc} "
                        "(expected: drained)"
                    )
                    continue
                if m.quarantined:
                    continue
                if rc == 0:
                    # a CLEAN exit while desired: an out-of-band drain
                    # (manual POST /admin/drain at a supervised replica,
                    # or ensure()'s respawn-after-drain handoff) — the
                    # fleet self-heals by respawning, but a deploy is
                    # not a crash: the flap budget is not spent and no
                    # crash warning is logged
                    m.flap_exempt = True
                    m.next_restart_t = now + self.backoff_base_s
                    logger.info(
                        f"supervisor: replica {m.rid} exited cleanly "
                        "(rc=0) while desired — out-of-band drain? "
                        f"respawning in {self.backoff_base_s:.2f}s "
                        "(flap budget not spent)"
                    )
                    continue
                m.flap_exempt = False
                recent = [t for t in m.restart_times
                          if now - t <= self.flap_window_s]
                if len(recent) >= self.flap_budget:
                    m.quarantined = True
                    m.next_restart_t = 0.0
                    self._registry.counter(
                        "pfx_replica_quarantines_total",
                        replica=m.rid,
                    ).inc()
                    logger.error(
                        f"QUARANTINE: replica {m.rid} (port {m.port}) "
                        f"crash-looped {len(recent)} time(s) within "
                        f"{self.flap_window_s:g}s (flap budget "
                        f"{self.flap_budget}; last rc={rc}); NOT "
                        "restarting it again — inspect "
                        f"{m.log_path or 'its log'} and redeploy, then "
                        "restart the control plane"
                    )
                    continue
                backoff = min(
                    self.backoff_max_s,
                    self.backoff_base_s * (2.0 ** len(recent)),
                )
                m.next_restart_t = now + backoff
                logger.warning(
                    f"supervisor: replica {m.rid} crashed rc={rc}; "
                    f"restart {len(recent) + 1} in {backoff:.2f}s"
                )
            elif (m.desired and not m.quarantined
                  and m.next_restart_t > 0 and now >= m.next_restart_t):
                if not m.flap_exempt:
                    m.restart_times = [
                        t for t in m.restart_times
                        if now - t <= self.flap_window_s
                    ]
                    m.restart_times.append(now)
                m.flap_exempt = False
                m.restarts += 1
                self._registry.counter(
                    "pfx_replica_restarts_total", replica=m.rid
                ).inc()
                self._spawn(m, now)

    # -- views / teardown ------------------------------------------------
    def views(self) -> List[Dict[str, Any]]:
        return [m.view() for m in self._snapshot()]

    def desired_count(self) -> int:
        return sum(1 for m in self._snapshot()
                   if m.desired and not m.quarantined)

    def quarantined_count(self) -> int:
        return sum(1 for m in self._snapshot() if m.quarantined)

    def kill_all(self) -> None:
        """Hard teardown for the force-quit path: SIGKILL every live
        child, no drain, never raises (runs on signal escape paths
        where a secondary failure must not mask the exit)."""
        for m in self._snapshot():
            if m.proc is not None:
                try:
                    m.proc.kill()
                except OSError:
                    pass
            elif m.adopted_pid is not None:
                try:
                    os.kill(m.adopted_pid, signal.SIGKILL)
                except OSError:
                    pass
                m.adopted_pid = None
                m.adopted_boot_id = None

    def stop_all(self, timeout: float = 30.0) -> None:
        """Graceful teardown: SIGTERM every live child (each drains via
        the PR 3 contract and exits 0), kill stragglers.  Adopted
        replicas (not our children — no Popen handle) get the same
        SIGTERM and a signal-0 liveness wait."""
        live = [m for m in self._snapshot() if m.proc is not None]
        adopted = [m for m in self._snapshot()
                   if m.proc is None and m.adopted_pid is not None]
        for m in live:
            m.desired = False
            try:
                m.proc.terminate()
            except OSError:
                pass
        for m in adopted:
            m.desired = False
            try:
                os.kill(m.adopted_pid, signal.SIGTERM)
            except OSError:
                m.adopted_pid = None
                m.adopted_boot_id = None
        deadline = time.monotonic() + timeout
        for m in live:
            if m.proc is None:
                continue
            left = max(0.1, deadline - time.monotonic())
            try:
                m.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                logger.warning(
                    f"supervisor: replica {m.rid} ignored SIGTERM for "
                    f"{timeout:g}s; killing"
                )
                m.proc.kill()
                try:
                    m.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            m.proc = None
        for m in adopted:
            if m.adopted_pid is None:
                continue
            while (_pid_alive(m.adopted_pid)
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            if _pid_alive(m.adopted_pid):
                logger.warning(
                    f"supervisor: adopted replica {m.rid} ignored "
                    f"SIGTERM for {timeout:g}s; killing")
                try:
                    os.kill(m.adopted_pid, signal.SIGKILL)
                except OSError:
                    pass
            m.adopted_pid = None
            m.adopted_boot_id = None


class ElasticController:
    """The control loop: consume the router's replica snapshots, emit
    scale decisions, drive the supervisor + the authenticated remote
    drain.  ``core`` needs the RouterCore surface (``replica_views``,
    ``add_replica``, ``drain``); tests drive :meth:`tick` directly with
    injected clocks and stub cores."""

    def __init__(self, core: Any, supervisor: ReplicaSupervisor,
                 policy: ScalePolicy, *, role: str = "monolith",
                 registry: Optional[Registry] = None) -> None:
        self.core = core
        self.supervisor = supervisor
        self.policy = policy.validate()
        self.role = role
        reg = registry or get_registry()
        # disaggregated pool controllers label their counters with the
        # pool so prefill/decode decisions replay per pool; the monolith
        # fleet stays UNLABELED — the PR 11 drill contracts read it that
        # way, and one monolith controller per process needs no label
        labels = {} if role == "monolith" else {"pool": role}
        self._ticks = reg.counter("pfx_controller_ticks_total", **labels)
        self._ups = reg.counter("pfx_controller_scale_ups_total", **labels)
        self._downs = reg.counter(
            "pfx_controller_scale_downs_total", **labels
        )
        self._target_gauge = reg.gauge(
            "pfx_controller_target_replicas", **labels
        )
        self._breach_gauge = reg.gauge("pfx_controller_breach", **labels)
        # bounded decision log, the PR 8 replay contract (controller
        # edition): one row per tick; an untruncated log replays to
        # exact agreement with the counters (replay_controller_log)
        self.decision_log: deque = deque(
            maxlen=_env_int(CONTROLLER_LOG_CAP_ENV, 4096)
        )
        # appends happen on the control thread while /debug/controller
        # handler threads snapshot — list(deque) during an append is a
        # RuntimeError without this
        self._log_lock = threading.Lock()
        self.target = self.policy.min_replicas
        self._seq = 0
        self._last_up_t = float("-inf")
        self._last_scale_t = float("-inf")
        self._idle_since: Optional[float] = None
        # optional control-plane journal (core.router.FleetJournal):
        # every tick's decision + clock AGES land in it so a restarted
        # router resumes cooldowns instead of insta-rescaling
        self.journal: Optional[Any] = None
        self._at_max_warned = False
        self._no_slot_warned = False
        self._thread = None
        self._stop = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ElasticController":
        """Bring the fleet to ``min_replicas`` and start the loop."""
        self._register(self.supervisor.ensure(self.target))
        self._target_gauge.set(float(self.target))
        if self._thread is None or not self._thread.is_alive():
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop,
                name=f"elastic-controller-{self.role}", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.policy.interval_s):
            try:
                self.supervisor.poll()
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # one bad tick (a replica url racing its own exit, a
                # transient drain failure); crashing the control plane
                # on it would take down supervision entirely
                logger.warning(f"controller tick failed: {e}")

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _register(self, started: List[ManagedReplica]) -> None:
        for m in started:
            if m.key is None:
                m.key = self.core.add_replica(m.url, self.role)

    # -- the decision ----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Evaluate one control decision; returns (and logs) the
        decision row.  Pure function of the snapshots + injected clock —
        the unit tests drive it deterministically."""
        now = time.monotonic() if now is None else float(now)
        p = self.policy
        views = [v for v in self.core.replica_views()
                 if v["role"] == self.role]
        serving = [v for v in views
                   if v["state"] == "serving" and not v["draining"]]
        coming = [v for v in views if v["state"] in ("booting", "warm")]
        breach = any(v.get("slo_breach") for v in serving)
        depth_total = sum(
            v["depth"] + (v["in_flight"] if p.count_in_flight else 0)
            for v in serving
        )
        avg_depth = depth_total / max(1, len(serving))
        occ = max((v.get("occupancy", 0.0) for v in serving), default=0.0)
        # decode-pool signal: the WORST serving replica's admissible
        # blocks (free + reclaimable, from /healthz) — a pool whose
        # tightest arena is at/below low_blocks will start bouncing
        # adoptions; None until a poll carries the field
        min_blocks = min(
            (v["available_blocks"] for v in serving
             if v.get("available_blocks") is not None),
            default=None,
        )
        depth_hot = p.use_depth and avg_depth > p.high_depth
        occ_hot = p.use_occupancy and occ > p.high_occupancy
        blocks_hot = (p.low_blocks > 0 and min_blocks is not None
                      and min_blocks <= p.low_blocks)
        pressure = breach or depth_hot or occ_hot or blocks_hot
        # zero serving replicas is an OUTAGE, not idleness: with nothing
        # serving, depth/occupancy read 0 by construction, and scaling
        # down mid-outage would retire capacity exactly when the fleet
        # is returning 503s — idle requires at least one serving replica
        idle = (bool(serving) and not pressure
                and (not p.use_depth or avg_depth <= p.low_depth)
                and (not p.use_occupancy or occ <= p.low_occupancy)
                and (p.low_blocks == 0 or min_blocks is None
                     or min_blocks > 2 * p.low_blocks))
        self._idle_since = (
            (self._idle_since if self._idle_since is not None else now)
            if idle else None
        )

        action, reason = "hold", ""
        if pressure:
            why = ("slo burn-rate breach" if breach
                   else f"avg depth {avg_depth:.2f} > {p.high_depth:g}"
                   if depth_hot
                   else f"occupancy {occ:.2f} > {p.high_occupancy:g}"
                   if occ_hot
                   else f"available blocks {min_blocks} <= "
                        f"{p.low_blocks} (arena pressure)")
            if self.target >= p.max_replicas:
                reason = f"{why}, but at max_replicas {p.max_replicas}"
                if not self._at_max_warned:
                    self._at_max_warned = True
                    logger.warning(
                        f"controller: {reason} — the fleet cannot absorb "
                        "more load; raise --max-replicas or add hosts"
                    )
            elif coming:
                # a spawned replica is still walking booting -> serving:
                # let it land before deciding the fleet is still short
                reason = f"{why}; {len(coming)} replica(s) still warming"
            elif now - self._last_up_t < p.up_cooldown_s:
                reason = f"{why}; up-cooldown"
            else:
                started = self.supervisor.ensure(self.target + 1, now)
                if started:
                    action, reason = "scale_up", why
                    self.target += 1
                    self._last_up_t = self._last_scale_t = now
                    self._at_max_warned = False
                    self._no_slot_warned = False
                    self._register(started)
                else:
                    # every remaining slot is quarantined: a scale-up
                    # that spawns nothing must not move the target or
                    # the counters — the decision log records reality
                    reason = (
                        f"{why}, but no spawnable slot "
                        f"({self.supervisor.quarantined_count()} "
                        "quarantined)"
                    )
                    if not self._no_slot_warned:
                        self._no_slot_warned = True
                        logger.warning(
                            f"controller: {reason} — redeploy the "
                            "quarantined replica(s) and restart the "
                            "control plane"
                        )
        elif (idle and self.target > p.min_replicas
              and now - self._idle_since >= p.idle_s
              and now - self._last_scale_t >= p.down_cooldown_s):
            m = self.supervisor.pick_drain_slot()
            if m is not None and m.key is not None:
                action = "scale_down"
                reason = (f"idle {now - self._idle_since:.0f}s "
                          f"(avg depth {avg_depth:.2f}, occ {occ:.2f})")
                self.target -= 1
                self._last_scale_t = now
                self._idle_since = None
                self.supervisor.drain_slot(m.slot)
                try:
                    self.core.drain(m.key)
                except ValueError as e:
                    # already gone / auth misconfig: the slot stays
                    # retired (desired=False) either way, loudly
                    logger.warning(
                        f"controller: drain of {m.key} failed: {e}"
                    )

        self._seq += 1
        row = {
            "tick": self._seq,
            "t": round(now, 3),
            # the pool this row belongs to: disaggregated control planes
            # run one controller per pool, and a per-pool replay must
            # fold each pool's rows into ITS labeled counters
            "pool": self.role,
            "action": action,
            "reason": reason,
            "target": self.target,
            "serving": len(serving),
            "warming": len(coming),
            "breach": breach,
            "avg_depth": round(avg_depth, 3),
            "occupancy": round(occ, 3),
            "min_blocks": min_blocks,
            "quarantined": self.supervisor.quarantined_count(),
        }
        with self._log_lock:
            self.decision_log.append(row)
        j = self.journal
        if j is not None:
            # ages, not clock values: monotonic clocks never cross a
            # process boundary — restore_clocks rebases them as
            # new_now - (age + death window)
            j.record(
                "scale", pool=self.role, action=action, reason=reason,
                target=self.target, tick=self._seq, serving=len(serving),
                up_age_s=(round(now - self._last_up_t, 3)
                          if self._last_up_t != float("-inf") else None),
                scale_age_s=(round(now - self._last_scale_t, 3)
                             if self._last_scale_t != float("-inf")
                             else None),
                idle_for_s=(round(now - self._idle_since, 3)
                            if self._idle_since is not None else None),
            )
        self._ticks.inc()
        if action == "scale_up":
            self._ups.inc()
        elif action == "scale_down":
            self._downs.inc()
        self._target_gauge.set(float(self.target))
        self._breach_gauge.set(1.0 if pressure else 0.0)
        return row

    def journal_state(self) -> Dict[str, Any]:
        """This controller's journal-snapshot row — the same age-based
        clock encoding tick()'s ``scale`` records use, consumed by
        :meth:`restore_clocks` on the next boot."""
        now = time.monotonic()
        return {
            "target": self.target,
            "tick": self._seq,
            "up_age_s": (round(now - self._last_up_t, 3)
                         if self._last_up_t != float("-inf") else None),
            "scale_age_s": (round(now - self._last_scale_t, 3)
                            if self._last_scale_t != float("-inf")
                            else None),
            "idle_for_s": (round(now - self._idle_since, 3)
                           if self._idle_since is not None else None),
        }

    def restore_clocks(self, *, target: Optional[int] = None,
                       tick: Optional[int] = None,
                       up_age_s: Optional[float] = None,
                       scale_age_s: Optional[float] = None,
                       extra_age_s: float = 0.0) -> None:
        """Resume from a journaled ``scale`` record (router restart):
        the target is clamped into the current policy's bounds, the tick
        sequence continues instead of restarting at 0, and the cooldown
        clocks rebase as ``now - (journaled age + extra_age_s)`` where
        ``extra_age_s`` is the death window — real wall time passed, so
        cooldowns neither reset (which would allow an instant re-scale)
        nor freeze.  The idle dwell is deliberately NOT restored:
        idleness was not observed across the death window, and a restart
        must never open with a scale-down."""
        now = time.monotonic()
        p = self.policy
        extra = max(0.0, float(extra_age_s))
        if target is not None:
            try:
                self.target = max(p.min_replicas,
                                  min(p.max_replicas, int(target)))
            except (TypeError, ValueError):
                pass
        if tick is not None:
            try:
                self._seq = max(self._seq, int(tick))
            except (TypeError, ValueError):
                pass
        if up_age_s is not None:
            try:
                self._last_up_t = now - (max(0.0, float(up_age_s))
                                         + extra)
            except (TypeError, ValueError):
                pass
        if scale_age_s is not None:
            try:
                self._last_scale_t = now - (max(0.0, float(scale_age_s))
                                            + extra)
            except (TypeError, ValueError):
                pass
        self._idle_since = None
        self._target_gauge.set(float(self.target))
        logger.info(
            f"controller[{self.role}]: clocks restored from the fleet "
            f"journal (target {self.target}, tick {self._seq}, death "
            f"window {extra:.1f}s)")

    def view(self) -> Dict[str, Any]:
        """Operator snapshot for GET /debug/controller (auth-gated)."""
        with self._log_lock:
            decisions = list(self.decision_log)
        return {
            "policy": self.policy.view(),
            "target": self.target,
            "decisions": decisions,
            "replicas": self.supervisor.views(),
        }


def replay_controller_log(rows, pool: Optional[str] = None
                          ) -> Dict[str, int]:
    """Fold controller decision rows back into the counters they must
    reproduce (the PR 8 replay contract): on a run whose log was not
    truncated, ``ticks`` == pfx_controller_ticks_total, ``scale_ups`` ==
    pfx_controller_scale_ups_total and ``scale_downs`` ==
    pfx_controller_scale_downs_total — a scale action the log cannot
    explain shows up as a mismatch.  ``pool`` restricts the fold to one
    pool's rows (rows predating the field count as monolith), matching
    the ``pool``-labeled counters a disaggregated control plane keeps
    per pool."""
    out = {"ticks": 0, "scale_ups": 0, "scale_downs": 0, "holds": 0}
    for row in rows:
        if pool is not None and row.get("pool", "monolith") != pool:
            continue
        out["ticks"] += 1
        action = row.get("action")
        if action == "scale_up":
            out["scale_ups"] += 1
        elif action == "scale_down":
            out["scale_downs"] += 1
        else:
            out["holds"] += 1
    return out
