"""Module protocol: binds a model family to the engine.

Reference: ``BasicModule`` (ppfleetx/core/module/basic_module.py:29-86, a
Lightning-style protocol) + ``GPTModule`` (language_module.py:148).  Here a
module is the *functional* bundle the engine needs: param specs + loss +
metrics; train/eval stepping lives in the engine (pure jitted functions),
so the protocol is data-flow only — no training_step/backward hooks.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from paddlefleetx_tpu.utils.registry import MODULES


def resolve_model_dtype(cfg, model_cfg: Dict[str, Any]) -> None:
    """Fill model_cfg['dtype'] from Engine.mix_precision unless pinned.

    mix disabled == O0: fp32 compute (reference amp levels,
    distributed/apis/amp.py)."""
    if "dtype" not in model_cfg:
        mix = cfg.get("Engine", {}).get("mix_precision", {})
        model_cfg["dtype"] = (
            mix.get("dtype", "bfloat16") if mix.get("enable", True) else "float32"
        )


class BasicModule:
    """Interface consumed by the Engine."""

    def init_params(self, key: jax.Array) -> Any:
        raise NotImplementedError

    def logical_axes(self) -> Any:
        """Pytree of logical sharding-axis tuples matching params."""
        raise NotImplementedError

    def loss_fn(
        self,
        params: Any,
        batch: Dict[str, jax.Array],
        *,
        ctx=None,
        dropout_key: Optional[jax.Array] = None,
        train: bool = True,
    ) -> jax.Array:
        raise NotImplementedError

    def eval_metrics(self, loss: jax.Array) -> Dict[str, jax.Array]:
        return {"loss": loss}

    def export_spec(self):
        """(fwd, example_args): the inference forward and its example inputs
        (reference BasicModule.input_spec, basic_module.py:29-86) — consumed
        by tools/export.py for the StableHLO artifact."""
        raise NotImplementedError(
            f"{type(self).__name__} does not define export_spec(); "
            "add one to export this family"
        )

    # tokens per sample for ips reporting (reference language_module.py:100)
    tokens_per_sample: Optional[int] = None


@MODULES.register("GPTModule")
class GPTModule(BasicModule):
    """GPT pretraining (reference GPTModule language_module.py:148-227).

    Where the reference dispatches to single/hybrid/pipe model classes by
    world size (language_module.py:181-192), parallelism here is carried by
    the sharding rules the engine applies — one model."""

    def __init__(self, cfg):
        from paddlefleetx_tpu.models.gpt.config import GPTConfig

        model_cfg = dict(cfg.Model)
        model_cfg.pop("module", None)
        model_cfg.pop("name", None)
        resolve_model_dtype(cfg, model_cfg)
        dist = cfg.get("Distributed", {})
        if dist.get("sequence_parallel", False):
            model_cfg["sequence_parallel"] = True
        self.config = GPTConfig.from_config(model_cfg)
        self.tokens_per_sample = self.config.max_position_embeddings
        seq_len = cfg.get("Data", {}).get("Train", {}).get("dataset", {}).get("max_seq_len")
        if seq_len:
            self.tokens_per_sample = int(seq_len)

    def init_params(self, key):
        from paddlefleetx_tpu.models.gpt import model as gpt

        return gpt.init(self.config, key)

    def logical_axes(self):
        from paddlefleetx_tpu.models.gpt import model as gpt

        return gpt.gpt_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        from paddlefleetx_tpu.models.gpt import model as gpt

        return gpt.loss_fn(
            params, batch, self.config, ctx=ctx, dropout_key=dropout_key, train=train
        )

    def export_spec(self):
        import jax.numpy as jnp

        from paddlefleetx_tpu.models.gpt import model as gpt

        cfg = self.config
        tokens = jnp.zeros((1, self.tokens_per_sample), jnp.int32)

        def fwd(params, tokens):
            return gpt.forward(params, tokens, cfg, train=False)

        return fwd, (tokens,)


@MODULES.register("GeneralClsModule")
@MODULES.register("ViTModule")
class ViTModule(BasicModule):
    """ViT / general image classification (reference
    GeneralClsModule general_classification_module.py + vit modules)."""

    def __init__(self, cfg):
        from paddlefleetx_tpu.models.vit.model import ViTConfig

        model_cfg = dict(cfg.Model)
        model_cfg.pop("module", None)
        model_cfg.pop("name", None)
        resolve_model_dtype(cfg, model_cfg)
        self.config = ViTConfig.from_config(model_cfg)
        self.label_smoothing = float(model_cfg.get("label_smoothing", 0.0))
        self.tokens_per_sample = self.config.num_patches + 1  # ips = patches/s

    def init_params(self, key):
        from paddlefleetx_tpu.models import vit

        return vit.init(self.config, key)

    def logical_axes(self):
        from paddlefleetx_tpu.models import vit

        return vit.vit_logical_axes(self.config)

    def loss_fn(self, params, batch, *, ctx=None, dropout_key=None, train=True):
        from paddlefleetx_tpu.models import vit

        logits = vit.forward(
            params,
            batch["images"],
            self.config,
            ctx=ctx,
            dropout_key=dropout_key,
            train=train,
        )
        return vit.cls_loss(logits, batch["labels"], self.label_smoothing)

    def export_spec(self):
        import jax.numpy as jnp

        from paddlefleetx_tpu.models import vit

        cfg = self.config
        images = jnp.zeros(
            (1, cfg.image_size, cfg.image_size, cfg.in_channels), jnp.float32
        )

        def fwd(params, images):
            return vit.forward(params, images, cfg, train=False)

        return fwd, (images,)


def build_module(cfg) -> BasicModule:
    """Name-dispatched module construction (reference models/__init__.py:30,
    minus the eval())."""
    _register_family_modules()
    name = cfg.Model.get("module", "GPTModule")
    return MODULES.get(name)(cfg)


def _register_family_modules():
    """Import model-family module adapters so their @MODULES.register run.

    Lazy (not at package import) to keep `import paddlefleetx_tpu` light;
    idempotent because Registry rejects double registration only on distinct
    functions and imports are cached."""
    import paddlefleetx_tpu.models.debertav2.module  # noqa: F401
    import paddlefleetx_tpu.models.ernie.module  # noqa: F401
    import paddlefleetx_tpu.models.gpt.evaluation  # noqa: F401
    import paddlefleetx_tpu.models.multimodal.module  # noqa: F401
    import paddlefleetx_tpu.models.gpt.finetune  # noqa: F401
    import paddlefleetx_tpu.models.protein.module  # noqa: F401
    import paddlefleetx_tpu.models.t5.module  # noqa: F401
    import paddlefleetx_tpu.models.vision.module  # noqa: F401
